"""Fault-tolerant execution layer for the dcompact boundary.

The reference's elastic dcompact fans compaction jobs out to remote workers
that can crash, hang, or vanish (compaction_executor.h in /root/reference);
the LSM compaction design-space survey treats the failure/fallback policy as
a first-class design axis. This module is that policy, factored around the
CompactionExecutor seam so every transport (device, subprocess, HTTP
service) inherits it:

  DcompactOptions       retry/backoff/deadline/lease knobs, JSON-configurable
                        through utils.config (the SidePlugin shape).
  CircuitBreaker /      per-worker-URL health: consecutive failures open the
  WorkerHealthRegistry  breaker, a half-open probe re-admits recovered
                        workers, round-robin URL picks skip open circuits.
  LocalPinGate          graceful degradation: after N consecutive remote JOB
                        failures the scheduler pins jobs local for a cooldown
                        window instead of paying the remote timeout per job.
  execute_resilient     the retry driver the scheduler calls: per-attempt
                        retry with exponential backoff + jitter, a per-job
                        deadline, attempt-dir sweeping, DCOMPACTION_* stats,
                        and listener events.
  JobLease / sweep_orphan_jobs
                        heartbeat files in the shared job dir; a crashed
                        worker's orphaned job is detected by lease expiry
                        and its partial outputs swept on DB open.
  DcompactFaultInjector deterministic fault points for the subprocess/HTTP
                        transports (drop request, delay response, kill the
                        worker mid-job, truncate/corrupt results JSON) so
                        every path above is exercisable in tests.
"""

from __future__ import annotations

import dataclasses
import os
import random
import shutil
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.utils import statistics as stats_mod
from toplingdb_tpu.utils.status import IOError_
from toplingdb_tpu.utils import errors as _errors


# ---------------------------------------------------------------------------
# Policy
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DcompactOptions:
    """Retry/health/lease policy for distributed compaction. Lives on
    Options.dcompact and serializes through utils.config (JSON key
    "dcompact"), so a SidePlugin-style document can tune the whole failure
    policy without code."""

    # -- per-attempt retry ------------------------------------------------
    max_attempts: int = 3            # remote tries per job (>=1)
    backoff_base: float = 0.05       # seconds before attempt 2
    backoff_multiplier: float = 2.0  # exponential growth per retry
    backoff_jitter: float = 0.2      # +/- fraction of the computed delay
    attempt_timeout: float = 3600.0  # per-attempt transport timeout (s)
    job_deadline: float = 0.0        # wall-clock budget across attempts;
                                     # 0 = attempts bound the job alone
    # -- worker health / circuit breaking ---------------------------------
    breaker_failure_threshold: int = 3   # consecutive failures -> OPEN
    breaker_reset_timeout: float = 30.0  # OPEN -> HALF_OPEN probe delay (s)
    # -- graceful degradation ---------------------------------------------
    local_pin_failures: int = 3      # consecutive remote JOB failures ->
    local_pin_cooldown: float = 60.0  # ...pin jobs local for this long (s)
    # -- job leases -------------------------------------------------------
    lease_sec: float = 30.0          # heartbeat older than this = orphan

    def backoff_delay(self, retry_index: int, rng=None) -> float:
        """Delay before retry `retry_index` (1-based), with jitter."""
        d = self.backoff_base * (self.backoff_multiplier ** (retry_index - 1))
        j = self.backoff_jitter
        if j > 0:
            r = (rng or random).random()
            d *= 1.0 + j * (2.0 * r - 1.0)
        return max(0.0, d)

    def to_config(self) -> dict:
        base = DcompactOptions()
        return {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if getattr(self, f.name) != getattr(base, f.name)
        }

    @staticmethod
    def from_config(d: dict) -> "DcompactOptions":
        return DcompactOptions(**d)


# ---------------------------------------------------------------------------
# Worker health: per-URL circuit breakers
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Classic three-state breaker for ONE worker URL. CLOSED admits all
    traffic; `failure_threshold` consecutive failures OPEN it; after
    `reset_timeout` the next allow() admits exactly one HALF_OPEN probe —
    success re-CLOSEs, failure re-OPENs (and restarts the timer)."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout: float = 30.0, clock=time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._mu = ccy.Lock("resilience.CircuitBreaker._mu")
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def allow(self) -> bool:
        with self._mu:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout:
                    self.state = self.HALF_OPEN
                    self._probe_inflight = True
                    return True
                return False
            # HALF_OPEN: one probe at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def on_success(self) -> bool:
        """Returns True when this success CLOSEd a non-closed breaker."""
        with self._mu:
            self._probe_inflight = False
            self.consecutive_failures = 0
            if self.state != self.CLOSED:
                self.state = self.CLOSED
                return True
            return False

    def on_failure(self) -> bool:
        """Returns True when this failure OPENed a non-open breaker."""
        with self._mu:
            self._probe_inflight = False
            self.consecutive_failures += 1
            if self.state == self.HALF_OPEN or (
                    self.state == self.CLOSED
                    and self.consecutive_failures >= self.failure_threshold):
                self.state = self.OPEN
                self._opened_at = self._clock()
                return True
            return False


class WorkerHealthRegistry:
    """URL -> CircuitBreaker map + breaker-aware round-robin pick. Shared by
    every executor a factory makes, so health outlives individual jobs."""

    def __init__(self, policy: DcompactOptions | None = None,
                 clock=time.monotonic):
        self.policy = policy or DcompactOptions()
        self._clock = clock
        self._mu = ccy.Lock("resilience.WorkerHealthRegistry._mu")
        self._breakers: dict[str, CircuitBreaker] = {}
        self._rr = 0
        # Observers: callables (url, state, consecutive_failures) -> None,
        # fired on every state TRANSITION (open/close).
        self.observers: list = []
        self.skipped_open = 0  # picks that skipped >=1 open circuit

    def breaker(self, url: str) -> CircuitBreaker:
        with self._mu:
            b = self._breakers.get(url)
            if b is None:
                b = CircuitBreaker(self.policy.breaker_failure_threshold,
                                   self.policy.breaker_reset_timeout,
                                   self._clock)
                self._breakers[url] = b
            return b

    def _notify(self, url: str, b: CircuitBreaker) -> None:
        # observers must never take down job routing
        for obs in list(self.observers):
            with _errors.guard(listener=obs):
                obs(url, b.state, b.consecutive_failures)

    def pick(self, urls: list[str]) -> str | None:
        """Round-robin over `urls`, skipping URLs whose breaker refuses
        traffic. Returns None when every circuit is open (the caller then
        falls back to local WITHOUT paying a remote timeout)."""
        if not urls:
            return None
        with self._mu:
            start = self._rr
            self._rr += 1
        skipped = 0
        for i in range(len(urls)):
            url = urls[(start + i) % len(urls)]
            if self.breaker(url).allow():
                if skipped:
                    with self._mu:
                        self.skipped_open += skipped
                return url
            skipped += 1
        with self._mu:
            self.skipped_open += skipped
        return None

    def record_success(self, url: str) -> None:
        b = self.breaker(url)
        if b.on_success():
            self._notify(url, b)

    def record_failure(self, url: str) -> None:
        b = self.breaker(url)
        if b.on_failure():
            self._notify(url, b)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                url: {"state": b.state,
                      "consecutive_failures": b.consecutive_failures}
                for url, b in sorted(self._breakers.items())
            }


# ---------------------------------------------------------------------------
# Graceful degradation: pin jobs local after repeated remote failure
# ---------------------------------------------------------------------------


class LocalPinGate:
    """After `local_pin_failures` CONSECUTIVE remote job failures (a job
    counts as failed once every attempt is exhausted), route jobs straight
    to local for `local_pin_cooldown` seconds — a flaky fleet must not tax
    every job with the full retry ladder. Any remote success resets."""

    def __init__(self, policy: DcompactOptions, clock=time.monotonic):
        self.policy = policy
        self._clock = clock
        self._mu = ccy.Lock("resilience.LocalPinGate._mu")
        self._consecutive = 0
        self._pinned_until = 0.0
        self.pin_count = 0  # times the gate engaged (for introspection)

    def should_pin(self) -> bool:
        with self._mu:
            return self._clock() < self._pinned_until

    def note_job_success(self) -> None:
        with self._mu:
            self._consecutive = 0

    def note_job_failure(self) -> bool:
        """Returns True when THIS failure engaged the pin."""
        with self._mu:
            self._consecutive += 1
            if (self._consecutive >= max(1, self.policy.local_pin_failures)
                    and self._clock() >= self._pinned_until):
                self._pinned_until = (
                    self._clock() + self.policy.local_pin_cooldown)
                self._consecutive = 0
                self.pin_count += 1
                return True
            return False


# ---------------------------------------------------------------------------
# Job leases + orphan sweeping
# ---------------------------------------------------------------------------

HEARTBEAT_FILE = "heartbeat"
LEASE_FILE = "lease.json"


def write_lease(job_dir: str, job_id: int, attempt: int,
                lease_sec: float) -> None:
    """DB side: stamp the attempt dir with its lease terms before the
    worker starts, so ANY process (including a later DB open) can decide
    orphan-ness without out-of-band state."""
    import json

    try:
        with open(os.path.join(job_dir, LEASE_FILE), "w") as f:
            json.dump({"job_id": job_id, "attempt": attempt,
                       "pid": os.getpid(), "lease_sec": lease_sec,
                       "submitted_unix": time.time()}, f)
    except OSError:
        pass  # lease is advisory; the job itself still runs


class HeartbeatWriter:
    """Worker side: touch `job_dir/heartbeat` every ~lease/3 seconds while
    the job runs. A worker killed -9 stops heartbeating; the file's mtime
    then ages past the lease and the job dir becomes sweepable."""

    def __init__(self, job_dir: str, lease_sec: float):
        self._path = os.path.join(job_dir, HEARTBEAT_FILE)
        self._interval = max(0.2, float(lease_sec) / 3.0)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self) -> None:
        try:
            with open(self._path, "w") as f:
                f.write(f"{os.getpid()} {time.time():.3f}\n")
        except OSError:
            pass

    def start(self) -> "HeartbeatWriter":
        self.beat()
        self._thread = ccy.spawn("dcompact-heartbeat", self._loop,
                                 owner=self, stop=self.stop)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def _lease_expired(att_dir: str, lease_sec: float, now: float) -> bool:
    """An attempt dir is orphaned when its freshest liveness signal
    (heartbeat, else lease, else the dir itself) is older than the lease."""
    newest = None
    for name in (HEARTBEAT_FILE, LEASE_FILE, "params.json"):
        try:
            m = os.path.getmtime(os.path.join(att_dir, name))
        except OSError:
            continue
        newest = m if newest is None else max(newest, m)
    if newest is None:
        try:
            newest = os.path.getmtime(att_dir)
        except OSError:
            return True  # vanished under us: nothing to keep
    return (now - newest) > lease_sec


def sweep_orphan_jobs(job_root: str, lease_sec: float,
                      statistics=None, event_logger=None,
                      now: float | None = None) -> list[str]:
    """Scan `job_root/job-*` for attempt dirs whose lease expired (a
    `kill -9`'d worker leaves params + partial outputs + a stale
    heartbeat) and delete them. Runs on DB open; the compaction whose job
    died never installed, so its inputs are still live in the version and
    the picker simply re-runs it — sweeping is all the re-queue needed.
    Returns the swept job dirs."""
    now = time.time() if now is None else now
    swept: list[str] = []
    try:
        jobs = sorted(os.listdir(job_root))
    except OSError:
        return swept
    for job in jobs:
        if not job.startswith("job-"):
            continue
        job_dir = os.path.join(job_root, job)
        if not os.path.isdir(job_dir):
            continue
        atts = [a for a in sorted(os.listdir(job_dir))
                if a.startswith("att-")]
        live = False
        for att in atts:
            att_dir = os.path.join(job_dir, att)
            if not _lease_expired(att_dir, lease_sec, now):
                live = True
                continue
            shutil.rmtree(att_dir, ignore_errors=True)
            swept.append(att_dir)
            if event_logger is not None:
                event_logger.log("dcompact_orphan_swept", job_dir=att_dir)
        if not live:
            # Every attempt gone (or none existed): remove the skeleton.
            try:
                if not os.listdir(job_dir):
                    os.rmdir(job_dir)
            except OSError:
                pass
    if swept and statistics is not None:
        statistics.record_tick(stats_mod.DCOMPACTION_ORPHANS_SWEPT,
                               len(swept))
    return swept


# ---------------------------------------------------------------------------
# Deterministic fault injection for the transports
# ---------------------------------------------------------------------------


class DcompactFaultInjector:
    """env/fault_injection.py-style fault points for the dcompact
    transports, decided deterministically per (job, attempt) so chaos tests
    are reproducible. Plans:

      "drop"      the request never reaches a worker (raised before spawn)
      "delay"     the response is delayed `delay_sec` before the spawn runs
      "kill"      the worker dies hard mid-job (subprocess transport: the
                  child os._exit()s after writing heartbeats + partial
                  output, exactly a kill -9)
      "truncate"  results.json is cut to half its bytes after the worker
                  returns (a crash between write and rename)
      "corrupt"   results.json is overwritten with non-JSON garbage

    `schedule` maps attempt ordinal (0-based, global across jobs) or
    (job_id, attempt) to a plan; `rate` injects pseudo-randomly from `seed`
    with plan weights `plans`."""

    def __init__(self, schedule: dict | None = None, rate: float = 0.0,
                 plans: tuple = ("drop", "kill", "truncate"),
                 seed: int = 0, delay_sec: float = 0.05):
        self.schedule = dict(schedule or {})
        self.rate = rate
        self.plans = tuple(plans)
        self.delay_sec = delay_sec
        self._rng = random.Random(seed)
        self._mu = ccy.Lock("resilience.DcompactFaultInjector._mu")
        self._ordinal = 0
        self.injected: list[tuple[int, int, str]] = []  # (job, attempt, plan)

    def plan(self, job_id: int, attempt: int) -> str | None:
        with self._mu:
            ordinal = self._ordinal
            self._ordinal += 1
            p = self.schedule.get((job_id, attempt),
                                  self.schedule.get(ordinal))
            if p is None and self.rate > 0 and self.plans:
                if self._rng.random() < self.rate:
                    p = self.plans[self._rng.randrange(len(self.plans))]
            if p:
                self.injected.append((job_id, attempt, p))
            return p

    def injected_counts(self) -> dict:
        with self._mu:
            out: dict[str, int] = {}
            for _j, _a, p in self.injected:
                out[p] = out.get(p, 0) + 1
            return out

    # -- transport hooks -------------------------------------------------

    def before_spawn(self, plan: str | None) -> None:
        if plan == "drop":
            raise IOError_("injected: dcompact request dropped")
        if plan == "delay":
            time.sleep(self.delay_sec)

    def after_spawn(self, plan: str | None, job_dir: str) -> None:
        if plan not in ("truncate", "corrupt"):
            return
        rpath = os.path.join(job_dir, "results.json")
        try:
            if plan == "truncate":
                size = os.path.getsize(rpath)
                with open(rpath, "rb+") as f:
                    f.truncate(max(1, size // 2))
            else:
                with open(rpath, "wb") as f:
                    f.write(b"\x00garbage{{{not-json")
        except OSError:
            pass  # worker already failed: nothing to mangle


# ---------------------------------------------------------------------------
# The retry driver
# ---------------------------------------------------------------------------


def _notify_attempt(db, info) -> None:
    from toplingdb_tpu.utils.listener import notify

    notify(db.options.listeners, "on_dcompact_attempt", db, info)


def execute_resilient(db, factory, compaction, snapshots, alloc,
                      run_local, gate: LocalPinGate | None = None,
                      policy: DcompactOptions | None = None):
    """Run one compaction through `factory` with the full failure policy:
    per-attempt retry (exponential backoff + jitter), a per-job deadline,
    failed-attempt dir sweeping, circuit-breaker bookkeeping (when the
    factory exposes a health registry), graceful-degradation pinning, and
    DCOMPACTION_* stats + listener events for every decision. Falls back to
    `run_local` when allowed; re-raises the last remote error otherwise."""
    from toplingdb_tpu.utils.listener import DcompactAttemptInfo

    policy = policy or getattr(db.options, "dcompact", None) \
        or DcompactOptions()
    stats = db.options.statistics
    logger = getattr(db, "event_logger", None)
    health: WorkerHealthRegistry | None = getattr(factory, "health", None)

    def tick(name, n=1):
        if stats is not None:
            stats.record_tick(name, n)

    if health is not None and not getattr(factory, "_health_obs_wired",
                                          False):
        # Breaker transitions -> tickers + listener events. Wired once per
        # factory; a factory shared across DBs reports to the first.
        factory._health_obs_wired = True

        def _on_transition(url, state, consecutive_failures):
            tick(stats_mod.DCOMPACTION_BREAKER_OPEN
                 if state == CircuitBreaker.OPEN
                 else stats_mod.DCOMPACTION_BREAKER_CLOSE)
            if logger is not None:
                logger.log("dcompact_worker_health", url=url, state=state,
                           consecutive_failures=consecutive_failures)
            from toplingdb_tpu.utils.listener import (
                WorkerHealthInfo, notify,
            )

            notify(db.options.listeners, "on_worker_health_changed", db,
                   WorkerHealthInfo(
                       url=url, state=state,
                       consecutive_failures=consecutive_failures))

        health.observers.append(_on_transition)

    def fallback(reason: str, last_error):
        if not factory.allow_fallback_to_local():
            raise last_error
        tick(stats_mod.DCOMPACTION_FALLBACK_LOCAL)
        if logger is not None:
            logger.log("dcompact_fallback_local", reason=reason,
                       error=repr(last_error)[:300] if last_error else None)
        return run_local()

    if gate is not None and gate.should_pin():
        # Degraded mode: don't even try remote until the cooldown lapses.
        tick(stats_mod.DCOMPACTION_FALLBACK_PINNED)
        if not factory.allow_fallback_to_local():
            raise IOError_("dcompact pinned local but fallback disabled")
        if logger is not None:
            logger.log("dcompact_fallback_local", reason="pinned")
        return run_local()

    deadline = (time.monotonic() + policy.job_deadline
                if policy.job_deadline > 0 else None)
    max_attempts = max(1, policy.max_attempts)
    last_error: BaseException | None = None
    for attempt in range(max_attempts):
        if deadline is not None and time.monotonic() >= deadline:
            tick(stats_mod.DCOMPACTION_DEADLINE_EXCEEDED)
            if gate is not None:
                gate.note_job_failure()
            return fallback("deadline", last_error or IOError_(
                "dcompact job deadline exceeded before first attempt"))
        if attempt > 0:
            delay = policy.backoff_delay(attempt)
            if deadline is not None:
                delay = min(delay, max(0.0, deadline - time.monotonic()))
            if delay > 0:
                time.sleep(delay)
        executor = factory.new_executor(compaction)
        if executor is None:
            # Breaker-aware factories return None when every worker's
            # circuit is open: skip the remote timeout entirely.
            tick(stats_mod.DCOMPACTION_BREAKER_SKIPPED)
            if gate is not None:
                gate.note_job_failure()
            return fallback("all_circuits_open", last_error or IOError_(
                "every dcompact worker circuit is open"))
        executor.attempt = attempt
        url = getattr(executor, "url", "")
        t0 = time.monotonic()
        tick(stats_mod.DCOMPACTION_ATTEMPTS)
        try:
            outputs, cstats = executor.execute(db, compaction, snapshots,
                                               alloc)
        except Exception as e:
            last_error = e
            if health is not None and url:
                health.record_failure(url)
            elapsed = int((time.monotonic() - t0) * 1e6)
            if stats is not None:
                stats.record_in_histogram(
                    stats_mod.DCOMPACTION_ATTEMPT_MICROS, elapsed)
            will_retry = attempt + 1 < max_attempts
            _notify_attempt(db, DcompactAttemptInfo(
                db_name=db.dbname, job_id=getattr(executor, "_job_seq", 0),
                attempt=attempt, url=url, ok=False,
                error=repr(e)[:300], elapsed_micros=elapsed,
                will_retry=will_retry))
            if logger is not None:
                logger.log("dcompact_attempt_failed", attempt=attempt,
                           url=url, error=repr(e)[:300],
                           will_retry=will_retry)
            if will_retry:
                tick(stats_mod.DCOMPACTION_RETRIES)
                continue
            tick(stats_mod.DCOMPACTION_JOB_FAILURES)
            if gate is not None and gate.note_job_failure():
                tick(stats_mod.DCOMPACTION_LOCAL_PINS)
                if logger is not None:
                    logger.log("dcompact_pinned_local",
                               cooldown_sec=policy.local_pin_cooldown)
            return fallback("attempts_exhausted", e)
        elapsed = int((time.monotonic() - t0) * 1e6)
        if stats is not None:
            stats.record_in_histogram(
                stats_mod.DCOMPACTION_ATTEMPT_MICROS, elapsed)
        if health is not None and url:
            health.record_success(url)
        if gate is not None:
            gate.note_job_success()
        _notify_attempt(db, DcompactAttemptInfo(
            db_name=db.dbname, job_id=getattr(executor, "_job_seq", 0),
            attempt=attempt, url=url, ok=True, error=None,
            elapsed_micros=elapsed, will_retry=False))
        return outputs, cstats
    raise last_error  # unreachable: the loop returns or falls back
