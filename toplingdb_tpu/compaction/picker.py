"""Compaction picking: which files to merge next.

Leveled strategy mirrors the reference's score-driven picker
(db/compaction/compaction_picker_level.cc in /root/reference): L0 scores by
file count against the trigger, L1+ by level bytes against the target; the
highest-scoring level compacts into level+1, expanding inputs to all
overlapping files. Universal and FIFO pickers cover the other two styles
(reference compaction_picker_universal.cc, compaction_picker_fifo.cc).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.version_edit import FileMetaData
from toplingdb_tpu.db.version_set import Version


def _busy(f) -> bool:
    """A file the picker must not touch: already in a running job, or
    quarantined by the IntegrityScrubber (db/integrity.py) — corrupt
    bytes must never be merged into new SSTs."""
    return f.being_compacted or f.quarantined


@dataclass
class Compaction:
    """A picked compaction: inputs at `level` (+ overlapping at output_level),
    producing files at output_level (reference db/compaction/compaction.h)."""

    level: int
    output_level: int
    inputs: list[FileMetaData]          # files at `level`
    output_level_inputs: list[FileMetaData] = field(default_factory=list)
    bottommost: bool = False
    reason: str = ""
    max_output_file_size: int = 8 * 1024 * 1024
    cf_id: int = 0
    # User-defined-timestamp history trim point (reference
    # full_history_ts_low / increase_full_history_ts_low): among versions
    # with ts < this, only the newest survives compaction. 0 = keep all.
    full_history_ts_low: int = 0

    def all_inputs(self) -> list[tuple[int, FileMetaData]]:
        return [(self.level, f) for f in self.inputs] + [
            (self.output_level, f) for f in self.output_level_inputs
        ]

    def total_input_bytes(self) -> int:
        return sum(f.file_size for _, f in self.all_inputs())

    def num_input_files(self) -> int:
        return len(self.inputs) + len(self.output_level_inputs)


class CompactionPicker:
    def __init__(self, options, icmp):
        self.options = options
        self.icmp = icmp

    def compaction_score(self, version: Version) -> list[tuple[float, int]]:
        raise NotImplementedError

    def pick_compaction(self, version: Version) -> Compaction | None:
        raise NotImplementedError

    # -- shared helpers -------------------------------------------------

    def _key_range(self, files) -> tuple[bytes, bytes]:
        smallest = min((f.smallest for f in files), key=self.icmp.sort_key)
        largest = max((f.largest for f in files), key=self.icmp.sort_key)
        return smallest, largest

    def _expand_range_to_level(self, version: Version, level: int,
                               smallest: bytes, largest: bytes) -> list[FileMetaData]:
        """All files at `level` overlapping [smallest, largest] (internal
        keys) — INCLUDING being_compacted ones, so callers can detect a
        conflict with a running job and abort the pick (silently omitting
        them would produce overlapping outputs)."""
        su = dbformat.extract_user_key(smallest)
        lu = dbformat.extract_user_key(largest)
        return version.overlapping_files(level, su, lu)

    def _is_bottommost(self, version: Version, output_level: int,
                       smallest: bytes, largest: bytes) -> bool:
        ucmp = self.icmp.user_comparator
        su = dbformat.extract_user_key(smallest)
        lu = dbformat.extract_user_key(largest)
        for lvl in range(output_level + 1, version.num_levels):
            if version.overlapping_files(lvl, su, lu):
                return False
        return True



class LeveledCompactionPicker(CompactionPicker):
    def compaction_score(self, version: Version) -> list[tuple[float, int]]:
        """(score, level) sorted descending; score >= 1.0 needs compaction
        (reference VersionStorageInfo::ComputeCompactionScore)."""
        scores = []
        l0 = [f for f in version.files[0] if not _busy(f)]
        l0_score = len(l0) / self.options.level0_file_num_compaction_trigger
        if any(f.marked_for_compaction for f in l0):
            l0_score = max(l0_score, 1.0)
        scores.append((l0_score, 0))
        last = version.num_levels - 1
        if any(f.marked_for_compaction and not _busy(f)
               for f in version.files[last]):
            # Bottommost marked files are rewritten in place (reference
            # bottommost_files_marked_for_compaction_).
            scores.append((1.0, last))
        for level in range(1, version.num_levels - 1):
            total = sum(
                f.file_size for f in version.files[level] if not _busy(f)
            )
            score = total / self.options.max_bytes_for_level(level)
            if any(f.marked_for_compaction and not _busy(f)
                   for f in version.files[level]):
                # Collector-flagged files (reference
                # files_marked_for_compaction_) force the level eligible.
                score = max(score, 1.0)
            scores.append((score, level))
        scores.sort(key=lambda s: -s[0])
        return scores

    def pick_compaction(self, version: Version) -> Compaction | None:
        for score, level in self.compaction_score(version):
            if score < 1.0:
                break
            c = self._pick_level(version, level)
            if c is not None:
                return c
        return None

    # Reference kMinFilesForIntraL0Compaction.
    _INTRA_L0_MIN_FILES = 4

    def _try_intra_l0(self, version: Version) -> Compaction | None:
        """L0→L0 merge of the newest CONTIGUOUS run of free files
        (reference TryPickIntraL0Compaction, compaction_picker.cc): L0
        files hold disjoint seqno intervals in newest-first order, so a
        contiguous prefix merges into one file that slots back at its
        position; non-contiguous picks could interleave seqnos."""
        run = []
        total = 0
        cap = self.options.max_compaction_bytes or (1 << 62)
        for f in version.files[0]:  # newest-first
            if _busy(f):
                break
            if total + f.file_size > cap and run:
                break
            run.append(f)
            total += f.file_size
        if len(run) < self._INTRA_L0_MIN_FILES:
            return None
        return Compaction(
            level=0, output_level=0, inputs=run, output_level_inputs=[],
            bottommost=False, reason="intra-L0",
            max_output_file_size=1 << 62,  # one output file
        )

    def _pick_level(self, version: Version, level: int) -> Compaction | None:
        if level == version.num_levels - 1:
            # In-place rewrite of a collector-marked bottommost file.
            marked = [f for f in version.files[level]
                      if f.marked_for_compaction and not _busy(f)]
            if not marked:
                return None
            f0 = marked[0]
            return Compaction(
                level=level, output_level=level, inputs=[f0],
                output_level_inputs=[], bottommost=True,
                reason="bottommost marked",
                max_output_file_size=self.options.target_file_size(level),
            )
        if level == 0:
            inputs = [f for f in version.files[0] if not _busy(f)]
            if (len(inputs) < self.options.level0_file_num_compaction_trigger
                    and not any(f.marked_for_compaction for f in inputs)):
                return None
            if not inputs or any(_busy(f) for f in version.files[0]):
                # L0→L1 must take all L0 files; while some are busy,
                # compact the free newest prefix L0→L0 instead
                # (reference TryPickIntraL0Compaction) so read-amp and
                # the L0 stall triggers keep falling.
                return self._try_intra_l0(version)
            output_level = 1
        else:
            # Pick the largest not-being-compacted file (simple heuristic;
            # the reference uses kByCompensatedSize by default).
            candidates = [f for f in version.files[level] if not _busy(f)]
            if not candidates:
                return None
            marked = [f for f in candidates if f.marked_for_compaction]
            inputs = [max(marked or candidates, key=lambda f: f.file_size)]
            output_level = level + 1
        if output_level >= version.num_levels:
            return None
        smallest, largest = self._key_range(inputs)
        if level > 0:
            # Expand inputs at the same level to cover the user-key range
            # fully; abort on conflict with a running job.
            more = self._expand_range_to_level(version, level, smallest, largest)
            if any(_busy(f) for f in more):
                return None
            merged = {f.number: f for f in inputs + more}
            inputs = sorted(merged.values(), key=lambda f: f.number)
            smallest, largest = self._key_range(inputs)
        outputs = self._expand_range_to_level(version, output_level, smallest, largest)
        if any(_busy(f) for f in outputs):
            return self._try_intra_l0(version) if level == 0 else None
        all_small, all_large = self._key_range(inputs + outputs) if outputs else (smallest, largest)
        return Compaction(
            level=level,
            output_level=output_level,
            inputs=inputs,
            output_level_inputs=outputs,
            bottommost=self._is_bottommost(version, output_level, all_small, all_large),
            reason=f"L{level} score",
            max_output_file_size=self.options.target_file_size(output_level),
        )


class UniversalCompactionPicker(CompactionPicker):
    """Size-tiered universal compaction over L0-resident sorted runs
    (reference compaction_picker_universal.cc). Runs live in L0 (newest
    first) plus at most one full-keyspace run in the last level."""

    def compaction_score(self, version: Version) -> list[tuple[float, int]]:
        n = len(version.files[0])
        return [(n / max(1, self.options.level0_file_num_compaction_trigger), 0)]

    def pick_compaction(self, version: Version) -> Compaction | None:
        runs = [f for f in version.files[0] if not _busy(f)]
        if len(runs) < self.options.level0_file_num_compaction_trigger:
            return None
        if any(_busy(f) for f in version.files[0]):
            return None
        opts = self.options
        # 1. Size-amplification trigger: total/newest vs percent.
        last_level = version.num_levels - 1
        base = version.files[last_level]
        younger_bytes = sum(f.file_size for f in runs)
        base_bytes = sum(f.file_size for f in base)
        if base and not any(_busy(f) for f in base):
            if base_bytes > 0 and younger_bytes * 100 >= (
                opts.universal_max_size_amplification_percent * base_bytes
            ):
                smallest, largest = self._key_range(runs + base)
                return Compaction(
                    level=0, output_level=last_level, inputs=runs,
                    output_level_inputs=list(base), bottommost=True,
                    reason="universal size-amp",
                    max_output_file_size=2**62,
                )
        # 2. Size-ratio trigger: merge a prefix of similar-sized runs
        # (newest first; runs sorted newest→oldest already).
        picked = [runs[-1]]
        total = runs[-1].file_size
        for f in reversed(runs[:-1]):
            if total * (100 + opts.universal_size_ratio) >= f.file_size * 100:
                picked.append(f)
                total += f.file_size
            else:
                break
        if len(picked) >= opts.universal_min_merge_width:
            picked = picked[: opts.universal_max_merge_width]
            picked_set = {f.number for f in picked}
            inputs = [f for f in version.files[0] if f.number in picked_set]
            bottom = self._is_bottommost(
                version, 0, *self._key_range(inputs)
            ) and len(inputs) == len(version.files[0])
            return Compaction(
                level=0, output_level=0, inputs=inputs,
                bottommost=bottom, reason="universal size-ratio",
                max_output_file_size=2**62,
            )
        # 3. Fall back: merge all runs into the last level.
        if base and any(_busy(f) for f in base):
            return None
        smallest, largest = self._key_range(runs + list(base)) if base else self._key_range(runs)
        return Compaction(
            level=0, output_level=last_level, inputs=runs,
            output_level_inputs=list(base), bottommost=True,
            reason="universal merge-all", max_output_file_size=2**62,
        )


class FIFOCompactionPicker(CompactionPicker):
    """Drop oldest files when total size exceeds the budget, or when older
    than fifo_ttl_seconds (reference compaction_picker_fifo.cc incl.
    CompactionOptionsFIFO.ttl). Deletion-only: output nothing.
    `creation_time_fn` (set by the scheduler) reads a file's creation time
    from its cached table properties."""

    creation_time_fn = None  # f -> unix time | None

    def compaction_score(self, version: Version) -> list[tuple[float, int]]:
        total = sum(f.file_size for f in version.files[0])
        score = total / max(1, self.options.fifo_max_table_files_size)
        if self._ttl_expired(version):
            score = max(score, 1.0)
        return [(score, 0)]

    def _ttl_expired(self, version: Version) -> list:
        ttl = self.options.fifo_ttl_seconds
        if not ttl or self.creation_time_fn is None:
            return []
        import time as _t

        cutoff = int(_t.time()) - ttl
        out = []
        for f in version.files[0]:
            if _busy(f):
                continue
            ct = self.creation_time_fn(f)
            if ct and ct <= cutoff:
                out.append(f)
        return out

    def pick_compaction(self, version: Version) -> Compaction | None:
        expired = self._ttl_expired(version)
        if expired:
            return Compaction(
                level=0, output_level=0, inputs=expired, reason="fifo ttl",
            )
        total = sum(f.file_size for f in version.files[0])
        if total <= self.options.fifo_max_table_files_size:
            return None
        # files[0] is newest-first; drop from the tail (oldest).
        drop = []
        for f in reversed(version.files[0]):
            if _busy(f):
                break
            drop.append(f)
            total -= f.file_size
            if total <= self.options.fifo_max_table_files_size:
                break
        if not drop:
            return None
        return Compaction(
            level=0, output_level=0, inputs=drop, reason="fifo ttl/size",
        )


def create_picker(options, icmp) -> CompactionPicker:
    style = options.compaction_style
    if style == "leveled":
        return LeveledCompactionPicker(options, icmp)
    if style == "universal":
        return UniversalCompactionPicker(options, icmp)
    if style == "fifo":
        return FIFOCompactionPicker(options, icmp)
    from toplingdb_tpu.utils.status import InvalidArgument

    raise InvalidArgument(f"unknown compaction style {style!r}")
