"""The distributed-compaction executor boundary.

The serializable seam of the framework, modeled on the reference's
CompactionExecutor plugin API (db/compaction/compaction_executor.h:160-178 in
/root/reference):

  CompactionExecutorFactory.should_run_local / allow_fallback_to_local /
  new_executor — decide routing per job;
  CompactionExecutor.execute(db, compaction, snapshots, alloc) — run the data
  plane somewhere else and return (outputs, stats).

Three executors:
  DeviceCompactionExecutor      in-process JAX data plane (device=tpu|cpu) —
                                the TPU analogue of a same-host dcompact
                                worker with HBM DMA instead of NFS.
  SubprocessCompactionExecutor  full process boundary: CompactionParams
                                serialized to a job dir, a worker process
                                (toplingdb_tpu.compaction.worker) executes
                                and writes CompactionResults; outputs are
                                renamed into the DB dir (reference
                                CompactionJob::RunRemote,
                                compaction_job.cc:921-1152).
  (cluster fan-out over a TPU pod lives in toplingdb_tpu/parallel.)
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import subprocess
import sys
import time

from toplingdb_tpu.compaction.compaction_job import CompactionStats
from toplingdb_tpu.compaction.picker import Compaction
from toplingdb_tpu.db import filename
from toplingdb_tpu.utils.table_properties_collector import (
    serialize_collector_factory,
)
from toplingdb_tpu.db.version_edit import FileMetaData
from toplingdb_tpu.utils.status import Corruption, IOError_


def _telemetry():
    from toplingdb_tpu.utils import telemetry

    return telemetry


def _store_spec_of(env) -> str | None:
    """Serializable store spec a worker process can reopen: the HTTP URL
    of a StoreClient or the root path of a LocalObjectStore. None when
    the env has no store or its backend has no process-portable name."""
    store = getattr(env, "store", None)
    if store is None:
        return None
    url = getattr(store, "url", None)
    if isinstance(url, str) and url:
        return url
    root = getattr(store, "root", None)
    return root if isinstance(root, str) and root else None


class CompactionExecutor:
    def execute(self, db, compaction: Compaction, snapshots: list[int],
                new_file_number) -> tuple[list[FileMetaData], CompactionStats]:
        raise NotImplementedError

    def clean_files(self) -> None:
        pass


class CompactionExecutorFactory:
    """Reference CompactionExecutorFactory (compaction_executor.h:170-178)."""

    def should_run_local(self, compaction: Compaction) -> bool:
        return False

    def allow_fallback_to_local(self) -> bool:
        return True

    def new_executor(self, compaction: Compaction) -> CompactionExecutor:
        raise NotImplementedError

    def job_url(self, job_id: int, attempt: int) -> str:
        return ""


# ---------------------------------------------------------------------------
# In-process device executor
# ---------------------------------------------------------------------------


class DeviceCompactionExecutor(CompactionExecutor):
    def __init__(self, device: str = "tpu"):
        self.device = device

    def execute(self, db, compaction, snapshots, new_file_number):
        from toplingdb_tpu.db.blob import maybe_new_blob_gc
        from toplingdb_tpu.ops.device_compaction import run_device_compaction

        return run_device_compaction(
            db.env, db.dbname, db.icmp, compaction, db.table_cache,
            db.options.table_options_for_level(
                compaction.output_level, compaction.bottommost),
            snapshots,
            merge_operator=db.options.merge_operator,
            compaction_filter=db.options.compaction_filter,
            new_file_number=new_file_number,
            device_name=self.device,
            blob_resolver=db.blob_source.get,
            blob_gc=maybe_new_blob_gc(db, compaction, new_file_number),
            column_family=(compaction.cf_id, db.cf_name(compaction.cf_id)),
        )


class DeviceCompactionExecutorFactory(CompactionExecutorFactory):
    """Route compactions at/below `min_input_bytes` to the local CPU path and
    the rest to the device data plane (small jobs aren't worth the transfer —
    the same policy ShouldRunLocal expresses in the reference)."""

    def __init__(self, device: str = "tpu", min_input_bytes: int = 0,
                 allow_fallback: bool = True):
        self.device = device
        self.min_input_bytes = min_input_bytes
        self._allow_fallback = allow_fallback

    def should_run_local(self, compaction: Compaction) -> bool:
        return compaction.total_input_bytes() < self.min_input_bytes

    def allow_fallback_to_local(self) -> bool:
        return self._allow_fallback

    def new_executor(self, compaction: Compaction) -> CompactionExecutor:
        return DeviceCompactionExecutor(self.device)


# ---------------------------------------------------------------------------
# Serialized job boundary (dcompact analogue)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CompactionParams:
    """Everything a worker needs to run one compaction job — the analogue of
    the reference's CompactionParams (compaction_executor.h:33-118). Plugin
    objects travel as registry names (ObjectRpcParam.clazz analogue)."""

    job_id: int
    attempt: int
    dbname: str                      # source DB dir (shared storage)
    output_dir: str                  # where the worker writes SSTs
    input_files: list[str]           # absolute SST paths
    output_level: int
    bottommost: bool
    max_output_file_size: int
    snapshots: list[int]
    comparator: str                  # registry name
    merge_operator: str | None       # registry name
    compaction_filter: str | None    # registry name
    compression: int
    block_size: int
    creation_time: int
    table_format: str = "block"
    # SliceTransform serialized name (utils/slice_transform.py) or None —
    # required when table_format == 'plain' (prefix hash index) and feeds
    # prefix blooms for the other formats.
    prefix_extractor: str | None = None
    # Job-lease duration: the worker heartbeats job_dir/heartbeat at
    # ~lease_sec/3; a heartbeat older than lease_sec marks the job
    # orphaned (compaction/resilience.py). 0 disables heartbeating.
    lease_sec: float = 30.0
    smallest_seqno_guard: int = 0
    device: str = "cpu"
    cf_id: int = 0
    cf_name: str = "default"
    collectors: list = dataclasses.field(default_factory=list)
    # Propagated trace context (utils/telemetry.py inject()): the worker
    # adopts it, records its spans locally, and returns them in
    # results.json so the DB stitches one end-to-end trace. None = the
    # submitting op was untraced.
    trace: dict | None = None
    # Disaggregated-storage mode (toplingdb_tpu/storage/): when set, the
    # worker resolves inputs by content address from the shared store
    # (input_addrs pairs with input_files) and publishes outputs back —
    # ZERO SST bytes cross the job transport. None = classic path mode.
    store_spec: str | None = None
    input_addrs: list | None = None
    checksum_func: str | None = None  # output stamping func in store mode

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=1)

    @staticmethod
    def from_json(s: str) -> "CompactionParams":
        return CompactionParams(**json.loads(s))


@dataclasses.dataclass
class CompactionResults:
    """Worker → DB results (reference CompactionResults,
    compaction_executor.h:120-158)."""

    status: str                      # "ok" | error text
    output_files: list[dict]         # serialized FileMetaData (paths relative)
    stats: dict
    curl_time_usec: int = 0          # kept for parity with reference fields
    work_time_usec: int = 0
    # Worker-side finished span dicts (telemetry plane): the DB side
    # attaches them to the originating trace (attach_remote).
    spans: list = dataclasses.field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=1)

    @staticmethod
    def from_json(s: str) -> "CompactionResults":
        return CompactionResults(**json.loads(s))


def encode_file_meta(meta: FileMetaData, path: str) -> dict:
    return {
        "path": path,
        "file_size": meta.file_size,
        "smallest": meta.smallest.hex(),
        "largest": meta.largest.hex(),
        "smallest_seqno": meta.smallest_seqno,
        "largest_seqno": meta.largest_seqno,
        "num_entries": meta.num_entries,
        "num_deletions": meta.num_deletions,
        "num_range_deletions": meta.num_range_deletions,
        "blob_refs": list(meta.blob_refs),
        "marked_for_compaction": meta.marked_for_compaction,
    }


def decode_file_meta(d: dict, number: int) -> FileMetaData:
    return FileMetaData(
        number=number,
        file_size=d["file_size"],
        smallest=bytes.fromhex(d["smallest"]),
        largest=bytes.fromhex(d["largest"]),
        smallest_seqno=d["smallest_seqno"],
        largest_seqno=d["largest_seqno"],
        num_entries=d["num_entries"],
        num_deletions=d["num_deletions"],
        num_range_deletions=d["num_range_deletions"],
        blob_refs=list(d.get("blob_refs", [])),
        marked_for_compaction=d.get("marked_for_compaction", False),
        # Store-mode outputs arrive pre-stamped (the worker checksummed
        # them for their content address) — the install path's
        # stamp_file_checksum sees the digest and skips the re-read.
        file_checksum=bytes.fromhex(d["file_checksum"])
        if d.get("file_checksum") else b"",
        file_checksum_func_name=d.get("file_checksum_func_name", ""),
    )


_job_counter = itertools.count(1)


class SubprocessCompactionExecutor(CompactionExecutor):
    """Ship the job to a worker process through a shared job dir — the
    transport shape of dcompact (HTTP+NFS in the reference; a local spawn +
    shared filesystem here; the RPC hop is pluggable via `spawn`)."""

    def __init__(self, device: str = "cpu", job_root: str | None = None,
                 spawn=None, policy=None, fault_injector=None):
        self.device = device
        self.job_root = job_root
        self._local_spawn = spawn is None
        self.spawn = spawn or self._spawn_local
        self._job_seq = 0
        # Set by the retry driver (compaction/resilience.py) before each
        # execute(); attempt N gets its own att-NN dir so a failed
        # attempt's partial outputs never collide with the retry's.
        self.attempt = 0
        self.policy = policy          # DcompactOptions or None (defaults)
        self.fault_injector = fault_injector
        self.url = ""                 # transport identity (HTTP sets it)
        self._plan = None             # active injected-fault plan

    def _spawn_local(self, job_dir: str, device: str) -> None:
        env = dict(os.environ)
        if device == "cpu":
            env.setdefault("JAX_PLATFORMS", "cpu")
        if self._plan == "kill":
            # The worker crashes hard mid-job (os._exit after heartbeats +
            # partial output) — deterministically a kill -9.
            env["TPULSM_TEST_WORKER_CRASH"] = "mid_job"
        timeout = (self.policy.attempt_timeout
                   if self.policy is not None else 3600.0)
        r = subprocess.run(
            [sys.executable, "-m", "toplingdb_tpu.compaction.worker",
             "--job-dir", job_dir],
            capture_output=True, env=env, timeout=timeout,
        )
        if r.returncode != 0:
            raise IOError_(
                f"compaction worker failed rc={r.returncode}: "
                f"{r.stderr.decode(errors='replace')[-2000:]}"
            )

    def execute(self, db, compaction, snapshots, new_file_number):
        # Job ids come from a PROCESS-WIDE counter: the factory builds one
        # executor per compaction, and concurrent jobs with per-executor
        # counters collided on the same job dir (each deleting the
        # other's params/results mid-flight).
        self._job_seq = next(_job_counter)
        job_root = self.job_root or os.path.join(db.dbname, "dcompact")
        job_dir = os.path.join(
            job_root, f"job-{self._job_seq:05d}", f"att-{self.attempt:02d}"
        )
        os.makedirs(os.path.join(job_dir, "out"), exist_ok=True)
        try:
            return self._execute_in(db, compaction, snapshots,
                                    new_file_number, job_dir)
        except BaseException:
            # Sweep THIS attempt's partial state (params, lease, partial
            # outputs) so a retry or the on-open orphan sweep never sees
            # half-written SSTs as live job state.
            import shutil as _sh

            _sh.rmtree(job_dir, ignore_errors=True)
            self._rmdir_if_empty(os.path.dirname(job_dir))
            raise

    def _execute_in(self, db, compaction, snapshots, new_file_number,
                    job_dir):
        opts = db.options
        if opts.compaction_filter is not None:
            # Unregistered filters can't travel the serialized boundary;
            # raising here triggers fallback-to-local in the scheduler.
            from toplingdb_tpu.utils.compaction_filter import (
                create_compaction_filter,
            )

            create_compaction_filter(opts.compaction_filter.name())
        policy = self.policy
        if policy is None:
            policy = getattr(db.options, "dcompact", None)
        lease_sec = policy.lease_sec if policy is not None else 30.0
        # Store mode: when the DB runs on a SharedSstEnv and EVERY input
        # carries a checksum address, the worker pulls inputs from the
        # store and publishes outputs back — the job dir ships only
        # metadata. One unstamped input (pre-upgrade file) falls back to
        # path mode for the whole job.
        store_spec, input_addrs = None, None
        if hasattr(db.env, "publish_sst"):
            from toplingdb_tpu.storage.object_store import address_of_meta

            addrs = [address_of_meta(f) for _, f in compaction.all_inputs()]
            spec = _store_spec_of(db.env)
            if spec is not None and all(a is not None for a in addrs):
                store_spec, input_addrs = spec, addrs
        params = CompactionParams(
            job_id=self._job_seq,
            attempt=self.attempt,
            dbname=db.dbname,
            output_dir=os.path.join(job_dir, "out"),
            input_files=[
                filename.table_file_name(db.dbname, f.number)
                for _, f in compaction.all_inputs()
            ],
            output_level=compaction.output_level,
            bottommost=compaction.bottommost,
            max_output_file_size=compaction.max_output_file_size,
            snapshots=list(snapshots),
            comparator=opts.comparator.name(),
            merge_operator=(
                opts.merge_operator.name() if opts.merge_operator else None
            ),
            compaction_filter=(
                opts.compaction_filter.name() if opts.compaction_filter else None
            ),
            compression=opts.compression_for_level(
                compaction.output_level, compaction.bottommost),
            block_size=opts.table_options.block_size,
            creation_time=int(time.time()),
            device=self.device,
            table_format=getattr(opts.table_options, "format", "block"),
            prefix_extractor=(
                opts.table_options.prefix_extractor.name()
                if getattr(opts.table_options, "prefix_extractor", None)
                else None
            ),
            cf_id=compaction.cf_id,
            cf_name=db.cf_name(compaction.cf_id),
            collectors=[
                serialize_collector_factory(f)
                for f in opts.table_options.properties_collector_factories
            ],
            lease_sec=lease_sec,
            trace=_telemetry().inject(),
            store_spec=store_spec,
            input_addrs=input_addrs,
            checksum_func=(opts.file_checksum or "crc32c")
            if store_spec else None,
        )
        with open(os.path.join(job_dir, "params.json"), "w") as f:
            f.write(params.to_json())
        from toplingdb_tpu.compaction.resilience import write_lease

        write_lease(job_dir, self._job_seq, self.attempt, lease_sec)
        inj = self.fault_injector
        self._plan = inj.plan(self._job_seq, self.attempt) if inj else None
        t0 = time.time()
        if inj is not None:
            inj.before_spawn(self._plan)
        if self._plan == "kill" and not self._local_spawn:
            # Non-subprocess transports can't kill a real worker process;
            # simulate the observable state of one: heartbeats + a partial
            # output exist, then the connection dies.
            with open(os.path.join(job_dir, "out", "partial.sst"), "wb") as f:
                f.write(b"\x00" * 64)
            raise IOError_("injected: worker killed mid-job")
        self.spawn(job_dir, self.device)
        if inj is not None:
            inj.after_spawn(self._plan, job_dir)
        rpc_usec = int((time.time() - t0) * 1e6)
        try:
            with open(os.path.join(job_dir, "results.json")) as f:
                results = CompactionResults.from_json(f.read())
        except (OSError, ValueError, TypeError) as e:
            # Missing/truncated/garbage results.json: a worker crash
            # between compute and a complete write — a transport failure,
            # not DB corruption.
            raise IOError_(f"dcompact results unreadable: {e!r}") from e
        if results.status != "ok":
            raise IOError_(f"worker error: {results.status}")
        if results.spans:
            # Stitch the worker's spans into the compaction trace active
            # on this thread (no-op when the job ran untraced).
            _telemetry().attach_current(results.spans)
        # Rename outputs into the DB dir under fresh file numbers
        # (reference RunRemote rename loop, compaction_job.cc:1019-1073).
        # Store-mode outputs ADOPT instead: the bytes live in the shared
        # store under their content address; only the reference lands here.
        outputs = []
        shipped = 0
        for d in results.output_files:
            num = new_file_number()
            dst = filename.table_file_name(db.dbname, num)
            addr = d.get("store_addr")
            if addr and hasattr(db.env, "adopt"):
                db.env.adopt(dst, addr)
                try:
                    db.env.store.unpin(addr)  # ref now shields it from GC
                except Exception as e:  # noqa: BLE001
                    from toplingdb_tpu.utils import errors as _errors

                    _errors.swallow(reason="dcompact-adopt-unpin", exc=e)
            else:
                os.replace(os.path.join(params.output_dir, d["path"]), dst)
                shipped += int(d["file_size"])
            outputs.append(decode_file_meta(d, num))
        stats = CompactionStats(**results.stats)
        stats.sst_bytes_shipped = shipped
        stats.device = self.device
        stats.remote = True
        stats.work_time_usec = results.work_time_usec
        # Transport time, the analogue of the reference's curl_time_usec.
        stats.rpc_time_usec = rpc_usec - results.work_time_usec
        self._cleanup(job_dir)
        return outputs, stats

    @staticmethod
    def _rmdir_if_empty(path: str) -> None:
        try:
            if os.path.isdir(path) and not os.listdir(path):
                os.rmdir(path)
        except OSError:
            pass

    @classmethod
    def _cleanup(cls, job_dir: str) -> None:
        """Remove the whole attempt dir (outputs were renamed into the DB
        dir) and the job skeleton if this was its last attempt — a
        successful job leaves NO residue for the on-open orphan sweep."""
        import shutil as _sh

        _sh.rmtree(job_dir, ignore_errors=True)
        cls._rmdir_if_empty(os.path.dirname(job_dir))


class SubprocessCompactionExecutorFactory(CompactionExecutorFactory):
    def __init__(self, device: str = "cpu", allow_fallback: bool = True,
                 min_input_bytes: int = 0, job_root: str | None = None,
                 policy=None, fault_injector=None):
        self.device = device
        self._allow_fallback = allow_fallback
        self.min_input_bytes = min_input_bytes
        self.job_root = job_root
        self.policy = policy                  # DcompactOptions or None
        self.fault_injector = fault_injector  # DcompactFaultInjector

    def should_run_local(self, compaction: Compaction) -> bool:
        return compaction.total_input_bytes() < self.min_input_bytes

    def allow_fallback_to_local(self) -> bool:
        return self._allow_fallback

    def new_executor(self, compaction: Compaction) -> CompactionExecutor:
        return SubprocessCompactionExecutor(
            self.device, self.job_root, policy=self.policy,
            fault_injector=self.fault_injector)

    def job_url(self, job_id: int, attempt: int) -> str:
        return f"file://{self.job_root or 'dcompact'}/job-{job_id:05d}/att-{attempt:02d}"
