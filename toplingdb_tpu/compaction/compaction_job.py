"""CompactionJob: execute one picked compaction.

Mirrors the reference's CompactionJob::RunLocal →
ProcessKeyValueCompaction (db/compaction/compaction_job.cc:659,1390 in
/root/reference). The job is split into three shared stages so the CPU path
and the TPU/device path (toplingdb_tpu/ops/device_compaction.py) produce
byte-identical outputs:

  collect_inputs()              open input files, gather range tombstones
  CompactionIterator / device   the data plane (survivor stream)
  build_outputs()               output-file cutting + table building
"""

from __future__ import annotations

import bisect
import time
from dataclasses import dataclass

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.blob import decode_blob_index
from toplingdb_tpu.db.level_iterator import LevelIterator
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone, fragment_tombstones
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.compaction.picker import Compaction
from toplingdb_tpu.table.factory import new_table_builder
from toplingdb_tpu.table.merging_iterator import MergingIterator


@dataclass
class CompactionStats:
    """Per-job stats (reference CompactionJobStats / CompactionResults
    timing fields, compaction_executor.h:120-158)."""

    input_records: int = 0
    output_records: int = 0
    input_bytes: int = 0
    output_bytes: int = 0
    output_files: int = 0
    dropped_obsolete: int = 0
    dropped_tombstone: int = 0
    merged_records: int = 0
    work_time_usec: int = 0
    rpc_time_usec: int = 0   # transport time for remote jobs (curl analogue)
    device: str = "cpu"


def collect_inputs(compaction: Compaction, table_cache, icmp):
    """Open all input files; returns (children_iterators, range_del_agg)
    (reference VersionSet::MakeInputIterator, compaction_job.cc:1470)."""
    children = []
    rd = RangeDelAggregator(icmp.user_comparator)

    def add_tombs(f):
        r = table_cache.get_reader(f.number)
        for b, e in r.range_del_entries():
            rd.add(RangeTombstone.from_table_entry(b, e))
        return r

    if compaction.level == 0:
        for f in compaction.inputs:
            r = add_tombs(f)
            children.append(r.new_iterator())
    else:
        files = sorted(compaction.inputs, key=lambda f: icmp.sort_key(f.smallest))
        children.append(LevelIterator(table_cache, files, icmp))
        for f in files:
            add_tombs(f)
    if compaction.output_level_inputs:
        files = sorted(
            compaction.output_level_inputs, key=lambda f: icmp.sort_key(f.smallest)
        )
        children.append(LevelIterator(table_cache, files, icmp))
        for f in files:
            add_tombs(f)
    return children, rd


def surviving_tombstone_fragments(rd: RangeDelAggregator, snapshots: list[int],
                                  bottommost: bool, ucmp):
    """Tombstones that must be written to outputs. At the bottommost level a
    fragment is droppable only in snapshot stripe 0 (same rule as point
    DELETIONs); newer-than-a-snapshot tombstones must be kept or they would
    resurrect older kept entries."""
    if rd.empty():
        return []
    snaps = sorted(snapshots)
    frags = fragment_tombstones(rd.tombstones(), ucmp)
    if bottommost:
        return [f for f in frags if bisect.bisect_left(snaps, f.seq) > 0]
    return frags


def build_outputs(env, dbname: str, icmp, compaction: Compaction,
                  entries_iter, surviving_tombstones, new_file_number,
                  table_options, stats: CompactionStats,
                  creation_time: int,
                  column_family: tuple[int, str] = (0, "default"),
                  ) -> list[FileMetaData]:
    """Cut the survivor stream into output tables (reference
    CompactionOutputs / SubcompactionState::AddToOutput)."""
    outputs: list[FileMetaData] = []
    builder = None
    wfile = None
    fnum = None
    blob_refs: set[int] = set()

    def open_output():
        nonlocal builder, wfile, fnum
        fnum = new_file_number()
        wfile = env.new_writable_file(filename.table_file_name(dbname, fnum))
        builder = new_table_builder(wfile, icmp, table_options,
                                    creation_time=creation_time,
                                    column_family_id=column_family[0],
                                    column_family_name=column_family[1])
        blob_refs.clear()

    def close_output(pending_tombstones):
        nonlocal builder, wfile, fnum
        if builder is None:
            return
        for frag in pending_tombstones:
            b, e = frag.to_table_entry()
            builder.add_tombstone(b, e)
        if builder.num_entries == 0:
            wfile.close()
            env.delete_file(filename.table_file_name(dbname, fnum))
            builder = None
            wfile = None
            return
        props = builder.finish()
        wfile.sync()
        wfile.close()
        meta = FileMetaData(
            number=fnum,
            file_size=env.get_file_size(filename.table_file_name(dbname, fnum)),
            smallest=builder.smallest_key,
            largest=builder.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
            num_entries=props.num_entries,
            num_deletions=props.num_deletions,
            num_range_deletions=props.num_range_deletions,
            blob_refs=sorted(blob_refs),
            marked_for_compaction=builder.need_compaction,
        )
        outputs.append(meta)
        stats.output_bytes += meta.file_size
        stats.output_files += 1
        builder = None
        wfile = None

    last_user_key = None
    for ikey, value in entries_iter:
        if builder is None:
            open_output()
        uk = dbformat.extract_user_key(ikey)
        if (builder.file_size() >= compaction.max_output_file_size
                and last_user_key is not None
                and not surviving_tombstones
                and icmp.user_comparator.compare(uk, last_user_key) != 0):
            # Cut outputs only at user-key boundaries (all versions of a key
            # stay in one file, reference CompactionOutputs::ShouldStopBefore).
            # When range tombstones survive, a single output is produced:
            # add_tombstone widens file bounds to the tombstone span, and
            # splitting would make sibling outputs overlap at L1+ (proper
            # per-file tombstone partitioning is a later-round refinement).
            close_output([])
            open_output()
        builder.add(ikey, value)
        if ikey[-8] == dbformat.ValueType.BLOB_INDEX:
            blob_refs.add(decode_blob_index(value)[0])
        stats.output_records += 1
        last_user_key = uk
    if surviving_tombstones and builder is None:
        open_output()
    close_output(surviving_tombstones)
    return outputs


def run_compaction_to_tables(
    env, dbname: str, icmp, compaction: Compaction, table_cache,
    table_options, snapshots: list[int], merge_operator=None,
    compaction_filter=None, new_file_number=None, creation_time=None,
    blob_resolver=None, blob_gc=None, column_family: tuple[int, str] = (0, "default"),
) -> tuple[list[FileMetaData], CompactionStats]:
    """The CPU data plane: heap merge → CompactionIterator GC → outputs.
    `blob_gc` is an optional BlobGarbageCollector rewriting survivors out of
    aged blob files (reference blob GC during compaction)."""
    t0 = time.time()
    stats = CompactionStats()
    stats.input_bytes = compaction.total_input_bytes()
    children, rd = collect_inputs(compaction, table_cache, icmp)
    merger = MergingIterator(icmp.compare, children)
    merger.seek_to_first()
    ci = CompactionIterator(
        merger, icmp, snapshots,
        bottommost_level=compaction.bottommost,
        merge_operator=merge_operator,
        compaction_filter=compaction_filter,
        compaction_filter_level=compaction.output_level,
        range_del_agg=None if rd.empty() else rd,
        blob_resolver=blob_resolver,
    )
    tombs = surviving_tombstone_fragments(
        rd, snapshots, compaction.bottommost, icmp.user_comparator
    )
    stream = ci.entries()
    if blob_gc is not None and blob_gc.active:
        stream = blob_gc.rewrite(stream)
    try:
        outputs = build_outputs(
            env, dbname, icmp, compaction, stream, tombs,
            new_file_number, table_options, stats,
            creation_time if creation_time is not None else int(time.time()),
            column_family=column_family,
        )
    except BaseException:
        if blob_gc is not None:
            blob_gc.abort()
        raise
    if blob_gc is not None:
        blob_gc.finish()
    stats.input_records = ci.num_input_records
    stats.dropped_obsolete = ci.num_dropped_obsolete
    stats.dropped_tombstone = ci.num_dropped_tombstone
    stats.merged_records = ci.num_merged
    stats.work_time_usec = int((time.time() - t0) * 1e6)
    return outputs, stats


def make_version_edit(compaction: Compaction, outputs: list[FileMetaData]) -> VersionEdit:
    edit = VersionEdit(column_family=compaction.cf_id)
    for level, f in compaction.all_inputs():
        edit.delete_file(level, f.number)
    for meta in outputs:
        edit.add_file(compaction.output_level, meta)
    return edit
