"""CompactionJob: execute one picked compaction.

Mirrors the reference's CompactionJob::RunLocal →
ProcessKeyValueCompaction (db/compaction/compaction_job.cc:659,1390 in
/root/reference). The job is split into three shared stages so the CPU path
and the TPU/device path (toplingdb_tpu/ops/device_compaction.py) produce
byte-identical outputs:

  collect_inputs()              open input files, gather range tombstones
  CompactionIterator / device   the data plane (survivor stream)
  build_outputs()               output-file cutting + table building
"""

from __future__ import annotations

import bisect
import time
from dataclasses import dataclass

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.blob import decode_blob_index
from toplingdb_tpu.db.level_iterator import LevelIterator
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone, fragment_tombstones
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.compaction.picker import Compaction
from toplingdb_tpu.table.factory import new_table_builder
from toplingdb_tpu.table.merging_iterator import MergingIterator
from toplingdb_tpu.utils import errors as _errors


@dataclass
class CompactionStats:
    """Per-job stats (reference CompactionJobStats / CompactionResults
    timing fields, compaction_executor.h:120-158)."""

    input_records: int = 0
    output_records: int = 0
    input_bytes: int = 0
    output_bytes: int = 0
    output_files: int = 0
    input_files: int = 0
    dropped_obsolete: int = 0
    dropped_tombstone: int = 0
    merged_records: int = 0
    work_time_usec: int = 0
    rpc_time_usec: int = 0      # transport time for remote jobs (curl role)
    prepare_time_usec: int = 0  # params serde + job-dir/open setup (worker)
    waiting_time_usec: int = 0  # queue wait before the job ran (worker)
    transfer_time_usec: int = 0  # host<->device upload+download (device jobs)
    # Phase breakdown of work_time (VERDICT r03 item 2; the reference's
    # CompactionResults timing split, compaction_executor.h:146-150, extended
    # with device-plane phases). Phases can OVERLAP under the streamed shard
    # path (device wait happens inside the encode loop), so they need not sum
    # to work_time_usec.
    input_scan_usec: int = 0    # SST read + block decode into columnar bufs
    host_compute_usec: int = 0  # host-twin sort+GC (accelerator-less mode)
    device_wait_usec: int = 0   # blocking waits on device compute + D2H
    resolve_usec: int = 0       # host complex-group (merge/SD) resolution
    encode_write_usec: int = 0  # SST block build + frame + file write
    finish_usec: int = 0        # trailer decode, zero-seq patch, output metas
    pipeline_stall_usec: int = 0  # writer starved waiting on compute chunks
    prefetch_hits: int = 0      # input-scan reads served from readahead
    prefetch_misses: int = 0    # input-scan reads that went to the file
    device: str = "cpu"
    remote: bool = False        # ran in a worker process (dcompact)
    pipelined: bool = False     # ran the 3-stage pipeline (ops/pipeline.py)
    # Mesh plane (ops/mesh_compaction.py): >1 chips means the job's
    # key-range shards fanned out over a device mesh; fallbacks counts
    # eligibility misses while the knob was on PLUS mid-job chip
    # demotions (a wedged chip's shards re-ran on the survivors).
    mesh_chips: int = 0
    mesh_shards: int = 0
    mesh_fallbacks: int = 0
    # SST payload bytes that crossed the job transport (storage/: 0 when
    # the worker resolved inputs from the shared store and published its
    # outputs back — the job shipped only metadata).
    sst_bytes_shipped: int = 0

    def phase_dict(self) -> dict:
        """Non-zero timing phases, seconds — for bench/dcompact reporting.
        Includes an `other_s` residual (clamped at 0) so the phases sum to
        at least work_time_s (VERDICT r04 item weak-3): wall the named
        timers missed is reported, not hidden. Under the pipelined and
        streamed-shard paths the stages run concurrently, so the named
        phases OVER-count wall time; that over-count is reported
        explicitly as `pipeline_overlap_s` = sum(phases) - wall — the
        wall-clock the pipeline saved versus running the phases back to
        back."""
        out = {}
        accounted = 0
        for f in ("input_scan_usec", "host_compute_usec",
                  "transfer_time_usec", "device_wait_usec", "resolve_usec",
                  "encode_write_usec", "finish_usec", "pipeline_stall_usec",
                  "work_time_usec"):
            v = getattr(self, f)
            if v:
                out[f.replace("_usec", "_s")] = round(v / 1e6, 3)
                if f != "work_time_usec":
                    accounted += v
        resid = self.work_time_usec - accounted
        if self.work_time_usec:
            out["other_s"] = round(max(0, resid) / 1e6, 3)
            if resid < 0:
                out["pipeline_overlap_s"] = round(-resid / 1e6, 3)
        return out


# Stats phase field → telemetry span name: every compaction mode reports
# its interior through CompactionStats, so one synthesis point gives every
# mode (serial / columnar / device / pipelined / remote) a stage waterfall
# without restructuring the data planes. The DB-side scheduler emits them
# under its compaction root; a dcompact worker emits them under its own
# adopted root so the stitched trace shows the remote interior. Live
# per-shard spans from the pipeline workers land beside these.
_PHASE_SPANS = (
    ("waiting_time_usec", "compaction.queue_wait"),
    ("prepare_time_usec", "compaction.prepare"),
    ("input_scan_usec", "compaction.input_scan"),
    ("host_compute_usec", "compaction.compute"),
    ("transfer_time_usec", "compaction.transfer"),
    ("device_wait_usec", "compaction.device_wait"),
    ("resolve_usec", "compaction.resolve"),
    ("encode_write_usec", "compaction.encode_write"),
    ("rpc_time_usec", "compaction.rpc"),
)


def emit_phase_spans(stats) -> None:
    """Pre-finished child spans from a CompactionStats phase breakdown,
    attached under the calling thread's active span (no-op untraced)."""
    from toplingdb_tpu.utils import telemetry

    for field, name in _PHASE_SPANS:
        v = getattr(stats, field, 0)
        if v:
            telemetry.span_event(name, v)


def collect_inputs(compaction: Compaction, table_cache, icmp):
    """Open all input files; returns (children_iterators, range_del_agg)
    (reference VersionSet::MakeInputIterator, compaction_job.cc:1470)."""
    children = []
    rd = RangeDelAggregator(icmp.user_comparator)

    def add_tombs(f):
        r = table_cache.get_reader(f.number)
        for b, e in r.range_del_entries():
            rd.add(RangeTombstone.from_table_entry(b, e))
        return r

    if compaction.level == 0:
        for f in compaction.inputs:
            r = add_tombs(f)
            children.append(r.new_iterator())
    else:
        files = sorted(compaction.inputs, key=lambda f: icmp.sort_key(f.smallest))
        children.append(LevelIterator(table_cache, files, icmp))
        for f in files:
            add_tombs(f)
    if compaction.output_level_inputs:
        files = sorted(
            compaction.output_level_inputs, key=lambda f: icmp.sort_key(f.smallest)
        )
        children.append(LevelIterator(table_cache, files, icmp))
        for f in files:
            add_tombs(f)
    return children, rd


def gen_subcompaction_boundaries(compaction: Compaction, icmp,
                                 max_subcompactions: int) -> list[bytes]:
    """User-key boundaries splitting the compaction into ranges (reference
    CompactionJob::GenSubcompactionBoundaries, compaction_job.cc:604-640 —
    anchors come from input-file bounds instead of TableReader::Anchors;
    same spirit: cheap, even-ish partitions at user-key granularity)."""
    import functools

    ucmp = icmp.user_comparator
    anchors = set()
    for _, f in compaction.all_inputs():
        anchors.add(dbformat.extract_user_key(f.smallest))
        anchors.add(dbformat.extract_user_key(f.largest))
    ordered = sorted(anchors, key=functools.cmp_to_key(ucmp.compare))
    inner = ordered[1:-1]
    k = min(max_subcompactions, len(inner) + 1)
    if k <= 1:
        return []
    bounds: list[bytes] = []
    for i in range(1, k):
        b = inner[(i * len(inner)) // k]
        if not bounds or ucmp.compare(b, bounds[-1]) > 0:
            bounds.append(b)
    return bounds


class _BoundedMerger:
    """View of a positioned iterator that ends at user key `hi` (exclusive);
    the subcompaction's input window."""

    def __init__(self, it, icmp, hi: bytes | None):
        self._it = it
        self._ucmp = icmp.user_comparator
        self._hi = hi

    def valid(self):
        if not self._it.valid():
            return False
        if self._hi is None:
            return True
        uk = dbformat.extract_user_key(self._it.key())
        return self._ucmp.compare(uk, self._hi) < 0

    def key(self):
        return self._it.key()

    def value(self):
        return self._it.value()

    def next(self):
        self._it.next()


def _clip_fragments(frags, lo: bytes | None, hi: bytes | None, ucmp):
    """Restrict tombstone fragments to [lo, hi) so sibling subcompactions
    don't write overlapping tombstone spans."""
    out = []
    for f in frags:
        if lo is not None and ucmp.compare(f.end, lo) <= 0:
            continue
        if hi is not None and ucmp.compare(f.begin, hi) >= 0:
            continue
        nb = f.begin if lo is None or ucmp.compare(f.begin, lo) >= 0 else lo
        ne = f.end if hi is None or ucmp.compare(f.end, hi) <= 0 else hi
        out.append(type(f)(f.seq, nb, ne))
    return out


def surviving_tombstone_fragments(rd: RangeDelAggregator, snapshots: list[int],
                                  bottommost: bool, ucmp):
    """Tombstones that must be written to outputs. At the bottommost level a
    fragment is droppable only in snapshot stripe 0 (same rule as point
    DELETIONs); newer-than-a-snapshot tombstones must be kept or they would
    resurrect older kept entries."""
    if rd.empty():
        return []
    snaps = sorted(snapshots)
    frags = fragment_tombstones(rd.tombstones(), ucmp)
    if bottommost:
        return [f for f in frags if bisect.bisect_left(snaps, f.seq) > 0]
    return frags


def verify_output_table(env, path: str, icmp, table_options,
                        expected: dict, expected_entries: int) -> None:
    """Protection-driven output verification (the reference's
    paranoid_file_checks, generalized with per-entry checksums): re-read
    a just-written output SST from disk and check every entry against the
    multiset of checksums computed from the survivor stream that was
    meant to land in it. Catches the native/device block writers altering
    key or value bytes between emission and disk."""
    import dataclasses as _dc

    from toplingdb_tpu.table.factory import open_table
    from toplingdb_tpu.utils import protection as _p
    from toplingdb_tpu.utils.status import Corruption

    pb = table_options.protection_bytes_per_key
    topts = _dc.replace(table_options, verify_checksums=True)
    reader = open_table(env.new_random_access_file(path), icmp, topts)
    try:
        remaining = dict(expected)
        n = 0
        it = reader.new_iterator()
        it.seek_to_first()
        for ikey, val in it.entries():
            uk, _seq, t = dbformat.split_internal_key(ikey)
            cs = _p.truncate(_p.protect_entry(t, uk, val), pb)
            left = remaining.get(cs, 0)
            if left <= 0:
                raise Corruption(
                    f"compaction output {path}: entry {uk!r} (type {t}) "
                    f"does not match any emitted survivor — output bytes "
                    f"corrupted by the write plane"
                )
            remaining[cs] = left - 1
            n += 1
        if n != expected_entries:
            raise Corruption(
                f"compaction output {path}: {n} entries on disk, "
                f"{expected_entries} emitted"
            )
    finally:
        reader.close()


def build_outputs(env, dbname: str, icmp, compaction: Compaction,
                  entries_iter, surviving_tombstones, new_file_number,
                  table_options, stats: CompactionStats,
                  creation_time: int,
                  column_family: tuple[int, str] = (0, "default"),
                  ) -> list[FileMetaData]:
    """Cut the survivor stream into output tables (reference
    CompactionOutputs / SubcompactionState::AddToOutput). With
    protection_bytes_per_key active, each emitted entry's checksum is
    banked and the finished file is re-read and verified against the bank
    (verify_output_table) before it can reach the MANIFEST."""
    from toplingdb_tpu.utils import protection as _p

    pb = getattr(table_options, "protection_bytes_per_key", 0)
    outputs: list[FileMetaData] = []
    builder = None
    wfile = None
    fnum = None
    blob_refs: set[int] = set()
    emitted: dict[int, int] = {}  # checksum -> count for the open output
    emitted_n = 0

    def open_output():
        nonlocal builder, wfile, fnum, emitted, emitted_n
        fnum = new_file_number()
        wfile = env.new_writable_file(filename.table_file_name(dbname, fnum))
        builder = new_table_builder(wfile, icmp, table_options,
                                    creation_time=creation_time,
                                    column_family_id=column_family[0],
                                    column_family_name=column_family[1])
        blob_refs.clear()
        emitted = {}
        emitted_n = 0

    def close_output(pending_tombstones):
        nonlocal builder, wfile, fnum
        if builder is None:
            return
        for frag in pending_tombstones:
            b, e = frag.to_table_entry()
            builder.add_tombstone(b, e)
        if builder.num_entries == 0:
            wfile.close()
            env.delete_file(filename.table_file_name(dbname, fnum))
            builder = None
            wfile = None
            return
        props = builder.finish()
        wfile.sync()
        wfile.close()
        if pb:
            verify_output_table(
                env, filename.table_file_name(dbname, fnum), icmp,
                table_options, emitted, emitted_n,
            )
        meta = FileMetaData(
            number=fnum,
            file_size=env.get_file_size(filename.table_file_name(dbname, fnum)),
            smallest=builder.smallest_key,
            largest=builder.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
            num_entries=props.num_entries,
            num_deletions=props.num_deletions,
            num_range_deletions=props.num_range_deletions,
            blob_refs=sorted(blob_refs),
            marked_for_compaction=builder.need_compaction,
        )
        outputs.append(meta)
        stats.output_bytes += meta.file_size
        stats.output_files += 1
        builder = None
        wfile = None

    last_user_key = None
    try:
        for ikey, value in entries_iter:
            if builder is None:
                open_output()
            uk = dbformat.extract_user_key(ikey)
            if (builder.file_size() >= compaction.max_output_file_size
                    and last_user_key is not None
                    and not surviving_tombstones
                    and icmp.user_comparator.compare(uk, last_user_key) != 0):
                # Cut outputs only at user-key boundaries (all versions of a
                # key stay in one file, reference
                # CompactionOutputs::ShouldStopBefore). When range tombstones
                # survive, a single output is produced: add_tombstone widens
                # file bounds to the tombstone span, and splitting would make
                # sibling outputs overlap at L1+ (proper per-file tombstone
                # partitioning is a later-round refinement).
                close_output([])
                open_output()
            if pb:
                cs = _p.truncate(
                    _p.protect_entry(ikey[-8], uk, value), pb)
                emitted[cs] = emitted.get(cs, 0) + 1
                emitted_n += 1
            builder.add(ikey, value)
            if ikey[-8] == dbformat.ValueType.BLOB_INDEX:
                blob_refs.add(decode_blob_index(value)[0])
            stats.output_records += 1
            last_user_key = uk
        if surviving_tombstones and builder is None:
            open_output()
        close_output(surviving_tombstones)
    except BaseException:
        # Failed job: no partial or completed output may survive (the
        # reference's CompactionJob cleanup contract) — e.g. a mid-stream
        # NotSupported from a restrictive format (cuckoo duplicate user
        # key) must not leave orphan SSTs.
        if wfile is not None:
            try:
                wfile.close()
            except Exception as e:
                _errors.swallow(reason="compact-abort-close", exc=e)
        for m in outputs:
            try:
                env.delete_file(filename.table_file_name(dbname, m.number))
            except Exception as e:
                _errors.swallow(reason="compact-abort-delete-output", exc=e)
        # fnum may name an output whose builder never constructed (the
        # ctor raised) — the file exists, so delete unconditionally; a
        # stale fnum from a completed output is already gone above and the
        # double delete is swallowed.
        if fnum is not None:
            try:
                env.delete_file(filename.table_file_name(dbname, fnum))
            except Exception as e:
                _errors.swallow(reason="compact-abort-delete-current", exc=e)
        raise
    return outputs


def run_compaction_to_tables(
    env, dbname: str, icmp, compaction: Compaction, table_cache,
    table_options, snapshots: list[int], merge_operator=None,
    compaction_filter=None, new_file_number=None, creation_time=None,
    blob_resolver=None, blob_gc=None, column_family: tuple[int, str] = (0, "default"),
    max_subcompactions: int = 1,
) -> tuple[list[FileMetaData], CompactionStats]:
    """The CPU data plane: heap merge → CompactionIterator GC → outputs.
    `blob_gc` is an optional BlobGarbageCollector rewriting survivors out of
    aged blob files (reference blob GC during compaction). With
    max_subcompactions > 1 the key range is partitioned at user-key anchors
    and ranges run on parallel threads (reference subcompaction fan-out,
    compaction_job.cc:671-685 — the native block codec releases the GIL, so
    threads scale the encode/decode work)."""
    t0 = time.time()
    stats = CompactionStats()
    stats.input_bytes = compaction.total_input_bytes()
    stats.input_files = len(compaction.all_inputs())
    gc_active = blob_gc is not None and blob_gc.active
    bounds = (
        gen_subcompaction_boundaries(compaction, icmp, max_subcompactions)
        if max_subcompactions > 1 and not gc_active else []
    )
    outputs = _run_subcompactions(
        env, dbname, icmp, compaction, table_cache, table_options,
        snapshots, merge_operator, compaction_filter, new_file_number,
        creation_time, blob_resolver, column_family, bounds, stats,
        blob_gc=blob_gc if gc_active else None,
    )
    if blob_gc is not None and not gc_active:
        blob_gc.finish()  # no-op close for an inactive collector
    stats.work_time_usec = int((time.time() - t0) * 1e6)
    return outputs, stats


def _run_subcompactions(env, dbname, icmp, compaction, table_cache,
                        table_options, snapshots, merge_operator,
                        compaction_filter, new_file_number, creation_time,
                        blob_resolver, column_family, bounds: list[bytes],
                        stats: CompactionStats,
                        blob_gc=None) -> list[FileMetaData]:
    """One worker per key range (a single unbounded range when bounds is
    empty — the degenerate case IS the single-threaded path, so the sub=1
    and sub>1 pipelines cannot diverge); each range runs the full
    merge→GC→build pipeline over its window and the results concatenate in
    range order. Tombstones are fragmented ONCE and clipped per range.
    `blob_gc` (single-range only) rewrites survivors out of aged blob
    files."""
    import threading

    from toplingdb_tpu.utils import telemetry

    ucmp = icmp.user_comparator
    ranges = [
        (bounds[i - 1] if i > 0 else None,
         bounds[i] if i < len(bounds) else None)
        for i in range(len(bounds) + 1)
    ]
    assert blob_gc is None or len(ranges) == 1
    ctime = creation_time if creation_time is not None else int(time.time())
    # Fragment once (quadratic in tombstone count — not per thread); the
    # readers' tombstone meta is cached, so per-thread aggregators for the
    # point-key GC stay cheap.
    rd0 = RangeDelAggregator(ucmp)
    for _, f in compaction.all_inputs():
        r = table_cache.get_reader(f.number)
        for b, e in r.range_del_entries():
            rd0.add(RangeTombstone.from_table_entry(b, e))
    all_frags = surviving_tombstone_fragments(
        rd0, snapshots, compaction.bottommost, ucmp
    )
    results: list = [None] * len(ranges)
    errors: list[BaseException] = []
    # Serial-plane telemetry: the streamed merge→GC→build stage per key
    # range, parented cross-thread under the compaction root.
    trace_handle = telemetry.current_handle()

    def work(idx: int, lo: bytes | None, hi: bytes | None) -> None:
        _tsp = telemetry.span_under(trace_handle,
                                    "compaction.subcompaction", range=idx)
        try:
            st = CompactionStats()
            children, rd = collect_inputs(compaction, table_cache, icmp)
            merger = MergingIterator(icmp.compare, children)
            if lo is None:
                merger.seek_to_first()
            else:
                merger.seek(dbformat.make_internal_key(
                    lo, dbformat.MAX_SEQUENCE_NUMBER,
                    dbformat.VALUE_TYPE_FOR_SEEK,
                ))
            ci = CompactionIterator(
                _BoundedMerger(merger, icmp, hi), icmp, snapshots,
                bottommost_level=compaction.bottommost,
                merge_operator=merge_operator,
                compaction_filter=compaction_filter,
                compaction_filter_level=compaction.output_level,
                range_del_agg=None if rd.empty() else rd,
                blob_resolver=blob_resolver,
                full_history_ts_low=getattr(
                    compaction, "full_history_ts_low", 0
                ),
            )
            frags = _clip_fragments(all_frags, lo, hi, ucmp)
            stream = ci.entries()
            if blob_gc is not None:
                stream = blob_gc.rewrite(stream)
            outs = build_outputs(
                env, dbname, icmp, compaction, stream, frags,
                new_file_number, table_options, st, ctime,
                column_family=column_family,
            )
            st.input_records = ci.num_input_records
            st.dropped_obsolete = ci.num_dropped_obsolete
            st.dropped_tombstone = ci.num_dropped_tombstone
            st.merged_records = ci.num_merged
            for ch in children:
                pc = getattr(ch, "prefetch_counts", None)
                if pc is not None:
                    h, m = pc()
                    st.prefetch_hits += h
                    st.prefetch_misses += m
            results[idx] = (outs, st)
        except BaseException as e:  # noqa: BLE001 — surfaced by the driver
            errors.append(e)
        finally:
            _tsp.finish()

    if len(ranges) == 1:
        work(0, None, None)
    else:
        threads = [
            ccy.spawn(f"subcompaction-{i}", work, args=(i, lo, hi),
                      start=False)
            for i, (lo, hi) in enumerate(ranges)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        if blob_gc is not None:
            blob_gc.abort()
        raise errors[0]
    if blob_gc is not None:
        blob_gc.finish()
    outputs: list[FileMetaData] = []
    for outs, st in results:
        outputs.extend(outs)
        stats.input_records += st.input_records
        stats.output_records += st.output_records
        stats.output_bytes += st.output_bytes
        stats.output_files += st.output_files
        stats.dropped_obsolete += st.dropped_obsolete
        stats.dropped_tombstone += st.dropped_tombstone
        stats.merged_records += st.merged_records
        stats.prefetch_hits += st.prefetch_hits
        stats.prefetch_misses += st.prefetch_misses
    return outputs


def make_version_edit(compaction: Compaction, outputs: list[FileMetaData]) -> VersionEdit:
    edit = VersionEdit(column_family=compaction.cf_id)
    for level, f in compaction.all_inputs():
        edit.delete_file(level, f.number)
    for meta in outputs:
        edit.add_file(compaction.output_level, meta)
    return edit
