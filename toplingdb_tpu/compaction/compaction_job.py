"""CompactionJob: execute one picked compaction on the local CPU.

Mirrors the reference's CompactionJob::RunLocal →
ProcessKeyValueCompaction (db/compaction/compaction_job.cc:659,1390 in
/root/reference): build the merged input iterator, drive the
CompactionIterator MVCC GC, and cut output files at the target size. The
executor boundary (executor.py) can divert `run` to a remote/TPU device; this
module is also the worker-side implementation.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.level_iterator import LevelIterator
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone, fragment_tombstones
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.compaction.picker import Compaction
from toplingdb_tpu.table.builder import TableBuilder
from toplingdb_tpu.table.merging_iterator import MergingIterator


@dataclass
class CompactionStats:
    """Per-job stats (reference CompactionJobStats / CompactionResults
    timing fields, compaction_executor.h:120-158)."""

    input_records: int = 0
    output_records: int = 0
    input_bytes: int = 0
    output_bytes: int = 0
    output_files: int = 0
    dropped_obsolete: int = 0
    dropped_tombstone: int = 0
    merged_records: int = 0
    work_time_usec: int = 0
    device: str = "cpu"


def run_compaction_to_tables(
    env, dbname: str, icmp, compaction: Compaction, table_cache,
    table_options, snapshots: list[int], merge_operator=None,
    compaction_filter=None, new_file_number=None,
) -> tuple[list[FileMetaData], CompactionStats]:
    """The data plane: merge inputs → GC → build output tables.
    `new_file_number` is a callable allocating file numbers."""
    t0 = time.time()
    stats = CompactionStats()
    stats.input_bytes = compaction.total_input_bytes()

    # Input iterators: every L0-ish input file individually; level inputs as
    # one concatenating iterator per level (reference
    # VersionSet::MakeInputIterator, compaction_job.cc:1470).
    children = []
    rd = RangeDelAggregator(icmp.user_comparator)
    if compaction.level == 0:
        for f in compaction.inputs:
            r = table_cache.get_reader(f.number)
            children.append(r.new_iterator())
            for b, e in r.range_del_entries():
                rd.add(RangeTombstone.from_table_entry(b, e))
    else:
        files = sorted(compaction.inputs, key=lambda f: icmp.sort_key(f.smallest))
        children.append(LevelIterator(table_cache, files, icmp))
        for f in files:
            r = table_cache.get_reader(f.number)
            for b, e in r.range_del_entries():
                rd.add(RangeTombstone.from_table_entry(b, e))
    if compaction.output_level_inputs:
        files = sorted(
            compaction.output_level_inputs, key=lambda f: icmp.sort_key(f.smallest)
        )
        children.append(LevelIterator(table_cache, files, icmp))
        for f in files:
            r = table_cache.get_reader(f.number)
            for b, e in r.range_del_entries():
                rd.add(RangeTombstone.from_table_entry(b, e))

    merger = MergingIterator(icmp.compare, children)
    merger.seek_to_first()
    ci = CompactionIterator(
        merger, icmp, snapshots,
        bottommost_level=compaction.bottommost,
        merge_operator=merge_operator,
        compaction_filter=compaction_filter,
        compaction_filter_level=compaction.output_level,
        range_del_agg=None if rd.empty() else rd,
    )

    outputs: list[FileMetaData] = []
    builder = None
    wfile = None
    fnum = None

    def open_output():
        nonlocal builder, wfile, fnum
        fnum = new_file_number()
        wfile = env.new_writable_file(filename.table_file_name(dbname, fnum))
        builder = TableBuilder(wfile, icmp, table_options,
                               creation_time=int(time.time()))

    def close_output(pending_tombstones):
        nonlocal builder, wfile, fnum
        if builder is None:
            return
        for frag in pending_tombstones:
            b, e = frag.to_table_entry()
            builder.add_tombstone(b, e)
        if builder.num_entries == 0:
            # Nothing written: abandon the file.
            wfile.close()
            env.delete_file(filename.table_file_name(dbname, fnum))
            builder = None
            wfile = None
            return
        props = builder.finish()
        wfile.sync()
        wfile.close()
        meta = FileMetaData(
            number=fnum,
            file_size=env.get_file_size(filename.table_file_name(dbname, fnum)),
            smallest=builder.smallest_key,
            largest=builder.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
            num_entries=props.num_entries,
            num_deletions=props.num_deletions,
            num_range_deletions=props.num_range_deletions,
        )
        outputs.append(meta)
        stats.output_bytes += meta.file_size
        stats.output_files += 1
        builder = None
        wfile = None

    # Surviving range tombstones. At the bottommost level a tombstone is only
    # droppable when no live snapshot can still observe a key it shadows —
    # exactly the stripe-0 rule point DELETIONs use; a tombstone newer than
    # some snapshot must be kept or it would resurrect older kept entries.
    surviving_tombstones = []
    if not rd.empty():
        import bisect as _bisect

        snaps = sorted(snapshots)
        frags = fragment_tombstones(rd.tombstones(), icmp.user_comparator)
        if compaction.bottommost:
            surviving_tombstones = [
                f for f in frags if _bisect.bisect_left(snaps, f.seq) > 0
            ]
        else:
            surviving_tombstones = frags

    last_user_key = None
    for ikey, value in ci.entries():
        if builder is None:
            open_output()
        uk = dbformat.extract_user_key(ikey)
        if (builder.file_size() >= compaction.max_output_file_size
                and last_user_key is not None
                and not surviving_tombstones
                and icmp.user_comparator.compare(uk, last_user_key) != 0):
            # Cut outputs only at user-key boundaries (all versions of a key
            # stay in one file, reference CompactionOutputs::ShouldStopBefore).
            # When range tombstones survive, a single output is produced:
            # add_tombstone widens file bounds to the tombstone span, and
            # splitting would make sibling outputs overlap at L1+ (proper
            # per-file tombstone partitioning is a later-round refinement).
            close_output([])
            open_output()
        builder.add(ikey, value)
        stats.output_records += 1
        last_user_key = uk
    if surviving_tombstones and builder is None:
        open_output()
    close_output(surviving_tombstones)

    stats.input_records = ci.num_input_records
    stats.dropped_obsolete = ci.num_dropped_obsolete
    stats.dropped_tombstone = ci.num_dropped_tombstone
    stats.merged_records = ci.num_merged
    stats.work_time_usec = int((time.time() - t0) * 1e6)
    return outputs, stats


def make_version_edit(compaction: Compaction, outputs: list[FileMetaData]) -> VersionEdit:
    edit = VersionEdit()
    for level, f in compaction.all_inputs():
        edit.delete_file(level, f.number)
    for meta in outputs:
        edit.add_file(compaction.output_level, meta)
    return edit
