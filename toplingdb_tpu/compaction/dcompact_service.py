"""dcompact worker service: HTTP job submission over shared storage.

The transport shape of the reference's distributed compaction (curl control
plane + NFS data plane; CompactionExecutorFactory::JobUrl,
compaction_executor.h:146,177 in /root/reference): a worker host runs
`DcompactWorkerService` (one process per TPU chip in a pod); the DB side's
`HttpCompactionExecutor` POSTs {"job_dir": ...} to /dcompact and waits for
CompactionResults. Bulk data (input SSTs, output SSTs, params/results JSON)
moves through the shared filesystem, exactly like the reference's
NFS/S3 exchange.

Worker:  python -m toplingdb_tpu.compaction.dcompact_service --port 8080 \
             [--device tpu] [--workers 1] [--chips N]

Pod-level packing (`--chips N`): the worker host owns N chips; each chip
is a failure domain behind its own circuit breaker (PR 1's
WorkerHealthRegistry reused with "chip:<i>" keys). Jobs are admitted with
as many healthy free chips as the pool can grant (chip-count-aware
admission) and run the mesh plane sized to the grant; a wedged chip
demotes later jobs to fewer chips — down to single-chip/local when every
breaker is open — instead of stalling the queue. Per-chip queue depths
ride /metrics beside the existing dcompact gauges.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time

from toplingdb_tpu.utils import concurrency as ccy
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.compaction.executor import (
    CompactionExecutorFactory,
    SubprocessCompactionExecutor,
)
from toplingdb_tpu.utils.status import IOError_


class ChipPool:
    """Per-chip work queues + chip-count-aware job admission for one
    worker host. `admit()` targets the least-loaded healthy chips and
    gang-waits for them; a chip that wedges while a job queues is dropped
    from the grant (fewer-chip demotion), and a grant that times out
    takes whatever subset is free NOW — so a dead device degrades
    throughput, never progress. Chip health is the SAME breaker machinery
    the DB side uses for worker URLs, keyed "chip:<i>", so
    record_failure/record_success from finished jobs open and re-close
    chips exactly like remote workers."""

    def __init__(self, chips: int, policy=None):
        from toplingdb_tpu.compaction.resilience import (
            DcompactOptions, WorkerHealthRegistry,
        )

        self.chips = ["chip:%d" % i for i in range(max(1, chips))]
        self.health = WorkerHealthRegistry(policy or DcompactOptions())
        self._cv = ccy.Condition("dcompact_service.ChipPool._cv")
        self._busy: set[str] = set()
        # Granted-but-unreleased + queued-targeting counts per chip — the
        # /metrics queue-depth gauge.
        self._depth = {c: 0 for c in self.chips}

    def _healthy(self) -> list[str]:
        return [c for c in self.chips if self.health.breaker(c).allow()]

    def _pick_targets(self, want: int) -> list[str]:
        healthy = self._healthy()
        healthy.sort(key=lambda c: self._depth[c])
        return healthy[: max(0, want)]

    def admit(self, want: int | None = None,
              timeout: float = 30.0) -> list[str]:
        """Block until the targeted chips are free; returns the granted
        chip list (possibly smaller than `want` — demotion), or [] when no
        healthy chip exists (caller runs local/serial)."""
        want = len(self.chips) if want is None else max(1, want)
        deadline = time.monotonic() + max(0.0, timeout)
        with self._cv:
            target = self._pick_targets(want)
            for c in target:
                self._depth[c] += 1
            while True:
                healthy = set(self._healthy())
                alive = [c for c in target if c in healthy]
                if len(alive) < len(target):
                    # Wedged while queued: demote to the survivors.
                    for c in set(target) - set(alive):
                        self._depth[c] -= 1
                    target = alive
                if not target:
                    return []
                free = [c for c in target if c not in self._busy]
                if len(free) == len(target):
                    self._busy.update(target)
                    return list(target)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # Take what is free NOW rather than stall the job.
                    for c in set(target) - set(free):
                        self._depth[c] -= 1
                    self._busy.update(free)
                    return list(free)
                self._cv.wait(min(0.05, remaining))

    def release(self, grant: list[str], ok: bool = True,
                failed_chips=()) -> None:
        with self._cv:
            for c in grant:
                self._busy.discard(c)
                self._depth[c] -= 1
            self._cv.notify_all()
        # Health updates OUTSIDE the pool lock: the registry/breaker locks
        # rank below (after) the pool's in the §2.10.1 order, but release
        # has no reason to nest them.
        for c in grant:
            if ok and c not in failed_chips:
                self.health.record_success(c)
            else:
                self.health.record_failure(c)

    def queue_depths(self) -> dict[str, int]:
        with self._cv:
            return dict(self._depth)

    def snapshot(self) -> dict:
        with self._cv:
            depths = dict(self._depth)
            busy = set(self._busy)
        health = self.health.snapshot()
        return {
            c: {"queue_depth": depths[c], "busy": c in busy,
                "state": health.get(c, {}).get("state", "closed")}
            for c in self.chips
        }


class DcompactWorkerService:
    """Hosts job execution: POST /dcompact {"job_dir": ...} → runs the job
    in-process (owning the chip), returns the results JSON. GET /stats for
    introspection."""

    def __init__(self, device: str = "cpu", max_workers: int = 1,
                 chips: int = 0):
        self.device = device
        self._sem = threading.Semaphore(max_workers)
        self._server: ThreadingHTTPServer | None = None
        self._counter_mu = ccy.Lock("dcompact_service.DcompactWorkerService._counter_mu")
        self.jobs_done = 0
        self.jobs_failed = 0
        # Pod-level packing: chips > 0 builds the per-chip admission pool;
        # 0 keeps the legacy one-process-per-chip shape.
        self.pool = ChipPool(chips) if chips > 0 else None

    def _run_with_chips(self, run) -> int:
        """Admit chips for one job, size the mesh plane to the grant via
        env, run, and feed the outcome back into the chip breakers. The
        env export is process-wide, so with --workers > 1 overlapping jobs
        may see each other's grant size — that only skews chip COUNTS
        (outputs are byte-identical at any count); the admission ledger
        itself is race-free under the pool lock."""
        if self.pool is None:
            return run()
        grant = self.pool.admit()
        saved = {k: os.environ.get(k)
                 for k in ("TPULSM_MESH_COMPACT", "TPULSM_MESH_DEVICES")}
        if len(grant) > 1:
            os.environ["TPULSM_MESH_COMPACT"] = "1"
            os.environ["TPULSM_MESH_DEVICES"] = str(len(grant))
        else:
            # 0/1 healthy chips: run local/serial, never half-meshed.
            os.environ.pop("TPULSM_MESH_COMPACT", None)
        ok = False
        try:
            rc = run()
            ok = True
            return rc
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            self.pool.release(grant, ok=ok)

    def _count(self, ok: bool) -> None:
        with self._counter_mu:
            if ok:
                self.jobs_done += 1
            else:
                self.jobs_failed += 1

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/stats":
                    body = {
                        "device": svc.device, "jobs_done": svc.jobs_done,
                        "jobs_failed": svc.jobs_failed,
                    }
                    if svc.pool is not None:
                        body["chips"] = svc.pool.snapshot()
                    self._reply(200, body)
                elif self.path == "/health":
                    # Liveness probe for the DB-side health registry /
                    # half-open breaker checks; tools/fleet_health.py
                    # maps this bare shape onto its health-doc format.
                    self._reply(200, {"ok": True, "device": svc.device})
                elif self.path == "/metrics":
                    # Minimal Prometheus exposition so the worker shows
                    # up on the same scrape config as the DB repos.
                    lines = []
                    for metric, v in (("dcompact_jobs_done",
                                       svc.jobs_done),
                                      ("dcompact_jobs_failed",
                                       svc.jobs_failed)):
                        m = f"tpulsm_{metric}"
                        lines.append(f"# TYPE {m} gauge")
                        lines.append(
                            f'{m}{{device="{svc.device}"}} {v}')
                    if svc.pool is not None:
                        snap = svc.pool.snapshot()
                        for metric, val in (
                            ("dcompact_chip_queue_depth",
                             lambda s: s["queue_depth"]),
                            ("dcompact_chip_busy",
                             lambda s: int(s["busy"])),
                            ("dcompact_chip_wedged",
                             lambda s: int(s["state"] != "closed")),
                        ):
                            m = f"tpulsm_{metric}"
                            lines.append(f"# TYPE {m} gauge")
                            for chip, s in snap.items():
                                lines.append(
                                    f'{m}{{chip="{chip}"}} {val(s)}')
                    data = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/dcompact":
                    self._reply(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                    job_dir = req["job_dir"]
                    with svc._sem:  # one job per chip at a time
                        import os

                        from toplingdb_tpu.compaction import worker

                        os.makedirs(job_dir, exist_ok=True)
                        # The worker owns the device: override the submitted
                        # params' device with this service's.
                        ppath = os.path.join(job_dir, "params.json")
                        with open(ppath) as pf:
                            params = json.load(pf)
                        dirty = False
                        if params.get("device") != svc.device:
                            params["device"] = svc.device
                            dirty = True
                        hdr = self.headers.get("X-Tpulsm-Trace")
                        if hdr and not params.get("trace"):
                            # Header-carried trace context (cross-host
                            # deployments where the submitter wrote params
                            # before sampling): fold into the job.
                            try:
                                params["trace"] = json.loads(hdr)
                                dirty = True
                            except ValueError:
                                pass
                        if dirty:
                            with open(ppath, "w") as pf:
                                json.dump(params, pf, indent=1)
                        rc = svc._run_with_chips(
                            lambda: worker.run_job(job_dir))
                    with open(f"{job_dir}/results.json") as f:
                        results = json.load(f)
                    svc._count(ok=True)
                    self._reply(200, results)
                except Exception as e:  # job failure → structured error
                    svc._count(ok=False)
                    self._reply(500, {"status": f"{type(e).__name__}: {e}",
                                      "output_files": [], "stats": {}})

        self._server = ThreadingHTTPServer((host, port), Handler)
        ccy.spawn("dcompact-http", self._server.serve_forever, owner=self,
                  stop=self.stop)
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


class HttpCompactionExecutorFactory(CompactionExecutorFactory):
    """DB-side factory: jobs go to worker URLs round-robin through a
    per-URL circuit breaker (compaction/resilience.py): consecutive
    failures open a worker's breaker, picks skip open circuits, and a
    half-open probe re-admits a recovered worker. new_executor returns
    None when EVERY circuit is open — the retry driver then falls back to
    local without paying a remote timeout. Falls back to local on any
    transport/worker error (scheduler policy)."""

    def __init__(self, worker_urls: list[str], device: str = "cpu",
                 allow_fallback: bool = True, min_input_bytes: int = 0,
                 job_root: str | None = None, timeout: float | None = None,
                 policy=None, fault_injector=None):
        from toplingdb_tpu.compaction.resilience import (
            DcompactOptions, WorkerHealthRegistry,
        )

        self.worker_urls = list(worker_urls)
        self.device = device
        self._allow_fallback = allow_fallback
        self.min_input_bytes = min_input_bytes
        self.job_root = job_root
        self.policy = policy or DcompactOptions()
        # Legacy knob: an explicit timeout overrides the policy's
        # per-attempt transport timeout.
        self.timeout = timeout if timeout is not None \
            else self.policy.attempt_timeout
        self.health = WorkerHealthRegistry(self.policy)
        self.fault_injector = fault_injector

    def should_run_local(self, compaction) -> bool:
        return compaction.total_input_bytes() < self.min_input_bytes

    def allow_fallback_to_local(self) -> bool:
        return self._allow_fallback

    def job_url(self, job_id: int, attempt: int) -> str:
        return self.worker_urls[(job_id + attempt) % len(self.worker_urls)]

    def new_executor(self, compaction):
        url = self.health.pick(self.worker_urls)
        if url is None:
            return None  # every circuit open: caller skips to local

        def spawn(job_dir: str, device: str) -> None:
            headers = {"Content-Type": "application/json"}
            try:
                # Cross-process trace propagation rides the control plane
                # as a header (the params.json copy serves non-HTTP
                # transports); the worker service folds it back into the
                # job's params before running.
                import os as _os

                with open(_os.path.join(job_dir, "params.json")) as pf:
                    ctx = json.load(pf).get("trace")
                if ctx:
                    headers["X-Tpulsm-Trace"] = json.dumps(ctx)
            except (OSError, ValueError):
                pass
            req = urllib.request.Request(
                url + "/dcompact",
                data=json.dumps({"job_dir": job_dir}).encode(),
                headers=headers,
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    if r.status != 200:
                        raise IOError_(f"worker {url} HTTP {r.status}")
                    r.read()  # results also land in job_dir/results.json
            except OSError as e:
                raise IOError_(f"dcompact POST to {url} failed: {e}") from e

        ex = SubprocessCompactionExecutor(
            self.device, self.job_root, spawn=spawn, policy=self.policy,
            fault_injector=self.fault_injector,
        )
        ex.url = url
        return ex


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="0.0.0.0",
                    help="bind address (cross-host deployments need non-loopback)")
    ap.add_argument("--device", default="cpu")
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--chips", type=int, default=0,
                    help="chips this host owns; >0 enables pod-level "
                         "packing (per-chip queues + mesh-sized jobs)")
    args = ap.parse_args(argv)
    svc = DcompactWorkerService(args.device, args.workers,
                                chips=args.chips)
    port = svc.start(args.port, args.host)
    print(f"dcompact worker listening on {args.host}:{port} "
          f"(device={svc.device}, chips={args.chips})", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        svc.stop()
    return 0


if __name__ == "__main__":
    main()
