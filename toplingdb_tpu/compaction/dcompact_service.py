"""dcompact worker service: HTTP job submission over shared storage.

The transport shape of the reference's distributed compaction (curl control
plane + NFS data plane; CompactionExecutorFactory::JobUrl,
compaction_executor.h:146,177 in /root/reference): a worker host runs
`DcompactWorkerService` (one process per TPU chip in a pod); the DB side's
`HttpCompactionExecutor` POSTs {"job_dir": ...} to /dcompact and waits for
CompactionResults. Bulk data (input SSTs, output SSTs, params/results JSON)
moves through the shared filesystem, exactly like the reference's
NFS/S3 exchange.

Worker:  python -m toplingdb_tpu.compaction.dcompact_service --port 8080 \
             [--device tpu] [--workers 1]
"""

from __future__ import annotations

import argparse
import json
import threading

from toplingdb_tpu.utils import concurrency as ccy
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.compaction.executor import (
    CompactionExecutorFactory,
    SubprocessCompactionExecutor,
)
from toplingdb_tpu.utils.status import IOError_


class DcompactWorkerService:
    """Hosts job execution: POST /dcompact {"job_dir": ...} → runs the job
    in-process (owning the chip), returns the results JSON. GET /stats for
    introspection."""

    def __init__(self, device: str = "cpu", max_workers: int = 1):
        self.device = device
        self._sem = threading.Semaphore(max_workers)
        self._server: ThreadingHTTPServer | None = None
        self._counter_mu = ccy.Lock("dcompact_service.DcompactWorkerService._counter_mu")
        self.jobs_done = 0
        self.jobs_failed = 0

    def _count(self, ok: bool) -> None:
        with self._counter_mu:
            if ok:
                self.jobs_done += 1
            else:
                self.jobs_failed += 1

    def start(self, port: int = 0, host: str = "127.0.0.1") -> int:
        svc = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/stats":
                    self._reply(200, {
                        "device": svc.device, "jobs_done": svc.jobs_done,
                        "jobs_failed": svc.jobs_failed,
                    })
                elif self.path == "/health":
                    # Liveness probe for the DB-side health registry /
                    # half-open breaker checks; tools/fleet_health.py
                    # maps this bare shape onto its health-doc format.
                    self._reply(200, {"ok": True, "device": svc.device})
                elif self.path == "/metrics":
                    # Minimal Prometheus exposition so the worker shows
                    # up on the same scrape config as the DB repos.
                    lines = []
                    for metric, v in (("dcompact_jobs_done",
                                       svc.jobs_done),
                                      ("dcompact_jobs_failed",
                                       svc.jobs_failed)):
                        m = f"tpulsm_{metric}"
                        lines.append(f"# TYPE {m} gauge")
                        lines.append(
                            f'{m}{{device="{svc.device}"}} {v}')
                    data = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/dcompact":
                    self._reply(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                    job_dir = req["job_dir"]
                    with svc._sem:  # one job per chip at a time
                        import os

                        from toplingdb_tpu.compaction import worker

                        os.makedirs(job_dir, exist_ok=True)
                        # The worker owns the device: override the submitted
                        # params' device with this service's.
                        ppath = os.path.join(job_dir, "params.json")
                        with open(ppath) as pf:
                            params = json.load(pf)
                        dirty = False
                        if params.get("device") != svc.device:
                            params["device"] = svc.device
                            dirty = True
                        hdr = self.headers.get("X-Tpulsm-Trace")
                        if hdr and not params.get("trace"):
                            # Header-carried trace context (cross-host
                            # deployments where the submitter wrote params
                            # before sampling): fold into the job.
                            try:
                                params["trace"] = json.loads(hdr)
                                dirty = True
                            except ValueError:
                                pass
                        if dirty:
                            with open(ppath, "w") as pf:
                                json.dump(params, pf, indent=1)
                        rc = worker.run_job(job_dir)
                    with open(f"{job_dir}/results.json") as f:
                        results = json.load(f)
                    svc._count(ok=True)
                    self._reply(200, results)
                except Exception as e:  # job failure → structured error
                    svc._count(ok=False)
                    self._reply(500, {"status": f"{type(e).__name__}: {e}",
                                      "output_files": [], "stats": {}})

        self._server = ThreadingHTTPServer((host, port), Handler)
        ccy.spawn("dcompact-http", self._server.serve_forever, owner=self,
                  stop=self.stop)
        return self._server.server_address[1]

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


class HttpCompactionExecutorFactory(CompactionExecutorFactory):
    """DB-side factory: jobs go to worker URLs round-robin through a
    per-URL circuit breaker (compaction/resilience.py): consecutive
    failures open a worker's breaker, picks skip open circuits, and a
    half-open probe re-admits a recovered worker. new_executor returns
    None when EVERY circuit is open — the retry driver then falls back to
    local without paying a remote timeout. Falls back to local on any
    transport/worker error (scheduler policy)."""

    def __init__(self, worker_urls: list[str], device: str = "cpu",
                 allow_fallback: bool = True, min_input_bytes: int = 0,
                 job_root: str | None = None, timeout: float | None = None,
                 policy=None, fault_injector=None):
        from toplingdb_tpu.compaction.resilience import (
            DcompactOptions, WorkerHealthRegistry,
        )

        self.worker_urls = list(worker_urls)
        self.device = device
        self._allow_fallback = allow_fallback
        self.min_input_bytes = min_input_bytes
        self.job_root = job_root
        self.policy = policy or DcompactOptions()
        # Legacy knob: an explicit timeout overrides the policy's
        # per-attempt transport timeout.
        self.timeout = timeout if timeout is not None \
            else self.policy.attempt_timeout
        self.health = WorkerHealthRegistry(self.policy)
        self.fault_injector = fault_injector

    def should_run_local(self, compaction) -> bool:
        return compaction.total_input_bytes() < self.min_input_bytes

    def allow_fallback_to_local(self) -> bool:
        return self._allow_fallback

    def job_url(self, job_id: int, attempt: int) -> str:
        return self.worker_urls[(job_id + attempt) % len(self.worker_urls)]

    def new_executor(self, compaction):
        url = self.health.pick(self.worker_urls)
        if url is None:
            return None  # every circuit open: caller skips to local

        def spawn(job_dir: str, device: str) -> None:
            headers = {"Content-Type": "application/json"}
            try:
                # Cross-process trace propagation rides the control plane
                # as a header (the params.json copy serves non-HTTP
                # transports); the worker service folds it back into the
                # job's params before running.
                import os as _os

                with open(_os.path.join(job_dir, "params.json")) as pf:
                    ctx = json.load(pf).get("trace")
                if ctx:
                    headers["X-Tpulsm-Trace"] = json.dumps(ctx)
            except (OSError, ValueError):
                pass
            req = urllib.request.Request(
                url + "/dcompact",
                data=json.dumps({"job_dir": job_dir}).encode(),
                headers=headers,
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    if r.status != 200:
                        raise IOError_(f"worker {url} HTTP {r.status}")
                    r.read()  # results also land in job_dir/results.json
            except OSError as e:
                raise IOError_(f"dcompact POST to {url} failed: {e}") from e

        ex = SubprocessCompactionExecutor(
            self.device, self.job_root, spawn=spawn, policy=self.policy,
            fault_injector=self.fault_injector,
        )
        ex.url = url
        return ex


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--host", default="0.0.0.0",
                    help="bind address (cross-host deployments need non-loopback)")
    ap.add_argument("--device", default="cpu")
    ap.add_argument("--workers", type=int, default=1)
    args = ap.parse_args(argv)
    svc = DcompactWorkerService(args.device, args.workers)
    port = svc.start(args.port, args.host)
    print(f"dcompact worker listening on {args.host}:{port} "
          f"(device={svc.device})", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        svc.stop()
    return 0


if __name__ == "__main__":
    main()
