"""Upstream-style remote compaction: the CompactionService path.

The reference ships TWO remote-compaction mechanisms: Topling's dcompact
(CompactionExecutor plugin + job dirs — ours lives in
compaction/executor.py + compaction/worker.py) and Meta's upstream
CompactionService (include/rocksdb/options.h:436: a plugin receives one
serialized per-subcompaction job; the worker side calls
DB::OpenAndCompact(name, output_dir, input, &output) —
include/rocksdb/db.h:320-325, db/compaction/compaction_service_job.cc).

This module is the upstream-shaped half:

  open_and_compact(dbname, output_dir, input_json)  worker side — opens the
      source DB READ-ONLY from shared storage (MANIFEST recovery only, no
      WAL ownership), resolves the job's input files out of the live
      Version, runs the shared compaction data plane, writes outputs to
      output_dir and returns the serialized result.
  CompactionServiceExecutorFactory  DB side — plugs the service into the
      SAME executor seam the scheduler already routes through
      (compaction/executor.py), so service jobs get fallback-to-local and
      stats merge-back for free. The transport is a pluggable callable:
      in-process (tests), subprocess (process isolation), or anything
      HTTP-shaped.

Options (comparator, merge operator, table format) are NOT shipped in the
job: the worker loads them from the DB's persisted OPTIONS file, the same
way the reference worker gets them from the options file named in
CompactionServiceInput.
"""

from __future__ import annotations

import dataclasses
import json
import itertools
import os
import subprocess
import sys
import time

from toplingdb_tpu.compaction.compaction_job import (
    CompactionStats,
    run_compaction_to_tables,
)
from toplingdb_tpu.compaction.executor import (
    CompactionExecutor,
    CompactionExecutorFactory,
    decode_file_meta,
    encode_file_meta,
)
from toplingdb_tpu.compaction.picker import Compaction
from toplingdb_tpu.db import filename
from toplingdb_tpu.utils.status import Corruption, InvalidArgument
from toplingdb_tpu.utils import errors as _errors


@dataclasses.dataclass
class CompactionServiceInput:
    """One job, serialized DB→worker (reference CompactionServiceInput,
    options.h / compaction_service_job.cc)."""

    cf_name: str
    input_files: list[int]           # file NUMBERS, resolved via the Version
    output_level: int
    bottommost: bool
    snapshots: list[int]
    max_output_file_size: int
    creation_time: int = 0
    device: str = "cpu"

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(s: str) -> "CompactionServiceInput":
        return CompactionServiceInput(**json.loads(s))


@dataclasses.dataclass
class CompactionServiceResult:
    """Worker→DB result (reference CompactionServiceResult)."""

    status: str                      # "ok" | error text
    output_files: list[dict]         # encode_file_meta dicts, paths relative
    stats: dict = dataclasses.field(default_factory=dict)
    bytes_read: int = 0
    bytes_written: int = 0

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @staticmethod
    def from_json(s: str) -> "CompactionServiceResult":
        return CompactionServiceResult(**json.loads(s))


def open_and_compact(dbname: str, output_dir: str, input_json: str,
                     env=None) -> str:
    """Worker entry point (reference DB::OpenAndCompact,
    include/rocksdb/db.h:320-325): one read-only open, one compaction,
    outputs under output_dir named like table files. Never touches the
    source DB dir. Returns CompactionServiceResult JSON (errors reported
    in .status rather than raised, matching the RPC shape)."""
    from toplingdb_tpu.db.db_readonly import ReadOnlyDB
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.utils.config import load_latest_options

    env = env or default_env()
    try:
        inp = CompactionServiceInput.from_json(input_json)
        # None (no OPTIONS file persisted) legitimately means defaults; a
        # CORRUPT/unreadable OPTIONS file must fail the job in-band rather
        # than silently compact with the wrong comparator/merge operator.
        options = load_latest_options(dbname, env=env)
        db = ReadOnlyDB.open(dbname, options, env=env)
        try:
            cfd = None
            for c in db._cfs.values():
                if c.handle.name == inp.cf_name:
                    cfd = c
                    break
            if cfd is None:
                raise InvalidArgument(f"no column family {inp.cf_name!r}")
            version = db.versions.cf_current(cfd.handle.id)
            by_number = {
                f.number: f for level_files in version.files
                for f in level_files
            }
            metas = []
            for num in inp.input_files:
                f = by_number.get(num)
                if f is None:
                    raise Corruption(
                        f"input file {num} not in the current version "
                        f"(compaction already superseded?)"
                    )
                metas.append(f)
            compaction = Compaction(
                level=0,  # per-file iterators: correct for any input mix
                output_level=inp.output_level,
                inputs=metas,
                bottommost=inp.bottommost,
                max_output_file_size=inp.max_output_file_size,
            )
            env.create_dir(output_dir)
            counter = [0]

            def alloc():
                counter[0] += 1
                return counter[0]

            from toplingdb_tpu.db.blob import BlobSource

            blob_source = BlobSource(env, dbname)
            topts = db.options.table_options
            outputs, stats = run_compaction_to_tables(
                env, output_dir, db.icmp, compaction, db.table_cache,
                topts, list(inp.snapshots),
                merge_operator=db.options.merge_operator,
                compaction_filter=getattr(
                    db.options, "compaction_filter", None
                ),
                new_file_number=alloc,
                creation_time=inp.creation_time or None,
                blob_resolver=blob_source.get,
                column_family=(cfd.handle.id, cfd.handle.name),
            )
            files = [
                encode_file_meta(
                    m, os.path.basename(
                        filename.table_file_name(output_dir, m.number)
                    )
                )
                for m in outputs
            ]
            return CompactionServiceResult(
                status="ok", output_files=files,
                stats=dataclasses.asdict(stats),
                bytes_read=stats.input_bytes,
                bytes_written=stats.output_bytes,
            ).to_json()
        finally:
            db.close()
    except Exception as e:  # RPC shape: errors travel in-band
        return CompactionServiceResult(
            status=f"{type(e).__name__}: {e}", output_files=[],
        ).to_json()


class InProcessCompactionService:
    """Transport: run the worker half in this process (reference
    compaction_service_test.cc's MyTestCompactionService shape)."""

    def __init__(self, env=None):
        self._env = env
        self.jobs = 0

    def __call__(self, dbname: str, output_dir: str, input_json: str) -> str:
        self.jobs += 1
        return open_and_compact(dbname, output_dir, input_json,
                                env=self._env)


class SubprocessCompactionService:
    """Transport: a fresh worker process per job (full isolation — the
    reference's remote worker binary, minus the network)."""

    def __call__(self, dbname: str, output_dir: str, input_json: str) -> str:
        import toplingdb_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(toplingdb_tpu.__file__)
        ))
        p = subprocess.run(
            [sys.executable, "-m",
             "toplingdb_tpu.compaction.compaction_service",
             dbname, output_dir],
            input=input_json, capture_output=True, text=True, cwd=pkg_root,
        )
        if p.returncode != 0 or not p.stdout.strip():
            return CompactionServiceResult(
                status=f"worker process failed: {p.stderr[-500:]}",
                output_files=[],
            ).to_json()
        return p.stdout.strip().splitlines()[-1]


class CompactionServiceExecutor(CompactionExecutor):
    """DB-side half: serialize → service → install (the role of
    ProcessKeyValueCompactionWithCompactionService,
    compaction_job.cc:1393-1402)."""

    def __init__(self, service, job_root: str | None = None):
        self._service = service
        self._job_root = job_root
        self._output_dir = None
        self._env = None

    _job_seq = itertools.count(1)

    def execute(self, db, compaction, snapshots, new_file_number):
        env = self._env = db.env
        root = self._job_root or os.path.join(db.dbname, "service_jobs")
        env.create_dir(root)
        # pid + process-global atomic counter (itertools.count next() is a
        # single bytecode under the GIL): unique under concurrent scheduler
        # fan-out AND across worker processes sharing the job root.
        seq = next(CompactionServiceExecutor._job_seq)
        out_dir = self._output_dir = os.path.join(
            root, f"job-{os.getpid()}-{seq:06d}",
        )
        # The worker reconstructs options from the persisted OPTIONS file,
        # which can only carry REGISTERED plugin objects — an unregistered
        # comparator/merge-operator/compaction-filter would silently compact
        # with defaults. Raise here instead: the scheduler falls back to
        # local, which has the live objects.
        from toplingdb_tpu.utils.config import options_to_config

        cfg = options_to_config(db.options)
        opts = db.options
        if opts.comparator.name() != "tpulsm.BytewiseComparator" and \
                "comparator" not in cfg:
            raise InvalidArgument(
                "unregistered comparator cannot travel the service boundary"
            )
        if opts.merge_operator is not None and "merge_operator" not in cfg:
            raise InvalidArgument(
                "unregistered merge operator cannot travel the service "
                "boundary"
            )
        if getattr(opts, "compaction_filter", None) is not None and \
                "compaction_filter" not in cfg:
            raise InvalidArgument(
                "unregistered compaction filter cannot travel the service "
                "boundary"
            )
        inp = CompactionServiceInput(
            cf_name=db.cf_name(compaction.cf_id),
            input_files=[f.number for _, f in compaction.all_inputs()],
            output_level=compaction.output_level,
            bottommost=compaction.bottommost,
            snapshots=list(snapshots),
            max_output_file_size=compaction.max_output_file_size,
            creation_time=int(time.time()),
        )
        t0 = time.time()
        try:
            res = CompactionServiceResult.from_json(
                self._service(db.dbname, out_dir, inp.to_json())
            )
            if res.status != "ok":
                raise Corruption(f"compaction service failed: {res.status}")
            outputs = []
            stats = CompactionStats(device="service")
            for k, v in (res.stats or {}).items():
                if hasattr(stats, k) and isinstance(v, (int, float)):
                    setattr(stats, k, v)
            # Install: move each output under a DB-allocated file number
            # (reference RunRemote's RenameFile loop, compaction_job.cc:1019).
            for d in res.output_files:
                num = new_file_number()
                src = os.path.join(out_dir, d["path"])
                dst = filename.table_file_name(db.dbname, num)
                env.rename_file(src, dst)
                outputs.append(decode_file_meta(d, num))
        except BaseException:
            # Self-contained cleanup: the scheduler's fallback path does
            # not call clean_files, and un-installed worker outputs must
            # not accumulate under the DB dir.
            self.clean_files()
            raise
        stats.rpc_time_usec = int((time.time() - t0) * 1e6)
        stats.device = "service"
        self.clean_files()  # emptied job dir
        return outputs, stats

    def clean_files(self):
        if self._output_dir is not None and self._env is not None:
            try:
                for child in self._env.get_children(self._output_dir):
                    self._env.delete_file(
                        os.path.join(self._output_dir, child)
                    )
            except Exception as e:
                _errors.swallow(reason="remote-output-cleanup", exc=e)
            try:
                os.rmdir(self._output_dir)  # best-effort for posix envs
            except OSError:
                pass


class CompactionServiceExecutorFactory(CompactionExecutorFactory):
    """ColumnFamilyOptions.compaction_service analogue, routed through the
    standard executor seam so the scheduler's fallback-to-local and stats
    merge-back apply."""

    def __init__(self, service=None, allow_fallback: bool = True,
                 job_root: str | None = None):
        self._service = service or InProcessCompactionService()
        self._allow_fallback = allow_fallback
        self._job_root = job_root

    def should_run_local(self, compaction: Compaction) -> bool:
        return False

    def allow_fallback_to_local(self) -> bool:
        return self._allow_fallback

    def new_executor(self, compaction: Compaction) -> CompactionExecutor:
        return CompactionServiceExecutor(self._service, self._job_root)

    def job_url(self, job_id: int, attempt: int) -> str:
        return f"service://job-{job_id:05d}/att-{attempt:02d}"


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print("usage: python -m toplingdb_tpu.compaction.compaction_service "
              "<dbname> <output_dir>  (input JSON on stdin)", file=sys.stderr)
        return 2
    print(open_and_compact(argv[0], argv[1], sys.stdin.read()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
