"""Background compaction scheduling.

Role of the reference's MaybeScheduleFlushOrCompaction → BGWorkCompaction
chain (db/db_impl/db_impl_compaction_flush.cc:2662-3279 in /root/reference):
after every flush/compaction the scores are re-evaluated and jobs run on a
bounded worker pool. Jobs route through the CompactionExecutor boundary when
one is configured (device=cpu|tpu|remote), with fallback to local
(reference compaction_job.cc:648-655).
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import traceback

from toplingdb_tpu.db import dbformat

from toplingdb_tpu.compaction.compaction_job import (
    make_version_edit,
    run_compaction_to_tables,
)
from toplingdb_tpu.compaction.picker import Compaction, create_picker


from toplingdb_tpu.compaction.compaction_job import (  # noqa: E402
    emit_phase_spans as _emit_phase_spans,
)


class CompactionScheduler:
    def __init__(self, db, background: bool = True):
        self.db = db
        self.picker = create_picker(db.options, db.icmp)
        # Age policies need table properties (creation_time lives there).
        self.picker.creation_time_fn = self._file_creation_time
        self.background = background
        self._pending = 0
        self._running = 0
        self._lock = ccy.Lock("scheduler.CompactionScheduler._lock")
        self._cv = ccy.Condition(lock=self._lock)
        self._shutdown = False
        self._manual_active = False
        self._paused = 0
        self.last_error: BaseException | None = None
        self.num_completed = 0
        self.num_trivial_moves = 0
        # Graceful-degradation gate for remote compaction: after N
        # consecutive remote JOB failures, jobs pin local for a cooldown
        # (compaction/resilience.py). Lazily built from options.dcompact.
        self._pin_gate = None
        # (retry_ts, FileMetaData) of marked-rewrite jobs postponed by
        # preclude_last_level_data_seconds; re-marked once aged.
        self._preclude_remark: list = []
        # Consecutive space-preflight refusals since the last job that ran
        # (log the FIRST refusal of a streak, tick all of them).
        self._space_blocks = 0

    # ------------------------------------------------------------------

    def pause(self) -> None:
        """Reference DB::PauseBackgroundWork: block until running jobs
        drain, then hold new ones."""
        with self._lock:
            self._paused += 1
        self.wait_idle()

    def resume_background(self) -> None:
        with self._lock:
            self._paused = max(0, self._paused - 1)
        self.maybe_schedule()

    def maybe_schedule(self) -> None:
        if self.db.options.disable_auto_compactions:
            return
        if self.background:
            with self._lock:
                # _paused must be checked under the lock, or a racing
                # schedule could slip in after pause() returned.
                if self._shutdown or self._manual_active or self._paused:
                    return
                if self._running + self._pending >= self.db.options.max_background_jobs:
                    return
                self._pending += 1
            ccy.spawn("compaction-bg", self._bg_work, owner=self,
                      stop=self.shutdown)
        else:
            with self._lock:
                if self._paused:
                    return
            while self._run_one():
                pass

    def _bg_work(self) -> None:
        # Keep running jobs in THIS thread until no work remains: _running
        # stays nonzero for the whole drain, so wait_idle() can never observe
        # a false idle gap between one job finishing and its follow-up being
        # scheduled.
        with self._lock:
            self._pending -= 1
            self._running += 1
        try:
            while True:
                with self._lock:
                    if self._shutdown or self._manual_active:
                        break
                if not self._run_one():
                    break
        except BaseException as e:
            # Surface to the DB's error handler: writes fail until resume()
            # (reference ErrorHandler, db/error_handler.h:28).
            self.last_error = e
            self.db._set_background_error(
                e, getattr(e, "_bg_reason", "compaction")
            )
            traceback.print_exc()
        finally:
            with self._lock:
                self._running -= 1
                self._cv.notify_all()

    def wait_idle(self) -> None:
        """Block until no compaction is running or pending (test/bench aid)."""
        while True:
            with self._lock:
                if self._running == 0 and self._pending == 0:
                    return
                self._cv.wait(timeout=0.1)

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
        self.wait_idle()

    # ------------------------------------------------------------------

    def _file_creation_time(self, f):
        """Creation time from table properties, memoized on the meta so the
        age sweeps never re-open files (a sweep across a big DB would
        otherwise thrash the table-cache LRU on every cycle)."""
        ct = getattr(f, "_creation_time_cache", None)
        if ct is not None:
            return ct or None  # 0 sentinel = previously failed / absent
        try:
            ct = self.db.table_cache.get_reader(f.number).properties \
                .creation_time
        except Exception as e:
            self.db.event_logger.log(
                "creation_time_unreadable", file_number=f.number,
                error=repr(e),
            )
            ct = 0
        f._creation_time_cache = ct
        return ct or None

    def _apply_periodic_marking(self) -> None:
        """Reference periodic_compaction_seconds: files past the age get
        marked so the picker rewrites them (the rewrite refreshes
        creation_time; 'bottommost marked' outputs suppress re-marks).
        Leveled style only — the universal/FIFO pickers don't consult
        marked_for_compaction (FIFO ages out via fifo_ttl_seconds)."""
        db = self.db
        per = db.options.periodic_compaction_seconds
        if not per or db.options.compaction_style != "leveled":
            return
        import time as _t

        cutoff = int(_t.time()) - per
        with db._mutex:
            for cf_id in list(db.versions.column_families):
                v = db.versions.cf_current(cf_id)
                for lvl in range(v.num_levels):
                    for f in v.files[lvl]:
                        if f.marked_for_compaction or f.being_compacted:
                            continue
                        ct = self._file_creation_time(f)
                        if ct and ct <= cutoff:
                            f.marked_for_compaction = True

    def _run_one(self) -> bool:
        db = self.db
        self._apply_periodic_marking()
        if self._preclude_remark:
            import time as _t

            now = _t.time()
            with self._lock:  # concurrent bg workers append + sweep
                pending = self._preclude_remark
                still = []
                expired = []
                for retry, f in pending:
                    (expired if retry <= now else still).append((retry, f))
                self._preclude_remark = still
            for _retry, f in expired:
                f.marked_for_compaction = True
        with db._mutex:
            # Visit CFs by descending top compaction score — fixed id order
            # would starve later CFs under sustained load on an earlier one.
            scored = []
            for cf_id in db.versions.column_families:
                version = db.versions.cf_current(cf_id)
                scores = self.picker.compaction_score(version)
                top = scores[0][0] if scores else 0.0
                scored.append((top, cf_id, version))
            scored.sort(key=lambda s: -s[0])
            c = None
            for top, cf_id, version in scored:
                if top < 1.0:
                    break
                c = self.picker.pick_compaction(version)
                if c is not None:
                    c.cf_id = cf_id
                    c.full_history_ts_low = self.db.options.full_history_ts_low
                    break
            if c is None:
                return False
            if self._space_refused(c):
                # Nothing is marked being_compacted yet, so the exact same
                # job stays pickable. Returning False stops the drain loop
                # (a True here would re-pick this compaction in a hot
                # loop); the pressure callback's _maybe_schedule_compaction
                # re-enters once the poller sees headroom again.
                return False
            for _, f in c.all_inputs():
                f.being_compacted = True
        try:
            self._run_compaction(c)
        finally:
            with db._mutex:
                for _, f in c.all_inputs():
                    f.being_compacted = False
        with self._lock:
            self.num_completed += 1
            self._space_blocks = 0
        return True

    def _space_refused(self, c: Compaction) -> bool:
        """Storage-pressure preflight (reference
        SstFileManagerImpl::EnoughRoomForCompaction): refuse to START a
        rewriting compaction while pressure is amber/red — degradation is
        amber-first, compactions pause before anything errors — or when
        the estimated output (~= input bytes) would eat into the reserved
        flush headroom / compaction buffer. FIFO deletion jobs are exempt:
        they only free space. Manual compact_range does not route through
        _run_one and stays operator-controlled."""
        db = self.db
        sfm = db._sfm
        if sfm is None or c.reason.startswith("fifo"):
            return False
        est = sum(f.file_size for _, f in c.all_inputs())
        if sfm.pressure() == "ok" and sfm.check_compaction(est):
            return False
        if db.stats is not None:
            from toplingdb_tpu.utils import statistics as _st

            db.stats.record_tick(_st.NO_SPACE_PREFLIGHT_BLOCKS, 1)
        with self._lock:
            first = self._space_blocks == 0
            self._space_blocks += 1
        if first:
            db.event_logger.log(
                "compaction_space_blocked", reason=c.reason,
                estimated_bytes=est, pressure=sfm.pressure(),
            )
        return True

    def _maybe_preclude_last_level(self, c: Compaction) -> None:
        """preclude_last_level_data_seconds (reference options.h +
        seqno_to_time_mapping consumer): a bottommost-targeting job whose
        inputs hold data YOUNGER than the cutoff keeps full MVCC
        semantics — no seqno zeroing, no tombstone dropping — until a
        later compaction finds it aged. Placement is NOT changed (the
        reference splits outputs to the penultimate level per key; a
        job-granularity retarget would install overlapping files into
        sorted-disjoint levels, so we defer the last-level TREATMENT
        instead — the documented design difference)."""
        import time as _time

        db = self.db
        secs = getattr(db.options, "preclude_last_level_data_seconds", 0)
        if not secs or not c.bottommost:
            # Same-level bottommost rewrites (marked-file rewrites,
            # universal L0 self-compactions) are last-level-treatment jobs
            # too — c.bottommost alone decides eligibility.
            return False
        cutoff_seq = db.seqno_to_time.get_proximal_seqno(
            int(_time.time()) - secs)
        if cutoff_seq is None:
            # The cutoff time predates every recorded sample: nothing can
            # be PROVEN old, so everything is treated as young.
            cutoff_seq = 0
        newest = max((f.largest_seqno for _, f in c.all_inputs()),
                     default=0)
        if newest > cutoff_seq:
            if c.reason == "bottommost marked":
                # A marked-file rewrite exists ONLY to drop garbage; run
                # precluded it would drop nothing and then suppress the
                # re-mark — cancelling the collector's request forever.
                # SKIP instead: unmark now, re-mark after a backoff so
                # the picker doesn't spin on the same young file.
                import time as _t2

                retry = _t2.time() + min(60.0, float(secs))
                with self._lock:
                    for f in c.inputs:
                        f.marked_for_compaction = False
                        self._preclude_remark.append((retry, f))
                return True
            c.bottommost = False
        return False

    def _run_compaction(self, c: Compaction) -> None:
        db = self.db
        if not c.output_level_inputs and not c.inputs:
            return
        if self._maybe_preclude_last_level(c):
            return  # postponed (young marked rewrite); re-marks later
        if c.reason.startswith("fifo"):
            # Deletion-only compaction.
            edit = make_version_edit(c, [])
            with db._mutex:
                db.versions.log_and_apply(edit)
                db._delete_obsolete_files()
            return
        def _bottom_move_ok(f) -> bool:
            # A bottommost rewrite exists to GC tombstones / fold merges;
            # a file with neither loses nothing by moving.
            if f.num_deletions or f.num_range_deletions:
                return False
            props = db.table_cache.get_reader(f.number).properties
            return props.num_merge_operands == 0

        if (len(c.inputs) == 1 and not c.output_level_inputs
                and c.level > 0 and c.output_level > c.level
                and db.options.compaction_filter is None
                and (not c.bottommost or _bottom_move_ok(c.inputs[0]))
                and not (db.options.enable_blob_garbage_collection
                         and c.inputs[0].blob_refs)):
            # Trivial move (reference Compaction::IsTrivialMove /
            # db_impl_compaction_flush.cc): nothing overlaps below — just
            # relocate the file's metadata, no rewrite, no IO.
            meta = c.inputs[0]
            from toplingdb_tpu.db.version_edit import VersionEdit

            edit = VersionEdit(column_family=c.cf_id)
            edit.delete_file(c.level, meta.number)
            edit.add_file(c.output_level, meta)
            with db._mutex:
                db.versions.log_and_apply(edit)
            with self._lock:
                self.num_trivial_moves += 1
            db.event_logger.log(
                "trivial_move", file_number=meta.number,
                from_level=c.level, to_level=c.output_level,
            )
            from toplingdb_tpu.utils.listener import CompactionJobInfo, notify

            notify(db.options.listeners, "on_compaction_completed", db,
                   CompactionJobInfo(
                       db_name=db.dbname, input_level=c.level,
                       output_level=c.output_level,
                       input_files=[meta.number], output_files=[meta.number],
                       input_records=meta.num_entries,
                       output_records=meta.num_entries,
                       elapsed_micros=0, device="move",
                       reason="trivial move",
                   ))
            return
        from toplingdb_tpu.utils.thread_status import thread_operation

        with thread_operation("compaction",
                              f"L{c.level}->L{c.output_level}", db.dbname):
            self._run_compaction_inner(c)

    def _run_compaction_inner(self, c: Compaction) -> None:
        from toplingdb_tpu.utils import telemetry as _tm

        db = self.db
        # Compactions are always traced while a tracer exists — they are
        # the ops RESYSTANCE-style stage visibility pays off on most.
        _root = (db.tracer.start(
            "compaction", level=c.level, output_level=c.output_level,
            reason=c.reason, cf_id=c.cf_id)
            if getattr(db, "tracer", None) is not None else _tm.NOOP_SPAN)
        try:
            self._run_compaction_traced(c, _root)
        finally:
            _root.finish()

    def _run_compaction_traced(self, c: Compaction, _root) -> None:
        from toplingdb_tpu.utils import telemetry as _tm

        db = self.db
        snapshots = db.snapshots.sequences()
        pending: list[int] = []

        def alloc() -> int:
            # Protect in-flight outputs from obsolete-file GC until the
            # version edit lands (reference DBImpl pending_outputs_).
            n = db.versions.new_file_number()
            with db._mutex:
                db._pending_outputs.add(n)
            pending.append(n)
            return n

        try:
            factory = db.options.compaction_executor_factory
            if factory is not None and not factory.should_run_local(c):
                # The resilient path: per-attempt retry with backoff, a
                # per-job deadline, breaker-aware worker picks, and the
                # graceful-degradation local pin — with DCOMPACTION_*
                # stats and listener events for every decision
                # (compaction/resilience.py).
                from toplingdb_tpu.compaction.resilience import (
                    execute_resilient,
                )

                outputs, stats = execute_resilient(
                    db, factory, c, snapshots, alloc,
                    run_local=lambda: self._run_local(c, snapshots, alloc),
                    gate=self._degradation_gate(),
                )
            else:
                outputs, stats = self._run_local(c, snapshots, alloc)
            _root.tag(mode=self._compaction_mode(stats),
                      input_records=stats.input_records,
                      output_records=stats.output_records)
            _emit_phase_spans(stats)
            if db.options.statistics is not None:
                db.options.statistics.record_compaction(stats)
            from toplingdb_tpu.utils.sync_point import sync_point_callback

            sync_point_callback("CompactionJob::BeforeInstall", c)
            if c.reason == "bottommost marked":
                # The rewrite already dropped everything droppable; keeping a
                # collector re-mark would rewrite the same file forever while
                # snapshots pin its remaining tombstones.
                for m in outputs:
                    m.marked_for_compaction = False
            # Whole-file checksums ride into the MANIFEST with the install
            # (covers local, device, and remote-worker outputs uniformly).
            db._stamp_file_checksums(outputs)
            edit = make_version_edit(c, outputs)
            with db._mutex:
                db.versions.log_and_apply(edit)
                db._delete_obsolete_files()
            if db._sfm is not None:
                from toplingdb_tpu.db import filename as _fn

                for m in outputs:
                    db._sfm.on_add_file(
                        _fn.table_file_name(db.dbname, m.number),
                        m.file_size)
            from toplingdb_tpu.utils.listener import CompactionJobInfo, notify

            db.event_logger.log(
                "compaction_finished", input_level=c.level,
                output_level=c.output_level, device=stats.device,
                input_records=stats.input_records,
                output_records=stats.output_records,
                input_bytes=stats.input_bytes, output_bytes=stats.output_bytes,
                micros=stats.work_time_usec, reason=c.reason,
            )
            notify(db.options.listeners, "on_compaction_completed", db,
                   CompactionJobInfo(
                       db_name=db.dbname, input_level=c.level,
                       output_level=c.output_level,
                       input_files=[f.number for _, f in c.all_inputs()],
                       output_files=[m.number for m in outputs],
                       input_records=stats.input_records,
                       output_records=stats.output_records,
                       elapsed_micros=stats.work_time_usec,
                       device=stats.device, reason=c.reason,
                   ))
        finally:
            with db._mutex:
                db._pending_outputs.difference_update(pending)

    @staticmethod
    def _compaction_mode(stats) -> str:
        """serial / columnar / device / pipelined / remote / mesh — the
        trace tag the ISSUE's per-mode waterfalls key on."""
        if getattr(stats, "remote", False):
            return "remote"
        if getattr(stats, "mesh_chips", 0) > 1:
            return "mesh"
        if getattr(stats, "pipelined", False):
            return "pipelined"
        if stats.device not in ("cpu",):
            return "device"
        if getattr(stats, "host_compute_usec", 0) \
                or getattr(stats, "encode_write_usec", 0):
            return "columnar"
        return "serial"

    def _degradation_gate(self):
        if self._pin_gate is None:
            from toplingdb_tpu.compaction.resilience import (
                DcompactOptions, LocalPinGate,
            )

            policy = getattr(self.db.options, "dcompact", None) \
                or DcompactOptions()
            self._pin_gate = LocalPinGate(policy)
        return self._pin_gate

    def _run_local(self, c: Compaction, snapshots, alloc):
        from toplingdb_tpu.db.blob import maybe_new_blob_gc

        db = self.db
        return run_compaction_to_tables(
            db.env, db.dbname, db.icmp, c, db.table_cache,
            db.options.table_options_for_level(c.output_level, c.bottommost),
            snapshots,
            merge_operator=db.options.merge_operator,
            compaction_filter=db.options.compaction_filter,
            new_file_number=alloc,
            blob_resolver=db.blob_source.get,
            blob_gc=maybe_new_blob_gc(db, c, alloc),
            column_family=(c.cf_id, db.cf_name(c.cf_id)),
            max_subcompactions=db.options.max_subcompactions,
        )

    # ------------------------------------------------------------------

    def compact_range(self, begin: bytes | None, end: bytes | None) -> None:
        """Manual compaction: push overlapping files down level by level
        (reference DBImpl::CompactRange). Pauses auto scheduling while
        running so picks cannot race."""
        with self._lock:
            self._manual_active = True
        try:
            self.wait_idle()
            self._compact_range_impl(begin, end)
        finally:
            with self._lock:
                self._manual_active = False
        # The per-level loop's frame pinned the previous Version (weak-ref
        # lifetime) during the last install; sweep again now it's released.
        with self.db._mutex:
            self.db._delete_obsolete_files()
        self.maybe_schedule()

    def _compact_range_impl(self, begin: bytes | None, end: bytes | None) -> None:
        for cf_id in sorted(self.db.versions.column_families):
            self._compact_range_cf(begin, end, cf_id)

    def _compact_range_cf(self, begin: bytes | None, end: bytes | None,
                          cf_id: int) -> None:
        db = self.db
        if cf_id not in db.versions.column_families:
            return  # dropped concurrently
        version = db.versions.cf_current(cf_id)
        if db.options.compaction_style == "universal":
            self._manual_universal(cf_id)
            return
        for level in range(0, version.num_levels - 1):
            with db._mutex:
                version = db.versions.cf_current(cf_id)
                if level == 0:
                    inputs = [f for f in version.files[0]
                              if not f.quarantined]
                else:
                    inputs = [
                        f for f in version.overlapping_files(level, begin, end)
                        if not f.quarantined
                    ]
                if not inputs:
                    continue
                smallest = min((f.smallest for f in inputs), key=db.icmp.sort_key)
                largest = max((f.largest for f in inputs), key=db.icmp.sort_key)
                su = dbformat.extract_user_key(smallest)
                lu = dbformat.extract_user_key(largest)
                outputs = version.overlapping_files(level + 1, su, lu)
                c = Compaction(
                    level=level, output_level=level + 1, inputs=inputs,
                    output_level_inputs=outputs,
                    bottommost=self.picker._is_bottommost(
                        version, level + 1, smallest, largest
                    ),
                    reason="manual",
                    max_output_file_size=db.options.target_file_size(level + 1),
                    cf_id=cf_id,
                    full_history_ts_low=db.options.full_history_ts_low,
                )
                for _, f in c.all_inputs():
                    f.being_compacted = True
            try:
                self._run_compaction(c)
            finally:
                with db._mutex:
                    for _, f in c.all_inputs():
                        f.being_compacted = False

    def _manual_universal(self, cf_id: int = 0) -> None:
        db = self.db
        with db._mutex:
            version = db.versions.cf_current(cf_id)
            runs = [f for f in version.files[0] if not f.quarantined]
            last = version.num_levels - 1
            base = [f for f in version.files[last] if not f.quarantined]
            if not runs and not base:
                return
            c = Compaction(
                level=0, output_level=last, inputs=runs,
                output_level_inputs=base, bottommost=True,
                reason="manual universal", max_output_file_size=2**62,
                cf_id=cf_id,
                full_history_ts_low=db.options.full_history_ts_low,
            )
            for _, f in c.all_inputs():
                f.being_compacted = True
        try:
            self._run_compaction(c)
        finally:
            with db._mutex:
                for _, f in c.all_inputs():
                    f.being_compacted = False

