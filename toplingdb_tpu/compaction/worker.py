"""Compaction worker process: the dcompact worker analogue.

Runs one serialized compaction job from a job dir (params.json → SST outputs
+ results.json). This is the process that owns the TPU in a disaggregated
deployment: the DB process never touches JAX; the worker reads input SSTs
from shared storage, runs the device data plane, and writes outputs back
(reference: the absent topling-dcompact worker binary, whose DB-side
contract is db/compaction/compaction_executor.h in /root/reference).

Usage: python -m toplingdb_tpu.compaction.worker --job-dir DIR
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time
import traceback


def run_job(job_dir: str) -> int:
    from toplingdb_tpu.compaction.executor import CompactionParams

    t_enter = time.time()
    pjson = os.path.join(job_dir, "params.json")
    try:
        # Queue wait: params were written when the DB submitted the job
        # (reference CompactionResults::waiting_time_usec).
        waiting_usec = max(0, int((t_enter - os.path.getmtime(pjson)) * 1e6))
    except OSError:
        waiting_usec = 0
    with open(pjson) as f:
        params = CompactionParams.from_json(f.read())
    # Job lease: heartbeat the job dir while we run so the DB side (and a
    # later DB open) can tell a live job from an orphan left by a crashed
    # worker (compaction/resilience.py).
    heartbeat = None
    lease_sec = float(getattr(params, "lease_sec", 0.0) or 0.0)
    if lease_sec > 0:
        from toplingdb_tpu.compaction.resilience import HeartbeatWriter

        heartbeat = HeartbeatWriter(job_dir, lease_sec).start()
    # Cross-process trace propagation: adopt the DB side's context (when
    # it sampled this compaction), record this worker's spans locally, and
    # append them to results.json for the primary to stitch.
    from toplingdb_tpu.utils import telemetry as _tm

    ctx = getattr(params, "trace", None)
    root = None
    if ctx and ctx.get("sampled"):
        tracer = _tm.Tracer(sample_every=1, proc="dcompact-worker")
        root = tracer.start_from(ctx, "dcompact.worker",
                                 job_id=params.job_id,
                                 attempt=params.attempt,
                                 device=params.device)
    try:
        return _run_job_inner(job_dir, params, t_enter, waiting_usec)
    finally:
        if root is not None:
            tracer_ = root._tracer
            root.finish()
            _append_result_spans(job_dir,
                                 tracer_.export_trace(root.trace_id))
        if heartbeat is not None:
            heartbeat.stop()


def _run_job_inner(job_dir: str, params, t_enter: float,
                   waiting_usec: int) -> int:
    store_mode = _StoreJobMode.maybe(params)
    try:
        return _run_job_body(job_dir, params, t_enter, waiting_usec,
                             store_mode)
    finally:
        if store_mode is not None:
            store_mode.cleanup()


def _run_job_body(job_dir: str, params, t_enter: float,
                  waiting_usec: int, store_mode) -> int:
    from toplingdb_tpu.compaction.compaction_job import (
        CompactionStats, build_outputs, surviving_tombstone_fragments,
    )
    from toplingdb_tpu.compaction.executor import CompactionResults
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table.builder import TableOptions
    from toplingdb_tpu.table.factory import open_table
    from toplingdb_tpu.utils.compaction_filter import create_compaction_filter

    if os.environ.get("TPULSM_TEST_WORKER_CRASH") == "mid_job":
        # Chaos hook (resilience.DcompactFaultInjector "kill" plan): die
        # the way kill -9 does — partial output on disk, heartbeats
        # stopped, no results.json, no cleanup.
        with open(os.path.join(params.output_dir, "partial.sst"),
                  "wb") as f:
            f.write(b"\x00" * 4096)
        os._exit(137)
    t0 = time.time()
    env = default_env()
    if store_mode is not None:
        # Disaggregated mode: inputs resolve from the shared store by
        # content address into a process-local scratch dir, outputs are
        # written there and published back — the job dir (the transport)
        # carries only params/results metadata, zero SST bytes.
        env = store_mode.attach(env)
    if params.comparator == dbformat.BYTEWISE.name():
        ucmp = dbformat.BYTEWISE
    elif params.comparator == dbformat.REVERSE_BYTEWISE.name():
        ucmp = dbformat.REVERSE_BYTEWISE
    elif params.comparator == dbformat.U64_TS_BYTEWISE.name():
        # Raw ordering is plain bytewise (inverted-ts suffix encoding), so
        # the worker's merge/GC path is unchanged; the UDT history-trim
        # optimization is local-only (keeping all versions is always safe).
        ucmp = dbformat.U64_TS_BYTEWISE
    else:
        raise ValueError(f"unknown comparator {params.comparator!r}")
    icmp = dbformat.InternalKeyComparator(ucmp)
    merge_op = (
        _merge_operator_by_name(params.merge_operator)
        if params.merge_operator else None
    )
    cfilter = (
        create_compaction_filter(params.compaction_filter)
        if params.compaction_filter else None
    )
    from toplingdb_tpu.utils.table_properties_collector import (
        create_collector_factory,
    )

    from toplingdb_tpu.utils.slice_transform import slice_transform_from_name

    topts = TableOptions(
        block_size=params.block_size, compression=params.compression,
        format=getattr(params, "table_format", "block"),
        prefix_extractor=(
            slice_transform_from_name(params.prefix_extractor)
            if getattr(params, "prefix_extractor", None) else None
        ),
        properties_collector_factories=[
            create_collector_factory(d)
            for d in getattr(params, "collectors", [])
        ],
    )

    from toplingdb_tpu.db.blob import BlobSource
    from toplingdb_tpu.db.version_edit import FileMetaData

    blob_source = BlobSource(env, params.dbname)
    counter = [0]

    def alloc():
        counter[0] += 1
        return counter[0]

    device_job = params.device in ("tpu", "cpu-jax", "device")
    if device_job and ucmp.name() == dbformat.BYTEWISE.name():
        # Full data plane — the same columnar/pipelined path the in-process
        # device executor takes (ops/device_compaction.py), so the worker
        # overlaps scan/compute/encode and reports the per-phase shape
        # (input_scan/host_compute/device_wait/encode_write/stall) in
        # results.json instead of one opaque work_time.
        from toplingdb_tpu.ops.device_compaction import run_device_compaction

        readers = {}
        metas = []
        for i, path in enumerate(params.input_files, 1):
            r = open_table(env.new_random_access_file(path), icmp, topts)
            readers[i] = r
            # Real key bounds + entry counts: the columnar/pipelined plane
            # shards by them (metas built bare broke every device job into
            # the error-fallback path before this).
            it = r.new_iterator()
            it.seek_to_first()
            smallest = it.key() if it.valid() else b""
            it.seek_to_last()
            largest = it.key() if it.valid() else smallest
            metas.append(FileMetaData(
                number=i, file_size=env.get_file_size(path),
                smallest=smallest, largest=largest,
                num_entries=r.properties.num_entries,
                num_deletions=r.properties.num_deletions,
            ))
        fake_compaction = Compaction(
            level=0, output_level=params.output_level, inputs=metas,
            bottommost=params.bottommost,
            max_output_file_size=params.max_output_file_size,
        )
        outputs, stats = run_device_compaction(
            env, params.output_dir, icmp, fake_compaction,
            _PathTableCache(readers), topts, params.snapshots,
            merge_operator=merge_op, compaction_filter=cfilter,
            new_file_number=alloc, creation_time=params.creation_time,
            device_name=params.device, blob_resolver=blob_source.get,
            column_family=(getattr(params, "cf_id", 0),
                           getattr(params, "cf_name", "default")),
        )
        stats.input_files = len(params.input_files)
        stats.input_bytes = sum(
            env.get_file_size(p) for p in params.input_files)
        stats.prepare_time_usec = max(
            0, int((time.time() - t_enter) * 1e6) - stats.work_time_usec)
        stats.waiting_time_usec = waiting_usec
        from toplingdb_tpu.compaction.compaction_job import emit_phase_spans

        emit_phase_spans(stats)  # worker-side interior, under its root
        results = CompactionResults(
            status="ok",
            output_files=_encode_outputs(outputs, env, params, store_mode),
            stats=dataclasses.asdict(stats),
            work_time_usec=stats.work_time_usec,
        )
        with open(os.path.join(job_dir, "results.json"), "w") as f:
            f.write(results.to_json())
        return 0

    # Per-entry path (CPU jobs and exotic comparators): read inputs raw —
    # unsorted for the device stream, host-sorted for the CPU reference.
    from toplingdb_tpu.utils import telemetry as _tm

    entries = []
    rd = RangeDelAggregator(ucmp)
    readers_l = []
    with _tm.span("compaction.input_scan", files=len(params.input_files)):
        for path in params.input_files:
            r = open_table(env.new_random_access_file(path), icmp, topts)
            readers_l.append(r)
            it = r.new_iterator()
            it.seek_to_first()
            for k, v in it.entries():
                entries.append((k, v))
            for b, e in r.range_del_entries():
                rd.add(RangeTombstone.from_table_entry(b, e))

    stats = CompactionStats(device=params.device)
    stats.input_records = len(entries)
    stats.input_files = len(params.input_files)
    stats.input_bytes = sum(env.get_file_size(p) for p in params.input_files)
    # Setup + input scan before the merge/GC work starts (the reference's
    # prepare_time_usec, compaction_executor.h:146-150).
    stats.prepare_time_usec = int((time.time() - t_enter) * 1e6)
    stats.waiting_time_usec = waiting_usec

    fake_compaction = Compaction(
        level=0, output_level=params.output_level, inputs=[],
        bottommost=params.bottommost,
        max_output_file_size=params.max_output_file_size,
    )

    if device_job:
        from toplingdb_tpu.ops.device_compaction import device_gc_entries

        stream = device_gc_entries(
            entries, icmp, params.snapshots, params.bottommost,
            merge_operator=merge_op, compaction_filter=cfilter,
            compaction_filter_level=params.output_level,
            rd=None if rd.empty() else rd,
            blob_resolver=blob_source.get,
        )
    else:
        # CPU reference path over a host-sorted stream.
        from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator

        entries.sort(key=lambda kv: icmp.sort_key(kv[0]))
        stream = CompactionIterator(
            _ListIter(entries), icmp, params.snapshots,
            bottommost_level=params.bottommost, merge_operator=merge_op,
            compaction_filter=cfilter,
            compaction_filter_level=params.output_level,
            range_del_agg=None if rd.empty() else rd,
            blob_resolver=blob_source.get,
        ).entries()

    tombs = surviving_tombstone_fragments(
        rd, params.snapshots, params.bottommost, ucmp
    )
    with _tm.span("compaction.encode_write"):
        outputs = build_outputs(
            env, params.output_dir, icmp, fake_compaction, stream, tombs,
            alloc, topts, stats, params.creation_time,
            column_family=(getattr(params, "cf_id", 0),
                           getattr(params, "cf_name", "default")),
        )
    results = CompactionResults(
        status="ok",
        output_files=_encode_outputs(outputs, env, params, store_mode),
        stats=dataclasses.asdict(stats),
        # Disjoint from prepare: waiting + prepare + work partition the
        # worker's wall clock (reference CompactionResults fields).
        work_time_usec=max(
            0, int((time.time() - t_enter) * 1e6) - stats.prepare_time_usec),
    )
    with open(os.path.join(job_dir, "results.json"), "w") as f:
        f.write(results.to_json())
    return 0


class _StoreJobMode:
    """Disaggregated-storage job context (storage/): resolve inputs from
    the shared store by content address, publish outputs back, pin them
    until the DB side adopts. All SST bytes live in a process-local
    scratch dir torn down when the job ends — never in the job dir."""

    @staticmethod
    def maybe(params):
        return (_StoreJobMode(params) if getattr(params, "store_spec", None)
                else None)

    def __init__(self, params):
        import tempfile

        self.params = params
        self.holder = f"dcompact-job-{params.job_id}"
        self.scratch = tempfile.mkdtemp(
            prefix=f"dcompact-store-{params.job_id}-")
        self.env = None
        self.store = None

    def attach(self, base_env):
        from toplingdb_tpu.storage import SharedSstEnv, open_store

        self.store = open_store(self.params.store_spec)
        self.env = SharedSstEnv(base_env, self.store)
        out_dir = os.path.join(self.scratch, "out")
        os.makedirs(out_dir, exist_ok=True)
        local_inputs = []
        for path, addr in zip(self.params.input_files,
                              self.params.input_addrs):
            lp = os.path.join(self.scratch, os.path.basename(path))
            self.env.adopt(lp, addr)  # materializes on first open
            local_inputs.append(lp)
        self.params.input_files = local_inputs
        self.params.output_dir = out_dir
        return self.env

    def publish_output(self, env, path: str, meta) -> dict:
        """Checksum-stamp + publish one output; returns the extra keys
        the DB side needs to adopt it (address + pre-computed digest)."""
        from toplingdb_tpu.storage.object_store import address_of_meta
        from toplingdb_tpu.utils.file_checksum import (
            FileChecksumGenFactory, stamp_file_checksum,
        )

        factory = FileChecksumGenFactory(
            getattr(self.params, "checksum_func", None) or "crc32c")
        stamp_file_checksum(env, path, meta, factory)
        addr = address_of_meta(meta)
        self.store.publish_file(path, addr, src_env=env.base)
        # Pin until the DB side's adopt makes a refs-table entry (the GC
        # mark phase sees that); the TTL bounds a crashed primary.
        self.store.pin(addr, self.holder)
        return {"store_addr": addr,
                "file_checksum": meta.file_checksum.hex(),
                "file_checksum_func_name": meta.file_checksum_func_name}

    def cleanup(self):
        import shutil

        shutil.rmtree(self.scratch, ignore_errors=True)
        if self.env is not None:
            self.env.close()


def _encode_outputs(outputs, env, params, store_mode) -> list[dict]:
    from toplingdb_tpu.compaction.executor import encode_file_meta

    docs = []
    for m in outputs:
        name = f"{m.number:06d}.sst"
        d = encode_file_meta(m, name)
        if store_mode is not None:
            d.update(store_mode.publish_output(
                env, os.path.join(params.output_dir, name), m))
        docs.append(d)
    return docs


def _append_result_spans(job_dir: str, spans: list) -> None:
    """Re-open results.json and attach the worker's finished spans (the
    results were written by the job body before the tracer could close its
    root). Best-effort: a failed job has no results.json to annotate."""
    import json

    path = os.path.join(job_dir, "results.json")
    try:
        with open(path) as f:
            results = json.load(f)
        results["spans"] = spans
        with open(path, "w") as f:
            json.dump(results, f, indent=1)
    except (OSError, ValueError):
        pass


def _merge_operator_by_name(name: str):
    from toplingdb_tpu.utils.merge_operator import create_merge_operator

    return create_merge_operator(name)


class _PathTableCache:
    """TableCache-shaped view over the job's already-open input readers
    (the worker addresses inputs by path, not by live version state)."""

    def __init__(self, readers: dict):
        self._readers = readers

    def get_reader(self, number: int):
        return self._readers[number]


class _ListIter:
    def __init__(self, items):
        self._items = items
        self._i = 0

    def valid(self):
        return self._i < len(self._items)

    def key(self):
        return self._items[self._i][0]

    def value(self):
        return self._items[self._i][1]

    def next(self):
        self._i += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--job-dir", required=True)
    args = ap.parse_args(argv)
    try:
        return run_job(args.job_dir)
    except Exception as e:
        traceback.print_exc()
        try:
            from toplingdb_tpu.compaction.executor import CompactionResults

            with open(os.path.join(args.job_dir, "results.json"), "w") as f:
                f.write(CompactionResults(
                    status=f"{type(e).__name__}: {e}", output_files=[],
                    stats={},
                ).to_json())
        except OSError:
            pass
        return 1


if __name__ == "__main__":
    sys.exit(main())
