"""CompactionIterator: the streaming MVCC garbage-collection state machine.

Re-expresses the semantics of the reference's CompactionIterator
(db/compaction/compaction_iterator.cc:475 `NextFromInput` in /root/reference)
in a per-user-key group form: the merged input stream (internal-key order) is
grouped by user key; within a group (versions newest→oldest) the survivors are
decided by snapshot "stripes".

Rules (with S = sorted live snapshot seqnos, stripe(seq) = index of the first
snapshot >= seq, i.e. entries in the same stripe are indistinguishable to every
snapshot):
  * Only the NEWEST entry of each stripe can survive; older same-stripe
    entries are obsolete.
  * DELETION surviving to the bottommost level is dropped entirely when no
    older version remains visible (its job is done).
  * SINGLE_DELETION annihilates together with the single older VALUE it meets
    in the same stripe; an unmatched one is kept (unless bottommost).
  * MERGE operands fold: chain ending at VALUE → full_merge(value_base);
    chain ending at DELETION in-stripe or at group end on the bottommost
    level → full_merge(None); otherwise operands partial-merge into one
    MERGE record (keeping the newest seqno) when the operator allows, else
    pass through unchanged.
  * A point entry covered by a range tombstone with tomb_seq > seq in the
    same stripe is dropped (reference CompactionRangeDelAggregator).
  * Surviving VALUEs at the bottommost level with seq below the earliest
    snapshot get their seqno zeroed (reference's seqno zeroing).
  * The compaction filter is consulted for surviving VALUEs whose seqno is
    not protected by any snapshot.

This grouped formulation is exactly what the TPU kernel implements with
vectorized segment ops (toplingdb_tpu/ops/compaction_kernels.py); this class
is the correctness reference for it.
"""

from __future__ import annotations

import bisect

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.utils.compaction_filter import Decision
from toplingdb_tpu.utils.status import Corruption


class CompactionIterator:
    def __init__(self, input_iter, icmp, snapshots: list[int],
                 bottommost_level: bool = False, merge_operator=None,
                 compaction_filter=None, compaction_filter_level: int = 0,
                 range_del_agg=None, preserve_deletes: bool = False,
                 blob_resolver=None, full_history_ts_low: int = 0):
        self._blob_resolver = blob_resolver  # BLOB_INDEX payload → value
        self._input = input_iter
        self._icmp = icmp
        self._ucmp = icmp.user_comparator
        self._snapshots = sorted(snapshots)
        self._earliest_snapshot = (
            self._snapshots[0] if self._snapshots else dbformat.MAX_SEQUENCE_NUMBER
        )
        self._bottommost = bottommost_level
        self._merge_op = merge_operator
        self._filter = compaction_filter
        self._filter_level = compaction_filter_level
        self._rd = range_del_agg
        self._full_history_ts_low = full_history_ts_low
        # User-defined timestamps: groups are per ENCODED key (key+ts), so a
        # "group" is one VERSION of a logical key — bottommost tombstone
        # dropping must be disabled (the tombstone still shadows older-ts
        # versions living in other groups, and history below it must remain
        # readable). History reclamation happens only via the
        # full_history_ts_low trim in entries().
        self._ts_sz = getattr(self._ucmp, "timestamp_size", 0)
        # Counters (feed compaction stats; reference compaction_job stats).
        self.num_input_records = 0
        self.num_dropped_obsolete = 0
        self.num_dropped_tombstone = 0
        self.num_dropped_filtered = 0
        self.num_merged = 0
        self.num_single_del_pairs = 0

    # ------------------------------------------------------------------

    def _stripe(self, seq: int) -> int:
        """Snapshot stripe index; entries with equal stripe are invisible to
        every snapshot boundary between them."""
        return bisect.bisect_left(self._snapshots, seq)

    def _tomb_covers(self, user_key: bytes, seq: int) -> bool:
        """Covered by a newer range tombstone in the same stripe.

        The search must be BOUNDED BY THE ENTRY'S STRIPE: a covering
        tombstone above the next snapshot must not mask an in-stripe one
        (tombstones at seqs t1 < snap < t2 both covering the key: the entry
        at seq < t1 dies by t1 even though the global max is t2)."""
        if self._rd is None:
            return False
        stripe = self._stripe(seq)
        upper = (self._snapshots[stripe] if stripe < len(self._snapshots)
                 else dbformat.MAX_SEQUENCE_NUMBER)
        return self._rd.max_covering_seq(user_key, upper) > seq

    # ------------------------------------------------------------------

    def entries(self):
        """Yields surviving (internal_key, value) in internal-key order.
        With a ts comparator and full_history_ts_low set, versions below the
        trim point collapse to their newest (reference UDT history trim)."""
        ts_sz = getattr(self._ucmp, "timestamp_size", 0)
        if not (ts_sz and self._full_history_ts_low):
            yield from self._entries_impl()
            return
        low_b = dbformat.encode_ts(self._full_history_ts_low)
        prev_stripped: bytes | None = None
        # Seqno of the newest RETAINED below-low version of the current
        # logical key, or None. A below-low version behind it may only drop
        # when the retained one is visible to EVERY live seqno snapshot
        # (seq < earliest_snapshot) — otherwise a snapshot older than the
        # retained version still reads the one behind it.
        kept_seq: int | None = None
        for ikey, val in self._entries_impl():
            uk = dbformat.extract_user_key(ikey)
            stripped, tsb = uk[:-ts_sz], uk[-ts_sz:]
            if stripped != prev_stripped:
                prev_stripped = stripped
                kept_seq = None
            # Suffixes store ~ts: suffix AFTER low_b ⇔ ts < ts_low.
            if tsb > low_b:
                # Versions come newest-ts first: the first below-low one is
                # the value visible at ts_low; later ones are unreachable
                # (reads below ts_low are outside the contract) unless a
                # live snapshot cannot yet see the retained one.
                if kept_seq is not None and kept_seq < self._earliest_snapshot:
                    self.num_dropped_obsolete += 1
                    continue
                seq_e = dbformat.extract_seqno(ikey)
                t_e = dbformat.extract_value_type(ikey)
                if (self._bottommost and kept_seq is None
                        and t_e in (ValueType.DELETION,
                                    ValueType.SINGLE_DELETION)
                        and seq_e < self._earliest_snapshot):
                    # The key's visible-at-ts_low state is "deleted" and
                    # nothing lies beneath this level: the tombstone has
                    # done its job — drop it, and the kept_seq guard drops
                    # the older versions it shadowed.
                    self.num_dropped_tombstone += 1
                    kept_seq = seq_e
                    continue
                kept_seq = seq_e
            yield ikey, val

    def _entries_impl(self):
        it = self._input
        if not it.valid():
            return
        group_key: bytes | None = None
        group: list[tuple[int, int, bytes]] = []
        while it.valid():
            ikey = it.key()
            uk, seq, t = dbformat.split_internal_key(ikey)
            self.num_input_records += 1
            if group_key is None or self._ucmp.compare(uk, group_key) != 0:
                if group_key is not None:
                    yield from self._process_group(group_key, group)
                group_key = uk
                group = []
            group.append((seq, t, it.value()))
            it.next()
        if group_key is not None:
            yield from self._process_group(group_key, group)

    # ------------------------------------------------------------------

    def _process_group(self, uk: bytes, entries: list[tuple[int, int, bytes]]):
        """entries: newest→oldest versions of one user key."""
        survivors: list[tuple[int, int, bytes]] = []
        i = 0
        n = len(entries)
        last_stripe = None
        pending_single_del: tuple[int, int, bytes] | None = None
        while i < n:
            seq, t, val = entries[i]
            stripe = self._stripe(seq)
            # Single-delete annihilation must precede the obsolete check: the
            # matching VALUE is in the same stripe by construction.
            if pending_single_del is not None:
                sd_seq, _, _ = pending_single_del
                if self._stripe(sd_seq) == stripe and t in (
                        ValueType.VALUE, ValueType.WIDE_COLUMN_ENTITY):
                    # Annihilate the pair (reference single-delete semantics).
                    self.num_single_del_pairs += 1
                    pending_single_del = None
                    last_stripe = stripe
                    i += 1
                    continue
                survivors.append(pending_single_del)
                pending_single_del = None
            # Obsolete: an entry in a stripe already served by a newer entry.
            if last_stripe is not None and stripe == last_stripe:
                self.num_dropped_obsolete += 1
                i += 1
                continue
            # Range tombstone coverage.
            if self._tomb_covers(uk, seq):
                self.num_dropped_tombstone += 1
                i += 1
                # The covered entry is deleted; the tombstone now represents
                # this stripe, so older same-stripe entries are obsolete.
                last_stripe = stripe
                continue
            if t == ValueType.MERGE:
                emitted, consumed, newest_stripe = self._fold_merge(uk, entries, i)
                survivors.extend(emitted)
                i += consumed
                last_stripe = newest_stripe
                continue
            if t == ValueType.SINGLE_DELETION:
                pending_single_del = (seq, t, val)
                last_stripe = stripe
                i += 1
                continue
            if t == ValueType.DELETION:
                if self._ts_sz or not (self._bottommost and stripe == 0):
                    survivors.append((seq, t, val))
                else:
                    self.num_dropped_tombstone += 1
                last_stripe = stripe
                i += 1
                continue
            if t in (ValueType.VALUE, ValueType.BLOB_INDEX,
                     ValueType.WIDE_COLUMN_ENTITY):
                survivors.append((seq, t, val))
                last_stripe = stripe
                i += 1
                continue
            raise Corruption(f"unexpected type {t} in compaction input")
        if pending_single_del is not None:
            sd_seq, sd_t, sd_v = pending_single_del
            if self._ts_sz or not (self._bottommost
                                   and self._stripe(sd_seq) == 0):
                survivors.append(pending_single_del)
            else:
                self.num_dropped_tombstone += 1

        # Compaction filter + seqno zeroing on the final survivors.
        out: list[tuple[int, int, bytes]] = []
        for seq, t, val in survivors:
            if (self._filter is not None and t == ValueType.VALUE
                    and seq <= self._earliest_snapshot):
                d, newv = self._filter.filter(self._filter_level, uk, val)
                if d == Decision.REMOVE:
                    self.num_dropped_filtered += 1
                    continue
                if d == Decision.CHANGE_VALUE:
                    val = newv if newv is not None else b""
            if (self._bottommost and t == ValueType.VALUE
                    and seq <= self._earliest_snapshot):
                seq = 0
            out.append((seq, t, val))
        for seq, t, val in out:
            yield dbformat.make_internal_key(uk, seq, t), val

    def _fold_merge(self, uk: bytes, entries, i: int):
        """Fold a run of MERGE operands starting at entries[i].
        Returns (emitted_entries, consumed_count, newest_stripe)."""
        newest_seq, _, _ = entries[i]
        newest_stripe = self._stripe(newest_seq)
        operands: list[bytes] = []  # newest→oldest
        j = i
        n = len(entries)
        # Collect operands in the same stripe chain. Operands in OLDER stripes
        # must stay separate (a snapshot could observe the partial chain).
        while j < n:
            seq, t, val = entries[j]
            if t != ValueType.MERGE or self._stripe(seq) != newest_stripe:
                break
            if self._tomb_covers(uk, seq):
                # Tombstone cuts the chain: operands below it are dead.
                j += 1
                while j < n and self._stripe(entries[j][0]) == newest_stripe:
                    self.num_dropped_obsolete += 1
                    j += 1
                if self._merge_op is None:
                    raise Corruption("merge entries but no merge_operator")
                v = self._merge_op.full_merge(uk, None, list(reversed(operands)))
                self.num_merged += 1
                return [(newest_seq, ValueType.VALUE, v)], j - i, newest_stripe
            operands.append(val)
            j += 1
        if self._merge_op is None:
            raise Corruption("merge entries but no merge_operator")
        # What terminated the chain?
        if j < n and self._stripe(entries[j][0]) == newest_stripe:
            seq, t, val = entries[j]
            if t in (ValueType.VALUE, ValueType.BLOB_INDEX,
                     ValueType.WIDE_COLUMN_ENTITY):
                if t == ValueType.BLOB_INDEX:
                    # The merge base lives in a blob file: fold the REAL
                    # value, never the raw index bytes.
                    if self._blob_resolver is None:
                        raise Corruption(
                            "merge over a blob value but no blob resolver"
                        )
                    val = self._blob_resolver(val)
                ops = list(reversed(operands))
                if t == ValueType.WIDE_COLUMN_ENTITY:
                    # Entity base: fold against the DEFAULT column, emit
                    # the entity back (reference MergeHelper over
                    # kTypeWideColumnEntity / wide_columns_helper).
                    from toplingdb_tpu.db.wide_columns import (
                        merge_into_entity,
                    )

                    v = merge_into_entity(
                        val,
                        lambda b: self._merge_op.full_merge(uk, b, ops))
                    out_t = ValueType.WIDE_COLUMN_ENTITY
                else:
                    v = self._merge_op.full_merge(uk, val, ops)
                    out_t = ValueType.VALUE
                self.num_merged += 1
                # Consume the base too; skip the rest of the stripe.
                j += 1
                while j < n and self._stripe(entries[j][0]) == newest_stripe:
                    self.num_dropped_obsolete += 1
                    j += 1
                return [(newest_seq, out_t, v)], j - i, newest_stripe
            if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
                v = self._merge_op.full_merge(uk, None, list(reversed(operands)))
                self.num_merged += 1
                j += 1
                while j < n and self._stripe(entries[j][0]) == newest_stripe:
                    self.num_dropped_obsolete += 1
                    j += 1
                return [(newest_seq, ValueType.VALUE, v)], j - i, newest_stripe
        # Chain ran to the end of the visible group (or into an older stripe).
        if j >= n and self._bottommost:
            # Nothing older can exist anywhere: safe to finalize.
            v = self._merge_op.full_merge(uk, None, list(reversed(operands)))
            self.num_merged += 1
            return [(newest_seq, ValueType.VALUE, v)], j - i, newest_stripe
        # Cannot finalize: combine with partial_merge when possible.
        if len(operands) > 1:
            combined = operands[-1]
            ok = True
            for op in reversed(operands[:-1]):  # fold oldest→newest
                r = self._merge_op.partial_merge(uk, combined, op)
                if r is None:
                    ok = False
                    break
                combined = r
            if ok:
                self.num_merged += 1
                return [(newest_seq, ValueType.MERGE, combined)], j - i, newest_stripe
        return (
            [(s, t, v) for s, t, v in entries[i:j]],
            j - i,
            newest_stripe,
        )
