"""DB and column-family options.

Condensed analogue of the reference's DBOptions/ColumnFamilyOptions
(include/rocksdb/options.h in /root/reference), keeping the fields the engine
actually consults. Construction-from-JSON lives in utils/config.py (the
SidePlugin-equivalent layer).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from toplingdb_tpu.db.dbformat import BYTEWISE, Comparator
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import TableOptions


@dataclass
class Options:
    # -- DB behavior ----------------------------------------------------
    create_if_missing: bool = True
    error_if_exists: bool = False
    paranoid_checks: bool = True
    read_only: bool = False             # set by ReadOnlyDB/SecondaryDB.open
    comparator: Comparator = field(default_factory=lambda: BYTEWISE)
    merge_operator: Any = None          # MergeOperator instance or None
    compaction_filter: Any = None
    # SliceTransform (utils/slice_transform.py) or None (reference
    # ColumnFamilyOptions.prefix_extractor): enables prefix bloom filters,
    # the 'plain' table format's prefix hash index, and
    # ReadOptions.prefix_same_as_start iteration. Propagated into
    # table_options at open.
    prefix_extractor: Any = None

    # -- write path -----------------------------------------------------
    memtable_rep: str = "skiplist"       # 'skiplist' (native C++) | 'vector'
    write_buffer_size: int = 4 * 1024 * 1024
    max_write_buffer_number: int = 2
    db_write_buffer_size: int = 0       # 0 = unlimited (WriteBufferManager)
    wal_enabled: bool = True
    # Group members insert their own batches into the (lock-free native)
    # memtable in parallel (reference allow_concurrent_memtable_write,
    # db/db_impl/db_impl_write.cc:550 LaunchParallelMemTableWriters).
    allow_concurrent_memtable_write: bool = True
    # Overlap group N+1's WAL append with group N's memtable insert
    # (reference enable_pipelined_write, db_impl_write.cc:657
    # PipelinedWriteImpl). Publish order is preserved.
    enable_pipelined_write: bool = False
    # Relax write ordering: seqno allocation + WAL stay ordered, memtable
    # inserts run unordered in each writer's thread; visibility advances as
    # a low watermark and GetSnapshot drains pending writes (reference
    # unordered_write, db_impl_write.cc:267-301 WriteImplWALOnly).
    unordered_write: bool = False
    # Async WAL writer (env/env.py AsyncIORing): WAL appends/fsyncs run on
    # a dedicated writer thread behind a bounded submit ring, the leader
    # waits on its durability barrier AFTER the memtable phase (outside
    # the commit critical section), and concurrent leaders' sync=True
    # barriers coalesce into shared fsyncs. A write is still acknowledged
    # only after its barrier settles; ordering relaxation: a barrier
    # FAILURE after the memtable insert latches a HARD background error
    # (writes raise until resume()) instead of preceding the insert.
    enable_async_wal: bool = False
    # Submit-ring capacity (entries) of the async WAL writer.
    async_wal_ring_size: int = 256
    # Async read plane (env/async_reads.py AsyncReadBatcher, engaged by
    # TPULSM_ASYNC_READS=1): number of reader rings — dedicated I/O
    # threads the batched block fetches fan out across. os.pread drops
    # the GIL, so N rings genuinely overlap a cold-cache miss storm.
    async_read_rings: int = 4
    # Per-reader-ring cap on queued read tasks (separate from the append
    # capacity so a miss storm cannot starve WAL appends).
    async_read_task_capacity: int = 256

    # -- LSM shape ------------------------------------------------------
    num_levels: int = 7
    level0_file_num_compaction_trigger: int = 4
    level0_slowdown_writes_trigger: int = 20
    level0_stop_writes_trigger: int = 36
    max_bytes_for_level_base: int = 64 * 1024 * 1024
    max_bytes_for_level_multiplier: float = 10.0
    target_file_size_base: int = 8 * 1024 * 1024
    target_file_size_multiplier: int = 1
    max_compaction_bytes: int = 25 * 8 * 1024 * 1024
    compaction_style: str = "leveled"   # leveled | universal | fifo

    # universal compaction knobs (reference universal_compaction.h)
    universal_size_ratio: int = 1
    universal_min_merge_width: int = 2
    universal_max_merge_width: int = 2**31 - 1
    universal_max_size_amplification_percent: int = 200

    # fifo knobs
    fifo_max_table_files_size: int = 1024 * 1024 * 1024
    # Drop FIFO files older than this (reference CompactionOptionsFIFO.ttl;
    # 0 = off).
    fifo_ttl_seconds: int = 0
    # Rewrite any file older than this so old data keeps moving down and
    # expired-data filters re-run (reference periodic_compaction_seconds;
    # 0 = off; leveled style only — FIFO ages out via fifo_ttl_seconds).
    periodic_compaction_seconds: int = 0

    # User-defined timestamps: versions with ts below this trim point
    # collapse to the newest one at compaction (reference
    # full_history_ts_low; DB.increase_full_history_ts_low raises it).
    # Only meaningful with a ts-carrying comparator. 0 = keep full history.
    full_history_ts_low: int = 0

    # -- background work ------------------------------------------------
    max_background_jobs: int = 2
    max_subcompactions: int = 1
    disable_auto_compactions: bool = False

    # -- blob files (key-value separation, reference db/blob/) ----------
    enable_blob_files: bool = False
    min_blob_size: int = 256
    # Compaction-time blob GC: rewrite survivors out of the oldest
    # `age_cutoff` fraction of referenced blob files (reference
    # enable_blob_garbage_collection / blob_garbage_collection_age_cutoff).
    enable_blob_garbage_collection: bool = False
    blob_garbage_collection_age_cutoff: float = 0.25
    # Blob VALUE cache (reference blob_cache option + BlobSource tier,
    # db/blob/blob_source.h): a utils.cache.Cache instance, or an int
    # capacity in bytes (an LRUCache is built), or None (no caching —
    # every Get re-reads the blob file).
    blob_cache: object | None = None
    # Cap on concurrently OPEN blob file readers (reference
    # blob_file_cache.cc holds readers in a capacity-bounded cache).
    blob_file_open_limit: int = 256

    # -- wide columns ---------------------------------------------------
    # Entities carry the dedicated kTypeWideColumnEntity-style value type;
    # this gate re-enables the pre-type magic-prefix sniff for databases
    # written by older versions (plain binary values starting with
    # \x00WCE1 would otherwise present as entities on those DBs).
    legacy_wide_column_unwrap: bool = False

    # -- observability --------------------------------------------------
    # Periodic ticker snapshots for DB.get_stats_history (reference
    # stats_persist_period_sec; 0 = manual persist_stats() only).
    stats_persist_period_sec: int = 0
    # Periodic stats DUMP (reference stats_dump_period_sec): snapshots the
    # tickers into the stats-history ring AND logs a compact `stats_dump`
    # line through the event log every N seconds. Served over HTTP at
    # /stats_history/<name>?window=S. 0 = off.
    stats_dump_period_sec: int = 0
    # Request-scoped span tracing (utils/telemetry.py): sample one DB
    # operation in N as a full span tree (1 = every op, 0 = off). Rare
    # high-value ops (flush, compaction) are always traced while a tracer
    # exists. Finished traces land in a bounded ring served at
    # /traces/<name>; remote spans (dcompact workers, replication
    # followers) stitch into the same trace.
    trace_sample_every: int = 0
    # Always-sample latency backstop: an op slower than this many µs
    # leaves a (root-only) trace even when the sampler skipped it. 0 = off.
    trace_slow_usec: int = 0
    # Bound on retained finished traces (and the remote-stitch index).
    trace_ring: int = 256
    # Health plane (utils/slo.py). Windowed-histogram ring span: every
    # `*.micros` histogram keeps, besides the cumulative series, a ring
    # of per-interval histograms covering the trailing
    # histogram_window_sec seconds, exposed as `*_recent` quantiles on
    # /metrics. 0 = cumulative-only histograms (no ring).
    histogram_window_sec: float = 60.0
    # Declarative SLO specs: a list/tuple of slo.SLOSpec (or dicts with
    # the same fields) evaluated with multi-window burn-rate alerting.
    # Empty = no SLO engine.
    slo_specs: tuple = ()
    # Background SLO evaluation cadence (0 = manual db.slo_engine
    # .evaluate() only — tests and embedders drive it by hand).
    slo_eval_period_sec: float = 0.0
    # Default fast window for specs that don't set their own; the slow
    # window defaults to 5x this.
    slo_window_sec: float = 60.0
    # Sampling cadence of the seqno↔time mapping (reference
    # seqno_to_time_mapping recording period).
    seqno_time_sample_period_sec: int = 60
    # Data written within this many seconds must not receive LAST-LEVEL
    # TREATMENT (reference preclude_last_level_data_seconds, the
    # tiered/temperature seam the seqno↔time mapping exists for). Design
    # difference from the reference: instead of splitting outputs to the
    # penultimate level per key, a bottommost job with young inputs keeps
    # full MVCC semantics (no seqno zeroing / tombstone dropping) and the
    # last-level treatment happens on a later compaction once aged —
    # placement is unchanged.
    preclude_last_level_data_seconds: int = 0

    # Cross-DB memtable memory budget (utils.rate_limiter.WriteBufferManager;
    # reference write_buffer_manager.h:37). Shared between DB instances;
    # over budget, writers flush their memtables early.
    write_buffer_manager: Optional[object] = None

    # -- storage pressure -----------------------------------------------
    # Shared utils.rate_limiter.SstFileManager instance, or None to have
    # DB.open build a private one when any pressure knob below is set
    # (reference NewSstFileManager). Tracks live SST+WAL+blob bytes,
    # paces trash deletion, and publishes the ok/amber/red pressure level.
    sst_file_manager: Optional[object] = None
    # Hard byte budget for the DB's tracked tree (reference
    # SstFileManager::SetMaxAllowedSpaceUsage). 0 = unlimited. A flush or
    # compaction whose estimated output would breach it refuses to start;
    # an actual breach latches a retryable SOFT "no_space" background
    # error that auto-resumes once space frees.
    max_allowed_space_usage: int = 0
    # Slack compactions must leave under the budget (reference
    # SetCompactionBufferSize): a compaction may only start if
    # used + estimated_output + buffer + flush headroom fits.
    compaction_buffer_size: int = 0
    # Bytes reserved for flush+WAL so ingest can always drain even at red
    # pressure (flushes may consume this slice; compactions may not).
    # 0 = auto: 2x write_buffer_size whenever a budget is set.
    flush_headroom_bytes: int = 0
    # Free-space poller cadence (reference SetStatsDumpPeriodSec analogue
    # for the space poller). 0 = no poller thread; pressure only updates
    # when something calls SstFileManager.poll() explicitly.
    free_space_poll_period_sec: float = 0.0
    # Pressure thresholds on the free fraction (min of budget-remaining
    # fraction and filesystem-free fraction): <= red → "red",
    # <= amber → "amber". De-escalation requires clearing the threshold
    # by the hysteresis margin so the level never flaps.
    disk_amber_free_ratio: float = 0.10
    disk_red_free_ratio: float = 0.05
    disk_pressure_hysteresis: float = 0.02

    # -- caches ---------------------------------------------------------
    # Shared block cache (utils.cache.LRUCache; optionally backed by a
    # utils.persistent_cache.PersistentCache secondary tier). None = the
    # reader's per-file behavior without a shared cache.
    block_cache: Optional[object] = None

    # -- table format ---------------------------------------------------
    table_options: TableOptions = field(default_factory=TableOptions)
    compression: int = fmt.NO_COMPRESSION
    bottommost_compression: Optional[int] = None
    # Per-level codec list (reference ColumnFamilyOptions::compression_per_level,
    # include/rocksdb/options.h): levels past the end reuse the last entry;
    # empty = `compression` (or table_options.compression).
    compression_per_level: list = field(default_factory=list)
    # SST format for bottommost-level outputs (e.g. "zip": the
    # searchable-compression ZipTable — the reference's ToplingZipTable
    # L2+ role, README.md:50-56). None = table_options.format everywhere.
    bottommost_format: Optional[str] = None

    # -- WAL lifecycle --------------------------------------------------
    # Keep up to N obsolete WAL files for reuse (reference
    # recycle_log_file_num, include/rocksdb/options.h:795): new WALs
    # overwrite a recycled file in place (recyclable record format stamps
    # each record with its log number, so the stale tail is inert).
    recycle_log_file_num: int = 0
    # Archive obsolete WALs under <db>/archive/ for this long instead of
    # deleting them (reference WAL_ttl_seconds / WalManager retention).
    wal_ttl_seconds: float = 0.0

    # -- distributed compaction (the dcompact boundary) -----------------
    compaction_executor_factory: Any = None  # CompactionExecutorFactory
    # Failure policy around the boundary: per-attempt retry with backoff +
    # jitter, per-job deadline, circuit-breaker thresholds, local-pin
    # degradation, and the job-lease duration (compaction/resilience.py).
    # JSON-configurable under the "dcompact" key (utils/config.py).
    dcompact: Any = None  # DcompactOptions; None = defaults, lazily built

    # -- disaggregated SST storage (toplingdb_tpu/storage/) -------------
    # Content-addressed shared object store for SSTs, keyed by the
    # MANIFEST-recorded whole-file checksums (requires file_checksum on).
    # A filesystem path selects the local-directory backend, an http://
    # URL a StoreServer, a store-shaped object passes through; None/""/"0"
    # keeps the classic local-files path (the byte-parity oracle).
    # Env var TPULSM_SHARED_STORE overrides at DB.open. When enabled the
    # DB env is wrapped in SharedSstEnv: tables publish on install, live
    # thereafter as references, and re-materialize through the persistent
    # cache tier on first read. See ARCHITECTURE.md "Disaggregated SST
    # storage".
    shared_store: Any = None

    # -- integrity plane (utils/protection.py, utils/file_checksum.py,
    # db/integrity.py) ---------------------------------------------------
    # Per-KV protection info (reference protection_bytes_per_key,
    # include/rocksdb/options.h + db/kv_checksum.h): 8/4/2/1-byte per-entry
    # checksums computed in WriteBatch, carried through the memtable, and
    # verified at every handoff (memtable insert, flush emission,
    # compaction output emission in the serial AND columnar/pipelined
    # planes, scan-plane chunk emission). 0 = off.
    protection_bytes_per_key: int = 0
    # Whole-file checksum function recorded per SST in the MANIFEST
    # (reference file_checksum_gen_factory): 'crc32c' (default) or
    # 'xxh64'; None/'off' disables. Verified by DB.verify_file_checksums,
    # checkpoint/backup/import/follower-bootstrap, and the scrubber.
    file_checksum: Optional[str] = "crc32c"
    # Background IntegrityScrubber cadence: re-read live SSTs from disk
    # and compare against MANIFEST checksums every N seconds (0 = manual
    # db.scrub() only), paced at integrity_scrub_bytes_per_sec.
    integrity_scrub_period_sec: int = 0
    integrity_scrub_bytes_per_sec: int = 32 * 1024 * 1024

    # -- observability --------------------------------------------------
    statistics: Any = None
    listeners: list = field(default_factory=list)
    info_log: Any = None

    def max_bytes_for_level(self, level: int) -> int:
        """Target size of level L (L>=1)."""
        base = self.max_bytes_for_level_base
        mult = self.max_bytes_for_level_multiplier
        size = base
        for _ in range(1, level):
            size = int(size * mult)
        return size

    def target_file_size(self, level: int) -> int:
        size = self.target_file_size_base
        for _ in range(1, max(1, level)):
            size *= self.target_file_size_multiplier
        return size

    def compression_for_level(self, level: int,
                              bottommost: bool = False) -> int:
        """Effective codec for an output level (reference
        Compaction::GetCompressionType: bottommost_compression wins at the
        last level, then compression_per_level, then the base codec)."""
        if bottommost and self.bottommost_compression is not None:
            return self.bottommost_compression
        if self.compression_per_level:
            idx = min(level, len(self.compression_per_level) - 1)
            return self.compression_per_level[idx]
        if self.compression != fmt.NO_COMPRESSION:
            return self.compression
        return self.table_options.compression

    def table_options_for_level(self, level: int, bottommost: bool = False):
        """table_options with the per-level codec and bottommost format
        applied (identity when nothing level-specific is configured)."""
        eff = self.compression_for_level(level, bottommost)
        fmt_ = self.table_options.format
        if bottommost and self.bottommost_format is not None:
            fmt_ = self.bottommost_format
        if eff == self.table_options.compression \
                and fmt_ == self.table_options.format:
            return self.table_options
        import dataclasses

        return dataclasses.replace(self.table_options, compression=eff,
                                   format=fmt_)


@dataclass
class ReadOptions:
    verify_checksums: bool = True
    snapshot: Any = None                # Snapshot object or None
    fill_cache: bool = True
    iterate_lower_bound: Optional[bytes] = None
    iterate_upper_bound: Optional[bytes] = None
    # Topling extension analogue: return existence without copying the value
    # (reference include/rocksdb/options.h:1637 just_check_key_exists).
    just_check_key_exists: bool = False
    # Fiber/io_uring MultiGet analogue (reference db_impl.cc:3026-3227 +
    # options.h:1723 async_queue_depth): memtable misses walk their SST
    # chains on parallel threads (pread releases the GIL).
    async_io: bool = False
    async_queue_depth: int = 8
    # Prefix-mode iteration (reference ReadOptions.prefix_same_as_start):
    # an iterator becomes invalid once it leaves the prefix group of its
    # Seek target (requires Options.prefix_extractor).
    prefix_same_as_start: bool = False
    # Escape hatch (reference total_order_seek): ignore prefix mode for this
    # read even when prefix_same_as_start defaults have been configured.
    total_order_seek: bool = False
    # Tailing iterator (reference ReadOptions.tailing → ForwardIterator,
    # db/forward_iterator.cc): forward-only, sees new writes after catching
    # up at end-of-data; incompatible with `snapshot`.
    tailing: bool = False
    # Iterator prefetch window in bytes (reference
    # ReadOptions.readahead_size): a fixed, immediately-armed
    # FilePrefetchBuffer window for table iteration. 0 = auto-scaling
    # (double on sequential reads, reset on seek).
    readahead_size: int = 0
    # User-defined timestamp to read AS OF (reference ReadOptions.timestamp,
    # the TOPLINGDB_WITH_TIMESTAMP feature): only versions with ts <= this
    # are visible. Requires a timestamp-carrying comparator. None = latest.
    timestamp: Optional[int] = None


@dataclass
class WriteOptions:
    sync: bool = False
    disable_wal: bool = False


@dataclass
class FlushOptions:
    wait: bool = True
