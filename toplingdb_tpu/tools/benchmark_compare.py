"""Compare two benchmark result files; fail on regression.

The analogue of the reference's tools/benchmark_compare.sh +
regression_test.sh (/root/reference): given a BASELINE results JSON and a
NEW one (both from tools/benchmark.py), print a per-workload ratio table
and exit nonzero when any workload's throughput fell below
threshold * baseline — the CI regression gate.
"""

from __future__ import annotations

import argparse
import json


def compare(base: dict, new: dict, threshold: float) -> tuple[list, bool]:
    base_by = {r["name"]: r for r in base["results"]}
    new_names = {r["name"] for r in new["results"]}
    rows = []
    regressed = False
    for r in new["results"]:
        b = base_by.get(r["name"])
        if b is None or not b["ops_per_sec"]:
            rows.append((r["name"], None, r["ops_per_sec"], None, ""))
            continue
        ratio = r["ops_per_sec"] / b["ops_per_sec"]
        flag = ""
        if ratio < threshold:
            flag = "REGRESSION"
            regressed = True
        elif ratio > 1 / threshold:
            flag = "improved"
        rows.append((r["name"], b["ops_per_sec"], r["ops_per_sec"],
                     ratio, flag))
    # A workload that vanished from the new run (crash, rename, empty suite)
    # is the failure the gate exists to catch, not a pass.
    for name, b in base_by.items():
        if name not in new_names:
            rows.append((name, b["ops_per_sec"], 0.0, 0.0, "MISSING"))
            regressed = True
    return rows, regressed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("baseline")
    ap.add_argument("new")
    ap.add_argument("--threshold", type=float, default=0.85,
                    help="fail when new < threshold * baseline ops/sec")
    args = ap.parse_args(argv)
    with open(args.baseline) as f:
        base = json.load(f)
    with open(args.new) as f:
        new = json.load(f)
    rows, regressed = compare(base, new, args.threshold)
    print(f"{'workload':<24} {'baseline':>12} {'new':>12} {'ratio':>7}")
    for name, b, n, ratio, flag in rows:
        bs = f"{b:12.0f}" if b is not None else f"{'(new)':>12}"
        rs = f"{ratio:7.2f}" if ratio is not None else f"{'-':>7}"
        print(f"{name:<24} {bs} {n:12.0f} {rs} {flag}")
    if regressed:
        print(f"FAILED: regression below {args.threshold:.0%} of baseline")
        return 1
    print("OK: no regressions")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
