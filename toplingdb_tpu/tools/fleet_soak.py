"""fleet_soak: the multi-process chaos soak (ISSUE 16's robustness bar).

Stands up a REAL fleet — a lease-coordinator process and one ShardServer
process per shard — drives seeded concurrent writers through a
FleetRouter, and runs the four chaos scenarios while they write:

  migrate-kill   kill -9 the s0 source process exactly at the fence
                 phase of a cross-process migration, then recover it via
                 the supervisor (respawn + /fleet/recover across the
                 process boundary; the half-built dest is discarded).
  partition      cut a second router off from the lease store with an
                 env/fault_injection.PartitionGate for longer than its
                 map lease: every write must fail CLOSED (Busy) — the
                 router may never route on topology it cannot re-validate.
  coordinator    kill -9 the coordinator and restart it from its durable
                 log on the same port: existing leases stay binding,
                 renewals resume, and fencing tokens keep strictly
                 increasing (double-grant impossibility across restart).
  stale-epoch    migrate s1 for real, then replay a write stamped with
                 the PRE-migration epoch at the new primary: it must be
                 rejected 409 and counted (`fleet.stale.epoch.rejects`),
                 never applied.

Oracle: writers record a key only once its write is ACKED; values are a
pure function of the key, so the ack-lost-then-retried case is
idempotent. At the end the fleet must satisfy merged-oracle parity —
`FleetRouter.scan()` yields exactly the acked key set, each key once
(zero lost, zero double-served) — and every server must report zero
writes accepted under an expired lease or stale epoch, then shut down
cleanly (SIGTERM → fence/drain/flush/close → exit 0).

    python -m toplingdb_tpu.tools.fleet_soak --dir /dev/shm/soak --fast
    python -m toplingdb_tpu.tools.fleet_soak --dir ... --seed 7 --full

Fast mode (~20s) is the tier-1 registration (tests/test_fleet.py); the
full soak adds more keys, rounds and a second migrate-kill pass.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import random
import shutil
import signal
import sys
import time
import urllib.error
import urllib.request

from toplingdb_tpu.env.fault_injection import PartitionGate
from toplingdb_tpu.sharding.fleet import (
    FleetRouter,
    FleetSupervisor,
    _http_json,
)
from toplingdb_tpu.sharding.lease import LeaseClient
from toplingdb_tpu.sharding.shard_map import ShardMap
from toplingdb_tpu.utils import errors as _errors
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import Busy, IOError_

SPLIT_KEY = b"%016d" % 500_000  # digit keyspace: half to s0, half to s1


class SoakFailure(AssertionError):
    """A chaos invariant did not hold."""


def _check(cond: bool, what: str) -> None:
    if not cond:
        raise SoakFailure(what)


class _Writer:
    """One seeded writer with a private key slice. A key is recorded in
    `acked` only after a successful ack; values derive from the key, so
    retrying an ack-lost write is idempotent."""

    def __init__(self, wid: int, router: FleetRouter, seed: int,
                 keyspace: int):
        self.wid = wid
        self.router = router
        self.rng = random.Random(seed * 1000003 + wid)
        self.keyspace = keyspace
        self.acked: dict[bytes, bytes] = {}
        self.rejects = 0
        self.stop = False
        self.error: Exception | None = None

    def _one_key(self) -> bytes:
        # Slice by writer id so oracles merge without conflicts.
        n = self.rng.randrange(self.keyspace) * 10 + self.wid
        return b"%016d" % n

    def run(self) -> None:
        try:
            while not self.stop:
                k = self._one_key()
                v = b"v-" + k
                try:
                    self.router.put(k, v)
                except (Busy, IOError_, OSError):
                    # Fence/failover/partition in progress: the write was
                    # refused (fail-closed) — NOT acked, NOT recorded.
                    self.rejects += 1
                    time.sleep(0.02)
                    continue
                self.acked[k] = v
        except Exception as e:  # noqa: BLE001 - soak verdict, re-raised
            self.error = e


def _post_raw(url: str, path: str, body: dict):
    req = urllib.request.Request(
        url + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def _sync_placement(sup: FleetSupervisor) -> None:
    doc = sup.coordinator.get_map()
    placement = {m.shard: m.url for m in sup.members.values()
                 if m.role == "primary"}
    sup.coordinator.cas_map(doc["version"], doc["map"], placement)


def _scenario_migrate_kill(sup, base_dir, log) -> None:
    """kill -9 the s0 source at the fence phase; recover across the
    process boundary; the shard serves again on its OLD epoch."""
    def bomb(phase):
        if phase == "fence":
            src = next(m for m in sup.members.values()
                       if m.shard == "s0" and m.role == "primary")
            src.proc.send_signal(signal.SIGKILL)
            src.proc.wait()
    try:
        sup.migrate("s0", os.path.join(base_dir, "s0-doomed"),
                    fault_hook=bomb)
        raise SoakFailure("migration survived kill -9 of its source")
    except SoakFailure:
        raise
    except Exception as e:  # the kill lands as transport chaos
        _errors.swallow(reason="soak-migrate-kill-expected", exc=e)
    src = sup.recover_migration("s0")
    _sync_placement(sup)
    st = _http_json(src.url, "/fleet/status", timeout=10)
    _check(not st.get("fenced", True),
           "recovered source still fenced after /fleet/recover")
    _check(not os.path.exists(os.path.join(base_dir, "s0-doomed")),
           "half-built migration dest not discarded")
    log("migrate-kill: source killed at fence, recovered, serving again")


def _scenario_partition(co_url, stats, oracle, log) -> None:
    """A router partitioned from the lease store past its map lease must
    fail writes CLOSED, and heal transparently."""
    gate = PartitionGate()
    client = LeaseClient(co_url, timeout=2.0, partition=gate)
    router = FleetRouter(client, statistics=stats, map_lease=0.25,
                         write_deadline=2.0)
    k = b"%016d" % 17  # last digit outside every writer's slice
    router.put(k, b"pre-partition")  # healthy path first
    gate.engage()
    time.sleep(0.35)  # let the map lease lapse while partitioned
    try:
        router.put(k, b"under-partition")
        raise SoakFailure("write routed on stale topology while "
                          "partitioned from the lease store")
    except Busy:
        pass
    _check(gate.blocked > 0, "partition gate never intercepted a call")
    _check(stats.get_ticker_count("fleet.write.rejects") > 0,
           "fail-closed reject not counted in fleet.write.rejects")
    gate.heal()
    router.put(k, b"post-partition")
    oracle[k] = b"post-partition"
    log(f"partition: fail-closed Busy while cut off "
        f"({gate.blocked} calls blocked), healed")


def _scenario_coordinator_crash(sup, cop, co_port, lease_log, ttl, log):
    """kill -9 the coordinator; restart from its durable log on the same
    port. Leases stay binding, tokens keep strictly increasing."""
    before = sup.coordinator.status()
    tok_floor = before["next_token"]
    held = {s: l["token"] for s, l in before["leases"].items()}
    cop.send_signal(signal.SIGKILL)
    cop.wait()
    cop2, url2 = FleetSupervisor.start_coordinator(
        lease_log, port=co_port, ttl=ttl)
    after = sup.coordinator.status()  # same port → same client works
    _check(after["next_token"] >= tok_floor,
           f"fencing tokens regressed across restart: "
           f"{after['next_token']} < {tok_floor}")
    for s, t in held.items():
        l = after["leases"].get(s)
        _check(l is not None and l["token"] == t,
               f"lease for {s} not honoured after coordinator restart")
    # Renewals must resume: wait one heartbeat period and re-read.
    deadline = time.monotonic() + ttl * 3
    while True:
        cur = sup.coordinator.status()["leases"]
        if all(cur.get(s, {}).get("remaining", -1) > 0 for s in held):
            break
        _check(time.monotonic() < deadline,
               "heartbeat renewals did not resume after restart")
        time.sleep(0.1)
    log(f"coordinator: crashed + replayed {len(held)} leases from log, "
        f"renewals resumed, tokens monotonic")
    return cop2, url2


def _scenario_stale_epoch(sup, router, log) -> None:
    """Migrate s1 for real, then replay a write stamped with the OLD
    epoch: the new primary must 409 it and count the reject."""
    with router._mu:
        old_epoch = router.map.epoch_of("s1")
    dest = sup.migrate("s1", os.path.join(
        os.path.dirname(sup.members[next(iter(sup.members))].path),
        "s1-moved"))
    _sync_placement(sup)
    from toplingdb_tpu.db.write_batch import WriteBatch

    b = WriteBatch()
    b.put(b"%016d" % 999_999, b"stale-epoch-write")
    try:
        _post_raw(dest.url, "/fleet/write", {
            "epoch": old_epoch,
            "batch_b64": base64.b64encode(b.data()).decode()})
        raise SoakFailure("write under a stale epoch was accepted")
    except urllib.error.HTTPError as e:
        _check(e.code == 409, f"stale epoch answered {e.code}, not 409")
    st = _http_json(dest.url, "/fleet/status", timeout=10)
    _check(st.get("stale_epoch_rejects", 0) > 0,
           "stale-epoch reject not counted on the server")
    _check(st["epoch"] > old_epoch, "cutover did not bump the epoch")
    log(f"stale-epoch: migrated s1 (epoch {old_epoch} -> {st['epoch']}), "
        f"pre-cutover write rejected 409")


def run_soak(base_dir: str, *, seed: int = 1234, fast: bool = True,
             log=print) -> dict:
    ttl = 1.5 if fast else 3.0
    keyspace = 2_000 if fast else 20_000
    write_window = 0.5 if fast else 3.0
    os.makedirs(base_dir, exist_ok=True)
    lease_log = os.path.join(base_dir, "lease.jsonl")
    stats = Statistics()
    cop, co_url = FleetSupervisor.start_coordinator(
        lease_log, ttl=ttl, grace=0.3)
    co_port = int(co_url.rsplit(":", 1)[1])
    sup = FleetSupervisor(co_url, statistics=stats, lease_ttl=ttl)
    writers: list[_Writer] = []
    threads = []
    router = None
    try:
        m = ShardMap.from_bounds([("s0", None, SPLIT_KEY),
                                  ("s1", SPLIT_KEY, None)])
        sup.coordinator.install_map(m.to_config(), {})
        for shard in ("s0", "s1"):
            sup.spawn_server(shard, os.path.join(base_dir, shard))
        _sync_placement(sup)
        router = FleetRouter(sup.coordinator, statistics=stats,
                             map_lease=ttl, write_deadline=15.0)
        writers = [_Writer(i, router, seed, keyspace) for i in range(3)]
        for w in writers:
            threads.append(ccy.spawn(f"soak-writer-{w.wid}", w.run,
                                     daemon=True))
        time.sleep(write_window)  # steady-state traffic first

        scenario_oracle: dict[bytes, bytes] = {}
        _scenario_migrate_kill(sup, base_dir, log)
        time.sleep(write_window)
        _scenario_partition(co_url, stats, scenario_oracle, log)
        cop, co_url = _scenario_coordinator_crash(
            sup, cop, co_port, lease_log, ttl, log)
        time.sleep(write_window)
        _scenario_stale_epoch(sup, router, log)
        if not fast:
            _scenario_migrate_kill(sup, base_dir, log)
        time.sleep(write_window)

        # -- drain writers, then merged-oracle parity --------------------
        for w in writers:
            w.stop = True
        for t in threads:
            t.join(timeout=30.0)
        for w in writers:
            if w.error is not None:
                raise SoakFailure(f"writer {w.wid} died: {w.error!r}")
        oracle: dict[bytes, bytes] = dict(scenario_oracle)
        for w in writers:
            oracle.update(w.acked)
        scanned = list(router.scan())
        keys = [k for k, _ in scanned]
        _check(len(keys) == len(set(keys)),
               "double-served: a key appeared twice in the merged scan")
        got = dict(scanned)
        lost = [k for k in oracle if k not in got]
        _check(not lost, f"lost {len(lost)} acked keys, e.g. "
               f"{sorted(lost)[:3]}")
        ghost = [k for k in got if k not in oracle]
        _check(not ghost, f"{len(ghost)} unacked ghost keys served, "
               f"e.g. {sorted(ghost)[:3]}")
        for k, v in oracle.items():
            _check(got[k] == v, f"value mismatch for {k!r}")
        # No server ever admitted a write without a live lease + epoch:
        # the rejects prove the checks fired; parity proves none leaked.
        n_writes = sum(len(w.acked) for w in writers)
        n_rejects = sum(w.rejects for w in writers)

        # -- graceful shutdown: SIGTERM → clean exit everywhere ----------
        members = list(sup.members.values())
        sup.stop_all()
        for mem in members:
            _check(mem.proc.returncode == 0,
                   f"{mem.holder} exited {mem.proc.returncode}, not 0 "
                   f"(graceful SIGTERM path broken)")
        result = {
            "ok": True, "seed": seed, "acked_writes": n_writes,
            "writer_rejects": n_rejects, "oracle_keys": len(oracle),
            "scanned_keys": len(keys),
            "map_refreshes": stats.get_ticker_count("fleet.map.refreshes"),
            "router_fail_closed":
                stats.get_ticker_count("fleet.write.rejects"),
        }
        log(f"soak OK: {json.dumps(result)}")
        return result
    finally:
        for w in writers:
            w.stop = True
        for t in threads:
            t.join(timeout=10.0)
        sup.stop_all()
        if cop.poll() is None:
            cop.terminate()
            cop.wait()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleet_soak")
    ap.add_argument("--dir", required=True, help="scratch directory")
    ap.add_argument("--seed", type=int, default=1234)
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--fast", action="store_true", default=True)
    mode.add_argument("--full", dest="fast", action="store_false")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch dir for inspection")
    args = ap.parse_args(argv)
    try:
        run_soak(args.dir, seed=args.seed, fast=args.fast)
        return 0
    except SoakFailure as e:
        print(f"SOAK FAILED: {e}", file=sys.stderr)
        return 1
    finally:
        if not args.keep:
            shutil.rmtree(args.dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
