"""Unified tier-1 lint driver: every static correctness plane, one exit
code.

Runs the four repo analyzers over the working tree and aggregates their
findings:

  telemetry    tools/check_telemetry    span/metric discipline
  concurrency  tools/check_concurrency  lock-rank order + thread lifecycle
  native-abi   tools/check_native_abi   ctypes bindings vs C signatures vs
                                        the §2.10.2 contract table
  errors       tools/check_errors       broad-except hygiene (every
                                        swallow is an annotated policy)

Each checker keeps its own exit semantics (0 clean / 1 findings); the
driver preserves them in the per-checker report and exits nonzero when
ANY checker found a violation — so CI needs exactly one invocation:

    python -m toplingdb_tpu.tools.lint_all [repo_root]

Per-checker wall time is printed so a checker that regresses past the
tier-1 budget (tests/test_lint_all.py holds the whole run under 10s) is
identifiable from the output alone.
"""

from __future__ import annotations

import os
import sys
import time

from toplingdb_tpu.tools import (
    check_concurrency,
    check_errors,
    check_native_abi,
    check_telemetry,
)

# (name, callable(repo_root) -> list[str]). Order is cheap-first so a
# fast failure surfaces before the heavier whole-tree passes.
CHECKERS = (
    ("native-abi", check_native_abi.run),
    ("telemetry", check_telemetry.run),
    ("errors", check_errors.run),
    ("concurrency", check_concurrency.run),
)


def run(repo_root: str | None = None):
    """-> (all_violations, per_checker {name: (violations, seconds)})."""
    repo_root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    results: dict[str, tuple[list[str], float]] = {}
    violations: list[str] = []
    for name, fn in CHECKERS:
        t0 = time.monotonic()
        try:
            found = list(fn(repo_root))
        except Exception as e:  # noqa: BLE001 — a crashed checker IS a finding
            found = [f"lint_all: checker {name!r} crashed: "
                     f"{type(e).__name__}: {e}"]
        results[name] = (found, time.monotonic() - t0)
        violations += found
    return violations, results


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv and not argv[0].startswith("-") else None
    violations, results = run(root)
    for v in violations:
        print(v)
    for name, (found, dt) in results.items():
        rc = 1 if found else 0
        print(f"lint_all: {name:<12} exit={rc} "
              f"{len(found):>3} violation(s) in {dt:6.2f}s")
    total = sum(dt for _, dt in results.values())
    print(f"lint_all: {len(violations)} violation(s) total in {total:.2f}s")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
