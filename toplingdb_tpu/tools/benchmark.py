"""Benchmark suite driver with machine-readable results.

The analogue of the reference's tools/benchmark.sh + benchmark_ci.py
(/root/reference): runs a named workload SUITE through db_bench and writes
one JSON results file per run, which tools/benchmark_compare.py diffs
against a baseline run (the benchmark_compare.sh / regression_test.sh
role).

Usage:
  python -m toplingdb_tpu.tools.benchmark --suite standard \
      --out results.json [--num 100000] [--db /tmp/bench]
  python -m toplingdb_tpu.tools.benchmark_compare base.json new.json \
      [--threshold 0.85]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import shutil
import time

SUITES = {
    # the reference benchmark.sh's canonical progression
    "standard": ("fillseq,readseq,fillrandom,readrandom,overwrite,"
                 "readreverse,seekrandom,deleterandom"),
    "write": "fillseq,fillrandom,fillbatch,overwrite,fillsync",
    "read": "fillrandom,readrandom,readseq,readreverse,multireadrandom,"
            "seekrandom,readmissing",
    "mixed": "fillrandom,readwhilewriting,readrandomwriterandom,"
             "updaterandom",
    "compact": "fillrandom,compact,readrandom",
    "quick": "fillseq,readrandom",
}


def run_suite(suite: str, num: int, db: str, value_size: int = 100) -> dict:
    """Run the suite in-process via db_bench's Bench and return the
    structured results document."""
    from toplingdb_tpu.tools import db_bench as dbb

    benchmarks = SUITES.get(suite, suite)  # unknown name = literal list
    parser = dbb.build_parser()
    ns = parser.parse_args([
        f"--benchmarks={benchmarks}", f"--num={num}", f"--db={db}",
        f"--value-size={value_size}",
    ])
    b = dbb.Bench(ns)
    b.run()
    return {
        "meta": {
            "suite": suite, "num": num, "value_size": value_size,
            "timestamp": int(time.time()),
            "platform": platform.platform(),
        },
        "results": b.results,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--suite", default="standard",
                    help=f"one of {', '.join(SUITES)} or a literal "
                         f"comma-separated workload list")
    ap.add_argument("--num", type=int, default=100000)
    ap.add_argument("--db", default="/tmp/tpulsm_benchmark")
    ap.add_argument("--value-size", type=int, default=100)
    ap.add_argument("--out", default=None, help="results JSON path")
    ap.add_argument("--keep-db", action="store_true")
    args = ap.parse_args(argv)
    doc = run_suite(args.suite, args.num, args.db, args.value_size)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"wrote {args.out} ({len(doc['results'])} workloads)")
    if not args.keep_db and os.path.exists(args.db):
        shutil.rmtree(args.db, ignore_errors=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
