"""IO-trace parser CLI (reference tools/io_tracer_parser_tool.cc).

Reads the JSONL IO trace written by env.io_tracer.IOTracer and reports
per-op and per-file aggregates (counts, bytes, latency).

Usage:
  python -m toplingdb_tpu.tools.io_tracer_parser TRACE [--json] [-n TOPN]
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def parse(trace_path: str) -> dict:
    per_op: dict[str, dict] = defaultdict(
        lambda: {"count": 0, "bytes": 0, "latency_us": 0}
    )
    per_file: dict[str, dict] = defaultdict(
        lambda: {"count": 0, "bytes": 0, "latency_us": 0}
    )
    total = 0
    with open(trace_path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            total += 1
            for agg in (per_op[rec["op"]],
                        per_file[rec.get("path", "?")]):
                agg["count"] += 1
                agg["bytes"] += rec.get("len", 0)
                agg["latency_us"] += rec.get("latency_us", 0)
    return {
        "total_records": total,
        "per_op": dict(per_op),
        "per_file": dict(per_file),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="io_tracer_parser",
        description="Parse a toplingdb_tpu IO trace",
    )
    ap.add_argument("trace")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("-n", "--top-n", type=int, default=10,
                    help="files shown, by bytes desc")
    args = ap.parse_args(argv)
    report = parse(args.trace)
    if args.json:
        print(json.dumps(report, indent=1))
        return 0
    print(f"records          {report['total_records']}")
    for op, agg in sorted(report["per_op"].items(),
                          key=lambda kv: -kv[1]["bytes"]):
        print(f"  {op:<12} count {agg['count']:>8}  bytes {agg['bytes']:>12}"
              f"  latency {agg['latency_us']}us")
    print("top files by bytes:")
    files = sorted(report["per_file"].items(),
                   key=lambda kv: -kv[1]["bytes"])[: args.top_n]
    for path, agg in files:
        print(f"  {agg['bytes']:>12}B {agg['count']:>7} ops  {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
