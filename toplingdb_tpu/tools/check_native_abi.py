"""ctypes↔C ABI contract checker (tier-1 CI): the native boundary as data.

A ctypes binding that drifts from its `extern "C"` definition does not
fail loudly — it reinterprets registers. A missing length argument next
to a buffer pointer is a heap overwrite waiting for the first oversized
key. Neither is caught by any Python test that happens not to cross the
drifted symbol. This checker makes the whole boundary a declared,
machine-checked contract:

  A1. every `extern "C"` export in native/tpulsm_native.cc has a ctypes
      binding in native/__init__.py (no unbound export), and every
      binding names a real definition (no phantom binding);
  A2. every binding's restype/argtypes match the C signature through the
      correspondence table below (arity AND per-position type);
  A3. a forward declaration and its definition must agree exactly;
  A4. every sanitize-variant artifact (_tpulsm_native.asan.so /
      .undefined.so) that is up to date with the source exports the
      IDENTICAL `tpulsm_*` symbol set (a variant must never silently
      lag the ABI; stale-by-mtime variants are skipped — the loader
      rebuilds those on demand);
  A5. every pointer parameter is covered by the buffer-pairing contract
      in ARCHITECTURE.md §2.10.2 — paired with an integer length/
      capacity parameter in the same signature, a literal element
      count, or explicitly exempted (`!`: opaque handle, NUL-terminated
      string, or internally sized). A stale, missing, or extra table
      row fails, exactly like the §2.10.1 lock-rank table.

Correspondence (C type → allowed ctypes tokens):

  void           → None (restype only)
  intN_t/uintN_t → c_intN / c_uintN          size_t → c_size_t
  const char*    → c_char_p
  const uint8_t* → c_char_p or POINTER(c_uint8)
  uint8_t*       → POINTER(c_uint8)          (writable: c_char_p is
                                              immutable in ctypes)
  intN_t*        → POINTER(c_intN)           (same for unsigned)
  void*          → c_void_p                  void** → POINTER(c_void_p)
  any pointer RETURN additionally allows c_void_p (opaque handles).

`--emit-table` prints a §2.10.2-format table inferred from the source
(pairing guessed as "the next integer parameter"; `!` otherwise) as a
starting point for hand-audit — never paste it unreviewed.

Run: python -m toplingdb_tpu.tools.check_native_abi [repo_root]
Exit 0 clean; 1 with one violation per line otherwise.
"""

from __future__ import annotations

import ast
import os
import re
import shutil
import subprocess
import sys

# -- correspondence table -------------------------------------------------

_SCALARS = {
    "int8_t": "c_int8", "uint8_t": "c_uint8",
    "int32_t": "c_int32", "uint32_t": "c_uint32",
    "int64_t": "c_int64", "uint64_t": "c_uint64",
    "size_t": "c_size_t", "int": "c_int32",
}

_INT_TYPES = set(_SCALARS)  # acceptable length-parameter types


def allowed_tokens(ctype: str, is_return: bool) -> set[str] | None:
    """ctypes tokens allowed for normalized C type `ctype`; None if the
    type is outside the contract vocabulary."""
    const = ctype.startswith("const ")
    base = ctype[6:] if const else ctype
    stars = len(base) - len(base.rstrip("*"))
    base = base.rstrip("*").strip()
    out: set[str] | None = None
    if stars == 0:
        if base == "void":
            out = {"None"} if is_return else None
        elif base in _SCALARS:
            out = {_SCALARS[base]}
    elif stars == 1:
        if base == "char":
            out = {"c_char_p"}
        elif base == "uint8_t":
            out = {"POINTER(c_uint8)"}
            if const:
                out.add("c_char_p")
        elif base in _SCALARS:
            out = {f"POINTER({_SCALARS[base]})"}
        elif base == "void":
            out = {"c_void_p"}
    elif stars == 2 and base == "void":
        out = {"POINTER(c_void_p)"}
    elif stars == 2 and base in ("uint8_t", "char"):
        # array of byte-buffer pointers; c_char_p elements are the
        # idiomatic ctypes spelling when the buffers are const
        out = {"POINTER(c_void_p)"}
        if const:
            out.add("POINTER(c_char_p)")
    if out is not None and stars > 0 and is_return:
        out.add("c_void_p")  # opaque handle returns
    return out


def _is_pointer(ctype: str) -> bool:
    return ctype.rstrip().endswith("*")


def _is_int(ctype: str) -> bool:
    c = ctype[6:] if ctype.startswith("const ") else ctype
    return c in _INT_TYPES


# -- C signature parsing --------------------------------------------------

_SIG_RE = re.compile(
    r"(?m)^([A-Za-z_][A-Za-z0-9_]*(?:\s*\*+|\s+[A-Za-z_][A-Za-z0-9_]*"
    r"(?:\s*\*+)?)*)\s+\**(tpulsm_[a-z0-9_]+)\s*\(")


def _strip_comments(src: str) -> str:
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.S)
    return re.sub(r"//[^\n]*", " ", src)


def _norm_type(toks: str) -> str:
    """'const uint8_t *' → 'const uint8_t*'; 'const void* const*' →
    'const void**' (const folded to one leading qualifier, stars glued)."""
    t = toks.replace("*", " * ").split()
    stars = t.count("*")
    words = [w for w in t if w not in ("*", "const")]
    const = "const " if "const" in t else ""
    return const + " ".join(words) + "*" * stars


def _parse_params(blob: str, sym: str) -> list[tuple[str, str]] | str:
    blob = blob.strip()
    if blob in ("", "void"):
        return []
    params = []
    for i, p in enumerate(blob.split(",")):
        p = p.strip()
        m = re.match(r"^(.*?)([A-Za-z_][A-Za-z0-9_]*)$", p, re.S)
        if not m or not m.group(1).strip():
            return f"{sym}: unparseable parameter {i}: {p!r}"
        params.append((_norm_type(m.group(1)), m.group(2)))
    return params


def parse_c_signatures(cc_path: str):
    """-> (signatures {sym: (ret, [(type, name), ...])}, violations)."""
    with open(cc_path, encoding="utf-8") as f:
        src = _strip_comments(f.read())
    sigs: dict[str, tuple[str, list[tuple[str, str]]]] = {}
    violations: list[str] = []
    for m in _SIG_RE.finditer(src):
        ret_raw, sym = m.group(1), m.group(2)
        stars_after = src[m.end(1):m.start(2)].count("*")
        close = src.find(")", m.end())  # param lists have no nested parens
        if close < 0:
            violations.append(f"{cc_path}: {sym}: unterminated parameters")
            continue
        nxt = src[close + 1:close + 80].lstrip()[:1]
        if nxt not in ("{", ";"):
            continue  # a call or macro, not a signature
        if "return" in ret_raw.split():
            continue
        ret = _norm_type(ret_raw) + "*" * stars_after
        params = _parse_params(src[m.end():close], sym)
        if isinstance(params, str):
            violations.append(f"{cc_path}: {params}")
            continue
        if sym in sigs:
            if sigs[sym] != (ret, params):
                violations.append(
                    f"{cc_path}: {sym}: forward declaration and definition "
                    f"disagree")
            continue
        sigs[sym] = (ret, params)
    return sigs, violations


# -- ctypes binding parsing ----------------------------------------------


def _ct_token(node: ast.expr, aliases: dict[str, str]) -> str | None:
    """AST expr → canonical ctypes token ('c_int32', 'POINTER(c_uint8)',
    'None'), resolving local aliases; None when unrecognized."""
    if isinstance(node, ast.Constant) and node.value is None:
        return "None"
    if isinstance(node, ast.Attribute):  # ctypes.c_int32
        return node.attr
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    if isinstance(node, ast.Call):
        fname = node.func.attr if isinstance(node.func, ast.Attribute) \
            else node.func.id if isinstance(node.func, ast.Name) else None
        if fname == "POINTER" and len(node.args) == 1:
            inner = _ct_token(node.args[0], aliases)
            return f"POINTER({inner})" if inner else None
    return None


def parse_ctypes_bindings(init_path: str):
    """-> (bindings {sym: {'restype': tok, 'argtypes': [tok], 'line': n}},
    violations). Scans every function in native/__init__.py for
    `<var>.<sym>.restype/argtypes = ...` with per-function alias
    resolution (u8p = ctypes.POINTER(ctypes.c_uint8), ...)."""
    with open(init_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=init_path)
    bindings: dict[str, dict] = {}
    violations: list[str] = []

    def scan(body, aliases):
        for node in body:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                    continue
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Name):
                    tok = _ct_token(sub.value, aliases)
                    if tok:
                        aliases[tgt.id] = tok
                    continue
                if not (isinstance(tgt, ast.Attribute)
                        and tgt.attr in ("restype", "argtypes")
                        and isinstance(tgt.value, ast.Attribute)
                        and tgt.value.attr.startswith("tpulsm_")):
                    continue
                sym = tgt.value.attr
                b = bindings.setdefault(
                    sym, {"restype": None, "argtypes": None,
                          "line": sub.lineno})
                if tgt.attr == "restype":
                    tok = _ct_token(sub.value, aliases)
                    if tok is None:
                        violations.append(
                            f"{init_path}:{sub.lineno}: {sym}: "
                            f"unrecognized restype expression")
                    b["restype"] = tok
                else:
                    if not isinstance(sub.value, (ast.List, ast.Tuple)):
                        violations.append(
                            f"{init_path}:{sub.lineno}: {sym}: argtypes "
                            f"is not a literal list (static check "
                            f"impossible)")
                        continue
                    toks = []
                    for el in sub.value.elts:
                        tok = _ct_token(el, aliases)
                        if tok is None:
                            violations.append(
                                f"{init_path}:{sub.lineno}: {sym}: "
                                f"unrecognized argtypes element")
                        toks.append(tok)
                    b["argtypes"] = toks

    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            scan(node.body, {})
    return bindings, violations


# -- §2.10.2 contract table ----------------------------------------------

_ROW_RE = re.compile(
    r"^\|\s*`(tpulsm_[a-z0-9_]+)`\s*\|\s*([^|]+?)\s*\|\s*(\d+)\s*"
    r"\|\s*([^|]*?)\s*\|\s*$")


def parse_contract_table(arch_path: str):
    """-> (rows {sym: (ret, argc, {ptr: spec})}, violations)."""
    rows: dict[str, tuple[str, int, dict[str, str]]] = {}
    violations: list[str] = []
    try:
        with open(arch_path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return rows, [f"{arch_path}: unreadable (ABI contract table lives "
                      f"in §2.10.2)"]
    sec = text.find("§2.10.2")
    if sec < 0:
        sec = text.find("### 2.10.2")
    if sec < 0:
        return rows, [f"{arch_path}: no '§2.10.2' section (ABI contract "
                      f"table missing)"]
    end = text.find("\n## ", sec)
    chunk = text[sec:end if end > 0 else len(text)]
    for off, line in enumerate(chunk.splitlines()):
        m = _ROW_RE.match(line.strip())
        if not m:
            continue
        sym, ret, argc, buffers = m.groups()
        specs: dict[str, str] = {}
        ok = True
        if buffers.strip() not in ("", "—", "-"):
            for part in buffers.split(","):
                part = part.strip().strip("`")
                if ":" not in part:
                    violations.append(
                        f"{arch_path}: §2.10.2 {sym}: malformed buffer "
                        f"spec {part!r} (want `name:len`, `name:N`, or "
                        f"`name:!`)")
                    ok = False
                    continue
                pname, spec = part.split(":", 1)
                specs[pname.strip()] = spec.strip()
        if ok:
            rows[sym] = (ret.strip(), int(argc), specs)
    return rows, violations


# -- variant artifact check ----------------------------------------------


def _exported_syms(so_path: str) -> set[str] | None:
    nm = shutil.which("nm")
    if nm is None:
        return None
    try:
        out = subprocess.run(
            [nm, "-D", "--defined-only", so_path],
            capture_output=True, text=True, timeout=30, check=True).stdout
    except (subprocess.SubprocessError, OSError):
        return None
    return {ln.split()[-1] for ln in out.splitlines()
            if " T " in ln and ln.split()[-1].startswith("tpulsm_")}


def check_variants(native_dir: str, source_syms: set[str],
                   notes: list[str]) -> list[str]:
    violations = []
    cc = os.path.join(native_dir, "tpulsm_native.cc")
    for fn in sorted(os.listdir(native_dir)):
        if not (fn.startswith("_tpulsm_native") and fn.endswith(".so")):
            continue
        so = os.path.join(native_dir, fn)
        try:
            if os.path.getmtime(so) < os.path.getmtime(cc):
                notes.append(f"note: {fn} stale by mtime — skipped "
                             f"(loader rebuilds on demand)")
                continue
        except OSError:
            continue
        syms = _exported_syms(so)
        if syms is None:
            notes.append(f"note: {fn}: nm unavailable — export set "
                         f"unchecked")
            continue
        missing = source_syms - syms
        extra = syms - source_syms
        for s in sorted(missing):
            violations.append(f"{so}: exports lag the source: {s} missing")
        for s in sorted(extra):
            violations.append(f"{so}: exports {s} which has no definition "
                              f"in tpulsm_native.cc")
    return violations


# -- the checks -----------------------------------------------------------


def check_contract(sigs, bindings, rows, cc, init, arch) -> list[str]:
    violations = []
    # A1: bidirectional coverage
    for sym in sorted(set(sigs) - set(bindings)):
        violations.append(
            f"{cc}: {sym}: exported but never bound in native/__init__.py "
            f"(unbound export)")
    for sym in sorted(set(bindings) - set(sigs)):
        violations.append(
            f"{init}:{bindings[sym]['line']}: {sym}: bound but not defined "
            f"in tpulsm_native.cc (phantom binding)")
    # A2: per-symbol shape
    for sym in sorted(set(sigs) & set(bindings)):
        ret, params = sigs[sym]
        b = bindings[sym]
        loc = f"{init}:{b['line']}"
        if b["restype"] is None or b["argtypes"] is None:
            violations.append(f"{loc}: {sym}: binding sets "
                              f"{'argtypes' if b['argtypes'] is None else 'restype'}"
                              f" but not "
                              f"{'restype' if b['argtypes'] is None else 'argtypes'}")
            continue
        want_ret = allowed_tokens(ret, is_return=True)
        if want_ret is None:
            violations.append(f"{cc}: {sym}: return type {ret!r} outside "
                              f"the contract vocabulary")
        elif b["restype"] not in want_ret:
            violations.append(
                f"{loc}: {sym}: restype {b['restype']} does not match C "
                f"return {ret!r} (allowed: {', '.join(sorted(want_ret))})")
        if len(b["argtypes"]) != len(params):
            violations.append(
                f"{loc}: {sym}: argtypes has {len(b['argtypes'])} entries, "
                f"C signature has {len(params)} parameters")
            continue
        for i, ((ptype, pname), tok) in enumerate(zip(params,
                                                      b["argtypes"])):
            want = allowed_tokens(ptype, is_return=False)
            if want is None:
                violations.append(
                    f"{cc}: {sym}: parameter {pname!r} type {ptype!r} "
                    f"outside the contract vocabulary")
            elif tok not in want:
                violations.append(
                    f"{loc}: {sym}: argtypes[{i}] ({pname}) is {tok}, C "
                    f"type {ptype!r} allows "
                    f"{', '.join(sorted(want))}")
    # A5: table vs source
    for sym in sorted(set(sigs) - set(rows)):
        violations.append(
            f"{arch}: §2.10.2 missing a row for {sym} (declare its buffer "
            f"pairing or exempt its pointers)")
    for sym in sorted(set(rows) - set(sigs)):
        violations.append(
            f"{arch}: §2.10.2 row for {sym} names no exported symbol "
            f"(stale row)")
    for sym in sorted(set(rows) & set(sigs)):
        ret, params = sigs[sym]
        tret, targc, specs = rows[sym]
        if tret != ret:
            violations.append(
                f"{arch}: §2.10.2 {sym}: return {tret!r} but source says "
                f"{ret!r} (stale row)")
        if targc != len(params):
            violations.append(
                f"{arch}: §2.10.2 {sym}: argc {targc} but source has "
                f"{len(params)} parameters (stale row)")
            continue
        names = {n for _, n in params}
        ptrs = {n for t, n in params if _is_pointer(t)}
        ints = {n for t, n in params if _is_int(t)}
        for p in sorted(ptrs - set(specs)):
            violations.append(
                f"{arch}: §2.10.2 {sym}: pointer parameter {p!r} has no "
                f"buffer-pairing spec (pair it `{p}:lenparam`, size it "
                f"`{p}:N`, or exempt it `{p}:!`)")
        for p, spec in specs.items():
            if p not in names:
                violations.append(
                    f"{arch}: §2.10.2 {sym}: spec names unknown parameter "
                    f"{p!r} (stale row)")
                continue
            if p not in ptrs:
                violations.append(
                    f"{arch}: §2.10.2 {sym}: {p!r} is not a pointer "
                    f"parameter (stale row)")
                continue
            if spec == "!" or spec.isdigit():
                continue
            if spec not in ints:
                violations.append(
                    f"{arch}: §2.10.2 {sym}: {p!r} paired with {spec!r} "
                    f"which is not an integer parameter of {sym}")
    return violations


# -- entry points ---------------------------------------------------------


def emit_table(sigs) -> str:
    lines = ["| symbol | ret | argc | buffers |",
             "|---|---|---|---|"]
    for sym in sorted(sigs):
        ret, params = sigs[sym]
        specs = []
        for i, (t, n) in enumerate(params):
            if not _is_pointer(t):
                continue
            nxt = next((n2 for t2, n2 in params[i + 1:] if _is_int(t2)),
                       None)
            specs.append(f"`{n}:{nxt}`" if nxt else f"`{n}:!`")
        lines.append(f"| `{sym}` | {ret} | {len(params)} | "
                     f"{', '.join(specs) if specs else '—'} |")
    return "\n".join(lines)


def run(repo_root: str | None = None, notes: list[str] | None = None):
    repo_root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    native = os.path.join(repo_root, "toplingdb_tpu", "native")
    cc = os.path.join(native, "tpulsm_native.cc")
    init = os.path.join(native, "__init__.py")
    arch = os.path.join(repo_root, "ARCHITECTURE.md")
    notes = notes if notes is not None else []
    sigs, violations = parse_c_signatures(cc)
    bindings, v2 = parse_ctypes_bindings(init)
    rows, v3 = parse_contract_table(arch)
    violations += v2 + v3
    violations += check_contract(sigs, bindings, rows, cc, init, arch)
    violations += check_variants(native, set(sigs), notes)
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if "--emit-table" in argv:
        argv = [a for a in argv if a != "--emit-table"]
        root = argv[0] if argv else os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        sigs, violations = parse_c_signatures(
            os.path.join(root, "toplingdb_tpu", "native",
                         "tpulsm_native.cc"))
        for v in violations:
            print(v, file=sys.stderr)
        print(emit_table(sigs))
        return 0
    notes: list[str] = []
    violations = run(argv[0] if argv else None, notes)
    for v in violations:
        print(v)
    for n in notes:
        print(n)
    print(f"check_native_abi: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
