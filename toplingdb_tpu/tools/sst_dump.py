"""sst_dump: SST inspection/verification (reference tools/sst_dump_tool.cc).

Usage:
  python -m toplingdb_tpu.tools.sst_dump --file=X.sst \
      [--command=scan|raw|verify|props] [--limit=N] \
      [--verify-file-checksum]
"""

from __future__ import annotations

import argparse

from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType, split_internal_key
from toplingdb_tpu.env import default_env
from toplingdb_tpu.table.factory import open_table
from toplingdb_tpu.utils import errors as _errors

_TYPE_NAMES = {
    int(ValueType.VALUE): "PUT",
    int(ValueType.DELETION): "DEL",
    int(ValueType.SINGLE_DELETION): "SDEL",
    int(ValueType.MERGE): "MERGE",
    int(ValueType.RANGE_DELETION): "RANGEDEL",
}


def _verify_file_checksum(env, path: str) -> int:
    """--verify-file-checksum: find the file's recorded digest in the
    containing DB directory's MANIFEST (utils/file_checksum offline
    lookup) and recompute it; falls back to printing a fresh crc32c when
    no MANIFEST records one (standalone/exported files)."""
    import os

    from toplingdb_tpu.db.filename import parse_file_name
    from toplingdb_tpu.utils.file_checksum import (
        FileChecksumGenFactory,
        compute_file_checksum,
        manifest_file_checksums,
    )

    dbdir = os.path.dirname(os.path.abspath(path)) or "."
    _, num = parse_file_name(os.path.basename(path))
    recorded = None
    try:
        recorded = manifest_file_checksums(dbdir, env).get(num)
    except Exception as e:
        # no CURRENT/MANIFEST next to the file: standalone mode
        _errors.swallow(reason="manifest-checksum-lookup", exc=e)
    func = recorded[0] if recorded else "crc32c"
    gen = FileChecksumGenFactory(func or "crc32c").create()
    actual = compute_file_checksum(env, path, gen)
    if recorded is None:
        print(f"no recorded checksum for {path}; computed "
              f"{func}:{actual.hex()}")
        return 0
    if actual == recorded[1]:
        print(f"OK: {path} {func}:{actual.hex()} matches MANIFEST")
        return 0
    print(f"MISMATCH: {path} MANIFEST records {func}:{recorded[1].hex()}, "
          f"disk has {actual.hex()}")
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", required=True)
    ap.add_argument("--command", default="scan",
                    choices=["scan", "raw", "verify", "props"])
    ap.add_argument("--limit", type=int, default=0)
    ap.add_argument("--verify-file-checksum", action="store_true",
                    dest="verify_file_checksum",
                    help="recompute the whole-file checksum and compare "
                         "with the one recorded in the containing DB "
                         "directory's MANIFEST")
    args = ap.parse_args(argv)

    env = default_env()
    if args.verify_file_checksum:
        return _verify_file_checksum(env, args.file)
    r = open_table(env.new_random_access_file(args.file), InternalKeyComparator())
    p = r.properties
    if args.command == "props":
        for f in p._INT_FIELDS:
            print(f"  {f}: {getattr(p, f)}")
        for f in p._STR_FIELDS:
            print(f"  {f}: {getattr(p, f)}")
        return 0
    if args.command in ("scan", "raw"):
        it = r.new_iterator()
        it.seek_to_first()
        n = 0
        for k, v in it.entries():
            uk, seq, t = split_internal_key(k)
            tname = _TYPE_NAMES.get(t, str(t))
            if args.command == "raw":
                print(f"{k.hex()} => {v.hex()}")
            else:
                print(f"'{uk!r}' seq:{seq}, type:{tname} => {v!r}")
            n += 1
            if args.limit and n >= args.limit:
                break
        for b, e in r.range_del_entries():
            uk, seq, t = split_internal_key(b)
            print(f"RANGEDEL ['{uk!r}', '{e!r}') seq:{seq}")
        print(f"# {n} entries")
        return 0
    if args.command == "verify":
        it = r.new_iterator()
        it.seek_to_first()
        n = sum(1 for _ in it.entries())  # checksum-verified reads
        ok = n == p.num_entries
        print(f"verified {n} entries; properties say {p.num_entries}: "
              f"{'OK' if ok else 'MISMATCH'}")
        return 0 if ok else 1
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(main())
