"""Block-cache trace analyzer CLI (reference
tools/block_cache_analyzer/block_cache_trace_analyzer.cc).

Reads the JSONL access trace written by utils.cache.BlockCacheTracer and
reports hit ratio, reuse distribution (how many blocks are accessed once /
twice / more), the hottest blocks, and a per-second miss-ratio timeline.

Usage:
  python -m toplingdb_tpu.tools.block_cache_analyzer TRACE [--json]
      [-n TOPN]
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter


def analyze(trace_path: str, top_n: int | None = 10) -> dict:
    """top_n=None returns EVERY block in hottest_blocks (callers that
    need full coverage, e.g. the legacy aggregate wrapper)."""
    hits = misses = 0
    per_key = Counter()
    key_misses = Counter()
    timeline: dict[int, list[int]] = {}
    with open(trace_path) as f:
        for line in f:
            if not line.strip():
                continue
            rec = json.loads(line)
            k = rec["key"]
            per_key[k] += 1
            sec = rec.get("ts_us", 0) // 1_000_000
            bucket = timeline.setdefault(sec, [0, 0])  # [hits, misses]
            if rec["hit"]:
                hits += 1
                bucket[0] += 1
            else:
                misses += 1
                key_misses[k] += 1
                bucket[1] += 1
    total = hits + misses
    reuse = Counter(per_key.values())
    return {
        "accesses": total,
        "hits": hits,
        "misses": misses,
        "hit_ratio": round(hits / total, 4) if total else 0.0,
        "unique_blocks": len(per_key),
        "accessed_once": reuse.get(1, 0),
        "accessed_2_to_10": sum(c for n, c in reuse.items() if 2 <= n <= 10),
        "accessed_over_10": sum(c for n, c in reuse.items() if n > 10),
        "hottest_blocks": [
            {"key": k, "accesses": c, "misses": key_misses.get(k, 0)}
            for k, c in per_key.most_common(top_n)
        ],  # most_common(None) = all, count-sorted
        "miss_ratio_timeline": [
            {"second": s, "accesses": h + m,
             "miss_ratio": round(m / (h + m), 4) if h + m else 0.0}
            for s, (h, m) in sorted(timeline.items())
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="block_cache_analyzer",
        description="Analyze a toplingdb_tpu block-cache access trace",
    )
    ap.add_argument("trace")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("-n", "--top-n", type=int, default=10)
    args = ap.parse_args(argv)
    report = analyze(args.trace, args.top_n)
    if args.json:
        print(json.dumps(report, indent=1))
        return 0
    print(f"accesses         {report['accesses']}")
    print(f"hit ratio        {report['hit_ratio']:.2%} "
          f"({report['hits']} hits / {report['misses']} misses)")
    print(f"unique blocks    {report['unique_blocks']} "
          f"(once {report['accessed_once']}, 2-10 "
          f"{report['accessed_2_to_10']}, >10 {report['accessed_over_10']})")
    print("hottest blocks:")
    for e in report["hottest_blocks"]:
        print(f"  {e['accesses']:>7} accesses ({e['misses']} misses)  "
              f"{e['key'][:48]}")
    if len(report["miss_ratio_timeline"]) > 1:
        print("miss ratio timeline:")
        for b in report["miss_ratio_timeline"][:20]:
            print(f"  t={b['second']} accesses={b['accesses']} "
                  f"miss_ratio={b['miss_ratio']:.2%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
