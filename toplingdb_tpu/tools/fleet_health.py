"""Fleet health aggregator: one table over every process in a cluster.

PR 8's sharding/replication/dcompact planes spread one logical store over
many processes, each already serving its own /metrics + /slo + health
doc. This tool (and the `/cluster/health` route in utils/config.py that
embeds it) pulls the JSON *health documents* (utils/slo.health_doc) from
registered fleet members — the primary, followers via ReplicationServer's
`/replication/health`, shard-server repos via `/health/<db>`, dcompact
workers via `/health` — merges their windowed histograms (exactly: the
power-of-two buckets sum), folds the per-member verdicts into one fleet
health, and renders one table.

CLI:  python -m toplingdb_tpu.tools.fleet_health URL [URL ...]
      (each URL points directly at a member's health-doc endpoint)
"""

from __future__ import annotations

import json
import sys
import urllib.request

from toplingdb_tpu.utils import slo as _slo
from toplingdb_tpu.utils import statistics as _st


def fetch_doc(url: str, timeout: float = 2.0) -> dict:
    """GET one member's health document."""
    with urllib.request.urlopen(url, timeout=timeout) as r:
        doc = json.loads(r.read().decode())
    if not isinstance(doc, dict):
        raise ValueError(f"{url}: health doc is not a JSON object")
    # A dcompact worker's bare /health ({"ok": true, ...}) maps onto the
    # doc shape: reachable-and-ok is green, anything else unhealthy.
    if "health" not in doc:
        doc = {"role": "worker",
               "health": _slo.HEALTH_GREEN if doc.get("ok")
               else _slo.HEALTH_UNHEALTHY,
               "detail": doc}  # name comes from the member registration
    return doc


class FleetHealthAggregator:
    """Collects health docs from (name, url) members and merges them —
    optionally together with locally-built docs (the embedding repo's
    own DBs) passed straight to summarize()."""

    def __init__(self, members=None, timeout: float = 2.0):
        self.members = list(members or [])  # (name, url) pairs
        self.timeout = timeout

    def collect(self) -> tuple[list[dict], dict[str, str]]:
        """Fetch every member; unreachable ones land in the error map
        (and count as unhealthy in the summary) instead of raising."""
        docs, errors = [], {}
        for name, url in self.members:
            try:
                d = fetch_doc(url, timeout=self.timeout)
                d.setdefault("name", name)
                docs.append(d)
            except Exception as e:
                errors[name] = repr(e)
        return docs, errors

    @staticmethod
    def merge_histograms(docs) -> dict[str, dict[str, _st.Histogram]]:
        """{hist_name: {"cumulative": Histogram, "recent": Histogram}}
        across all members — exact, because bucketed histograms merge by
        summation (the property WindowedHistogram preserves per slot)."""
        out: dict[str, dict[str, _st.Histogram]] = {}
        for d in docs:
            for hname, row in (d.get("histograms") or {}).items():
                slot = out.setdefault(hname, {})
                for series in ("cumulative", "recent"):
                    if row.get(series):
                        h = _st.Histogram.from_dict(row[series])
                        if series in slot:
                            slot[series].merge(h)
                        else:
                            slot[series] = h
        return out

    @staticmethod
    def summarize(docs, errors=None) -> dict:
        """One fleet view: worst-member health (unreachable = unhealthy),
        per-member rows, and merged histogram quantiles."""
        errors = errors or {}
        members, worst = [], _slo.HEALTH_GREEN
        for d in docs:
            h = d.get("health", _slo.HEALTH_GREEN)
            if _slo.health_num(h) > _slo.health_num(worst):
                worst = h
            slo_rows = (d.get("slo") or {}).get("specs") or {}
            members.append({
                "name": d.get("name"),
                "role": d.get("role", "?"),
                "health": h,
                "stall": (d.get("stall") or {}).get("state")
                if isinstance(d.get("stall"), dict) else d.get("stall"),
                "firing": sorted(n for n, r in slo_rows.items()
                                 if r.get("firing")),
                "last_sequence": d.get("last_sequence"),
            })
        for name in sorted(errors):
            worst = _slo.HEALTH_UNHEALTHY
            members.append({"name": name, "role": "?",
                            "health": "unreachable",
                            "error": errors[name]})
        hists = {}
        for hname, slot in sorted(
                FleetHealthAggregator.merge_histograms(docs).items()):
            hists[hname] = {
                series: {
                    "count": h.count,
                    "p50": round(h.percentile(50), 1),
                    "p99": round(h.percentile(99), 1),
                    "max": h.max,
                }
                for series, h in slot.items()
            }
        return {
            "health": worst,
            "n_members": len(docs),
            "n_unreachable": len(errors),
            "members": members,
            "histograms": hists,
        }

    def run(self) -> dict:
        docs, errors = self.collect()
        return self.summarize(docs, errors)


def render(summary: dict) -> str:
    """The human table: one row per member, then the merged latency
    quantiles."""
    lines = [f"fleet health: {summary['health']} "
             f"({summary['n_members']} members, "
             f"{summary['n_unreachable']} unreachable)"]
    fmt = "{:<24} {:<10} {:<12} {:<9} {:<16} {}"
    lines.append(fmt.format("MEMBER", "ROLE", "HEALTH", "STALL",
                            "LAST_SEQ", "FIRING"))
    for m in summary["members"]:
        lines.append(fmt.format(
            str(m.get("name"))[:24], str(m.get("role"))[:10],
            m.get("health", "?"), str(m.get("stall") or "-"),
            str(m.get("last_sequence") if m.get("last_sequence")
                is not None else "-"),
            ",".join(m.get("firing") or []) or
            (m.get("error", "")[:40] if m.get("error") else "-")))
    if summary["histograms"]:
        lines.append("")
        hfmt = "{:<28} {:<10} {:>10} {:>10} {:>10} {:>10}"
        lines.append(hfmt.format("HISTOGRAM", "SERIES", "COUNT", "P50",
                                 "P99", "MAX"))
        for hname, slot in summary["histograms"].items():
            for series, row in slot.items():
                lines.append(hfmt.format(
                    hname[:28], series, row["count"], row["p50"],
                    row["p99"], row["max"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 0 if argv else 2
    agg = FleetHealthAggregator([(u, u) for u in argv])
    summary = agg.run()
    print(render(summary))
    return 0 if summary["health"] != _slo.HEALTH_UNHEALTHY else 1


if __name__ == "__main__":
    raise SystemExit(main())
