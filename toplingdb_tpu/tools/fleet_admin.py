"""fleet_admin: operate the out-of-process shard fleet.

    python -m toplingdb_tpu.tools.fleet_admin --coordinator URL status
    python -m toplingdb_tpu.tools.fleet_admin --coordinator URL map
    python -m toplingdb_tpu.tools.fleet_admin --server URL server-status
    python -m toplingdb_tpu.tools.fleet_admin --server URL kill
    python -m toplingdb_tpu.tools.fleet_admin --server URL fence
    python -m toplingdb_tpu.tools.fleet_admin --server URL recover
    python -m toplingdb_tpu.tools.fleet_admin --coordinator URL \
        --server URL promote --shard S --holder H

`status` prints the coordinator's lease table (shard, holder, fencing
token, remaining TTL); `map` dumps the shard map + placement. Server
commands talk to one ShardServer: `server-status` its role/epoch/lease,
`kill` its graceful /fleet/shutdown, `fence`/`unfence` the write gate,
`recover` the cross-process ShardMigration.recover. `promote` reassigns
the shard's lease to the target server (force: for when the old primary
is positively dead) and POSTs its /fleet/promote — the manual form of
the supervisor's failover.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _get(url: str):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _post(url: str, body: dict):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _fail(e) -> int:
    if isinstance(e, urllib.error.HTTPError):
        print(f"HTTP {e.code}: {e.read().decode()[:300]}", file=sys.stderr)
    else:
        print(str(e), file=sys.stderr)
    return 1


def cmd_status(args) -> int:
    doc = _get(f"{args.coordinator}/lease/status")
    print(f"map_version={doc.get('map_version')} "
          f"shards={doc.get('n_shards')} "
          f"next_token={doc.get('next_token')}")
    placement = doc.get("placement", {})
    for shard, l in sorted(doc.get("leases", {}).items()):
        print(f"{shard}\tholder={l['holder']}\ttoken={l['token']}\t"
              f"remaining={l.get('remaining')}s\t"
              f"url={placement.get(shard, '?')}")
    for shard, url in sorted(placement.items()):
        if shard not in doc.get("leases", {}):
            print(f"{shard}\tUNLEASED\turl={url}")
    return 0


def cmd_map(args) -> int:
    print(json.dumps(_get(f"{args.coordinator}/lease/map"), indent=1))
    return 0


def cmd_server_status(args) -> int:
    print(json.dumps(_get(f"{args.server}/fleet/status"), indent=1))
    return 0


def cmd_kill(args) -> int:
    print(json.dumps(_post(f"{args.server}/fleet/shutdown", {})))
    return 0


def cmd_fence(args) -> int:
    print(json.dumps(_post(f"{args.server}/fleet/fence", {})))
    return 0


def cmd_unfence(args) -> int:
    print(json.dumps(_post(f"{args.server}/fleet/unfence", {})))
    return 0


def cmd_recover(args) -> int:
    print(json.dumps(_post(f"{args.server}/fleet/recover", {})))
    return 0


def cmd_promote(args) -> int:
    grant = _post(f"{args.coordinator}/lease/reassign", {
        "shard": args.shard, "holder": args.holder,
        "url": args.server, "force": args.force})
    out = _post(f"{args.server}/fleet/promote", grant)
    print(json.dumps({"grant": grant, "promoted": out}, indent=1))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fleet_admin")
    ap.add_argument("--coordinator", default=None,
                    help="lease coordinator base URL")
    ap.add_argument("--server", default=None,
                    help="shard server base URL")
    ap.add_argument("--shard", default=None)
    ap.add_argument("--holder", default=None,
                    help="lease holder id for promote")
    ap.add_argument("--force", action="store_true",
                    help="promote even over a live lease (dead primary)")
    ap.add_argument("command",
                    choices=["status", "map", "server-status", "kill",
                             "fence", "unfence", "recover", "promote"])
    args = ap.parse_args(argv)
    for u in ("coordinator", "server"):
        v = getattr(args, u)
        if v is not None:
            setattr(args, u, v.rstrip("/"))
    need = {
        "status": ("coordinator",),
        "map": ("coordinator",),
        "server-status": ("server",),
        "kill": ("server",),
        "fence": ("server",),
        "unfence": ("server",),
        "recover": ("server",),
        "promote": ("coordinator", "server", "shard", "holder"),
    }[args.command]
    missing = [f"--{n}" for n in need if getattr(args, n) is None]
    if missing:
        print(f"{args.command} requires {' '.join(missing)}",
              file=sys.stderr)
        return 2
    try:
        return {"status": cmd_status, "map": cmd_map,
                "server-status": cmd_server_status, "kill": cmd_kill,
                "fence": cmd_fence, "unfence": cmd_unfence,
                "recover": cmd_recover, "promote": cmd_promote,
                }[args.command](args)
    except Exception as e:  # noqa: BLE001 - CLI boundary
        return _fail(e)


if __name__ == "__main__":
    sys.exit(main())
