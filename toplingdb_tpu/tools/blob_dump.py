"""blob_dump: inspect one .blob file (reference tools/blob_dump.cc +
db/blob/blob_dump_tool.cc in /root/reference): header check, per-record
listing (key, value size, crc status), and summary totals.

Usage: python -m toplingdb_tpu.tools.blob_dump --file F [--show_records]
       [--limit N] [--no_verify]
"""

from __future__ import annotations

import argparse
import sys

from toplingdb_tpu.db.blob import MAGIC
from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils import errors as _errors


def dump_blob_file(path: str, show_records: bool = False, limit: int = 0,
                   verify: bool = True, out=sys.stdout) -> dict:
    """Walk every record; returns summary dict. Raises on bad magic;
    records after a corrupt point are reported and the walk stops."""
    with open(path, "rb") as f:
        data = f.read()
    if data[: len(MAGIC)] != MAGIC:
        raise ValueError(f"bad blob magic in {path}")
    off = len(MAGIC)
    n = 0
    total_key = 0
    total_val = 0
    bad_crc = 0
    corrupt_at = None
    while off < len(data):
        start = off
        try:
            klen, off = coding.decode_varint32(data, off)
            vlen, off = coding.decode_varint32(data, off)
            key = data[off: off + klen]
            off += klen
            val = data[off: off + vlen]
            off += vlen
            if off + 4 > len(data) or len(val) != vlen:
                raise ValueError("truncated record")
            stored = crc32c.unmask(coding.decode_fixed32(data, off))
            off += 4
        except Exception as e:
            _errors.swallow(reason="blob-scan-stop-at-corruption", exc=e)
            corrupt_at = start
            break
        ok = True
        if verify and crc32c.value(val) != stored:
            bad_crc += 1
            ok = False
        if show_records and (not limit or n < limit):
            print(f"  @{start}: key={key!r} value_size={vlen} "
                  f"crc={'OK' if ok else 'BAD'}", file=out)
        n += 1
        total_key += klen
        total_val += vlen
    summary = {
        "records": n,
        "key_bytes": total_key,
        "value_bytes": total_val,
        "file_bytes": len(data),
        "bad_crc": bad_crc,
        "corrupt_at": corrupt_at,
    }
    print(f"{path}: {n} records, {total_val} value bytes, "
          f"{len(data)} file bytes"
          + (f", {bad_crc} BAD CRC" if bad_crc else "")
          + (f", CORRUPT at offset {corrupt_at}" if corrupt_at is not None
             else ""), file=out)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="blob_dump")
    ap.add_argument("--file", required=True)
    ap.add_argument("--show_records", action="store_true")
    ap.add_argument("--limit", type=int, default=0)
    ap.add_argument("--no_verify", action="store_true")
    a = ap.parse_args(argv)
    s = dump_blob_file(a.file, a.show_records, a.limit, not a.no_verify)
    return 1 if (s["bad_crc"] or s["corrupt_at"] is not None) else 0


if __name__ == "__main__":
    sys.exit(main())
