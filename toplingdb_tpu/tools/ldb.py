"""ldb: CLI admin tool (reference tools/ldb_cmd.cc in /root/reference).

Usage:
  python -m toplingdb_tpu.tools.ldb --db=DIR <command> [args]
Commands:
  get KEY | put KEY VALUE | delete KEY | scan [--from=K] [--to=K] [--limit=N]
  batchput K1 V1 K2 V2 ... | deleterange BEGIN END
  manifest_dump | wal_dump WALFILE | list_files | checkpoint DEST
  dump_events [--since=UNIX_SECONDS | --since=-SECONDS_AGO]
  repair | ingest_extern_sst FILE | approxsize --from=K --to=K
  verify_checksum | verify_file_checksums | scrub [--report] [--deep]
  list_column_families | compact [--from --to]
  idump [--limit] | backup BACKUP_DIR | restore BACKUP_DIR ID (into --db)
"""

from __future__ import annotations

import argparse
import sys

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--db", required=True)
    ap.add_argument("--hex", action="store_true")
    ap.add_argument("command")
    ap.add_argument("cmd_args", nargs="*")
    ap.add_argument("--from", dest="from_key", default=None)
    ap.add_argument("--to", dest="to_key", default=None)
    ap.add_argument("--limit", type=int, default=0)
    ap.add_argument("--report", action="store_true",
                    help="scrub: print the full JSON pass report")
    ap.add_argument("--deep", action="store_true",
                    help="scrub: also re-verify every block + blob record")
    ap.add_argument("--since", type=float, default=None,
                    help="dump_events: unix seconds floor (negative = "
                         "that many seconds before now)")
    args = ap.parse_args(argv)

    def enc(s: str) -> bytes:
        return bytes.fromhex(s) if args.hex else s.encode()

    def dec(b: bytes) -> str:
        return b.hex() if args.hex else b.decode(errors="replace")

    cmd = args.command
    a = args.cmd_args

    if cmd == "repair":
        from toplingdb_tpu.db.repair import repair_db

        report = repair_db(args.db)
        print(report)
        return 0
    if cmd == "dump_events":
        return _dump_events(args.db, args.since)
    if cmd == "manifest_dump":
        return _manifest_dump(args.db)
    if cmd == "wal_dump":
        return _wal_dump(a[0])
    if cmd == "list_files":
        from toplingdb_tpu.env import default_env

        for child in default_env().get_children(args.db):
            print(child)
        return 0
    if cmd == "restore":
        # Offline restore: ldb --db=DEST restore BACKUP_DIR BACKUP_ID
        from toplingdb_tpu.utilities.backup_engine import BackupEngine

        BackupEngine(a[0]).restore_db_from_backup(int(a[1]), args.db)
        print(f"restored backup {a[1]} into {args.db}")
        return 0

    db = DB.open(args.db, Options(create_if_missing=(cmd in ("put", "batchput"))))
    try:
        if cmd == "get":
            v = db.get(enc(a[0]))
            if v is None:
                print("Key not found")
                return 1
            print(dec(v))
        elif cmd == "put":
            db.put(enc(a[0]), enc(a[1]))
            print("OK")
        elif cmd == "delete":
            db.delete(enc(a[0]))
            print("OK")
        elif cmd == "deleterange":
            db.delete_range(enc(a[0]), enc(a[1]))
            print("OK")
        elif cmd == "batchput":
            from toplingdb_tpu.db.write_batch import WriteBatch

            b = WriteBatch()
            for k, v in zip(a[::2], a[1::2]):
                b.put(enc(k), enc(v))
            db.write(b)
            print("OK")
        elif cmd == "scan":
            ro = ReadOptions(
                iterate_lower_bound=enc(args.from_key) if args.from_key else None,
                iterate_upper_bound=enc(args.to_key) if args.to_key else None,
            )
            it = db.new_iterator(ro)
            it.seek_to_first()
            n = 0
            for k, v in it.entries():
                print(f"{dec(k)} : {dec(v)}")
                n += 1
                if args.limit and n >= args.limit:
                    break
        elif cmd == "checkpoint":
            from toplingdb_tpu.utilities.checkpoint import create_checkpoint

            create_checkpoint(db, a[0])
            print(f"checkpoint created at {a[0]}")
        elif cmd == "stats":
            print(db.get_property("tpulsm.stats"))
        elif cmd == "ingest_extern_sst":
            from toplingdb_tpu.utilities.sst_file_writer import (
                ingest_external_file,
            )

            level = ingest_external_file(db, a[0])
            print(f"ingested at level {level}")
        elif cmd == "approxsize":
            lo = enc(args.from_key) if args.from_key else b""
            if args.to_key:
                hi = enc(args.to_key)
            else:
                # Unbounded: one byte past the largest live user key.
                from toplingdb_tpu.db import dbformat

                largest = max(
                    (dbformat.extract_user_key(f.largest)
                     for _, f in db.versions.current.all_files()),
                    default=b"",
                )
                hi = largest + b"\x00"
            print(db.get_approximate_sizes([(lo, hi)])[0])
        elif cmd == "verify_checksum":
            db.verify_checksum()
            print("OK")
        elif cmd == "verify_file_checksums":
            # Whole-file checksums vs the MANIFEST (DB.verify_file_checksums)
            res = db.verify_file_checksums()
            print(f"OK: {res['files_verified']} files "
                  f"({res['bytes_verified']} bytes) verified, "
                  f"{res['files_skipped']} without a recorded checksum")
        elif cmd == "scrub":
            # One synchronous IntegrityScrubber pass (db/integrity.py).
            import json as _json

            rep = db.scrub(deep=args.deep)
            if args.report:
                print(_json.dumps(rep, indent=1, default=str))
            else:
                print(f"scrubbed {rep['files_scanned']} files "
                      f"({rep['bytes_verified']} bytes): "
                      f"{len(rep['corruptions'])} corruptions, "
                      f"quarantined {rep['quarantined']}")
            if rep["corruptions"]:
                return 1
        elif cmd == "list_column_families":
            for h in db.list_column_families():
                print(h.name)
        elif cmd == "compact":
            lo = enc(args.from_key) if args.from_key else None
            hi = enc(args.to_key) if args.to_key else None
            db.compact_range(lo, hi)
            db.wait_for_compactions()
            print("compaction done")
        elif cmd == "idump":
            # Internal-key dump (reference ldb idump): every version of
            # every key with seqno + type, straight off the SSTs.
            from toplingdb_tpu.db import dbformat as _dbf

            n = 0
            v = db.versions.current
            for _, f in v.all_files():
                r = db.table_cache.get_reader(f.number)
                it = r.new_iterator()
                it.seek_to_first()
                for ik, val in it.entries():
                    uk, seq, t = _dbf.split_internal_key(ik)
                    print(f"{dec(uk)} @ {seq} : "
                          f"{_dbf.ValueType(t).name} => {dec(val)}")
                    n += 1
                    if args.limit and n >= args.limit:
                        break
                if args.limit and n >= args.limit:
                    break
            print(f"internal keys: {n}")
        elif cmd == "backup":
            from toplingdb_tpu.utilities.backup_engine import BackupEngine

            bid = BackupEngine(a[0]).create_backup(db)
            print(f"backup {bid} created in {a[0]}")
        else:
            print(f"unknown command {cmd!r}", file=sys.stderr)
            return 2
    finally:
        db.close()
    return 0


def _dump_events(dbname: str, since: float | None) -> int:
    """Print the structured event-log stream (the EventLogger JSONL lines
    the DB writes to <db>/LOG; the rolled LOG.old is read first so output
    stays chronological). `since` filters on time_micros; a negative value
    means that many seconds before now. Does NOT open the DB — DB.open
    would roll the very LOG being dumped."""
    import json as _json
    import time as _time

    from toplingdb_tpu.env import default_env

    env = default_env()
    floor_us = None
    if since is not None:
        base = _time.time() + since if since < 0 else since
        floor_us = int(base * 1e6)
    n = 0
    for fname in ("LOG.old", "LOG"):
        path = f"{dbname}/{fname}"
        if not env.file_exists(path):
            continue
        for line in env.read_file(path).decode(errors="replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = _json.loads(line)
            except ValueError:
                continue  # non-JSON noise must not kill the dump
            if floor_us is not None and rec.get("time_micros", 0) < floor_us:
                continue
            print(line)
            n += 1
    print(f"# {n} events", flush=True)
    return 0


def _manifest_dump(dbname: str) -> int:
    from toplingdb_tpu.db import filename
    from toplingdb_tpu.db.log import LogReader
    from toplingdb_tpu.db.version_edit import VersionEdit
    from toplingdb_tpu.env import default_env

    env = default_env()
    cur = env.read_file(filename.current_file_name(dbname)).decode().strip()
    num = int(cur[len("MANIFEST-"):])
    path = filename.manifest_file_name(dbname, num)
    print(f"# {cur}")
    for i, rec in enumerate(LogReader(env.new_sequential_file(path)).records()):
        e = VersionEdit.decode(rec)
        parts = []
        if e.comparator:
            parts.append(f"comparator={e.comparator}")
        if e.log_number is not None:
            parts.append(f"log_number={e.log_number}")
        if e.next_file_number is not None:
            parts.append(f"next_file={e.next_file_number}")
        if e.last_sequence is not None:
            parts.append(f"last_seq={e.last_sequence}")
        for lvl, n in e.deleted_files:
            parts.append(f"del(L{lvl},{n})")
        for lvl, m in e.new_files:
            parts.append(f"add(L{lvl},{m.number},{m.file_size}B,{m.num_entries}e)")
        print(f"edit {i}: " + " ".join(parts))
    return 0


def _wal_dump(path: str) -> int:
    from toplingdb_tpu.db.log import LogReader
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.env import default_env

    env = default_env()
    from toplingdb_tpu.db import filename as _fn
    import os as _os

    _t, _num = _fn.parse_file_name(_os.path.basename(path))
    for rec in LogReader(env.new_sequential_file(path),
                         log_number=_num).records():
        b = WriteBatch(rec)
        print(f"seq={b.sequence()} count={b.count()}")
        for cf, t, k, v in b.entries_cf():
            cftag = f" cf={cf}" if cf else ""
            print(f"  type={t}{cftag} key={k!r} value={v!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
