"""Exception-hygiene lint (tier-1 CI): no silent broad excepts.

A broad handler (`except Exception`, `except BaseException`, a tuple
containing either, or a bare `except:`) can hide a dying background
loop from every observability plane in the engine. This lint makes the
swallow policy explicit: every broad handler in `toplingdb_tpu/` must
do at least one of

  E1. re-raise — any `raise` statement in the handler body;
  E2. latch the DB background error —
      `_set_background_error(...)` / `set_background_error(...)`;
  E3. tick a declared ticker — `record_tick(...)` / `record_ticks(...)`
      (ticker NAMES are linted separately by check_telemetry);
  E4. route through the `utils/errors.py` policy helpers —
      `errors.swallow(reason="...", exc=e)` with a string-literal
      reason, or `errors.guard(listener=...)`;
  E5. consume the exception VALUE — `except ... as e` where `e` is read
      in the handler body (`err = e`, `pg.member_done(e)`,
      `{"error": repr(e)}`): the failure is being propagated or
      reported, not silenced. A bound-but-unread `e` does not count.

Handlers satisfying none of these are reported with a `file:line`
witness. Two supporting rules keep the policy calls honest:

  E6. every `swallow(...)` call carries a string-literal, non-empty
      `reason=` (a variable reason defeats grep-ability and review);
  E7. every `guard(...)` call carries a `listener=` argument.

Sites with no fallback work should drop the try/except entirely and use
`with errors.swallow(reason=...):` — no handler, nothing to annotate.

Run: python -m toplingdb_tpu.tools.check_errors [repo_root]
Exit 0 clean; 1 with one violation per line otherwise.
"""

from __future__ import annotations

import ast
import os
import sys

BROAD_NAMES = {"Exception", "BaseException"}
BG_ERROR_FNS = {"_set_background_error", "set_background_error"}
TICKER_FNS = {"record_tick", "record_ticks"}
# utils/errors.py implements the policy (its __exit__ IS the swallow).
EXEMPT_REL = {os.path.join("utils", "errors.py")}


def _callee(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _kw(node: ast.Call, name: str) -> ast.expr | None:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    exprs = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in exprs:
        if isinstance(e, ast.Name) and e.id in BROAD_NAMES:
            return True
        if isinstance(e, ast.Attribute) and e.attr in BROAD_NAMES:
            return True
    return False


def _annotated(handler: ast.ExceptHandler) -> bool:
    """True if the handler body satisfies one of E1-E5."""
    if handler.name:
        for node in ast.walk(handler):
            if isinstance(node, ast.Name) and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return True  # E5: exception value consumed
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = _callee(node)
            if name in BG_ERROR_FNS or name in TICKER_FNS:
                return True
            if name == "swallow":
                r = _kw(node, "reason")
                if isinstance(r, ast.Constant) and isinstance(r.value, str) \
                        and r.value:
                    return True
            if name == "guard" and _kw(node, "listener") is not None:
                return True
    return False


def check_file(path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
    except (OSError, SyntaxError) as e:
        return [f"{path}: unparseable: {e}"]
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node):
            if not _annotated(node):
                out.append(
                    f"{path}:{node.lineno}: broad except without an error "
                    f"policy — re-raise, latch the background error, tick "
                    f"a ticker, or call errors.swallow(reason=..., exc=e) "
                    f"/ errors.guard(listener=...)")
        if isinstance(node, ast.Call):
            name = _callee(node)
            if name == "swallow" and node.keywords:
                r = _kw(node, "reason")
                has_policy_kws = any(
                    kw.arg in ("reason", "exc", "stats")
                    for kw in node.keywords)
                if has_policy_kws and not (
                        isinstance(r, ast.Constant)
                        and isinstance(r.value, str) and r.value):
                    out.append(
                        f"{path}:{node.lineno}: errors.swallow() needs a "
                        f"non-empty string-literal reason=")
            if name == "guard" and any(
                    kw.arg in ("listener", "stats") for kw in node.keywords):
                if _kw(node, "listener") is None:
                    out.append(
                        f"{path}:{node.lineno}: errors.guard() needs a "
                        f"listener= argument naming the hook")
    return out


def run(repo_root: str | None = None) -> list[str]:
    repo_root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pkg = os.path.join(repo_root, "toplingdb_tpu")
    if not os.path.isdir(pkg):
        pkg = repo_root  # synthetic trees in tests
    violations = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.relpath(path, pkg) in EXEMPT_REL:
                continue
            violations.extend(check_file(path))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = run(root)
    for v in violations:
        print(v)
    print(f"check_errors: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
