"""Workload-trace analyzer CLI (reference tools/trace_analyzer_tool.cc).

Reads a trace produced by utils.trace.Tracer and reports per-op counts,
throughput over time, key/value size distributions, and the hottest keys;
optionally writes per-op key-access-count files (the reference's
-output_dir artifacts for downstream modeling).

Usage:
  python -m toplingdb_tpu.tools.trace_analyzer TRACE [-k TOPK]
      [--output-dir DIR] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter, defaultdict

from toplingdb_tpu.utils.trace import _OP_NAMES, read_trace


def _analyze_full(env, trace_path: str, top_k: int = 10):
    """(json-clean report, per-op key Counters). The single aggregation
    loop behind both the CLI and utils.trace.analyze_trace."""
    ops = Counter()
    key_hits: dict[str, Counter] = defaultdict(Counter)
    key_sizes = Counter()
    value_sizes = Counter()
    per_second = Counter()
    first_ts = last_ts = None
    total = 0
    for op, ts, slices in read_trace(env, trace_path):
        name = _OP_NAMES.get(op, str(op))
        ops[name] += 1
        total += 1
        if first_ts is None:
            first_ts = ts
        last_ts = ts
        per_second[ts // 1_000_000] += 1
        if slices:
            key_hits[name][bytes(slices[0])] += 1
            key_sizes[len(slices[0])] += 1
            if len(slices) > 1 and name in ("put", "merge"):
                value_sizes[len(slices[1])] += 1
    all_keys = Counter()
    for c in key_hits.values():
        all_keys.update(c)
    span_s = ((last_ts - first_ts) / 1e6) if total and last_ts != first_ts else 0.0
    qps = sorted(per_second.values())
    report = {
        "total_ops": total,
        "per_op": dict(ops),
        "unique_keys": len(all_keys),
        "time_span_s": round(span_s, 6),
        "avg_qps": round(total / span_s, 1) if span_s else float(total),
        "peak_qps": qps[-1] if qps else 0,
        "key_size_dist": _dist(key_sizes),
        "value_size_dist": _dist(value_sizes),
        "hottest_keys": [
            {"key": k.decode(errors="replace"), "count": c}
            for k, c in all_keys.most_common(top_k)
        ],
    }
    return report, key_hits


def analyze(env, trace_path: str, top_k: int = 10) -> dict:
    """JSON-serializable trace report."""
    return _analyze_full(env, trace_path, top_k)[0]


def _dist(c: Counter) -> dict:
    """Percentiles straight from the (size, count) pairs — O(distinct
    sizes) memory, never materializing one element per observation."""
    if not c:
        return {}
    items = sorted(c.items())
    n = sum(c.values())
    def pct(rank):  # value at 0-based rank
        cum = 0
        for size, cnt in items:
            cum += cnt
            if cum > rank:
                return size
        return items[-1][0]
    return {
        "count": n,
        "min": items[0][0],
        "p50": pct(n // 2),
        "p99": pct(min(n - 1, (n * 99) // 100)),
        "max": items[-1][0],
        "avg": round(sum(s * cnt for s, cnt in items) / n, 1),
    }


def write_key_counts(key_hits: dict, output_dir: str) -> list[str]:
    """Per-op '<op>-key_counts.txt' files: 'hex_key count' per line sorted
    by count desc (the reference analyzer's key-space artifacts)."""
    os.makedirs(output_dir, exist_ok=True)
    written = []
    for op, counts in key_hits.items():
        path = os.path.join(output_dir, f"{op}-key_counts.txt")
        with open(path, "w") as f:
            for k, c in counts.most_common():
                f.write(f"{k.hex()} {c}\n")
        written.append(path)
    return written


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_analyzer",
        description="Analyze a toplingdb_tpu workload trace",
    )
    ap.add_argument("trace")
    ap.add_argument("-k", "--top-k", type=int, default=10)
    ap.add_argument("--output-dir", default=None)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    from toplingdb_tpu.env import default_env

    report, key_hits = _analyze_full(default_env(), args.trace, args.top_k)
    if args.output_dir:
        for p in write_key_counts(key_hits, args.output_dir):
            print(f"wrote {p}", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=1))
        return 0
    print(f"total ops        {report['total_ops']}")
    print(f"unique keys      {report['unique_keys']}")
    print(f"time span        {report['time_span_s']:.3f}s "
          f"(avg {report['avg_qps']} qps, peak {report['peak_qps']})")
    for op, n in sorted(report["per_op"].items(), key=lambda kv: -kv[1]):
        print(f"  {op:<14} {n}")
    if report["key_size_dist"]:
        d = report["key_size_dist"]
        print(f"key sizes        min {d['min']} p50 {d['p50']} "
              f"p99 {d['p99']} max {d['max']}")
    if report["value_size_dist"]:
        d = report["value_size_dist"]
        print(f"value sizes      min {d['min']} p50 {d['p50']} "
              f"p99 {d['p99']} max {d['max']}")
    print("hottest keys:")
    for e in report["hottest_keys"]:
        print(f"  {e['count']:>8}  {e['key']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
