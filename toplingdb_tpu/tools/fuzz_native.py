"""Greybox fuzz harness for the native parser surface.

The reference ships libFuzzer targets (fuzz/db_fuzzer.cc,
fuzz/db_map_fuzzer.cc, fuzz/sst_file_writer_fuzzer.cc); this is the
equivalent harness for our native C++ surface without compiler
instrumentation (atheris/libFuzzer are not in the image): structure-aware
MUTATION of valid inputs plus FEEDBACK-DRIVEN corpus growth — a mutant
that produces a previously-unseen outcome signature (return code, decoded
count bucket, error class) joins the corpus and is mutated further, the
greybox loop's novelty search over observable behavior. Differential
checks cross-validate native accept/reject decisions against the Python
twins, so semantic divergence (not just crashes) is a failure.

Targets:
  wb       WriteBatch wire-image insert (skiplist + trie native parsers)
  block    single data-block decode (tpulsm_decode_block vs Python Block)
  scan     whole-SST fused scan (tpulsm_scan_blocks)
  manifest MANIFEST/VersionEdit recovery
  abi      contract-driven shapes: argument lists are generated from the
           parsed C signatures + the §2.10.2 buffer-pairing table
           (tools/check_native_abi), so every parser-surface export is
           driven with correctly-paired caps and hostile content/indices

Usage: python -m toplingdb_tpu.tools.fuzz_native --target wb --runs 5000
       [--corpus DIR] [--seed N]
Exit code 0 = no findings; 1 = a finding was written to the corpus dir.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import random
import sys
from toplingdb_tpu.utils import errors as _errors


def _mutate(rng: random.Random, data: bytes, max_ops: int = 4) -> bytes:
    """Byte-level structure-agnostic mutations (bit flips, splices,
    truncations, varint-ish small-int overwrites, duplications)."""
    b = bytearray(data)
    for _ in range(rng.randrange(1, max_ops + 1)):
        if not b:
            b = bytearray(rng.randbytes(rng.randrange(1, 64)))
            continue
        op = rng.randrange(6)
        i = rng.randrange(len(b))
        if op == 0:
            b[i] ^= 1 << rng.randrange(8)
        elif op == 1:
            b[i] = rng.randrange(256)
        elif op == 2:  # truncate tail
            del b[i:]
        elif op == 3:  # splice a random window elsewhere
            j = rng.randrange(len(b))
            w = rng.randrange(1, 16)
            b[i:i] = b[j:j + w]
        elif op == 4:  # small-integer overwrite (length fields)
            b[i] = rng.choice((0, 1, 0x7F, 0x80, 0xFF))
        else:  # duplicate tail
            b += b[i:i + rng.randrange(1, 32)]
    return bytes(b)


class Corpus:
    """Signature-novelty corpus: inputs keyed by outcome signature."""

    def __init__(self, path: str | None):
        self.path = path
        self.items: list[bytes] = []
        self.signatures: set = set()
        if path:
            os.makedirs(path, exist_ok=True)
            for n in sorted(os.listdir(path)):
                try:
                    self.items.append(
                        open(os.path.join(path, n), "rb").read())
                except OSError:
                    pass

    def maybe_add(self, data: bytes, signature) -> bool:
        if signature in self.signatures:
            return False
        self.signatures.add(signature)
        self.items.append(data)
        if self.path:
            h = hashlib.sha1(data).hexdigest()[:16]
            with open(os.path.join(self.path, f"c-{h}"), "wb") as f:
                f.write(data)
        return True

    def pick(self, rng: random.Random, seeds: list[bytes]) -> bytes:
        pool = self.items if (self.items and rng.random() < 0.7) else seeds
        return rng.choice(pool)


# -- targets ----------------------------------------------------------------

def _wb_seeds(rng):
    from toplingdb_tpu.db.write_batch import WriteBatch

    seeds = []
    for shape in range(4):
        wb = WriteBatch()
        for i in range(rng.randrange(1, 24)):
            k = b"k%04d" % rng.randrange(200)
            if shape == 0:
                wb.put(k, b"v" * rng.randrange(0, 40))
            elif shape == 1:
                wb.delete(k)
            elif shape == 2:
                wb.merge(k, b"m%d" % i)
            else:
                wb.put_entity(k, b"\x00WCE1\x01\x00\x02vv")
        seeds.append(wb.data())
    return seeds


def fuzz_wb(rng, runs, corpus: Corpus):
    from toplingdb_tpu.db.memtable import NativeSkipListRep, NativeTrieRep
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.utils.status import Corruption

    seeds = _wb_seeds(rng)
    findings = 0
    for it in range(runs):
        data = _mutate(rng, corpus.pick(rng, seeds))
        rep = NativeSkipListRep() if it % 2 else NativeTrieRep()
        before = len(rep)
        r = rep.insert_wb(data, 1000)
        if r is None:
            # Native rejected (or unsupported): rejection must be CLEAN.
            if len(rep) != before:
                print(f"FINDING[wb]: rejected batch mutated the rep "
                      f"({before} -> {len(rep)})")
                corpus.maybe_add(data, ("FINDING", it))
                findings += 1
            sig = ("rej",)
        else:
            count = r[0]
            # Differential: if the native wire parser ACCEPTED, the
            # Python decode must ALSO accept, with the same record count
            # (a python-side raise on natively-valid bytes IS the
            # divergence class this harness exists to catch).
            try:
                py_count = sum(1 for _ in WriteBatch(data).entries_cf())
            except Corruption:
                py_count = "corruption"
            except Exception as e:  # noqa: BLE001
                py_count = type(e).__name__
            if py_count != count:
                print(f"FINDING[wb]: native applied {count} records, "
                      f"python says {py_count!r}")
                corpus.maybe_add(data, ("FINDING", it))
                findings += 1
            sig = ("ok", min(count, 8))
        corpus.maybe_add(data, sig)
    return findings


def _block_seeds(rng):
    from toplingdb_tpu.table.block import BlockBuilder

    seeds = []
    for interval in (1, 4, 16):
        bb = BlockBuilder(interval)
        for i in range(rng.randrange(2, 40)):
            bb.add(b"key%05d" % i + b"\x01" * 8, b"val%d" % i)
        seeds.append(bb.finish())
    return seeds


def fuzz_block(rng, runs, corpus: Corpus):
    import numpy as np

    from toplingdb_tpu import native

    lib = native.lib()
    seeds = _block_seeds(rng)
    key_out = np.empty(1 << 20, np.uint8)
    val_out = np.empty(1 << 20, np.uint8)
    ko = np.empty(1 << 16, np.int32)
    kl = np.empty(1 << 16, np.int32)
    vo = np.empty(1 << 16, np.int32)
    vl = np.empty(1 << 16, np.int32)
    findings = 0
    for it in range(runs):
        data = _mutate(rng, corpus.pick(rng, seeds))
        buf = np.frombuffer(data, np.uint8)
        rc = lib.tpulsm_decode_block(
            buf.tobytes(), len(buf),
            native.np_u8p(key_out), len(key_out),
            native.np_u8p(val_out), len(val_out),
            native.np_i32p(ko), native.np_i32p(kl),
            native.np_i32p(vo), native.np_i32p(vl), 1 << 16,
        )
        if rc >= 0:
            # Differential: Python block iterator over the same bytes must
            # decode the same entry count (or reject).
            try:
                from toplingdb_tpu.table.block import BlockIter

                bi = BlockIter(data, None)
                bi.seek_to_first()
                py_n = sum(1 for _ in bi.entries())
            except Exception as e:
                _errors.swallow(reason="py-decoder-refused", exc=e)
                py_n = None
            if py_n is not None and py_n != rc:
                print(f"FINDING[block]: native decoded {rc}, python {py_n}")
                corpus.maybe_add(data, ("FINDING", it))
                findings += 1
        corpus.maybe_add(data, ("rc", max(-9, min(int(rc), 8))))
    return findings


def fuzz_scan(rng, runs, corpus: Corpus):
    import numpy as np

    from toplingdb_tpu import native
    from toplingdb_tpu.db.dbformat import (
        InternalKeyComparator,
        ValueType,
        make_internal_key,
    )
    from toplingdb_tpu.env import MemEnv
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    from toplingdb_tpu.table.reader import TableReader

    lib = native.lib()
    icmp = InternalKeyComparator()
    env = MemEnv()
    seeds = []
    for comp in (0, fmt.SNAPPY_COMPRESSION):
        w = env.new_writable_file("/f.sst")
        tb = TableBuilder(w, icmp, TableOptions(block_size=512,
                                                compression=comp))
        for i in range(300):
            tb.add(make_internal_key(b"k%05d" % i, i + 1, ValueType.VALUE),
                   b"v%04d" % i)
        tb.finish()
        w.close()
        seeds.append(bytes(env.read_file("/f.sst")))

    # Handles come from the REAL footer of the seed; mutants reuse them so
    # the scan sees plausible-but-corrupt block spans.
    r = TableReader(env.new_random_access_file("/f.sst"), icmp,
                    TableOptions())
    idx = r.new_index_iterator()
    idx.seek_to_first()
    handles = [fmt.BlockHandle.decode_exact(e) for _, e in idx.entries()]
    b_offs = np.array([h.offset for h in handles], np.int64)
    b_lens = np.array([h.size for h in handles], np.int64)
    key_out = np.empty(1 << 20, np.uint8)
    val_out = np.empty(1 << 20, np.uint8)
    ko = np.empty(1 << 16, np.int32)
    kl = np.empty(1 << 16, np.int32)
    vo = np.empty(1 << 16, np.int32)
    vl = np.empty(1 << 16, np.int32)
    findings = 0
    for it in range(runs):
        data = _mutate(rng, corpus.pick(rng, seeds))
        buf = np.frombuffer(data, np.uint8)
        rc = lib.tpulsm_scan_blocks(
            native.np_u8p(buf), len(buf),
            native.np_i64p(b_offs), native.np_i64p(b_lens), len(handles),
            1,  # verify_crc on: corrupt payloads must be CAUGHT
            native.np_u8p(key_out), len(key_out),
            native.np_u8p(val_out), len(val_out),
            native.np_i32p(ko), native.np_i32p(kl),
            native.np_i32p(vo), native.np_i32p(vl), 1 << 16, 0, 0,
        )
        if rc < -8 or rc > 1 << 16:
            print(f"FINDING[scan]: out-of-contract rc {rc}")
            corpus.maybe_add(data, ("FINDING", it))
            findings += 1
        corpus.maybe_add(data, ("rc", max(-9, min(int(rc), 4))))
    return findings


def fuzz_manifest(rng, runs, corpus: Corpus):
    import tempfile

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.status import Corruption, IOError_

    # Seed: a real MANIFEST from a tiny DB.
    d = tempfile.mkdtemp(prefix="fz_mf_")
    db = DB.open(d, Options(create_if_missing=True))
    for i in range(200):
        db.put(b"k%04d" % i, b"v")
    db.flush()
    db.close()
    findings = 0
    cur = open(os.path.join(d, "CURRENT")).read().strip()
    seed = open(os.path.join(d, cur), "rb").read()
    for it in range(runs):
        # Re-read CURRENT every round: a successful open ROLLS the
        # manifest and repoints CURRENT — mutating the stale file would
        # silently stop exercising the parser.
        cur = open(os.path.join(d, "CURRENT")).read().strip()
        mpath = os.path.join(d, cur)
        data = _mutate(rng, corpus.pick(rng, [seed]))
        open(mpath, "wb").write(data)
        try:
            db = DB.open(d, Options())
            db.close()
            sig = ("open-ok",)
        except (Corruption, IOError_, ValueError, KeyError) as e:
            sig = ("err", type(e).__name__)
        except Exception as e:  # noqa: BLE001
            print(f"FINDING[manifest]: unexpected {type(e).__name__}: "
                  f"{str(e)[:120]}")
            corpus.maybe_add(data, ("FINDING", it))
            findings += 1
            sig = ("unexpected", type(e).__name__)
        corpus.maybe_add(data, sig)
    open(mpath, "wb").write(seed)
    import shutil

    shutil.rmtree(d, ignore_errors=True)
    return findings


# -- contract-driven shapes (tools/check_native_abi) ------------------------

# Parser-surface exports: every pointer they take is paired with an
# explicit length/cap and the C side bounds-checks untrusted indices
# against them, so contract-shaped hostile inputs are safe to run
# in-process. Producer-surface exports (builders, memtables) trust their
# offs/lens arrays by design and are excluded.
ABI_FUZZ_SYMS = (
    "tpulsm_crc32c_extend", "tpulsm_xxh64", "tpulsm_wb_protect",
    "tpulsm_block_seek", "tpulsm_decode_block", "tpulsm_decode_blocks",
    "tpulsm_inflate_blocks", "tpulsm_scan_blocks",
    "tpulsm_scan_blocks_refvals",
    # Zip data plane: every kernel validates its full input surface
    # (section length floors, offs/lens bounds, entry/group windows)
    # before touching a byte, so hostile contract-shaped input is safe.
    "tpulsm_zip_newkey", "tpulsm_zip_encode_keys",
    "tpulsm_zip_encode_values", "tpulsm_zip_decode_keys",
    "tpulsm_zip_group_decode", "tpulsm_zip_table_handle_new",
)

_BLOB_NAMES = ("data", "block", "file_buf", "rep", "target",
               "key_buf", "val_buf", "kmeta", "vblob")


def _cdiv(a: int, b: int) -> int:
    return (max(a, 0) + max(b, 1) - 1) // max(b, 1)


# §2.10.2 `:!` exemptions fall in two classes: opaque handles the fuzzer
# cannot mint (symbol stays unfuzzable), and derived capacities the
# callee recomputes from its scalar parameters. This table sizes the
# second class — worst case, so an under-allocation can never masquerade
# as a kernel bug — from the same scalars the argument list carries.
_DERIVED_ELEMS = {
    ("tpulsm_zip_encode_keys", "meta_out"): lambda v: 4 * max(v["n"], 1),
    ("tpulsm_zip_encode_keys", "gso_out"):
        lambda v: 4 * _cdiv(v["n"], v["group"]),
    ("tpulsm_zip_encode_values", "go_out"):
        lambda v: 4 * (_cdiv(v["n"], v["vg"]) + 1),
    ("tpulsm_zip_encode_values", "flags_out"):
        lambda v: _cdiv(_cdiv(v["n"], v["vg"]), 8),
    ("tpulsm_zip_decode_keys", "key_offs"): lambda v: v["e1"] - v["e0"],
    ("tpulsm_zip_decode_keys", "key_lens"): lambda v: v["e1"] - v["e0"],
    ("tpulsm_zip_group_decode", "raw_offs"):
        lambda v: v["g1"] - v["g0"] + 1,
}

# Ranges for scalars whose default 0..3 draw would pin a kernel in its
# reject path (e.g. zip klen < 8 is always -3): wide enough to cross the
# accept/reject boundary in both directions.
_SCALAR_HINTS = {
    "klen": (6, 72), "uklen": (0, 64), "group": (0, 33), "vg": (0, 33),
    "meta16": (0, 2), "lens32": (0, 2), "n": (0, 513), "e0": (-2, 64),
    "e1": (-2, 64), "g0": (-2, 8), "g1": (-2, 8), "key_base": (0, 4),
    "compress": (0, 2), "level": (0, 9), "max_dict_bytes": (0, 1025),
}


def load_abi_contract(repo_root: str | None = None):
    """Parse the three sources of truth the ABI checker cross-validates
    (C signatures, ctypes bindings, §2.10.2 table) and return
    (sigs, bindings, rows). Raises if any of them fails to parse — a
    fuzz run on a drifted contract would test the wrong shapes."""
    from toplingdb_tpu.tools import check_native_abi as abi

    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    nat = os.path.join(root, "toplingdb_tpu", "native")
    sigs, v1 = abi.parse_c_signatures(os.path.join(nat, "tpulsm_native.cc"))
    bindings, v2 = abi.parse_ctypes_bindings(os.path.join(nat, "__init__.py"))
    rows, v3 = abi.parse_contract_table(os.path.join(root, "ARCHITECTURE.md"))
    if v1 or v2 or v3:
        raise RuntimeError("ABI contract failed to parse: "
                           + "; ".join(v1 + v2 + v3))
    return sigs, bindings, rows


def shapes_from_contract(rng, sym, sigs, bindings, rows, data=b""):
    """Build one concrete ctypes argument list for `sym` from the parsed
    contract: the §2.10.2 row says which integer parameter sizes each
    buffer, the C signature says constness/element width, and the binding
    token says the exact ctypes value to construct. `data` feeds the
    primary input blob so the corpus loop drives the parser; index arrays
    get values straddling the valid range (including negatives) to hit
    the bounds-check paths. Returns (args, keepalive) or None when the
    symbol takes opaque handles (`:!`) the fuzzer cannot mint."""
    import ctypes

    import numpy as np

    _, params = sigs[sym]
    specs = rows[sym][2]
    argtoks = bindings[sym]["argtypes"]
    if any(s == "!" and (sym, p) not in _DERIVED_ELEMS
           for p, s in specs.items()):
        return None  # true opaque handles: not mintable from bytes
    ptr_ct = {"POINTER(c_uint8)": (np.uint8, ctypes.c_uint8),
              "POINTER(c_int8)": (np.int8, ctypes.c_int8),
              "POINTER(c_int32)": (np.int32, ctypes.c_int32),
              "POINTER(c_uint32)": (np.uint32, ctypes.c_uint32),
              "POINTER(c_int64)": (np.int64, ctypes.c_int64),
              "POINTER(c_uint64)": (np.uint64, ctypes.c_uint64)}
    # Element count for every sizing parameter: the primary blob's length
    # param carries len(data); other counts stay small so out-buffers are
    # bounded and count-indexed loops terminate quickly.
    blob = next((n for _, n in params if n in specs
                 and n in _BLOB_NAMES), None)
    sized: dict[str, int] = {}
    for pname, spec in specs.items():
        if spec.isdigit() or spec == "!":
            continue
        sized[spec] = (len(data) if pname == blob
                       else sized.get(spec, rng.randrange(0, 257)))
    # Scalars draw before buffers so derived-capacity outputs (zip group
    # counts, entry windows) can size themselves from the same values.
    scalars: dict[str, int] = {}
    for _, pname in params:
        if pname in specs:
            continue
        if pname in sized:
            scalars[pname] = sized[pname]
        else:
            lo, hi = _SCALAR_HINTS.get(pname, (0, 4))
            scalars[pname] = rng.randrange(lo, hi)
    args, keepalive = [], []
    for (ctype, pname), tok in zip(params, argtoks):
        if pname not in specs:  # scalar: a chosen size, or a flag/seed
            args.append(scalars[pname])
            continue
        spec = specs[pname]
        derive = _DERIVED_ELEMS.get((sym, pname))
        if derive is not None:
            n = derive(scalars)
        elif spec.isdigit():
            n = int(spec)
        else:
            n = sized[spec]
        if tok == "c_char_p":
            raw = (data if pname == blob
                   else rng.randbytes(n))[:n].ljust(n, b"\x00")
            keepalive.append(raw)
            args.append(raw)
            continue
        dt, ct = ptr_ct[tok]
        if not ctype.startswith("const"):
            arr = np.zeros(max(n, 1), dt)  # out-buffer sized to its cap
        elif dt is np.uint8:
            raw = (data if pname == blob else rng.randbytes(n))
            arr = np.frombuffer(raw[:n].ljust(n, b"\x00"), dt).copy()
        else:
            # Untrusted index/length array: straddle the valid range.
            hi = max(len(data), 2)
            arr = np.array([rng.randrange(-4, 2 * hi)
                            for _ in range(max(n, 1))], dt)
        keepalive.append(arr)
        args.append(ctypes.cast(arr.ctypes.data, ctypes.POINTER(ct)))
    return args, keepalive


def fuzz_abi(rng, runs, corpus: Corpus):
    from toplingdb_tpu import native

    lib = native.lib()
    sigs, bindings, rows = load_abi_contract()
    syms = [s for s in ABI_FUZZ_SYMS
            if s in sigs and s in bindings and s in rows
            and hasattr(lib, s)]
    if not syms:
        print("fuzz[abi]: no contract symbols available (native lib "
              "missing?)")
        return 0
    seeds = _block_seeds(rng) + [rng.randbytes(256)]
    findings = 0
    for it in range(runs):
        sym = syms[it % len(syms)]
        data = _mutate(rng, corpus.pick(rng, seeds))
        shaped = shapes_from_contract(rng, sym, sigs, bindings, rows, data)
        if shaped is None:
            continue
        args, keepalive = shaped
        rc = getattr(lib, sym)(*args)
        if sigs[sym][0] == "void*" and rc:
            # Minted handles (zip table ctor) borrow the keepalive
            # buffers: free before they go away, and never leak.
            import ctypes

            lib.tpulsm_table_handle_free(ctypes.c_void_p(rc))
            rc = 1  # signature: handle minted vs refused, not the address
        del keepalive
        signed = sigs[sym][0] in ("int32_t", "int64_t")
        if signed and rc < -16:
            # Error codes are small negative ints; anything below the
            # contract band means a length/count escaped as a status.
            print(f"FINDING[abi]: {sym} returned out-of-contract rc {rc}")
            corpus.maybe_add(data, ("FINDING", it))
            findings += 1
        sig = (sym, max(-16, min(int(rc), 8)) if signed
               else "h%d" % bool(rc))
        corpus.maybe_add(data, sig)
    return findings


TARGETS = {"wb": fuzz_wb, "block": fuzz_block, "scan": fuzz_scan,
           "manifest": fuzz_manifest, "abi": fuzz_abi}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", choices=sorted(TARGETS) + ["all"],
                    default="all")
    ap.add_argument("--runs", type=int, default=2000)
    ap.add_argument("--corpus", default=None,
                    help="persist + reuse interesting inputs here")
    ap.add_argument("--seed", type=int, default=1234)
    args = ap.parse_args(argv)
    total = 0
    names = sorted(TARGETS) if args.target == "all" else [args.target]
    for name in names:
        rng = random.Random(args.seed)
        corpus = Corpus(os.path.join(args.corpus, name)
                        if args.corpus else None)
        f = TARGETS[name](rng, args.runs, corpus)
        print(f"fuzz[{name}]: {args.runs} runs, "
              f"{len(corpus.signatures)} signatures, {f} findings")
        total += f
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
