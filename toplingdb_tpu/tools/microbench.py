"""Microbenchmarks of the hot primitives (reference microbench/
db_basic_bench.cc): block build/decode, crc32c, xxh64, memtable insert,
host/native sort. Prints one JSON object per benchmark.

Usage: python -m toplingdb_tpu.tools.microbench [--n=N] [--filter=SUBSTR]
"""

from __future__ import annotations

import argparse
import json
import time


def _bench(name, fn, n_items, repeats=3):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({
        "bench": name, "items": n_items, "best_s": round(best, 5),
        "items_per_s": round(n_items / best) if best else None,
    }))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--filter", default="")
    args = ap.parse_args(argv)
    n = args.n

    import numpy as np

    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
    from toplingdb_tpu.db.memtable import MemTable
    from toplingdb_tpu.utils import crc32c

    icmp = InternalKeyComparator()
    entries = [
        (dbformat.make_internal_key(b"key%08d" % i, i + 1, ValueType.VALUE),
         b"value-%08d" % i)
        for i in range(n)
    ]
    payload = b"x" * (1 << 20)

    def run(name, fn, items):
        if args.filter in name:
            _bench(name, fn, items)

    run("crc32c_1MiB", lambda: [crc32c.value(payload) for _ in range(16)],
        16 << 20)
    run("xxh64_1MiB", lambda: [crc32c.xxh64(payload) for _ in range(16)],
        16 << 20)

    def memtable_insert():
        m = MemTable(icmp)
        for i, (ik, v) in enumerate(entries):
            m.add(i + 1, int(ValueType.VALUE), ik[:-8], v)

    run("memtable_insert", memtable_insert, n)

    from toplingdb_tpu.env import MemEnv
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions

    env = MemEnv()

    def block_build():
        w = env.new_writable_file("/mb.sst")
        b = TableBuilder(w, icmp, TableOptions())
        for ik, v in entries:
            b.add(ik, v)
        b.finish()
        w.close()

    run("table_build", block_build, n)

    from toplingdb_tpu.table.reader import TableReader

    if args.filter in "table_scan":
        block_build()  # scan setup — skip when filtered out

    def table_scan():
        r = TableReader(env.new_random_access_file("/mb.sst"), icmp,
                        TableOptions())
        it = r.new_iterator()
        it.seek_to_first()
        c = 0
        for _ in it.entries():
            c += 1
        assert c == n

    run("table_scan", table_scan, n)

    from toplingdb_tpu.ops import compaction_kernels as ck

    key_buf = bytearray()
    offs, lens = [], []
    for ik, _ in entries:
        offs.append(len(key_buf))
        lens.append(len(ik))
        key_buf += ik
    kb = np.frombuffer(bytes(key_buf), dtype=np.uint8)
    ko = np.array(offs, np.int64)
    kl = np.array(lens, np.int64)

    if ck.host_sort_order(kb[: int(kl[0])], ko[:1], kl[:1]) is not None:
        run("native_sort", lambda: ck.host_sort_order(kb, ko, kl), n)
    run("lexsort_twin",
        lambda: ck.host_encode_sort(kb, ko, kl, 12), n)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
