"""Microbenchmarks of the hot primitives (reference microbench/
db_basic_bench.cc): block build/decode, crc32c, xxh64, memtable insert,
host/native sort. Prints one JSON object per benchmark.

Usage: python -m toplingdb_tpu.tools.microbench [--n=N] [--filter=SUBSTR]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _bench(name, fn, n_items, repeats=3):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    print(json.dumps({
        "bench": name, "items": n_items, "best_s": round(best, 5),
        "items_per_s": round(n_items / best) if best else None,
    }))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--filter", default="")
    args = ap.parse_args(argv)
    n = args.n

    if args.filter in "compaction_mesh":
        # The mesh case needs >1 device; the count is fixed at jax
        # backend creation, so rewrite the env NOW if jax isn't up yet.
        import sys as _sys

        if "jax" not in _sys.modules:
            from toplingdb_tpu.parallel import mesh_plan as _mp

            _mp.configure_virtual_devices(8)

    import numpy as np

    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
    from toplingdb_tpu.db.memtable import MemTable
    from toplingdb_tpu.utils import crc32c

    icmp = InternalKeyComparator()
    entries = [
        (dbformat.make_internal_key(b"key%08d" % i, i + 1, ValueType.VALUE),
         b"value-%08d" % i)
        for i in range(n)
    ]
    payload = b"x" * (1 << 20)

    def run(name, fn, items):
        if args.filter in name:
            _bench(name, fn, items)

    run("crc32c_1MiB", lambda: [crc32c.value(payload) for _ in range(16)],
        16 << 20)
    run("xxh64_1MiB", lambda: [crc32c.xxh64(payload) for _ in range(16)],
        16 << 20)

    def memtable_insert():
        m = MemTable(icmp)
        for i, (ik, v) in enumerate(entries):
            m.add(i + 1, int(ValueType.VALUE), ik[:-8], v)

    run("memtable_insert", memtable_insert, n)

    def rep_insert_batch(rep_name):
        from toplingdb_tpu.db.memtable import create_memtable_rep

        m = n
        keys = np.random.default_rng(1).integers(0, m * 2, m)
        kb = np.zeros(m * 12, np.uint8)
        for j in range(12):
            kb[j::12] = (keys // 10 ** (11 - j)) % 10 + 48
        offs = np.arange(m, dtype=np.int64) * 12
        lens = np.full(m, 12, np.int32)
        invs = (~((np.arange(m, dtype=np.uint64) + 1) << np.uint64(8)
                  | np.uint64(1)))
        vb = np.full(m * 16, 118, np.uint8)
        voffs = np.arange(m, dtype=np.int64) * 16
        vlens = np.full(m, 16, np.int32)

        def go():
            # Fresh rep per repeat: a COLD insert, not a re-insert into
            # an already-populated structure.
            rep = create_memtable_rep(rep_name)
            rep.insert_batch(kb, offs, lens, invs, vb, voffs, vlens, m)

        return go

    run("skiplist_insert_batch", rep_insert_batch("skiplist"), n)
    run("cspp_trie_insert_batch", rep_insert_batch("cspp"), n)

    def host_merge_runs():
        from toplingdb_tpu.ops import compaction_kernels as ck

        rng = np.random.default_rng(2)
        runs = []
        seq_base = 1
        for _ in range(4):
            m = n // 4
            uk = np.sort(rng.integers(0, n, m))
            # Internal-key order: duplicate user keys need seq DESCENDING
            # within the run (the merge's presorted precondition).
            recs = []
            j = m
            for k in uk:
                packed = ((seq_base + j) << 8) | 1
                j -= 1
                recs.append(b"%012d" % k + packed.to_bytes(8, "little"))
            seq_base += m
            runs.append(recs)
        recs = [r for rr in runs for r in rr]
        buf = np.frombuffer(b"".join(recs), np.uint8)
        lens = np.full(len(recs), 20, np.int64)
        offs = np.arange(len(recs), dtype=np.int64) * 20
        rs = np.cumsum([0] + [len(rr) for rr in runs], dtype=np.int64)
        return lambda: ck.host_sort_order(buf, offs, lens, run_starts=rs)

    run("host_merge_runs_4way", host_merge_runs(), n)

    from toplingdb_tpu.env import MemEnv
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions

    env = MemEnv()

    def block_build():
        w = env.new_writable_file("/mb.sst")
        b = TableBuilder(w, icmp, TableOptions())
        for ik, v in entries:
            b.add(ik, v)
        b.finish()
        w.close()

    run("table_build", block_build, n)

    from toplingdb_tpu.table.reader import TableReader

    if args.filter in "table_scan":
        block_build()  # scan setup — skip when filtered out

    def table_scan():
        r = TableReader(env.new_random_access_file("/mb.sst"), icmp,
                        TableOptions())
        it = r.new_iterator()
        it.seek_to_first()
        c = 0
        for _ in it.entries():
            c += 1
        assert c == n

    run("table_scan", table_scan, n)

    from toplingdb_tpu.ops import compaction_kernels as ck

    key_buf = bytearray()
    offs, lens = [], []
    for ik, _ in entries:
        offs.append(len(key_buf))
        lens.append(len(ik))
        key_buf += ik
    kb = np.frombuffer(bytes(key_buf), dtype=np.uint8)
    ko = np.array(offs, np.int64)
    kl = np.array(lens, np.int64)

    if ck.host_sort_order(kb[: int(kl[0])], ko[:1], kl[:1]) is not None:
        run("native_sort", lambda: ck.host_sort_order(kb, ko, kl), n)
    run("lexsort_twin",
        lambda: ck.host_encode_sort(kb, ko, kl, 12), n)

    # readrandom: ZipTable (searchable compression, ToplingZipTable role)
    # vs BlockBasedTable+zstd — the BASELINE.md rows 19-22 comparison.
    import random as _random

    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.factory import new_table_builder, open_table
    from toplingdb_tpu.utils import codecs

    zstd_ok = codecs.available("zstd")
    probes = _random.Random(3).sample(range(n), min(n, 20_000))
    probe_keys = [entries[i][0] for i in probes]

    def build_fmt(path, topt):
        w = env.new_writable_file(path)
        b = new_table_builder(w, icmp, topt)
        for ik, v in entries:
            b.add(ik, v)
        b.finish()
        w.close()

    def readrandom(path, topt):
        r = open_table(env.new_random_access_file(path), icmp, topt)
        it = r.new_iterator()
        for ik in probe_keys:
            it.seek(ik)
            assert it.valid() and it.key() == ik

    if zstd_ok:
        t_block = TableOptions(compression=fmt.ZSTD_COMPRESSION,
                               filter_policy=None)
        t_zip = TableOptions(format="zip", compression=fmt.ZSTD_COMPRESSION,
                             filter_policy=None)
        if args.filter in "readrandom_block_zstd" or \
                args.filter in "readrandom_zip":
            build_fmt("/mb_block.sst", t_block)
            build_fmt("/mb_zip.sst", t_zip)
        run("readrandom_block_zstd",
            lambda: readrandom("/mb_block.sst", t_block), len(probe_keys))
        run("readrandom_zip",
            lambda: readrandom("/mb_zip.sst", t_zip), len(probe_keys))

    # Pipelined vs serial compaction data plane: the SAME job run with
    # TPULSM_PIPELINE=0 and =1, printing per-phase sums vs wall so the
    # scan/compute/encode overlap is directly visible.
    if args.filter in "compaction_pipeline":
        from toplingdb_tpu.compaction.picker import Compaction
        from toplingdb_tpu.db.table_cache import TableCache
        from toplingdb_tpu.db.version_edit import FileMetaData
        from toplingdb_tpu.ops.columnar_io import (
            ColumnarKV, write_tables_columnar,
        )
        from toplingdb_tpu.ops.device_compaction import run_device_compaction
        from toplingdb_tpu.ops.pipeline import MIN_PIPELINE_ROWS

        n_c = max(n, MIN_PIPELINE_ROWS * 2)
        cenv = MemEnv()
        rng2 = np.random.default_rng(7)
        per_run = n_c // 4
        metas = []
        fn_c = [9]
        for _run in range(4):
            draws = rng2.integers(0, n_c // 2, per_run, dtype=np.int64)
            seqs = np.arange(_run * per_run + 1, _run * per_run + per_run + 1,
                             dtype=np.uint64)
            ik = np.empty((per_run, 16), dtype=np.uint8)
            for j in range(8):
                ik[:, 7 - j] = (draws // 10 ** j) % 10 + ord("0")
            packed = (seqs << np.uint64(8)) | np.uint64(1)
            ik[:, 8:] = packed[:, None] >> (np.arange(8) * 8).astype(
                np.uint64)[None, :] & np.uint64(0xFF)
            vals = np.full((per_run, 20), ord("v"), dtype=np.uint8)
            s = np.lexsort((np.iinfo(np.int64).max - seqs.view(np.int64),
                            draws))
            kv = ColumnarKV(
                np.ascontiguousarray(ik[s]).reshape(-1),
                np.arange(per_run, dtype=np.int32) * 16,
                np.full(per_run, 16, dtype=np.int32),
                np.ascontiguousarray(vals[s]).reshape(-1),
                np.arange(per_run, dtype=np.int32) * 20,
                np.full(per_run, 20, dtype=np.int32),
            )
            fn_c[0] += 1
            files = write_tables_columnar(
                cenv, "/cp", (lambda: fn_c[0]), icmp, TableOptions(), kv,
                np.arange(per_run, dtype=np.int32),
                np.full(per_run, -1, dtype=np.int64),
                np.full(per_run, 1, dtype=np.int32), seqs[s], [],
                creation_time=1,
            )
            for fnum, path, props, smallest, largest, _sel in files:
                metas.append(FileMetaData(
                    number=fnum, file_size=cenv.get_file_size(path),
                    smallest=smallest, largest=largest,
                ))
        tc = TableCache(cenv, "/cp", icmp, TableOptions())
        saved_env = {k: os.environ.get(k)
                     for k in ("TPULSM_PIPELINE", "TPULSM_HOST_SORT",
                               "TPULSM_PIPELINE_SHARDS")}
        os.environ["TPULSM_HOST_SORT"] = "1"
        os.environ["TPULSM_PIPELINE_SHARDS"] = "4"
        try:
            fn_c[0] = 1000
            for knob in ("0", "1"):
                os.environ["TPULSM_PIPELINE"] = knob
                best = None
                for _ in range(2):
                    c = Compaction(level=0, output_level=2,
                                   inputs=list(metas), bottommost=True,
                                   max_output_file_size=1 << 62)
                    t0 = time.perf_counter()
                    outs, stats = run_device_compaction(
                        cenv, "/cp", icmp, c, tc, TableOptions(), [],
                        new_file_number=(lambda: (fn_c.__setitem__(
                            0, fn_c[0] + 1), fn_c[0])[1]),
                        creation_time=1, device_name="cpu-jax",
                    )
                    dt = time.perf_counter() - t0
                    if best is None or dt < best[0]:
                        best = (dt, stats)
                    for m in outs:
                        cenv.delete_file("/cp/%06d.sst" % m.number)
                dt, stats = best
                ph = stats.phase_dict()
                phase_sum = round(sum(
                    v for k2, v in ph.items()
                    if k2 not in ("work_time_s", "other_s",
                                  "pipeline_overlap_s")
                    and isinstance(v, (int, float))), 3)
                print(json.dumps({
                    "bench": f"compaction_pipeline_{knob}", "items": n_c,
                    "wall_s": round(dt, 3), "phase_sum_s": phase_sum,
                    "pipeline_overlap_s": ph.get("pipeline_overlap_s", 0.0),
                    "MBps": round(36 * n_c / dt / 1e6, 2),
                }))
        finally:
            for k2, v in saved_env.items():
                if v is None:
                    os.environ.pop(k2, None)
                else:
                    os.environ[k2] = v

    # Mesh compaction (§2.2.4): the SAME uniform shard set through the
    # mesh shard runner at 1 chip vs 8 — strong scaling of one fanned-out
    # job. On virtual CPU devices XLA executes every "chip" through one
    # shared host threadpool, so no cross-device overlap materializes and
    # the ratio reports ~1x with virtual_devices=true provenance; the
    # >=4x-at-8-chips win is asserted only on a real multi-device backend.
    if args.filter in "compaction_mesh":
        import jax

        from toplingdb_tpu.parallel import mesh_plan

        mesh_plan.pin_cpu_backend()
        n_dev = len(jax.devices())
        if n_dev < 2:
            print(json.dumps({"bench": "compaction_mesh",
                              "skip": f"{n_dev} device(s)"}))
        else:
            virtual = jax.default_backend() == "cpu"
            rows_per_shard = max(2048, n // 16)
            rows = mesh_plan.mesh_compact_rows(rows_per_shard,
                                               min(8, n_dev), repeats=2)
            for r in rows:
                print(json.dumps({
                    "bench": "compaction_mesh_%d" % r["devices"],
                    "items": r["rows"], "shards": r["shards"],
                    "best_s": r["best_s"], "items_per_s": r["rows_per_s"],
                    "MBps": r["MBps"],
                }))
            base = rows[0]["rows_per_s"]
            top = rows[-1]
            scaling = round(top["rows_per_s"] / base, 2) if base else None
            ok = None if virtual else bool(scaling and scaling >= 4.0)
            print(json.dumps({
                "bench": "compaction_mesh_scaling",
                "devices": top["devices"], "mesh_scaling_x": scaling,
                "virtual_devices": virtual, "expect_ge_x": 4.0,
                "pass": ok,
            }))
            if ok is False:
                return 1

    # Native zip encode plane vs the Python ZipTableBuilder oracle: the
    # SAME survivor segment emitted through write_tables_zip_columnar with
    # TPULSM_ZIP_PLANE=0 and =1 (byte-identical table files are asserted;
    # the ratio is the batched dict-sample/entropy-encode/index-build win).
    if args.filter in "zip_encode":
        from toplingdb_tpu.ops.columnar_io import ColumnarKV
        from toplingdb_tpu.table.zip_table import write_tables_zip_columnar

        n_z = max(n, 4096)
        zenv = MemEnv()
        zq = np.arange(n_z, dtype=np.int64)
        zseqs = np.arange(1, n_z + 1, dtype=np.uint64)
        ikz = np.empty((n_z, 16), dtype=np.uint8)
        for j in range(8):
            ikz[:, 7 - j] = (zq // 10 ** j) % 10 + ord("0")
        packed_z = (zseqs << np.uint64(8)) | np.uint64(1)
        ikz[:, 8:] = packed_z[:, None] >> (np.arange(8) * 8).astype(
            np.uint64)[None, :] & np.uint64(0xFF)
        vz = np.full((n_z, 48), ord("z"), dtype=np.uint8)
        for j in range(8):
            vz[:, 7 - j] = (zq // 10 ** j) % 10 + ord("0")
        zkv = ColumnarKV(
            np.ascontiguousarray(ikz).reshape(-1),
            np.arange(n_z, dtype=np.int32) * 16,
            np.full(n_z, 16, dtype=np.int32),
            np.ascontiguousarray(vz).reshape(-1),
            np.arange(n_z, dtype=np.int32) * 48,
            np.full(n_z, 48, dtype=np.int32),
        )
        topt_z = TableOptions(
            format="zip",
            compression=(fmt.ZSTD_COMPRESSION if zstd_ok
                         else fmt.NO_COMPRESSION),
            filter_policy=None)
        fz = [100]
        outs_z = {}

        def zip_build(knob):
            def go():
                os.environ["TPULSM_ZIP_PLANE"] = knob
                fz[0] = 100  # same file numbers per run: bytes comparable
                files = write_tables_zip_columnar(
                    zenv, "/zb", (lambda: (fz.__setitem__(
                        0, fz[0] + 1), fz[0])[1]), icmp, topt_z, zkv,
                    np.arange(n_z, dtype=np.int64),
                    np.full(n_z, -1, dtype=np.int64),
                    np.full(n_z, 1, dtype=np.int32), zseqs, [],
                    creation_time=1)
                blobs = []
                for _fnum, path, _props, _sm, _lg, _sel in files:
                    f = zenv.new_random_access_file(path)
                    blobs.append(f.read(0, zenv.get_file_size(path)))
                    zenv.delete_file(path)
                outs_z[knob] = blobs
            return go

        saved_zp = os.environ.get("TPULSM_ZIP_PLANE")
        try:
            for knob in ("0", "1"):
                _bench(f"zip_encode_{knob}", zip_build(knob), n_z)
            assert outs_z["0"] == outs_z["1"] and outs_z["1"], \
                "zip plane output diverged from the Python builder"
        finally:
            if saved_zp is None:
                os.environ.pop("TPULSM_ZIP_PLANE", None)
            else:
                os.environ["TPULSM_ZIP_PLANE"] = saved_zp

    # Chunked vs per-entry iterator data plane: the SAME multi-level DB
    # scanned with TPULSM_ITER_CHUNK=0 and =1 (byte-identical output is
    # asserted; the ratio is the scan plane's win).
    if args.filter in "iter_chunk":
        import shutil as _sh
        import tempfile as _tf

        from toplingdb_tpu.db.db import DB
        from toplingdb_tpu.db.write_batch import WriteBatch
        from toplingdb_tpu.options import Options

        di = _tf.mkdtemp(prefix="mb_iter_", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
        dbi = DB.open(di, Options(create_if_missing=True,
                                  write_buffer_size=8 << 20))
        for i in range(0, n, 1000):
            b = WriteBatch()
            for j in range(i, min(i + 1000, n)):
                k = (j * 2654435761) % (n * 2)
                b.put(b"%016d" % k, b"value-%016d" % j)
            dbi.write(b)
        dbi.flush()
        dbi.wait_for_compactions()
        saved_chunk = os.environ.get("TPULSM_ITER_CHUNK")
        rows = {}

        def iter_scan(knob):
            def go():
                os.environ["TPULSM_ITER_CHUNK"] = knob
                it = dbi.new_iterator()
                it.seek_to_first()
                c = 0
                while it.valid():
                    it.key()
                    it.value()
                    it.next()
                    c += 1
                rows[knob] = c
            return go

        try:
            for knob in ("0", "1"):
                _bench(f"iter_chunk_{knob}", iter_scan(knob), n)
            assert rows["0"] == rows["1"], rows
        finally:
            if saved_chunk is None:
                os.environ.pop("TPULSM_ITER_CHUNK", None)
            else:
                os.environ["TPULSM_ITER_CHUNK"] = saved_chunk
            dbi.close()
            _sh.rmtree(di, ignore_errors=True)

    # Native group-commit write plane vs the Python interiors: the SAME
    # mixed-batch-size protected fillrandom (WAL on) through DB.write with
    # TPULSM_WRITE_PLANE=0 and =1. At the intended scale (--n >= 1000000:
    # the 1M-op mixed-size run) the native plane must win; smaller runs
    # (the test suite's smoke --n) just print both rows.
    if args.filter in "write_group_native":
        import shutil as _sh
        import tempfile as _tf
        import threading as _th

        from toplingdb_tpu.db.db import DB
        from toplingdb_tpu.db.write_batch import WriteBatch
        from toplingdb_tpu.options import Options

        n_w = max(n, 4000)
        nt_w = 4
        sizes = (10, 100, 1000)  # mixed batch sizes, round-robin
        per = n_w // nt_w

        def mkbatches():
            out = []
            for t in range(nt_w):
                bs, i, si = [], 0, 0
                while i < per:
                    bsz = min(sizes[si % len(sizes)], per - i)
                    si += 1
                    b = WriteBatch(protection_bytes_per_key=8)
                    for j in range(i, i + bsz):
                        k = ((t * per + j) * 2654435761) % (n_w * 2)
                        b.put(b"%016d" % k, b"v" * (8 + (j % 3) * 24))
                    bs.append(b)
                    i += bsz
                out.append(bs)
            return out

        saved_wp = os.environ.get("TPULSM_WRITE_PLANE")
        results = {}
        try:
            for knob in ("0", "1"):
                os.environ["TPULSM_WRITE_PLANE"] = knob
                best = None
                for _ in range(3):
                    batches = mkbatches()
                    dw = _tf.mkdtemp(prefix="mb_wg_", dir="/dev/shm"
                                     if os.path.isdir("/dev/shm") else None)
                    dbw = DB.open(dw, Options(
                        create_if_missing=True,
                        write_buffer_size=1 << 30,
                        protection_bytes_per_key=8))
                    errs = []

                    def go(bs):
                        try:
                            for b in bs:
                                dbw.write(b)
                        except Exception as e:  # noqa: BLE001
                            errs.append(e)

                    ts = [_th.Thread(target=go, args=(bs,))
                          for bs in batches]
                    t0 = time.perf_counter()
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
                    dt = time.perf_counter() - t0
                    assert not errs, errs
                    dbw.close()
                    _sh.rmtree(dw, ignore_errors=True)
                    if best is None or dt < best:
                        best = dt
                results[knob] = best
                print(json.dumps({
                    "bench": f"write_group_native_{knob}", "items": n_w,
                    "best_s": round(best, 4),
                    "items_per_s": round(n_w / best),
                }))
        finally:
            if saved_wp is None:
                os.environ.pop("TPULSM_WRITE_PLANE", None)
            else:
                os.environ["TPULSM_WRITE_PLANE"] = saved_wp
        if n_w >= 1_000_000:
            assert results["1"] <= results["0"], (
                f"native write plane lost: plane1 {results['1']:.3f}s vs "
                f"plane0 {results['0']:.3f}s")

    # Persistent cache tier: spill 4KiB blocks through the write-behind
    # queue, then measure disk-tier lookups — the row reports the tier's
    # measured hit rate (reference block_cache_tier stats role).
    if args.filter in "persistent_cache_tier":
        import shutil as _sh
        import tempfile as _tf

        from toplingdb_tpu.utils.persistent_cache import PersistentCache

        pdir = _tf.mkdtemp(prefix="mb_pc_")
        n_blk = max(64, min(2048, n // 64))
        pc = PersistentCache(pdir, capacity_bytes=64 << 20)
        blocks = {b"blk%06d" % i: bytes([i % 251]) * 4096
                  for i in range(n_blk)}
        for k, v in blocks.items():
            pc.insert(k, v)
        pc.flush()

        def pc_reads():
            for k in blocks:
                assert pc.lookup(k) is not None
            for i in range(n_blk // 4):
                pc.lookup(b"missing%06d" % i)  # measured miss path

        _bench("persistent_cache_tier", pc_reads, n_blk + n_blk // 4)
        print(json.dumps({"bench": "persistent_cache_tier_stats",
                          **pc.stats()}))
        pc.close()
        _sh.rmtree(pdir, ignore_errors=True)

    # Async read plane (§2.2.5): one cold-cache 128-key MultiGet through
    # the reader rings (TPULSM_ASYNC_READS=1) vs the sync twin (=0).
    # Both twins run on a DelayedReadEnv (1ms per pread: models a
    # disaggregated-storage read — page-cache preads are ~µs, nothing to
    # overlap — and the wrapped handles keep both twins off the native
    # fast chains, on the same Python walk). Byte parity is asserted
    # ALWAYS; the >=2x overlap win is asserted on multi-core hosts and
    # provenance-tagged on a single core, the compaction_mesh pattern.
    if args.filter in "async_reads":
        import shutil as _sh
        import tempfile as _tf

        from toplingdb_tpu.db.db import DB
        from toplingdb_tpu.env import default_env
        from toplingdb_tpu.env.fault_injection import DelayedReadEnv
        from toplingdb_tpu.options import Options
        from toplingdb_tpu.utils.cache import LRUCache

        adir = _tf.mkdtemp(prefix="mb_ar_", dir="/dev/shm"
                           if os.path.isdir("/dev/shm") else None)
        n_k = max(4096, min(30_000, n))
        db = DB.open(adir, Options(create_if_missing=True,
                                   write_buffer_size=128 * 1024))
        for i in range(n_k):
            db.put(b"%016d" % ((i * 2654435761) % (n_k * 2)),
                   b"value-%016d" % i)
        db.flush()
        db.wait_for_compactions()
        db.close()
        import random as _rnd

        rng = _rnd.Random(13)
        probes = [b"%016d" % ((rng.randrange(n_k) * 2654435761)
                              % (n_k * 2)) for _ in range(128)]
        warm = [b"%016d" % ((rng.randrange(n_k) * 2654435761)
                            % (n_k * 2)) for _ in range(64)]
        saved_ar = os.environ.get("TPULSM_ASYNC_READS")
        ar_best: dict[str, float] = {}
        ar_view: dict[str, list] = {}
        try:
            for knob in ("1", "0"):
                os.environ["TPULSM_ASYNC_READS"] = knob
                best = float("inf")
                for _ in range(3):
                    # fresh handles + tiny cache: every run is cold
                    dbr = DB.open(adir,
                                  Options(block_cache=LRUCache(64 * 1024)),
                                  env=DelayedReadEnv(default_env(),
                                                     delay_sec=0.001))
                    # Warm per-file metadata (index/filter blocks stay
                    # resident in the reader) on a DISJOINT probe set:
                    # the tiny block cache keeps data blocks cold, so
                    # the timed batch measures data-block fan-out, not
                    # serial index loads — identically for both twins.
                    dbr.multi_get(warm)
                    t0 = time.perf_counter()
                    out = dbr.multi_get(probes)
                    best = min(best, time.perf_counter() - t0)
                    dbr.close()
                ar_best[knob] = best
                ar_view[knob] = out
                print(json.dumps({
                    "bench": "async_reads_%s" % knob, "items": len(probes),
                    "best_s": round(best, 5),
                    "items_per_s": round(len(probes) / best),
                }))
        finally:
            if saved_ar is None:
                os.environ.pop("TPULSM_ASYNC_READS", None)
            else:
                os.environ["TPULSM_ASYNC_READS"] = saved_ar
        assert ar_view["1"] == ar_view["0"], \
            "async read plane parity violation"
        speed = round(ar_best["0"] / ar_best["1"], 2)
        multi_core = (os.cpu_count() or 1) > 1
        ok = bool(speed >= 2.0) if multi_core else None
        print(json.dumps({
            "bench": "async_reads_speedup", "async_read_speedup_x": speed,
            "delay_model_us": 1000, "single_core_host": not multi_core,
            "expect_ge_x": 2.0, "parity": True, "pass": ok,
        }))
        _sh.rmtree(adir, ignore_errors=True)
        if ok is False:
            return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
