"""db_crashtest: the crash-test MATRIX driver (reference
tools/db_crashtest.py:17-28 in /root/reference): sweeps db_stress's
option-variant matrix (blob / unordered+concurrent / pipelined /
universal-compaction / tiny-buffer) through blackbox AND whitebox
kill-recover rounds, dividing a wall-clock budget across the cells.

CI-able 5-minute soak (the documented invocation):

    python -m toplingdb_tpu.tools.db_crashtest --duration 300

Each cell runs `db_stress --crash-test [--whitebox] --variant=V` in a
fresh directory; any verification failure fails the whole matrix. A
summary table prints at the end.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile
import time

from toplingdb_tpu.tools.db_stress import VARIANTS


def run_cell(variant: str, mode: str, budget_s: float, base: str,
             seed: int, ops: int, threads: int) -> tuple[bool, str]:
    """One (variant, blackbox|whitebox) cell under its time slice."""
    d = os.path.join(base, f"{variant}_{mode}")
    os.makedirs(d, exist_ok=True)
    rounds = 3
    kill_after = max(1.0, budget_s / (rounds + 1))
    cmd = [
        sys.executable, "-m", "toplingdb_tpu.tools.db_stress",
        f"--db={d}/db", "--crash-test", f"--rounds={rounds}",
        f"--kill-after={kill_after}", f"--variant={variant}",
        f"--seed={seed}", f"--ops={ops}", f"--threads={threads}",
    ]
    if mode == "whitebox":
        cmd.append("--whitebox")
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=budget_s * 3 + 120)
    except subprocess.TimeoutExpired:
        return False, "TIMEOUT"
    dt = time.time() - t0
    ok = r.returncode == 0 and "crash test passed" in r.stdout
    tail = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    return ok, f"{dt:.0f}s {tail}" if ok else (r.stdout + r.stderr)[-1500:]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="db_crashtest")
    ap.add_argument("--duration", type=float, default=300.0,
                    help="total wall-clock budget (seconds)")
    ap.add_argument("--variants", default=",".join(sorted(VARIANTS)),
                    help="comma-separated variant subset")
    ap.add_argument("--modes", default="blackbox,whitebox")
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keep", action="store_true",
                    help="keep the work dirs on success")
    a = ap.parse_args(argv)

    variants = [v for v in a.variants.split(",") if v]
    for v in variants:
        if v not in VARIANTS:
            ap.error(f"unknown variant {v!r} (have {sorted(VARIANTS)})")
    modes = [m for m in a.modes.split(",") if m]
    for m in modes:
        if m not in ("blackbox", "whitebox"):
            ap.error(f"unknown mode {m!r} (blackbox|whitebox)")
    cells = [(v, m) for v in variants for m in modes]
    per_cell = a.duration / max(1, len(cells))
    base = tempfile.mkdtemp(prefix="tpulsm_crashmatrix_")
    print(f"crash matrix: {len(cells)} cells x ~{per_cell:.0f}s in {base}")

    failures = []
    for i, (v, m) in enumerate(cells):
        ok, info = run_cell(v, m, per_cell, base, a.seed + i, a.ops,
                            a.threads)
        status = "OK " if ok else "FAIL"
        print(f"  [{status}] {v:<12} {m:<9} {info if ok else ''}")
        if not ok:
            failures.append((v, m, info))
    for v, m, info in failures:
        print(f"--- {v}/{m} output tail ---\n{info}")
    if not failures and not a.keep:
        shutil.rmtree(base, ignore_errors=True)
    print("MATRIX", "FAILED" if failures else "PASSED",
          f"({len(cells) - len(failures)}/{len(cells)} cells)")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
