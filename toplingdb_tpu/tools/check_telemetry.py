"""Telemetry name lint (tier-1 CI): keeps metric and span names from
silently forking.

Two invariants over the whole `toplingdb_tpu/` tree:

  1. Every ticker/histogram name passed to `record_tick` /
     `record_ticks` / `record_in_histogram` / `get_ticker_count` /
     `get_histogram` — whether as a string literal or as an attribute of a
     `utils.statistics` alias (`st.FOO`, `_st.FOO`, `stats_mod.FOO`, ...)
     — must be DECLARED in utils/statistics.py.
  2. Every span name passed as a string literal to the telemetry span
     factories (`span`, `span_under`, `span_event`, `span_event_under`,
     `start`, `start_from`, `maybe_sample`, `note_slow`) must appear in
     ARCHITECTURE.md's Telemetry span table.
  3. Every Prometheus gauge emitted through the `g(...)` helper idiom
     (utils/config.py's exposition blocks) with a literal metric name
     must be declared in utils/statistics.py GAUGE_NAMES — a typo'd
     gauge would otherwise silently fork a new series.
  4. Every literal `SLOSpec(kind=...)` must name a kind in
     utils/slo.py KINDS, and a literal `SLOSpec(histogram=...)` must
     name a histogram declared in utils/statistics.py.

Run: python -m toplingdb_tpu.tools.check_telemetry [repo_root]
Exit 0 clean; 1 with one violation per line otherwise.
"""

from __future__ import annotations

import ast
import os
import sys

TICKER_FNS = {"record_tick", "record_in_histogram", "get_ticker_count",
              "get_histogram"}
SPAN_FNS = {"span", "span_under", "span_event", "span_event_under",
            "start", "start_from", "maybe_sample", "note_slow"}
GAUGE_FNS = {"g"}
# Module aliases under which utils.statistics name constants are accessed.
STAT_ALIASES = {"st", "_st", "stats_mod", "_stats_mod", "statistics",
                "stats"}


def declared_stat_names() -> tuple[set[str], set[str]]:
    """(name VALUES, CONSTANT attribute names) declared in statistics.py."""
    from toplingdb_tpu.utils import statistics as mod

    values, attrs = set(), set()
    for attr in dir(mod):
        if attr.isupper() and isinstance(getattr(mod, attr), str):
            attrs.add(attr)
            values.add(getattr(mod, attr))
    return values, attrs


def span_names_in_architecture(repo_root: str) -> set[str]:
    """Span names listed in ARCHITECTURE.md's Telemetry section (every
    `backtick-quoted` token in that section counts as declared)."""
    import re

    path = os.path.join(repo_root, "ARCHITECTURE.md")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return set()
    lower = text.lower()
    start = lower.find("telemetry")
    if start < 0:
        return set()
    # Section runs until the next top/second-level heading after it.
    end = len(text)
    for m in re.finditer(r"\n#{1,3} ", text[start:]):
        end = start + m.start()
        break
    return set(re.findall(r"`([a-z0-9_.]+)`", text[start:end]))


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _first_str_arg(node: ast.Call) -> str | None:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def check_file(path: str, stat_values: set[str], stat_attrs: set[str],
               span_names: set[str], gauge_names: set[str] = frozenset(),
               slo_kinds: set[str] = frozenset()) -> list[str]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [f"{path}: syntax error: {e}"]
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in TICKER_FNS:
            lit = _first_str_arg(node)
            if lit is not None and lit not in stat_values:
                out.append(
                    f"{path}:{node.lineno}: ticker/histogram name {lit!r} "
                    f"is not declared in utils/statistics.py")
            a0 = node.args[0] if node.args else None
            if (isinstance(a0, ast.Attribute)
                    and isinstance(a0.value, ast.Name)
                    and a0.value.id in STAT_ALIASES
                    and a0.attr.isupper()
                    and a0.attr not in stat_attrs):
                out.append(
                    f"{path}:{node.lineno}: statistics constant "
                    f"{a0.value.id}.{a0.attr} does not exist")
        if name in SPAN_FNS:
            lit = _first_str_arg(node)
            if name in ("span_under", "span_event_under", "start_from"):
                # First positional is the parent handle / context; the
                # span name is the second positional.
                lit = None
                if len(node.args) > 1 and isinstance(node.args[1],
                                                     ast.Constant) \
                        and isinstance(node.args[1].value, str):
                    lit = node.args[1].value
            if lit is not None and "." in lit and lit not in span_names:
                out.append(
                    f"{path}:{node.lineno}: span name {lit!r} is not in "
                    f"ARCHITECTURE.md's Telemetry span table")
        if name in GAUGE_FNS:
            lit = _first_str_arg(node)
            if lit is not None and lit not in gauge_names:
                out.append(
                    f"{path}:{node.lineno}: gauge name {lit!r} is not "
                    f"declared in utils/statistics.py GAUGE_NAMES")
        if name == "SLOSpec":
            for kw in node.keywords:
                if not (isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    continue
                if kw.arg == "kind" and kw.value.value not in slo_kinds:
                    out.append(
                        f"{path}:{node.lineno}: SLO kind "
                        f"{kw.value.value!r} is not in utils/slo.py KINDS")
                if kw.arg == "histogram" \
                        and kw.value.value not in stat_values:
                    out.append(
                        f"{path}:{node.lineno}: SLO histogram "
                        f"{kw.value.value!r} is not declared in "
                        f"utils/statistics.py")
    return out


def run(repo_root: str | None = None) -> list[str]:
    repo_root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pkg = os.path.join(repo_root, "toplingdb_tpu")
    stat_values, stat_attrs = declared_stat_names()
    span_names = span_names_in_architecture(repo_root)
    from toplingdb_tpu.utils import slo as _slo
    from toplingdb_tpu.utils import statistics as _stmod

    gauge_names = set(_stmod.GAUGE_NAMES)
    slo_kinds = set(_slo.KINDS)
    skip = {os.path.abspath(__file__)}
    violations = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.abspath(path) in skip:
                continue
            violations.extend(
                check_file(path, stat_values, stat_attrs, span_names,
                           gauge_names, slo_kinds))
    return violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv else None
    violations = run(root)
    for v in violations:
        print(v)
    print(f"check_telemetry: {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
