"""shard_admin: operate the sharding plane over the SidePlugin HTTP layer.

    python -m toplingdb_tpu.tools.shard_admin --url http://host:port status
    python -m toplingdb_tpu.tools.shard_admin --url ... status --cluster C
    python -m toplingdb_tpu.tools.shard_admin --url ... split \
        --cluster C --shard S --key K
    python -m toplingdb_tpu.tools.shard_admin --url ... merge \
        --cluster C --left A --right B
    python -m toplingdb_tpu.tools.shard_admin --url ... migrate \
        --cluster C --shard S --dest /path/to/new-instance
    python -m toplingdb_tpu.tools.shard_admin --url ... balance --cluster C

`status` with no --cluster lists registered clusters; with one it prints
the shard table (range, epoch, state, fence, stall, traffic). `split` /
`merge` / `migrate` / `balance` POST the matching /shards/<cluster>/...
endpoint; migrate is synchronous and prints the cutover summary (new
epoch, destination path). Keys are utf-8 by default; pass --hex to send
--key as hex bytes.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _get(url: str):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def _post(url: str, body: dict):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


def _fail(e) -> int:
    if isinstance(e, urllib.error.HTTPError):
        print(f"HTTP {e.code}: {e.read().decode()[:300]}", file=sys.stderr)
    else:
        print(str(e), file=sys.stderr)
    return 1


def cmd_status(base: str, args) -> int:
    if not args.cluster:
        print(json.dumps(_get(f"{base}/shards"), indent=1))
        return 0
    view = _get(f"{base}/shards/{args.cluster}")
    print(f"cluster={args.cluster} map_version={view.get('map_version')} "
          f"shards={view.get('n_shards')}")
    for row in view.get("shards", []):
        rng = (f"[{row.get('start_hex') or '-inf'}, "
               f"{row.get('end_hex') or '+inf'})")
        tr = row.get("traffic", {})
        firing = ",".join(row.get("slo_firing") or [])
        alert = row.get("last_slo_alert") or {}
        alert_s = (f"\tlast_alert={alert.get('slo_name')}:"
                   f"{alert.get('state')}" if alert else "")
        print(f"{row['name']}\tepoch={row['epoch']}\t{row.get('state')}"
              f"{' FENCED' if row.get('fenced') else ''}\t{rng}\t"
              f"health={row.get('health', '?')}"
              f"{'!' + firing if firing else ''}\t"
              f"stall={row.get('stall', '?')}\t"
              f"r={tr.get('reads', 0)} w={tr.get('writes', 0)} "
              f"wB={tr.get('write_bytes', 0)}{alert_s}")
    return 0


def _key_payload(args) -> dict:
    if args.hex:
        return {"split_key_hex": args.key}
    return {"split_key": args.key}


def cmd_split(base: str, args) -> int:
    out = _post(f"{base}/shards/{args.cluster}/split",
                {"shard": args.shard, **_key_payload(args)})
    print(json.dumps(out, indent=1))
    return 0


def cmd_merge(base: str, args) -> int:
    out = _post(f"{base}/shards/{args.cluster}/merge",
                {"left": args.left, "right": args.right})
    print(json.dumps(out, indent=1))
    return 0


def cmd_migrate(base: str, args) -> int:
    out = _post(f"{base}/shards/{args.cluster}/migrate",
                {"shard": args.shard, "dest": args.dest})
    print(json.dumps(out, indent=1))
    return 0


def cmd_balance(base: str, args) -> int:
    out = _post(f"{base}/shards/{args.cluster}/balance", {})
    print(json.dumps(out, indent=1))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="shard_admin")
    ap.add_argument("--url", required=True,
                    help="SidePluginRepo HTTP base, e.g. http://127.0.0.1:8080")
    ap.add_argument("--cluster", default=None)
    ap.add_argument("--shard", default=None)
    ap.add_argument("--key", default=None, help="split key (utf-8)")
    ap.add_argument("--hex", action="store_true",
                    help="--key is hex-encoded bytes")
    ap.add_argument("--left", default=None)
    ap.add_argument("--right", default=None)
    ap.add_argument("--dest", default=None,
                    help="migration destination directory")
    ap.add_argument("command",
                    choices=["status", "split", "merge", "migrate",
                             "balance"])
    args = ap.parse_args(argv)
    base = args.url.rstrip("/")
    need = {
        "split": ("cluster", "shard", "key"),
        "merge": ("cluster", "left", "right"),
        "migrate": ("cluster", "shard", "dest"),
        "balance": ("cluster",),
        "status": (),
    }[args.command]
    missing = [f"--{n}" for n in need if getattr(args, n) is None]
    if missing:
        print(f"{args.command} requires {' '.join(missing)}",
              file=sys.stderr)
        return 2
    try:
        return {"status": cmd_status, "split": cmd_split,
                "merge": cmd_merge, "migrate": cmd_migrate,
                "balance": cmd_balance}[args.command](base, args)
    except Exception as e:  # noqa: BLE001 - CLI boundary
        return _fail(e)


if __name__ == "__main__":
    sys.exit(main())
