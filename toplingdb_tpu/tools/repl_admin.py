"""repl_admin: operate the replication plane over the SidePlugin HTTP layer.

    python -m toplingdb_tpu.tools.repl_admin --url http://host:port status
    python -m toplingdb_tpu.tools.repl_admin --url ... lag [--db NAME]
    python -m toplingdb_tpu.tools.repl_admin --url ... promote --db NAME

`status` dumps every registered DB's /replication view; `lag` prints a
one-line applied-seq / lag summary per DB (scriptable: exits 1 when any
follower lags more than --max-lag sequences); `promote` POSTs
/promote/<name>, turning a registered FollowerDB into a read-write primary
after its final catch-up (failover).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url: str, body: dict):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _db_names(base: str, only: str | None) -> list[str]:
    if only:
        return [only]
    return _get(f"{base}/dbs").get("dbs", [])


def cmd_status(base: str, args) -> int:
    out = {}
    for name in _db_names(base, args.db):
        try:
            out[name] = _get(f"{base}/replication/{name}")
        except Exception as e:  # noqa: BLE001
            out[name] = {"error": str(e)}
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


def cmd_lag(base: str, args) -> int:
    worst = 0
    rows = []
    primary_seq = None
    views = {name: _get(f"{base}/replication/{name}")
             for name in _db_names(base, args.db)}
    for name, v in views.items():
        if v.get("role") in ("primary", "router"):
            primary_seq = max(primary_seq or 0,
                              v.get("last_sequence",
                                    v.get("primary_sequence", 0)))
    for name, v in views.items():
        applied = v.get("applied_sequence", v.get("last_sequence", 0))
        lag = (max(0, primary_seq - applied)
               if primary_seq is not None and v.get("role") == "follower"
               else 0)
        worst = max(worst, lag)
        rows.append(f"{name}\trole={v.get('role', '?')}\t"
                    f"applied={applied}\tlag_seq={lag}")
    print("\n".join(rows))
    if args.max_lag is not None and worst > args.max_lag:
        print(f"worst lag {worst} > --max-lag {args.max_lag}",
              file=sys.stderr)
        return 1
    return 0


def cmd_promote(base: str, args) -> int:
    if not args.db:
        print("promote requires --db NAME", file=sys.stderr)
        return 2
    try:
        out = _post(f"{base}/promote/{args.db}", {})
    except urllib.error.HTTPError as e:
        print(f"promote failed: HTTP {e.code} {e.read().decode()[:200]}",
              file=sys.stderr)
        return 1
    print(json.dumps(out, indent=1))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="repl_admin")
    ap.add_argument("--url", required=True,
                    help="SidePluginRepo HTTP base, e.g. http://127.0.0.1:8080")
    ap.add_argument("--db", default=None, help="restrict to one DB name")
    ap.add_argument("--max-lag", type=int, default=None,
                    help="lag: exit 1 when any follower lags more sequences")
    ap.add_argument("command", choices=["status", "lag", "promote"])
    args = ap.parse_args(argv)
    base = args.url.rstrip("/")
    return {"status": cmd_status, "lag": cmd_lag,
            "promote": cmd_promote}[args.command](base, args)


if __name__ == "__main__":
    sys.exit(main())
