"""db_bench: the canonical benchmark driver.

Workload set mirrors the reference's db_bench dispatch
(tools/db_bench_tool.cc:3784-3893 in /root/reference): comma-separated
benchmarks run in order against one DB. `--json` loads a SidePlugin-style
config document (the Topling -json flag analogue).

Usage:
  python -m toplingdb_tpu.tools.db_bench --benchmarks=fillseq,readrandom \
      --num=100000 --db=/tmp/bench_db [--json=config.json] [--value-size=100]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import time

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions, WriteOptions
from toplingdb_tpu.db.write_batch import WriteBatch


class Bench:
    def __init__(self, args):
        self.args = args
        self.rng = random.Random(args.seed)
        if args.json:
            from toplingdb_tpu.utils.config import options_from_config

            with open(args.json) as f:
                cfg = json.load(f)
            self.options = options_from_config(cfg.get("options", cfg))
        else:
            self.options = Options()
        if args.statistics and self.options.statistics is None:
            from toplingdb_tpu.utils.statistics import Statistics

            self.options.statistics = Statistics()
        self.db: DB | None = None
        if ("mergerandom" in args.benchmarks
                or "readwhilemerging" in args.benchmarks):
            # merge workloads write uint64 operands; reads after them would
            # fail with MergeInProgress without an operator.
            self._ensure_merge_operator()

    def _ensure_merge_operator(self) -> None:
        if self.options.merge_operator is None:
            from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

            self.options.merge_operator = UInt64AddOperator()
            if self.db is not None:
                self.open_db(fresh=False)

    def key(self, i: int) -> bytes:
        return b"%016d" % i

    def value(self, i: int) -> bytes:
        data = (b"%d" % i) * (self.args.value_size // max(1, len(b"%d" % i)) + 1)
        return data[: self.args.value_size]

    def open_db(self, fresh: bool) -> None:
        if self.db is not None:
            self.db.close()
            self.db = None
        if fresh and not self.args.use_existing_db and os.path.exists(self.args.db):
            shutil.rmtree(self.args.db)
        self.db = DB.open(self.args.db, self.options)

    def run(self) -> None:
        self.results = []  # structured rows for tools/benchmark.py
        for name in self.args.benchmarks.split(","):
            name = name.strip()
            fn = getattr(self, "bench_" + name, None)
            if fn is None:
                print(f"unknown benchmark: {name}")
                continue
            fresh = name.startswith("fill")
            if self.db is None or fresh:
                self.open_db(fresh)
            n = self.args.num
            t0 = time.time()
            ops = fn(n)
            dt = time.time() - t0
            ops = ops or n
            self.results.append({
                "name": name, "ops": ops, "seconds": round(dt, 4),
                "ops_per_sec": round(ops / dt, 1),
                "micros_per_op": round(dt * 1e6 / ops, 3),
            })
            print(
                f"{name:<20} : {dt * 1e6 / ops:10.3f} micros/op "
                f"{ops / dt:12.0f} ops/sec; {dt:8.2f} s"
            )
        if self.db is not None:
            if self.args.print_stats and self.db.stats is not None:
                print(self.db.stats.to_string())
            self.db.close()

    # -- workloads ------------------------------------------------------

    def bench_fillseq(self, n):
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        batch = self.args.batch_size
        i = 0
        while i < n:
            b = WriteBatch()
            for _ in range(min(batch, n - i)):
                b.put(self.key(i), self.value(i))
                i += 1
            self.db.write(b, wo)
        return n

    def bench_fillrandom(self, n):
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        batch = self.args.batch_size
        i = 0
        while i < n:
            b = WriteBatch()
            for _ in range(min(batch, n - i)):
                b.put(self.key(self.rng.randrange(n)), self.value(i))
                i += 1
            self.db.write(b, wo)
        return n

    def bench_overwrite(self, n):
        return self.bench_fillrandom(n)

    def bench_readseq(self, n):
        it = self.db.new_iterator()
        it.seek_to_first()
        count = 0
        while it.valid() and count < n:
            it.key(), it.value()
            it.next()
            count += 1
        return count

    def bench_readrandom(self, n):
        ro = ReadOptions()
        hits = 0
        for _ in range(n):
            if self.db.get(self.key(self.rng.randrange(self.args.num)), ro) is not None:
                hits += 1
        return n

    def bench_fillrandomblob(self, n):
        """fillrandom with blob separation on: every value >= min_blob_size
        lands in .blob files (reference db_bench --enable_blob_files)."""
        self.options.enable_blob_files = True
        if self.args.value_size < self.options.min_blob_size:
            self.options.min_blob_size = max(1, self.args.value_size // 2)
        if self.options.blob_cache is None and self.args.blob_cache_size:
            self.options.blob_cache = self.args.blob_cache_size
        self.open_db(fresh=True)
        return self.bench_fillrandom(n)

    def bench_readrandomblob(self, n):
        """readrandom against blob-separated values — exercises the
        BlobSource value cache + file-reader LRU (reference
        db/blob/blob_source.h tier)."""
        self.db.flush()
        self.db.wait_for_compactions()
        return self.bench_readrandom(n)

    def bench_seekrandom(self, n):
        ro = ReadOptions()
        it = self.db.new_iterator(ro)
        for _ in range(n):
            it.seek(self.key(self.rng.randrange(self.args.num)))
            if it.valid():
                it.key(), it.value()
        return n

    def bench_mergerandom(self, n):
        import struct

        wo = WriteOptions(disable_wal=self.args.disable_wal)
        for i in range(n):
            self.db.merge(self.key(self.rng.randrange(self.args.num)),
                          struct.pack("<Q", 1), wo)
        return n

    def bench_fillrandombatch(self, n):
        saved = self.args.batch_size
        self.args.batch_size = max(saved, 100)
        try:
            return self.bench_fillrandom(n)
        finally:
            self.args.batch_size = saved

    def bench_multireadrandom(self, n):
        ro = ReadOptions()
        done = 0
        while done < n:
            ks = [self.key(self.rng.randrange(self.args.num))
                  for _ in range(min(16, n - done))]
            self.db.multi_get(ks, ro)
            done += len(ks)
        return n

    def _with_background(self, bg_op, fg_bench, n):
        """Run fg_bench(n) while a daemon thread loops bg_op(i) — the
        shared scaffold of the *while-writing / *while-merging mixes."""
        import threading

        stop = threading.Event()

        def loop():
            i = 0
            while not stop.is_set():
                bg_op(i)
                i += 1

        t = ccy.spawn("db-bench-background", loop)
        try:
            return fg_bench(n)
        finally:
            stop.set()
            t.join()

    def bench_readwhilewriting(self, n):
        return self._with_background(
            lambda i: self.db.put(
                self.key(self.rng.randrange(self.args.num)), self.value(i)
            ),
            self.bench_readrandom, n,
        )

    def bench_deleteseq(self, n):
        for i in range(n):
            self.db.delete(self.key(i))
        return n

    def bench_deleterandom(self, n):
        for _ in range(n):
            self.db.delete(self.key(self.rng.randrange(self.args.num)))
        return n

    def bench_fillsync(self, n):
        wo = WriteOptions(sync=True)
        m = min(n, max(1, n // 100))  # reference runs num/100 synced writes
        for i in range(m):
            self.db.put(self.key(self.rng.randrange(n)), self.value(i), wo)
        return m

    def bench_fill100K(self, n):
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        m = min(n, max(1, n // 1000))
        big = b"x" * 100_000
        for i in range(m):
            self.db.put(self.key(i), big, wo)
        return m

    def bench_readmissing(self, n):
        ro = ReadOptions()
        for _ in range(n):
            # '.' suffix never collides with written keys.
            self.db.get(self.key(self.rng.randrange(self.args.num)) + b".",
                        ro)
        return n

    def bench_readhot(self, n):
        ro = ReadOptions()
        span = max(1, self.args.num // 100)  # hottest 1% of the key space
        for _ in range(n):
            self.db.get(self.key(self.rng.randrange(span)), ro)
        return n

    def bench_readreverse(self, n):
        it = self.db.new_iterator()
        it.seek_to_last()
        count = 0
        while it.valid() and count < n:
            it.key(), it.value()
            it.prev()
            count += 1
        return count

    def bench_updaterandom(self, n):
        # read-modify-write (reference updaterandom)
        ro = ReadOptions()
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        for i in range(n):
            k = self.key(self.rng.randrange(self.args.num))
            self.db.get(k, ro)
            self.db.put(k, self.value(i), wo)
        return n

    def bench_appendrandom(self, n):
        ro = ReadOptions()
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        for i in range(n):
            k = self.key(self.rng.randrange(self.args.num))
            old = self.db.get(k, ro) or b""
            self.db.put(k, (old + self.value(i))[:1024], wo)
        return n

    def bench_readrandomwriterandom(self, n):
        ro = ReadOptions()
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        for i in range(n):
            k = self.key(self.rng.randrange(self.args.num))
            if i % 10 < 9:  # reference readwritepercent default: 90% reads
                self.db.get(k, ro)
            else:
                self.db.put(k, self.value(i), wo)
        return n

    def bench_readwhilemerging(self, n):
        import struct

        self._ensure_merge_operator()
        return self._with_background(
            lambda i: self.db.merge(
                self.key(self.rng.randrange(self.args.num)),
                struct.pack("<Q", 1),
            ),
            self.bench_readrandom, n,
        )

    def bench_seekrandomwhilewriting(self, n):
        return self._with_background(
            lambda i: self.db.put(
                self.key(self.rng.randrange(self.args.num)), self.value(i)
            ),
            self.bench_seekrandom, n,
        )

    def bench_fillseekseq(self, n):
        # Sequential writes interleaved with a seek to every 16th
        # just-written key (the reference's fillseekseq write+seek mix).
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        for i in range(n):
            self.db.put(self.key(i), self.value(i), wo)
            if i % 16 == 0:
                it = self.db.new_iterator()
                it.seek(self.key(i))
                assert it.valid() and it.key() == self.key(i)
        return n

    def bench_randomtransaction(self, n):
        from toplingdb_tpu.utilities.transactions import TransactionDB

        # Each txn moves "value" between 4 random accounts atomically
        # (reference randomtransaction's bank workload shape).
        self.db.close()
        tdb = TransactionDB.open(self.args.db, self.options)
        try:
            m = max(1, n // 10)
            for _ in range(m):
                t = tdb.begin_transaction()
                for _ in range(4):
                    k = self.key(self.rng.randrange(self.args.num))
                    v = t.get(k) or b"0"
                    t.put(k, v[:64] + b"+")
                t.commit()
            return m * 4
        finally:
            tdb.close()
            self.db = DB.open(self.args.db, self.options)

    def bench_compact(self, n):
        self.db.compact_range()
        return 1

    def bench_compactall(self, n):
        return self.bench_compact(n)

    def bench_waitforcompaction(self, n):
        self.db.wait_for_compactions()
        return 1

    def bench_flush(self, n):
        self.db.flush()
        return 1

    def bench_verifychecksum(self, n):
        # The engine's own checksum sweep (reference DB::VerifyChecksum) —
        # it pins/locks correctly and closes its readers.
        self.db.verify_checksum()
        return 1

    def bench_crc32c(self, n):
        from toplingdb_tpu.utils import crc32c

        block = b"x" * 4096
        for _ in range(n):
            crc32c.value(block)
        return n

    def bench_xxhash(self, n):
        from toplingdb_tpu.utils import crc32c

        block = b"x" * 4096
        for _ in range(n):
            crc32c.xxh64(block)
        return n

    def bench_stats(self, n):
        print(self.db.get_property("tpulsm.stats"))
        return 1

    def bench_levelstats(self, n):
        print(self.db.get_property("tpulsm.levelstats"))
        return 1

    def bench_sstables(self, n):
        from toplingdb_tpu.db.dbformat import extract_user_key

        for cf_id in self.db.versions.column_families:
            v = self.db.versions.cf_current(cf_id)
            for level, level_files in enumerate(v.files):
                for f in level_files:
                    print(f"cf{cf_id} L{level} #{f.number} "
                          f"{f.file_size}B "
                          f"[{extract_user_key(f.smallest)!r} .. "
                          f"{extract_user_key(f.largest)!r}]")
        return 1

    def bench_memstats(self, n):
        for cf_id, cfd in self.db._cfs.items():
            print(f"cf{cf_id} mem_entries={cfd.mem.num_entries} "
                  f"imm={len(cfd.imm)}")
        return 1


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser()
    ap.add_argument("--benchmarks", default="fillseq,readrandom")
    ap.add_argument("--num", type=int, default=100000)
    ap.add_argument("--db", default="/tmp/tpulsm_bench")
    ap.add_argument("--value-size", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=1)
    ap.add_argument("--seed", type=int, default=301)
    ap.add_argument("--json", default=None, help="SidePlugin-style config")
    ap.add_argument("--disable-wal", action="store_true")
    ap.add_argument("--use-existing-db", action="store_true")
    ap.add_argument("--statistics", action="store_true")
    ap.add_argument("--print-stats", action="store_true")
    ap.add_argument("--blob-cache-size", type=int, default=32 << 20,
                    help="BlobSource value cache bytes for *blob workloads")
    return ap


def main(argv=None):
    args = build_parser().parse_args(argv)
    Bench(args).run()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
