"""db_bench: the canonical benchmark driver.

Workload set mirrors the reference's db_bench dispatch
(tools/db_bench_tool.cc:3784-3893 in /root/reference): comma-separated
benchmarks run in order against one DB. `--json` loads a SidePlugin-style
config document (the Topling -json flag analogue).

Usage:
  python -m toplingdb_tpu.tools.db_bench --benchmarks=fillseq,readrandom \
      --num=100000 --db=/tmp/bench_db [--json=config.json] [--value-size=100]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import time

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions, WriteOptions
from toplingdb_tpu.db.write_batch import WriteBatch


class Bench:
    def __init__(self, args):
        self.args = args
        self.rng = random.Random(args.seed)
        if args.json:
            from toplingdb_tpu.utils.config import options_from_config

            with open(args.json) as f:
                cfg = json.load(f)
            self.options = options_from_config(cfg.get("options", cfg))
        else:
            self.options = Options()
        if args.statistics and self.options.statistics is None:
            from toplingdb_tpu.utils.statistics import Statistics

            self.options.statistics = Statistics()
        if ("mergerandom" in args.benchmarks
                and self.options.merge_operator is None):
            # mergerandom writes uint64 operands; reads after it would fail
            # with MergeInProgress without an operator.
            from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

            self.options.merge_operator = UInt64AddOperator()
        self.db: DB | None = None

    def key(self, i: int) -> bytes:
        return b"%016d" % i

    def value(self, i: int) -> bytes:
        data = (b"%d" % i) * (self.args.value_size // max(1, len(b"%d" % i)) + 1)
        return data[: self.args.value_size]

    def open_db(self, fresh: bool) -> None:
        if self.db is not None:
            self.db.close()
            self.db = None
        if fresh and not self.args.use_existing_db and os.path.exists(self.args.db):
            shutil.rmtree(self.args.db)
        self.db = DB.open(self.args.db, self.options)

    def run(self) -> None:
        for name in self.args.benchmarks.split(","):
            name = name.strip()
            fn = getattr(self, "bench_" + name, None)
            if fn is None:
                print(f"unknown benchmark: {name}")
                continue
            fresh = name.startswith("fill")
            if self.db is None or fresh:
                self.open_db(fresh)
            n = self.args.num
            t0 = time.time()
            ops = fn(n)
            dt = time.time() - t0
            ops = ops or n
            print(
                f"{name:<20} : {dt * 1e6 / ops:10.3f} micros/op "
                f"{ops / dt:12.0f} ops/sec; {dt:8.2f} s"
            )
        if self.db is not None:
            if self.args.print_stats and self.db.stats is not None:
                print(self.db.stats.to_string())
            self.db.close()

    # -- workloads ------------------------------------------------------

    def bench_fillseq(self, n):
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        batch = self.args.batch_size
        i = 0
        while i < n:
            b = WriteBatch()
            for _ in range(min(batch, n - i)):
                b.put(self.key(i), self.value(i))
                i += 1
            self.db.write(b, wo)
        return n

    def bench_fillrandom(self, n):
        wo = WriteOptions(disable_wal=self.args.disable_wal)
        batch = self.args.batch_size
        i = 0
        while i < n:
            b = WriteBatch()
            for _ in range(min(batch, n - i)):
                b.put(self.key(self.rng.randrange(n)), self.value(i))
                i += 1
            self.db.write(b, wo)
        return n

    def bench_overwrite(self, n):
        return self.bench_fillrandom(n)

    def bench_readseq(self, n):
        it = self.db.new_iterator()
        it.seek_to_first()
        count = 0
        while it.valid() and count < n:
            it.key(), it.value()
            it.next()
            count += 1
        return count

    def bench_readrandom(self, n):
        ro = ReadOptions()
        hits = 0
        for _ in range(n):
            if self.db.get(self.key(self.rng.randrange(self.args.num)), ro) is not None:
                hits += 1
        return n

    def bench_seekrandom(self, n):
        ro = ReadOptions()
        it = self.db.new_iterator(ro)
        for _ in range(n):
            it.seek(self.key(self.rng.randrange(self.args.num)))
            if it.valid():
                it.key(), it.value()
        return n

    def bench_mergerandom(self, n):
        import struct

        wo = WriteOptions(disable_wal=self.args.disable_wal)
        for i in range(n):
            self.db.merge(self.key(self.rng.randrange(self.args.num)),
                          struct.pack("<Q", 1), wo)
        return n

    def bench_fillrandombatch(self, n):
        saved = self.args.batch_size
        self.args.batch_size = max(saved, 100)
        try:
            return self.bench_fillrandom(n)
        finally:
            self.args.batch_size = saved

    def bench_multireadrandom(self, n):
        ro = ReadOptions()
        done = 0
        while done < n:
            ks = [self.key(self.rng.randrange(self.args.num))
                  for _ in range(min(16, n - done))]
            self.db.multi_get(ks, ro)
            done += len(ks)
        return n

    def bench_readwhilewriting(self, n):
        import threading

        stop = []

        def writer():
            i = 0
            while not stop:
                self.db.put(self.key(self.rng.randrange(self.args.num)),
                            self.value(i))
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            return self.bench_readrandom(n)
        finally:
            stop.append(1)
            t.join()

    def bench_deleteseq(self, n):
        for i in range(n):
            self.db.delete(self.key(i))
        return n

    def bench_compact(self, n):
        self.db.compact_range()
        return 1

    def bench_stats(self, n):
        print(self.db.get_property("tpulsm.stats"))
        return 1


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--benchmarks", default="fillseq,readrandom")
    ap.add_argument("--num", type=int, default=100000)
    ap.add_argument("--db", default="/tmp/tpulsm_bench")
    ap.add_argument("--value-size", type=int, default=100)
    ap.add_argument("--batch-size", type=int, default=1)
    ap.add_argument("--seed", type=int, default=301)
    ap.add_argument("--json", default=None, help="SidePlugin-style config")
    ap.add_argument("--disable-wal", action="store_true")
    ap.add_argument("--use-existing-db", action="store_true")
    ap.add_argument("--statistics", action="store_true")
    ap.add_argument("--print-stats", action="store_true")
    args = ap.parse_args(argv)
    Bench(args).run()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
