"""Concurrency lint (tier-1 CI): lock-order static analysis, lock
hierarchy enforcement, and thread-lifecycle checks over the whole
`toplingdb_tpu/` tree.

The package has ONE way to make locks and threads — the factories in
utils/concurrency.py (`ccy.Lock(name)`, `ccy.RLock(name)`,
`ccy.Condition(...)`, `ccy.spawn(name, target, ...)`). That single
funnel is what makes whole-tree static analysis possible; this lint is
the other half of the bargain. Invariants:

Locks
  L1. No raw `threading.Lock/RLock/Condition/Thread` outside
      utils/concurrency.py — everything goes through the factories.
  L2. Every `ccy.Lock`/`ccy.RLock` carries a string-literal lock-class
      name of the form `<module>.<Class-or-fn>.<attr>`, prefixed with
      the defining module's stem. `ccy.Condition` carries either such a
      name or `lock=` (aliasing an existing lock).
  L3. A lock-class name names ONE creation site (striped locks share a
      site, never a copy-pasted name) — duplicate names would silently
      merge classes in the order graph.
  L4. Locks are held via `with` only; bare `.acquire()`/`.release()`
      on a lock attribute defeats the region analysis.
  L5. The inter-class acquisition-order graph — built from nested
      `with` scopes plus cross-function edges through call resolution —
      must be acyclic. Any cycle is reported with a witness (file:line
      and call chain) for every edge on it.
  L6. Every lock class must appear in ARCHITECTURE.md's lock-hierarchy
      table, and every acquisition edge must go from a lower rank to a
      strictly higher rank. Stale table rows (classes that no longer
      exist) are also errors.

Threads
  T1. Every `ccy.spawn` carries a literal (or f-string) thread name.
  T2. Every spawned thread has a reachable join path: either
      `owner=` (dynamic lifecycle ownership via the ThreadRegistry —
      DB.close()/tests assert leaks) or a static `.join(` on the
      binding the spawn result was stored into.

Run: python -m toplingdb_tpu.tools.check_concurrency [repo_root]
Exit 0 clean; 1 with one violation per line otherwise.
"""

from __future__ import annotations

import ast
import os
import re
import sys

CCY_ALIASES = {"ccy", "concurrency"}
RAW_BANNED = {"Lock", "RLock", "Condition", "Thread"}
EXEMPT_REL = {os.path.join("utils", "concurrency.py")}

# Method names too generic to attribute to a package-level definition:
# a call `x.get(...)` is far more likely dict.get than DB.get, so these
# never resolve through the "globally unique name" rule (same-class
# `self.<name>()` calls still resolve).
_COMMON_CALLEES = {
    "get", "put", "set", "add", "remove", "pop", "append", "extend",
    "close", "open", "read", "write", "flush", "seek", "tell",
    "items", "keys", "values", "update", "copy", "clear", "sort",
    "join", "split", "strip", "encode", "decode", "format", "count",
    "start", "stop", "run", "wait", "notify", "notify_all", "send",
    "recv", "submit", "result", "cancel", "acquire", "release",
    "index", "insert", "find", "replace", "next", "setdefault",
    "discard", "startswith", "endswith", "lower", "upper", "search",
    "match", "group", "commit", "name", "exists", "empty", "size",
}

_LOCK_NAME_RE = re.compile(r"^[A-Za-z_][\w.]*$")


def _modname(path: str) -> str:
    stem = os.path.splitext(os.path.basename(path))[0]
    if stem == "__init__":
        return os.path.basename(os.path.dirname(path))
    return stem


def _is_ccy_call(node: ast.Call, attr: str) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == attr
            and isinstance(f.value, ast.Name) and f.value.id in CCY_ALIASES)


def _expr_key(e: ast.AST) -> str | None:
    """Dotted key for a Name/Attribute chain: `t` -> "t",
    `self._thread` -> "self._thread"."""
    parts = []
    while isinstance(e, ast.Attribute):
        parts.append(e.attr)
        e = e.value
    if isinstance(e, ast.Name):
        parts.append(e.id)
        return ".".join(reversed(parts))
    return None


class _FuncInfo:
    """Per-function acquisition and call events, in source order."""

    __slots__ = ("fid", "path", "modname", "classname", "direct", "calls")

    def __init__(self, fid, path, modname, classname):
        self.fid = fid
        self.path = path
        self.modname = modname
        self.classname = classname
        # (held lock-class tuple, acquired lock-class, lineno)
        self.direct: list[tuple[tuple[str, ...], str, int]] = []
        # (held lock-class tuple, callee name, is_self_call, lineno)
        self.calls: list[tuple[tuple[str, ...], str, bool, int]] = []


class Analysis:
    """Whole-tree lock/thread model. `violations` is the lint output;
    `edges` the inter-class acquisition-order graph with witnesses."""

    def __init__(self, repo_root: str, pkg_dir: str):
        self.repo_root = repo_root
        self.pkg_dir = pkg_dir
        self.violations: list[str] = []
        self.modules: list[tuple[str, str, ast.AST]] = []  # path, mod, tree
        # Lock-class registry --------------------------------------------
        self.lock_sites: dict[str, tuple[str, int]] = {}   # name -> site
        self.class_attr: dict[tuple[str, str, str], str] = {}
        self.attr_classes: dict[str, set[str]] = {}        # attr -> names
        self.name_classes: dict[tuple[str, str], set[str]] = {}  # mod,var
        self._cond_aliases: list[tuple] = []
        # Function registry ----------------------------------------------
        self.funcs: dict[str, _FuncInfo] = {}
        self.defs_by_name: dict[str, list[str]] = {}
        self.methods: dict[tuple[str, str], dict[str, str]] = {}
        # name -> fid (only same-(mod,class) lookups use this)
        # Edge graph ------------------------------------------------------
        # (A, B) -> (path, lineno, description)
        self.edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    # -- loading ---------------------------------------------------------

    def load(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.pkg_dir):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    with open(path, encoding="utf-8") as f:
                        src = f.read()
                    tree = ast.parse(src, filename=path)
                except (OSError, SyntaxError) as e:
                    self.violations.append(f"{path}: unparseable: {e}")
                    continue
                self.modules.append((path, _modname(path), tree))

    def _rel(self, path: str) -> str:
        return os.path.relpath(path, self.pkg_dir)

    def _exempt(self, path: str) -> bool:
        return self._rel(path) in EXEMPT_REL

    # -- pass 1: lock creation sites + local lint ------------------------

    def collect_locks(self) -> None:
        for path, mod, tree in self.modules:
            self._collect_locks_in(path, mod, tree)
        # Condition(lock=X) aliases resolve once every direct lock is known.
        for path, mod, classname, target, lock_expr, lineno in \
                self._cond_aliases:
            cls = self.resolve(lock_expr, mod, classname)
            if cls is None:
                self.violations.append(
                    f"{path}:{lineno}: ccy.Condition(lock=...) wraps an "
                    f"expression that does not resolve to a known lock "
                    f"class")
                continue
            self._bind(mod, classname, target, cls)

    def _bind(self, mod, classname, target, lockclass) -> None:
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and classname:
            self.class_attr[(mod, classname, target.attr)] = lockclass
            self.attr_classes.setdefault(target.attr, set()).add(lockclass)
        elif isinstance(target, ast.Name):
            self.name_classes.setdefault(
                (mod, target.id), set()).add(lockclass)

    def _collect_locks_in(self, path, mod, tree) -> None:
        viol = self.violations

        def handle_factory(node: ast.Call, classname: str | None,
                           target: ast.AST | None) -> None:
            kind = node.func.attr
            lit = None
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                lit = node.args[0].value
            lock_kw = next(
                (kw.value for kw in node.keywords if kw.arg == "lock"), None)
            if kind == "Condition" and lock_kw is not None:
                if target is not None:
                    self._cond_aliases.append(
                        (path, mod, classname, target, lock_kw, node.lineno))
                return
            if lit is None:
                viol.append(
                    f"{path}:{node.lineno}: ccy.{kind}() needs a "
                    f"string-literal lock-class name")
                return
            if not _LOCK_NAME_RE.match(lit) or \
                    not lit.startswith(mod + "."):
                viol.append(
                    f"{path}:{node.lineno}: lock-class name {lit!r} must "
                    f"be '<module>.<scope>.<attr>' prefixed with "
                    f"{mod + '.'!r}")
            if lit in self.lock_sites:
                op, ol = self.lock_sites[lit]
                viol.append(
                    f"{path}:{node.lineno}: lock-class name {lit!r} "
                    f"already created at {op}:{ol} — duplicate names "
                    f"merge lock classes")
            else:
                self.lock_sites[lit] = (path, node.lineno)
            if target is not None:
                self._bind(mod, classname, target, lit)

        def walk(node, classname):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    walk(child, child.name)
                    continue
                if isinstance(child, ast.Assign) and \
                        isinstance(child.value, ast.Call) and \
                        isinstance(child.value.func, ast.Attribute) and \
                        child.value.func.attr in ("Lock", "RLock",
                                                  "Condition") and \
                        isinstance(child.value.func.value, ast.Name) and \
                        child.value.func.value.id in CCY_ALIASES:
                    handle_factory(child.value, classname,
                                   child.targets[0])
                    continue
                walk(child, classname)

        walk(tree, None)
        # Factory calls that are NOT simple assignments (returned, passed
        # as args, ...) still need the name lint.
        assigned = set()

        def mark(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.Assign) and \
                        isinstance(child.value, ast.Call):
                    assigned.add(id(child.value))
                mark(child)

        mark(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and id(node) not in assigned and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("Lock", "RLock", "Condition") and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in CCY_ALIASES:
                handle_factory(node, None, None)

    # -- resolution ------------------------------------------------------

    def resolve(self, expr: ast.AST, mod: str,
                classname: str | None) -> str | None:
        """Lock class acquired by `with <expr>:`, or None."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            if isinstance(expr.value, ast.Name) and \
                    expr.value.id == "self" and classname:
                cls = self.class_attr.get((mod, classname, attr))
                if cls is not None:
                    return cls
            cands = self.attr_classes.get(attr, ())
            if len(cands) == 1:
                return next(iter(cands))
            return None
        if isinstance(expr, ast.Name):
            cands = self.name_classes.get((mod, expr.id), ())
            if len(cands) == 1:
                return next(iter(cands))
        return None

    # -- pass 2: per-function acquisition/call events --------------------

    def collect_funcs(self) -> None:
        for path, mod, tree in self.modules:
            self._collect_funcs_in(path, mod, tree)

    def _collect_funcs_in(self, path, mod, tree) -> None:
        ana = self

        def visit_func(fn, classname, qualprefix):
            fid = f"{mod}:{qualprefix}{fn.name}"
            info = _FuncInfo(fid, path, mod, classname)
            # Redefinitions (e.g. overloads behind `if`) keep the first.
            if fid not in ana.funcs:
                ana.funcs[fid] = info
                ana.defs_by_name.setdefault(fn.name, []).append(fid)
                if classname:
                    ana.methods.setdefault((mod, classname), {})[
                        fn.name] = fid
            else:
                info = ana.funcs[fid]
            held: list[str] = []

            def record_calls(expr):
                """Call events inside an expression (not nested defs)."""
                for node in ast.walk(expr):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if isinstance(f, ast.Attribute):
                        is_self = (isinstance(f.value, ast.Name)
                                   and f.value.id == "self")
                        info.calls.append(
                            (tuple(held), f.attr, is_self, node.lineno))
                    elif isinstance(f, ast.Name):
                        info.calls.append(
                            (tuple(held), f.id, False, node.lineno))

            def walk_stmts(stmts):
                for st in stmts:
                    if isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        visit_func(st, classname,
                                   f"{qualprefix}{fn.name}.")
                        continue
                    if isinstance(st, ast.ClassDef):
                        visit_class(st, f"{qualprefix}{fn.name}.")
                        continue
                    if isinstance(st, (ast.With, ast.AsyncWith)):
                        pushed = 0
                        for item in st.items:
                            record_calls(item.context_expr)
                            cls = ana.resolve(item.context_expr, mod,
                                              classname)
                            if cls is not None:
                                info.direct.append(
                                    (tuple(held), cls, st.lineno))
                                held.append(cls)
                                pushed += 1
                        walk_stmts(st.body)
                        del held[len(held) - pushed:len(held)]
                        continue
                    # Generic statement: collect calls from its
                    # expressions, then recurse into its statement bodies.
                    for field in st._fields:
                        val = getattr(st, field, None)
                        if isinstance(val, list) and val and \
                                isinstance(val[0], ast.stmt):
                            walk_stmts(val)
                        elif isinstance(val, ast.expr):
                            record_calls(val)
                        elif isinstance(val, list):
                            for v in val:
                                if isinstance(v, ast.expr):
                                    record_calls(v)

            walk_stmts(fn.body)

        def visit_class(cls_node, qualprefix):
            for st in cls_node.body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit_func(st, cls_node.name,
                               f"{qualprefix}{cls_node.name}.")
                elif isinstance(st, ast.ClassDef):
                    visit_class(st, f"{qualprefix}{cls_node.name}.")

        for st in tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_func(st, None, "")
            elif isinstance(st, ast.ClassDef):
                visit_class(st, "")

    # -- pass 3: call resolution + closure + edges -----------------------

    def _resolve_call(self, name, is_self, mod, classname) -> str | None:
        if name.startswith("__"):
            return None
        if is_self and classname:
            fid = self.methods.get((mod, classname), {}).get(name)
            if fid is not None:
                return fid
        if name in _COMMON_CALLEES:
            return None
        fids = self.defs_by_name.get(name, ())
        if len(fids) == 1:
            return fids[0]
        return None

    def build_edges(self) -> None:
        closures: dict[str, dict[str, tuple[tuple[str, ...], int]]] = {}

        def closure(fid, stack):
            if fid in closures:
                return closures[fid]
            if fid in stack:
                return {}
            stack.add(fid)
            info = self.funcs[fid]
            out: dict[str, tuple[tuple[str, ...], int]] = {}
            for _held, cls, line in info.direct:
                out.setdefault(cls, ((fid,), line))
            for _held, name, is_self, line in info.calls:
                callee = self._resolve_call(name, is_self, info.modname,
                                            info.classname)
                if callee is None:
                    continue
                for cls, (chain, cl) in closure(callee, stack).items():
                    out.setdefault(cls, ((fid,) + chain, cl))
            stack.discard(fid)
            closures[fid] = out
            return out

        for fid in self.funcs:
            closure(fid, set())

        def add_edge(a, b, path, line, desc):
            if a == b:
                return  # striping / RLock reentrancy
            self.edges.setdefault((a, b), (path, line, desc))

        for fid, info in self.funcs.items():
            for held, cls, line in info.direct:
                for a in held:
                    add_edge(a, cls, info.path, line,
                             f"{a} held at `with` acquiring {cls} "
                             f"in {fid}")
            for held, name, is_self, line in info.calls:
                if not held:
                    continue
                callee = self._resolve_call(name, is_self, info.modname,
                                            info.classname)
                if callee is None:
                    continue
                for cls, (chain, cl) in closures[callee].items():
                    for a in held:
                        add_edge(a, cls, info.path, line,
                                 f"{a} held in {fid} calling "
                                 f"{' -> '.join(chain)} which acquires "
                                 f"{cls} at line {cl}")

    # -- pass 4: cycles --------------------------------------------------

    def check_cycles(self) -> None:
        graph: dict[str, list[str]] = {}
        for (a, b) in self.edges:
            graph.setdefault(a, []).append(b)
            graph.setdefault(b, [])
        # Tarjan SCC, iterative.
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        onstack: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(root):
            work = [(root, iter(graph[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            onstack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        onstack.add(w)
                        work.append((w, iter(graph[w])))
                        advanced = True
                        break
                    elif w in onstack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    pv = work[-1][0]
                    low[pv] = min(low[pv], low[v])
                if low[v] == index[v]:
                    scc = []
                    while True:
                        w = stack.pop()
                        onstack.discard(w)
                        scc.append(w)
                        if w == v:
                            break
                    sccs.append(scc)

        for v in graph:
            if v not in index:
                strongconnect(v)

        for scc in sccs:
            if len(scc) < 2:
                continue
            cyc = self._find_cycle(set(scc))
            lines = [f"lock-order cycle: {' -> '.join(cyc + [cyc[0]])}"]
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                path, line, desc = self.edges[(a, b)]
                lines.append(f"    {a} -> {b}: {path}:{line}: {desc}")
            self.violations.append("\n".join(lines))

    def _find_cycle(self, scc: set[str]) -> list[str]:
        start = sorted(scc)[0]
        seen = {start: None}
        queue = [start]
        while queue:
            v = queue.pop(0)
            for (a, b) in self.edges:
                if a != v or b not in scc:
                    continue
                if b == start:
                    # Reconstruct start -> ... -> v, edge v -> start.
                    out = []
                    cur = v
                    while cur is not None:
                        out.append(cur)
                        cur = seen[cur]
                    return list(reversed(out))
                if b not in seen:
                    seen[b] = v
                    queue.append(b)
        return sorted(scc)  # unreachable, defensive

    # -- pass 5: declared hierarchy --------------------------------------

    def check_hierarchy(self) -> None:
        ranks = hierarchy_from_architecture(self.repo_root)
        if ranks is None:
            return  # synthetic trees without ARCHITECTURE.md: skip
        for name, (path, line) in sorted(self.lock_sites.items()):
            if name not in ranks:
                self.violations.append(
                    f"{path}:{line}: lock class {name!r} is not declared "
                    f"in ARCHITECTURE.md's lock-hierarchy table")
        for name in sorted(ranks):
            if name not in self.lock_sites:
                self.violations.append(
                    f"ARCHITECTURE.md: lock-hierarchy row {name!r} names "
                    f"a lock class that no longer exists")
        for (a, b), (path, line, desc) in sorted(self.edges.items()):
            ra, rb = ranks.get(a), ranks.get(b)
            if ra is None or rb is None:
                continue  # already reported as undeclared
            if ra >= rb:
                self.violations.append(
                    f"{path}:{line}: acquisition edge {a} (rank {ra}) -> "
                    f"{b} (rank {rb}) violates the declared lock "
                    f"hierarchy: {desc}")

    # -- thread lifecycle + raw-primitive lint ---------------------------

    def check_threads(self) -> None:
        for path, mod, tree in self.modules:
            if self._exempt(path):
                continue
            self._check_threads_in(path, mod, tree)

    def _check_threads_in(self, path, mod, tree) -> None:
        viol = self.violations
        # L1: raw threading primitives.
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "threading":
                for alias in node.names:
                    if alias.name in RAW_BANNED:
                        viol.append(
                            f"{path}:{node.lineno}: `from threading "
                            f"import {alias.name}` — use the "
                            f"utils/concurrency factories")
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in RAW_BANNED and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "threading":
                viol.append(
                    f"{path}:{node.lineno}: raw threading."
                    f"{node.func.attr}() — use ccy."
                    f"{'spawn' if node.func.attr == 'Thread' else node.func.attr}"
                    f" from utils/concurrency")
            # L4: bare acquire/release on a lock attribute.
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("acquire", "release"):
                tgt = node.func.value
                attr = tgt.attr if isinstance(tgt, ast.Attribute) else None
                if attr in self.attr_classes:
                    viol.append(
                        f"{path}:{node.lineno}: bare .{node.func.attr}() "
                        f"on lock attribute {attr!r} — hold locks with "
                        f"`with` so regions stay statically analyzable")
        # T-rules: spawn discipline.
        joined: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                key = _expr_key(node.func.value)
                if key:
                    joined.add(key)
        # `for t in threads: t.join()` marks `threads` joined too.
        for node in ast.walk(tree):
            if isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name) and \
                    node.target.id in joined:
                key = _expr_key(node.iter)
                if key:
                    joined.add(key)
        # Bind each spawn call to the name its result lands in.
        bound: dict[int, str] = {}
        spawns: list[ast.Call] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _is_ccy_call(node, "spawn"):
                spawns.append(node)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                key = _expr_key(node.targets[0])
                if key:
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Call) and \
                                _is_ccy_call(sub, "spawn"):
                            bound[id(sub)] = key
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "append":
                key = _expr_key(node.func.value)
                if key:
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Call) and \
                                    _is_ccy_call(sub, "spawn"):
                                bound[id(sub)] = key
        for node in spawns:
            a0 = node.args[0] if node.args else None
            named = (isinstance(a0, ast.Constant)
                     and isinstance(a0.value, str)) or \
                isinstance(a0, ast.JoinedStr)
            if not named:
                viol.append(
                    f"{path}:{node.lineno}: ccy.spawn() needs a literal "
                    f"(or f-string) thread name as its first argument")
            has_owner = any(kw.arg == "owner" for kw in node.keywords)
            if has_owner:
                continue
            key = bound.get(id(node))
            if key is None or key not in joined:
                viol.append(
                    f"{path}:{node.lineno}: spawned thread has no join "
                    f"path — pass owner= (ThreadRegistry lifecycle) or "
                    f"store the thread and .join() it in this module")


def hierarchy_from_architecture(repo_root: str) -> dict[str, int] | None:
    """Parse the lock-hierarchy table: rows `| <rank> | \\`<class>\\` | ...`
    under a heading containing 'lock hierarchy'. Repeated rank numbers
    group incomparable classes. Returns {class: rank} or None if the
    table is absent."""
    path = os.path.join(repo_root, "ARCHITECTURE.md")
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    m = re.search(r"^#{1,5}.*lock hierarchy.*$", text,
                  re.IGNORECASE | re.MULTILINE)
    if not m:
        return None
    section = text[m.end():]
    nxt = re.search(r"\n#{1,5} ", section)
    if nxt:
        section = section[: nxt.start()]
    ranks: dict[str, int] = {}
    for line in section.splitlines():
        rm = re.match(r"\|\s*(\d+)\s*\|", line)
        if not rm:
            continue
        cm = re.search(r"`([\w.]+)`", line)
        if cm:
            ranks[cm.group(1)] = int(rm.group(1))
    return ranks or None


def analyze(repo_root: str | None = None) -> Analysis:
    repo_root = repo_root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pkg = os.path.join(repo_root, "toplingdb_tpu")
    if not os.path.isdir(pkg):
        pkg = repo_root  # synthetic trees in tests
    ana = Analysis(repo_root, pkg)
    ana.load()
    ana.collect_locks()
    ana.collect_funcs()
    ana.build_edges()
    ana.check_cycles()
    ana.check_hierarchy()
    ana.check_threads()
    return ana


def run(repo_root: str | None = None) -> list[str]:
    return analyze(repo_root).violations


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    root = argv[0] if argv and not argv[0].startswith("-") else None
    ana = analyze(root)
    if "--dump-graph" in (argv or []):
        for (a, b), (path, line, desc) in sorted(ana.edges.items()):
            print(f"{a} -> {b}  [{path}:{line}]")
    for v in ana.violations:
        print(v)
    print(f"check_concurrency: {len(ana.lock_sites)} lock classes, "
          f"{len(ana.edges)} acquisition edges, "
          f"{len(ana.violations)} violation(s)")
    return 1 if ana.violations else 0


if __name__ == "__main__":
    sys.exit(main())
