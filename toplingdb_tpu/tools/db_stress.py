"""db_stress: randomized stateful stress + crash-recovery harness.

Reference db_stress_tool/ + tools/db_crashtest.py in /root/reference: an
ExpectedState mirrors every key's latest value and survives kills; worker
threads run random ops; blackbox mode kill -9's the child process at random
intervals, reopens, and verifies against the model.

Crash-consistent model: every op is journaled write-ahead (fsync) BEFORE the
synced DB write, and committed AFTER it. On recovery, a key whose newest
journal record is uncommitted may legally hold either the pending value or
the previous committed one (the reference's ExpectedState pending-write
semantics).

Usage:
  python -m toplingdb_tpu.tools.db_stress --ops=20000 --threads=4 \
      --db=/tmp/stressdb [--crash-test --rounds=3]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time


class ExpectedState:
    """Write-ahead op journal: lines
      {"op": "W"|"D", "id": n, "key": k, "value": v}   (pre-write, fsynced)
      {"op": "C", "id": n}                             (post-write commit)
    Recovery derives, per key: last committed value + optional pending op.
    """

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a")
        self._mu = ccy.Lock("db_stress.ExpectedState._mu")
        self._next_id = 1

    def load(self):
        """Returns (committed: {key: value|None}, pending: {key: [values]})."""
        committed: dict[str, str | None] = {}
        key_ops: dict[int, tuple[str, str | None]] = {}
        committed_ids: set[int] = set()
        order: list[tuple[int, str]] = []
        if os.path.exists(self.path):
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail line from a crash
                    if rec["op"] == "C":
                        committed_ids.add(rec["id"])
                    else:
                        v = rec.get("value") if rec["op"] == "W" else None
                        key_ops[rec["id"]] = (rec["key"], v)
                        order.append((rec["id"], rec["key"]))
                        if rec["id"] >= self._next_id:
                            self._next_id = rec["id"] + 1
        pending: dict[str, list[str | None]] = {}
        for op_id, key in order:
            _, v = key_ops[op_id]
            if op_id in committed_ids:
                committed[key] = v
                pending.pop(key, None)
            else:
                pending.setdefault(key, []).append(v)
        return committed, pending

    def begin(self, key: str, value: str | None) -> int:
        with self._mu:
            op_id = self._next_id
            self._next_id += 1
            rec = {"op": "W" if value is not None else "D", "id": op_id,
                   "key": key}
            if value is not None:
                rec["value"] = value
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())
            return op_id

    def commit(self, op_id: int) -> None:
        with self._mu:
            self._f.write(json.dumps({"op": "C", "id": op_id}) + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()


def _cf_map(db) -> dict:
    return {h.name: h for h in db.list_column_families()}


def _resolve_cf(cf_by_name: dict, k: str):
    """Journal keys may carry a 'cfN|' prefix (multi_cf variant): returns
    (cf_handle_or_None, raw_key). A journaled CF that the DB does not
    know is ITSELF a verification failure — falling back to the default
    CF would mask exactly the data-loss class this harness hunts."""
    if "|" not in k:
        return None, k
    cfname, raw = k.split("|", 1)
    if cfname not in cf_by_name:
        raise AssertionError(f"journaled CF {cfname!r} missing from DB")
    return cf_by_name[cfname], raw


def verify(db, committed, pending) -> int:
    bad = 0
    keys = set(committed) | set(pending)
    cfs = _cf_map(db)
    for k in sorted(keys):
        cf, raw = _resolve_cf(cfs, k)
        got = db.get(raw.encode(), cf=cf)
        acceptable = set()
        if k in committed:
            acceptable.add(committed[k])
        elif k in pending:
            acceptable.add(None)  # pending op on a never-committed key
        for v in pending.get(k, ()):
            acceptable.add(v)
        want = {v.encode() if v is not None else None for v in acceptable}
        if got not in want:
            bad += 1
            if bad <= 10:
                print(f"MISMATCH key={k} got={got} acceptable={want}")
    return bad


# Option-variant matrix (reference tools/db_crashtest.py:17-28's parameter
# sweep): each variant exercises a different durability/write-path/storage
# configuration under the SAME expected-state model.
VARIANTS = {
    "default": {},
    "blob": {"enable_blob_files": True, "min_blob_size": 32,
             "enable_blob_garbage_collection": True,
             "blob_garbage_collection_age_cutoff": 0.5},
    "unordered": {"unordered_write": True,
                  "allow_concurrent_memtable_write": True},
    "pipelined": {"enable_pipelined_write": True},
    "universal": {"compaction_style": "universal"},
    "tiny_buffer": {"write_buffer_size": 16 * 1024},
    "cspp": {"memtable_rep": "cspp"},
    # reference db_crashtest.py matrix rows: user-defined timestamps and
    # multi-CF ops (writes fan across families; the model keys carry the
    # cf so verification stays exact).
    "timestamp": {"_ts": True},
    "multi_cf": {"_cfs": 3},
}


def variant_options(args):
    from toplingdb_tpu.options import Options

    kw = {k: v for k, v in VARIANTS[args.variant].items()
          if not k.startswith("_")}
    if VARIANTS[args.variant].get("_ts"):
        from toplingdb_tpu.db.dbformat import U64TsBytewiseComparator

        kw["comparator"] = U64TsBytewiseComparator()
    kw.setdefault("write_buffer_size", args.write_buffer_size)
    return Options(**kw)


def run_stress(args) -> int:
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import WriteOptions

    model_path = args.db + ".journal"
    expected = ExpectedState(model_path)
    committed, pending = expected.load()
    db = DB.open(args.db, variant_options(args))
    vspec = VARIANTS[args.variant]
    use_ts = bool(vspec.get("_ts"))
    n_cfs = int(vspec.get("_cfs", 0))
    cfs = [None]
    if n_cfs:
        existing = {h.name: h for h in db.list_column_families()}
        for i in range(n_cfs):
            nm = "cf%d" % i
            if nm in existing:
                cfs.append(existing[nm])
            else:
                cfs.append(db.create_column_family(nm))

    bad = verify(db, committed, pending)
    if bad:
        print(f"VERIFICATION FAILED: {bad} mismatches")
        db.close()
        return 1
    print(f"verified {len(committed) + len(pending)} keys from previous "
          f"state: OK")
    # Fold pending into committed using what the DB actually holds.
    model = dict(committed)
    cf_by_name = _cf_map(db)
    for k in pending:
        cf, raw = _resolve_cf(cf_by_name, k)
        got = db.get(raw.encode(), cf=cf)
        model[k] = got.decode() if got is not None else None

    lock = ccy.Lock("db_stress.run_stress.lock")
    errors = []
    ops_done = [0]

    def worker(tid: int):
        rng = random.Random(args.seed + tid)
        wo_sync = WriteOptions(sync=True)
        while ops_done[0] < args.ops and not errors:
            try:
                k = "key%06d" % rng.randrange(args.max_key)
                cf = None
                if n_cfs:
                    ci = rng.randrange(len(cfs))
                    cf = cfs[ci]
                    if ci:
                        k = "cf%d|%s" % (ci - 1, k)
                raw = k.split("|", 1)[1] if "|" in k else k
                r = rng.random()
                with lock:
                    if r < 0.55:
                        v = "val%010d" % rng.randrange(10**9)
                        op = expected.begin(k, v)
                        # User timestamps must stay MONOTONIC ACROSS CRASH
                        # RESTARTS (newest-ts-wins reads would otherwise
                        # keep returning pre-crash values and the model
                        # would flag them as lost writes): the journal op
                        # id is persisted and strictly increasing — use it
                        # as the timestamp.
                        kw = {"ts": op} if use_ts else {}
                        db.put(raw.encode(), v.encode(), wo_sync, cf=cf,
                               **kw)
                        expected.commit(op)
                        model[k] = v
                    elif r < 0.75:
                        op = expected.begin(k, None)
                        kw = {"ts": op} if use_ts else {}
                        db.delete(raw.encode(), wo_sync, cf=cf, **kw)
                        expected.commit(op)
                        model[k] = None
                    elif r < 0.9:
                        got = db.get(raw.encode(), cf=cf)
                        want = model.get(k)
                        wantb = want.encode() if want is not None else None
                        if k in model and got != wantb:
                            errors.append(f"read mismatch {k}: {got} != {wantb}")
                    else:
                        it = db.new_iterator(cf=cf)
                        it.seek(raw.encode())
                        for _ in range(5):
                            if not it.valid():
                                break
                            it.next()
                    ops_done[0] += 1
            except Exception as e:
                errors.append(repr(e))

    threads = [ccy.spawn(f"stress-worker-{t}", worker, args=(t,),
                         daemon=False, start=False)
               for t in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    expected.close()
    db.close()
    if errors:
        print("STRESS ERRORS:", errors[:5])
        return 1
    print(f"stress OK: {ops_done[0]} ops, {args.threads} threads")
    return 0


def run_disk_budget_stress(args) -> int:
    """--disk-budget mode: the storage-pressure invariant harness. The DB
    runs on a FaultInjectionEnv whose writable bytes are capped; mid-run
    the budget is slammed to zero (disk full) and later refilled (operator
    frees space / trash drains). The invariant, checked on every op: the
    DB is in EXACTLY one of
      serving                    — no latch, op succeeds
      SOFT-latched-recovering    — bg error latched, reason no_space,
                                   severity SOFT (auto-recovery armed)
      cleanly-shed               — op refused by a no-space-classified
                                   error or Busy while pressure is red
    Anything else (HARD/FATAL latch, corruption, an unclassified raise,
    a lost acked write) fails the run. Recovery must be autonomous: this
    harness NEVER calls resume()."""
    import shutil

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.env.fault_injection import FaultInjectionEnv
    from toplingdb_tpu.options import Options, WriteOptions
    from toplingdb_tpu.utils.statistics import Statistics
    from toplingdb_tpu.utils.status import Busy, Severity, is_no_space

    shutil.rmtree(args.db, ignore_errors=True)
    fe = FaultInjectionEnv(PosixEnv())
    budget = args.disk_budget
    fe.set_disk_budget("*", budget)
    opts = Options(write_buffer_size=args.write_buffer_size,
                   free_space_poll_period_sec=0.02,
                   flush_headroom_bytes=2 * args.write_buffer_size,
                   statistics=Statistics())
    db = DB.open(args.db, opts, env=fe)
    rng = random.Random(args.seed)
    wo = WriteOptions(sync=True)
    model: dict[str, str] = {}
    served = shed = 0
    starve_at, refill_at = args.ops // 3, (2 * args.ops) // 3

    def state() -> str:
        err = db._bg_error
        if err is not None:
            if (db._bg_error_reason == "no_space"
                    and db._bg_error_severity == Severity.SOFT_ERROR):
                return "soft-latched-recovering"
            return f"BAD-LATCH({db._bg_error_reason}," \
                   f"{db._bg_error_severity.name})"
        return "shedding" if db.disk_pressure() == "red" else "serving"

    try:
        for i in range(args.ops):
            if i == starve_at:
                fe.set_disk_budget("*", 0)
            if i == refill_at:
                fe.add_disk_budget("*", max(budget, 1 << 22))
            k = "key%06d" % rng.randrange(args.max_key)
            v = "val%010d" % rng.randrange(10 ** 9)
            try:
                db.put(k.encode(), v.encode(), wo)
                model[k] = v
                served += 1
            except Exception as e:
                if not (is_no_space(e) or isinstance(e, Busy)):
                    print(f"UNCLASSIFIED FAILURE at op {i}: {e!r}")
                    return 1
                shed += 1
            st = state()
            if st.startswith("BAD-LATCH"):
                print(f"INVARIANT VIOLATION at op {i}: {st}")
                return 1
        # Budget is refilled: the latch must clear with ZERO resume()
        # calls from here, however the run ended.
        deadline = time.monotonic() + 30.0
        while db._bg_error is not None and time.monotonic() < deadline:
            time.sleep(0.05)
        if db._bg_error is not None:
            print(f"AUTO-RECOVERY STALLED: {state()}")
            return 1
        bad = sum(1 for k, v in model.items()
                  if db.get(k.encode()) != v.encode())
        if bad:
            print(f"PARITY FAILED: {bad} acked writes lost")
            return 1
        print(f"disk-budget stress OK: {served} served, {shed} shed, "
              f"{len(model)} keys verified, state={state()}")
        return 0
    finally:
        db.close()


def run_crash_test(args) -> int:
    """Crash loop (reference tools/db_crashtest.py). Blackbox: run the
    stress child, kill -9 it at a random wall-clock moment. Whitebox
    (--whitebox): the child ALSO self-kills at armed TEST_KILL_RANDOM
    markers inside the engine's durability windows (after-WAL,
    memtable-switch, after-SST-write, before/after-MANIFEST-write), hitting
    the exact crash points wall-clock kills rarely land on. Either way the
    next round reopens and verifies against the expected-state journal."""
    from toplingdb_tpu.utils.kill_point import KILLED_EXIT_CODE

    rng = random.Random(args.seed or None)
    for round_ in range(args.rounds):
        cmd = [
            sys.executable, "-m", "toplingdb_tpu.tools.db_stress",
            f"--db={args.db}", f"--ops={args.ops}",
            f"--threads={args.threads}", f"--seed={args.seed + round_}",
            f"--max-key={args.max_key}", f"--variant={args.variant}",
        ]
        env = dict(os.environ)
        if args.whitebox:
            env["TPULSM_KILL_ODDS"] = str(args.kill_odds)
            env["TPULSM_KILL_SEED"] = str(args.seed + round_)
            if args.kill_prefix:
                env["TPULSM_KILL_PREFIX"] = args.kill_prefix
        child = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, env=env)
        kill_after = rng.uniform(0.5, args.kill_after)
        try:
            out, _ = child.communicate(timeout=kill_after)
            if child.returncode == KILLED_EXIT_CODE:
                print(f"round {round_}: whitebox kill point fired; "
                      f"verifying...")
            elif child.returncode != 0:
                print(out.decode())
                print(f"round {round_}: child failed rc={child.returncode}")
                return 1
            else:
                print(f"round {round_}: completed cleanly")
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait()
            print(f"round {round_}: killed at {kill_after:.1f}s; verifying...")
        # Verification happens at the start of the next child run.
    vcmd = [
        sys.executable, "-m", "toplingdb_tpu.tools.db_stress",
        f"--db={args.db}", "--ops=0", "--threads=1",
        f"--max-key={args.max_key}", f"--variant={args.variant}",
    ]
    r = subprocess.run(vcmd, capture_output=True)
    sys.stdout.write(r.stdout.decode())
    if r.returncode != 0:
        print("FINAL VERIFICATION FAILED")
        return 1
    print("crash test passed")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--db", default="/tmp/tpulsm_stress")
    ap.add_argument("--ops", type=int, default=10000)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--max-key", type=int, default=2000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--write-buffer-size", type=int, default=64 * 1024)
    ap.add_argument("--variant", default="default", choices=sorted(VARIANTS))
    ap.add_argument("--crash-test", action="store_true")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--kill-after", type=float, default=5.0)
    # Whitebox mode (reference db_crashtest.py whitebox / TEST_KILL_RANDOM).
    ap.add_argument("--whitebox", action="store_true")
    ap.add_argument("--kill-odds", type=int, default=300)
    ap.add_argument("--kill-prefix", default="")
    # Disk-full mode: byte budget for the injected filesystem (0 = off).
    ap.add_argument("--disk-budget", type=int, default=0)
    args = ap.parse_args(argv)
    if args.disk_budget > 0:
        return run_disk_budget_stress(args)
    if args.crash_test:
        return run_crash_test(args)
    return run_stress(args)


if __name__ == "__main__":
    sys.exit(main())
