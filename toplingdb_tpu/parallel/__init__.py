"""Multi-chip parallelism: the framework's scale-out axes.

Two axes over a jax.sharding.Mesh, mirroring the reference's two parallelism
mechanisms (SURVEY.md §2.3):

  'jobs'   one compaction job per chip — the dcompact fan-out axis
           (reference: one CompactionJob per worker process). Jobs are
           independent: no collectives on the hot path.
  'range'  key-range sharding WITHIN one job — the subcompaction axis
           (reference GenSubcompactionBoundaries, compaction_job.cc:604-640),
           realized as a distributed sample-sort: local sort → splitter
           all_gather → all_to_all redistribution → local merge → boundary
           halo exchange (ppermute) for the GC mask.

distributed_gc.py implements the 'range' axis; fanout.py stacks jobs on the
'jobs' axis and drives whole pods.
"""
