"""Multichip probe CLI: a THIN wrapper over parallel/mesh_plan.py.

Two modes, both printing one JSON line:

  weak (default)  range-axis WEAK-SCALING of the distributed GC step:
                  `run_distributed_gc` over a (jobs=1, range=R) mesh for
                  R = 1,2,4..devices with a FIXED per-device row count —
                  the measured story for the all_to_all/ppermute
                  collective design (VERDICT r04 item 10).
  mesh            MEASURED mesh compaction: the same uniform key-range
                  shards through the mesh shard runner
                  (ops/mesh_compaction.py) at 1 chip vs all chips —
                  strong scaling of one fanned-out job (bench.py promotes
                  this into compaction_mesh_MBps / mesh_scaling_x).

On a CPU host the devices are virtual
(--xla_force_host_platform_device_count), so the numbers characterize
partitioning/dispatch overhead scaling, not chip throughput; the same
harness runs unchanged on a real multi-chip backend.

Runs in a SUBPROCESS (bench.py invokes `python -m
toplingdb_tpu.parallel.scaling_probe ...`) because the device count must
be set before the jax backend exists.

Exit codes: 0 measured; 3 SKIP (environment cannot run the probe — no
jax backend / too few devices; the caller drops the row); 1 the
measurement itself failed.
"""

from __future__ import annotations

import argparse
import json
import sys

from toplingdb_tpu.parallel import mesh_plan


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("weak", "mesh"), default="weak")
    ap.add_argument("--rows-per-device", type=int, default=1 << 16)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    # Virtual CPU devices must be configured BEFORE the backend exists.
    mesh_plan.configure_virtual_devices(args.devices)
    try:
        import jax

        mesh_plan.pin_cpu_backend()
        n_dev = len(jax.devices())
    except Exception as e:  # no usable backend: a skip, not a failure
        print(json.dumps({"skip": f"jax backend unavailable: {e!r}"[:200]}))
        return mesh_plan.EXIT_SKIP
    if n_dev < args.devices:
        print(json.dumps({"skip": f"{n_dev} devices < {args.devices} "
                                  "requested"}))
        return mesh_plan.EXIT_SKIP

    try:
        if args.mode == "mesh":
            rows = mesh_plan.mesh_compact_rows(
                args.rows_per_device, args.devices, args.repeats)
            print(json.dumps({"mesh_compact": rows}))
        else:
            rows = mesh_plan.weak_scaling_rows(
                args.rows_per_device, args.devices, args.repeats)
            print(json.dumps({"weak_scaling": rows}))
    except Exception as e:  # noqa: BLE001 — measurement broke
        print(json.dumps({"error": repr(e)[:300]}))
        return mesh_plan.EXIT_FAILURE
    return mesh_plan.EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
