"""Range-axis WEAK-SCALING probe for the distributed GC step.

Runs `run_distributed_gc` over a (jobs=1, range=R) mesh for R in the
requested device counts with a FIXED per-device row count, and prints one
JSON line of per-R wall times — the measured story for the all_to_all /
ppermute collective design (VERDICT r04 item 10). On a CPU host the
devices are virtual (--xla_force_host_platform_device_count), so the
numbers characterize the COLLECTIVE/PARTITIONING overhead scaling, not
chip throughput; the same harness runs unchanged on a real multi-chip
backend.

Runs in a SUBPROCESS (bench.py invokes `python -m
toplingdb_tpu.parallel.scaling_probe --rows-per-device N --devices 8`)
because the device count must be set before the jax backend exists.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from toplingdb_tpu.utils import errors as _errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows-per-device", type=int, default=1 << 16)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args(argv)

    # Virtual CPU devices must be configured BEFORE the backend exists.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()

    # Re-assert via jax.config too: on axon hosts sitecustomize pre-imports
    # jax and force-registers the tunnel backend over JAX_PLATFORMS.
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:
        _errors.swallow(reason="jax-platform-pin", exc=e)
    import numpy as np
    from jax.sharding import Mesh

    from toplingdb_tpu.db.dbformat import ValueType, make_internal_key
    from toplingdb_tpu.ops import compaction_kernels as ck
    from toplingdb_tpu.ops.columnar import ColumnarEntries
    from toplingdb_tpu.parallel.distributed_gc import run_distributed_gc

    rows_list = []
    counts = [1 << i for i in range(args.devices.bit_length())
              if (1 << i) <= args.devices]
    for r in counts:
        n = args.rows_per_device * r
        rng = np.random.default_rng(7)
        draws = rng.integers(0, n, n)
        entries = [
            (make_internal_key(b"%012d" % draws[i], i + 1, ValueType.VALUE),
             b"v")
            for i in range(n)
        ]
        col = ColumnarEntries.from_entries(entries, 12)
        padded = ck.pad_columns(col)
        job = {
            "key_words": np.asarray(padded["key_words"]),
            "key_len": np.asarray(padded["key_len"]),
            "inv_hi": np.asarray(padded["inv_hi"]),
            "inv_lo": np.asarray(padded["inv_lo"]),
            "vtype": np.asarray(padded["vtype"]),
            "w": padded["w"],
            "n": col.n,
        }
        devices = jax.devices()[:r]
        mesh = Mesh(np.array(devices).reshape(1, r), ("jobs", "range"))
        best = None
        for _ in range(args.repeats):
            t0 = time.time()
            run_distributed_gc(mesh, [job], [], True)
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        rows_list.append({"range_devices": r, "rows": n,
                          "rows_per_device": args.rows_per_device,
                          "best_s": round(best, 4),
                          "rows_per_s": round(n / best)})
    print(json.dumps({"weak_scaling": rows_list}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
