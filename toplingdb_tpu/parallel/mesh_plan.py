"""Shard→device planning shared by the mesh compaction execution mode
(ops/mesh_compaction.py) and the range weak-scaling probe
(parallel/scaling_probe.py).

One compaction job's uniform key-range shards (device_compaction's
`_prepare_uniform_shards` output) are placed round-robin over the range
axis of a (jobs=1, range=R) `jax.sharding.Mesh`; each shard's committed
uploads pin its fused merge+GC program to its chip, so the per-shard
kernels — and therefore the bytes they produce — are IDENTICAL to the
single-chip plane. Eligibility is decided here (one fallback matrix for
the execution mode, the probe, and the tests); measurement loops for the
probe/bench subprocesses live here too so the probe CLI stays thin.

Knobs: `TPULSM_MESH_DEVICES` caps how many chips a plan may use;
`TPULSM_MESH_MIN_ROWS` is the row floor below which fan-out overhead
would dominate (the enable knob `TPULSM_MESH_COMPACT` itself is read by
ops/mesh_compaction.py, keeping this module import-light).
"""

from __future__ import annotations

import os
import re
import time
from dataclasses import dataclass, field

from toplingdb_tpu.utils import errors as _errors

# Probe exit codes (bench.py keys on these): 0 = measured, EXIT_SKIP =
# environment cannot run the probe (missing backend, too few devices) —
# NOT a failure, the caller just drops the row; EXIT_FAILURE = the
# measurement itself broke.
EXIT_OK = 0
EXIT_FAILURE = 1
EXIT_SKIP = 3

# Below this many total survivor rows a mesh fan-out loses to dispatch +
# per-chip jit overhead; the job stays on one chip.
DEFAULT_MESH_MIN_ROWS = 1 << 18

# In-flight uploads per chip: 2 = classic double buffer (shard s+D's H2D
# streams while shard s computes on the same chip).
UPLOAD_DEPTH = 2


def configure_virtual_devices(n: int, platform: str = "cpu") -> None:
    """Rewrite env so the NEXT jax backend init exposes `n` virtual host
    devices. Must run before jax creates its backend — i.e. at subprocess
    entry (the probe, microbench) — because the device count is fixed at
    backend creation."""
    os.environ["JAX_PLATFORMS"] = platform
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}"
    ).strip()


def pin_cpu_backend() -> None:
    """Re-assert the CPU platform via jax.config: on axon hosts
    sitecustomize pre-imports jax and force-registers the tunnel backend
    over JAX_PLATFORMS."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception as e:
        _errors.swallow(reason="jax-platform-pin", exc=e)


def device_limit() -> int | None:
    """TPULSM_MESH_DEVICES: cap on chips a mesh plan may use (0/unset =
    every visible device)."""
    env = os.environ.get("TPULSM_MESH_DEVICES")
    if not env:
        return None
    try:
        n = int(env)
    except ValueError:
        return None
    return n if n > 0 else None


def mesh_min_rows() -> int:
    env = os.environ.get("TPULSM_MESH_MIN_ROWS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return DEFAULT_MESH_MIN_ROWS


def mesh_devices(limit: int | None = None) -> list:
    """The chips a mesh plan may schedule onto: jax.devices() of the
    default backend, capped by `limit` / TPULSM_MESH_DEVICES."""
    import jax

    devs = list(jax.devices())
    lim = limit if limit is not None else device_limit()
    if lim is not None:
        devs = devs[: max(1, lim)]
    return devs


def build_range_mesh(devices):
    """(jobs=1, range=R) Mesh over `devices` — the same topology the
    distributed-GC step and the weak-scaling probe use, so one mesh shape
    describes both the collective path and the per-chip shard path."""
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(devices).reshape(1, len(devices)),
                ("jobs", "range"))


@dataclass
class MeshPlan:
    """One job's shard→chip placement. `assignments[s]` is the index into
    `devices` whose chip runs shard s; round-robin keeps each chip's queue
    ≤ ceil(S/D) deep and makes shard s and s+D the double-buffer pair."""

    devices: list
    assignments: list[int]
    total_rows: int
    depth: int = UPLOAD_DEPTH

    @property
    def n_devices(self) -> int:
        return len(self.devices)

    @property
    def window(self) -> int:
        """How many shards may be dispatched ahead of the consumer."""
        return self.depth * len(self.devices)


def check_eligibility(shards, any_complex: bool, devices,
                      min_rows: int | None = None):
    """The fallback matrix, one place: returns (reason, total_rows) with
    reason None when a mesh plan is allowed. Reasons (ARCHITECTURE.md
    §2.2.4): no-uniform-shards, single-shard, complex-groups,
    below-row-floor, single-device."""
    if not shards:
        return "no-uniform-shards", 0
    total = sum(int(c[3]) for chunks, _ranges in shards for c in chunks)
    if len(shards) < 2:
        return "single-shard", total
    if any_complex:
        # MERGE/SINGLE_DELETION groups fold host-side in stream order;
        # fanning the shards out buys nothing until the fold is sharded.
        return "complex-groups", total
    if total < (mesh_min_rows() if min_rows is None else min_rows):
        return "below-row-floor", total
    if len(devices) < 2:
        return "single-device", total
    return None, total


def plan_shards(shards, any_complex: bool = False, devices=None,
                min_rows: int | None = None):
    """(MeshPlan, None) when the job is mesh-eligible, (None, reason)
    otherwise. `shards` is device_compaction's `_prepare_uniform_shards`
    output (list of (chunks, row_ranges), or None when ineligible there)."""
    if devices is None:
        devices = mesh_devices()
    reason, total = check_eligibility(shards, any_complex, devices,
                                      min_rows)
    if reason is not None:
        return None, reason
    assignments = [s % len(devices) for s in range(len(shards))]
    return MeshPlan(list(devices), assignments, total), None


# ---------------------------------------------------------------------------
# Probe/bench measurement loops (subprocess side; jax imported lazily so
# configure_virtual_devices can run first)
# ---------------------------------------------------------------------------


def make_weak_scaling_job(n: int, seed: int = 7) -> dict:
    """Synthetic padded GC job of n rows for the distributed-GC step."""
    import numpy as np

    from toplingdb_tpu.db.dbformat import ValueType, make_internal_key
    from toplingdb_tpu.ops import compaction_kernels as ck
    from toplingdb_tpu.ops.columnar import ColumnarEntries

    rng = np.random.default_rng(seed)
    draws = rng.integers(0, n, n)
    entries = [
        (make_internal_key(b"%012d" % draws[i], i + 1, ValueType.VALUE),
         b"v")
        for i in range(n)
    ]
    col = ColumnarEntries.from_entries(entries, 12)
    padded = ck.pad_columns(col)
    return {
        "key_words": np.asarray(padded["key_words"]),
        "key_len": np.asarray(padded["key_len"]),
        "inv_hi": np.asarray(padded["inv_hi"]),
        "inv_lo": np.asarray(padded["inv_lo"]),
        "vtype": np.asarray(padded["vtype"]),
        "w": padded["w"],
        "n": col.n,
    }


def weak_scaling_rows(rows_per_device: int, max_devices: int,
                      repeats: int = 3) -> list[dict]:
    """The probe's measurement loop: run_distributed_gc over a
    (jobs=1, range=R) mesh for R = 1,2,4..max_devices with a FIXED
    per-device row count; best-of-`repeats` wall per R."""
    import jax

    from toplingdb_tpu.parallel.distributed_gc import run_distributed_gc

    rows_list = []
    counts = [1 << i for i in range(max_devices.bit_length())
              if (1 << i) <= max_devices]
    for r in counts:
        n = rows_per_device * r
        job = make_weak_scaling_job(n)
        mesh = build_range_mesh(jax.devices()[:r])
        best = None
        for _ in range(repeats):
            t0 = time.time()
            run_distributed_gc(mesh, [job], [], True)
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        rows_list.append({"range_devices": r, "rows": n,
                          "rows_per_device": rows_per_device,
                          "best_s": round(best, 4),
                          "rows_per_s": round(n / best)})
    return rows_list


def _make_uniform_shards(n_shards: int, rows_per_shard: int,
                         key_len: int = 20, seed: int = 11):
    """Synthetic `_prepare_uniform_shards`-shaped input: n_shards range
    shards of presorted uniform internal keys (key_len includes the 8-byte
    trailer), disjoint user-key ranges so the stitched order is the
    concatenation — exactly the shard shape the mesh runner consumes."""
    import numpy as np

    from toplingdb_tpu.ops import compaction_kernels as ck

    rng = np.random.default_rng(seed)
    shards = []
    row_base = 0
    uk_len = key_len - 8
    for s in range(n_shards):
        uk = np.sort(rng.integers(0, rows_per_shard * 4, rows_per_shard))
        recs = []
        # Internal-key order: duplicate user keys need seq DESCENDING
        # within the run (the fused kernel's presorted precondition).
        j = rows_per_shard
        for k in uk:
            packed = ((row_base + j) << 8) | 1
            j -= 1
            recs.append((b"%02d" % s) + (b"%0*d" % (uk_len - 2, int(k)))
                        + packed.to_bytes(8, "little"))
        buf = np.frombuffer(b"".join(recs), np.uint8)
        chunk = ck.prepare_uniform_chunk(buf, rows_per_shard, key_len)
        shards.append(([chunk], [(row_base, row_base + rows_per_shard)]))
        row_base += rows_per_shard
    return shards


def mesh_compact_rows(rows_per_shard: int, max_devices: int,
                      repeats: int = 3, n_shards: int | None = None,
                      key_len: int = 20) -> list[dict]:
    """MEASURED mesh compaction rows (the MULTICHIP_r* dry-run promoted):
    run the SAME uniform shards through the mesh shard runner
    (ops/mesh_compaction.py) at 1 chip and at max_devices chips, wall and
    bytes/s per config. The shard set is fixed (strong scaling — one job
    fanned out), so rows_per_s ratio IS the mesh speedup."""
    import jax

    from toplingdb_tpu.ops import mesh_compaction as mc

    if n_shards is None:
        n_shards = max(2, max_devices) * UPLOAD_DEPTH
    shards = _make_uniform_shards(n_shards, rows_per_shard,
                                  key_len=key_len)
    total = n_shards * rows_per_shard
    out = []
    counts = sorted({1, min(max_devices, len(jax.devices()))})
    for r in counts:
        devices = jax.devices()[:r]
        plan, _reason = plan_shards(shards, devices=devices, min_rows=1)
        best = None
        for _ in range(repeats):
            t0 = time.time()
            if plan is None:  # r == 1: the serial single-chip twin
                run = mc.MeshShardRun(None, shards, None, [], True)
            else:
                run = mc.MeshShardRun(plan, shards, None, [], True)
            for s in range(len(shards)):
                run.finish(s)
            dt = time.time() - t0
            best = dt if best is None else min(best, dt)
        out.append({"devices": r, "rows": total, "shards": n_shards,
                    "best_s": round(best, 4),
                    "rows_per_s": round(total / best),
                    "MBps": round(total * key_len / best / 1e6, 2)})
    return out
