"""Distributed compaction data plane: sample-sort + GC over a device mesh.

One compaction's key-range sharded over the 'range' mesh axis (the
subcompaction analogue), many independent jobs over the 'jobs' axis (the
dcompact analogue). The step is a single jitted shard_map program:

  1. local multi-operand sort of each shard's slice            (VPU)
  2. regular-sample splitters, all_gather over 'range'         (ICI)
  3. bucket partition + all_to_all redistribution              (ICI)
  4. local merge sort of received buckets                      (VPU)
  5. halo exchange of boundary (key, stripe) via ppermute      (ICI)
  6. vectorized GC mask (stripes / first-in-group)             (VPU)

Entries travel as fixed-width sort columns (key words + len + inv seqno
words); values never leave the host. Bucket skew is handled with a capacity
factor; overflow is reported per shard so the host can retry single-device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from toplingdb_tpu.db.dbformat import ValueType

_SIGN = 0x80000000
INT32MAX = np.iinfo(np.int32).max


def _lex_less(a, b):
    """Lexicographic a < b over trailing column dim. a: [..., C], b: [..., C]."""
    # Walk columns from most-significant; strict-less decided at first diff.
    c = a.shape[-1]
    lt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(c):
        ai = a[..., i]
        bi = b[..., i]
        lt = lt | (eq & (ai < bi))
        eq = eq & (ai == bi)
    return lt


def _local_sort(cols, payload):
    """cols: [P, C] sort columns; payload: [P, K] carried along."""
    c = cols.shape[-1]
    k = payload.shape[-1]
    operands = tuple(cols[:, i] for i in range(c)) + tuple(
        payload[:, i] for i in range(k)
    )
    out = jax.lax.sort(operands, num_keys=c)
    return (
        jnp.stack(out[:c], axis=1),
        jnp.stack(out[c:], axis=1),
    )


def _gc_mask_local(cols, vtype, tomb_hi_i32, tomb_lo_i32, prev_last_cols,
                   prev_last_stripe, prev_valid, snap_hi, snap_lo,
                   bottommost):
    """Mask survivors within one locally-sorted shard; the halo (previous
    shard's last key/stripe) stitches group/stripe continuity. tomb_*:
    per-row max covering range-tombstone seqno words (rode the sort as
    payload; zero = uncovered)."""
    n = cols.shape[0]
    w = cols.shape[1] - 3  # key words + len + inv_hi + inv_lo
    key_cols = cols[:, : w + 1]  # words + len identify the user key
    prev_rows = jnp.roll(key_cols, 1, axis=0)
    prev_rows = prev_rows.at[0].set(prev_last_cols[: w + 1])
    same_key = jnp.all(key_cols == prev_rows, axis=1)
    same_key = jnp.where(
        jnp.arange(n) == 0, same_key & prev_valid, same_key
    )
    new_key = ~same_key

    u = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
    inv_hi = u(cols[:, w + 1]) ^ jnp.uint32(_SIGN)
    inv_lo = u(cols[:, w + 2]) ^ jnp.uint32(_SIGN)
    packed_hi = ~inv_hi
    packed_lo = ~inv_lo
    seq_hi = packed_hi >> 8
    seq_lo = (packed_hi << 24) | (packed_lo >> 8)
    snap_lt = (snap_hi[None, :] < seq_hi[:, None]) | (
        (snap_hi[None, :] == seq_hi[:, None]) & (snap_lo[None, :] < seq_lo[:, None])
    )
    stripe = jnp.sum(snap_lt, axis=1).astype(jnp.int32)
    prev_stripe = jnp.roll(stripe, 1)
    prev_stripe = prev_stripe.at[0].set(prev_last_stripe)
    first_in_stripe = new_key | (stripe != prev_stripe)

    # Range-tombstone shadowing: the SAME traced rule as the single-chip
    # GC mask (shared helper, so the two cannot diverge).
    from toplingdb_tpu.ops.compaction_kernels import _tomb_covered

    covered = _tomb_covered(seq_hi, seq_lo, u(tomb_hi_i32), u(tomb_lo_i32),
                            snap_hi, snap_lo, stripe)

    is_pad = vtype < 0
    keep = first_in_stripe & ~covered & ~is_pad
    drop_bottom_del = bottommost & (stripe == 0) & (vtype == int(ValueType.DELETION))
    keep = keep & ~drop_bottom_del
    zero_seq = keep & bottommost & (stripe == 0) & (vtype == int(ValueType.VALUE))
    # Complex rows (MERGE / SINGLE_DELETE) flag per row; the group-level
    # broadcast happens on the host, which sees the global sorted order
    # (groups may span shard boundaries).
    is_complex = ((vtype == int(ValueType.MERGE))
                  | (vtype == int(ValueType.SINGLE_DELETION))) & ~is_pad
    return keep, zero_seq, stripe, is_complex


def make_distributed_gc_step(mesh: Mesh, num_key_words: int,
                             bottommost: bool, capacity_factor: float = 2.0):
    """Builds the jitted multi-chip compaction step over `mesh` with axes
    ('jobs', 'range').

    Input (per job, stacked on the leading jobs axis):
      cols   [J, P, C] int32 — C = num_key_words + 3 sort columns
      vtype  [J, P]    int32 — value types (-1 = padding)
      idx    [J, P]    int32 — original entry indices (host value lookup)
      snap_hi/lo [S]   uint32 — padded snapshot words (replicated)
    Output:
      keep, zero_seq [J, P] bool; sorted idx [J, P]; overflow [J, R] int32
    """
    r = mesh.shape["range"]
    c = num_key_words + 3

    def step(cols, vtype, idx, tomb_hi, tomb_lo, snap_hi, snap_lo):
        j, p_local = vtype.shape  # inside shard_map: local job count, local rows

        def one_job(cols1, vtype1, idx1, th1, tl1):
            cap = int(capacity_factor * p_local / r) if r > 1 else p_local
            cap = max(cap, 1)
            payload = jnp.concatenate(
                [vtype1[:, None], idx1[:, None],
                 th1[:, None], tl1[:, None]], axis=1
            )
            cols_s, pay_s = _local_sort(cols1, payload)

            if r > 1:
                # --- splitters: sample r-1 local, all_gather, take global ---
                stride = max(p_local // r, 1)
                samples = cols_s[::stride][: r]  # [<=r, C]
                samples = jnp.pad(
                    samples, ((0, r - samples.shape[0]), (0, 0)),
                    constant_values=INT32MAX,
                )
                all_samples = jax.lax.all_gather(
                    samples, "range", tiled=True
                )  # [r*r, C]
                srt, _ = _local_sort(all_samples, jnp.zeros((r * r, 1), jnp.int32))
                splitters = srt[r:: r][: r - 1]  # [r-1, C] global splitters

                # --- bucket id per row: count of splitters <= row ---
                ge = ~_lex_less(
                    cols_s[:, None, :], splitters[None, :, :]
                )  # row >= splitter
                bucket = jnp.sum(ge, axis=1).astype(jnp.int32)  # [p_local]

                # --- scatter into [r, cap(+1 spill slot), C+K] ---
                # Pad rows (vtype -1 payload) don't consume capacity: they go
                # straight to the spill slot and are reconstructed as padding
                # on the receive side. Only real rows count toward overflow.
                is_pad_row = pay_s[:, 0] < 0
                onehot = jax.nn.one_hot(bucket, r, dtype=jnp.int32) * (
                    ~is_pad_row[:, None]
                )  # [p, r]
                pos = jnp.cumsum(onehot, axis=0) - onehot  # pos within bucket
                slot = jnp.sum(pos * onehot, axis=1)
                overflow = jnp.sum(
                    ((slot >= cap) & ~is_pad_row).astype(jnp.int32)
                )
                slot = jnp.where(is_pad_row, cap, jnp.minimum(slot, cap))
                send_cols = jnp.full((r, cap + 1, c), INT32MAX, dtype=jnp.int32)
                send_pay = jnp.full((r, cap + 1, 4), -1, dtype=jnp.int32)
                # Pad-slot cover words must be ZERO (not -1): an all-ones
                # word would read as a huge covering tombstone.
                send_pay = send_pay.at[:, :, 2:].set(0)
                send_cols = send_cols.at[bucket, slot].set(cols_s)
                send_pay = send_pay.at[bucket, slot].set(pay_s)
                send_cols = send_cols[:, :cap]
                send_pay = send_pay[:, :cap]

                # --- all_to_all over 'range' ---
                recv_cols = jax.lax.all_to_all(
                    send_cols, "range", split_axis=0, concat_axis=0, tiled=True
                ).reshape(r * cap, c)
                recv_pay = jax.lax.all_to_all(
                    send_pay, "range", split_axis=0, concat_axis=0, tiled=True
                ).reshape(r * cap, 4)
                cols_s, pay_s = _local_sort(recv_cols, recv_pay)
            else:
                overflow = jnp.zeros((), dtype=jnp.int32)

            return cols_s, pay_s, overflow

        cols_s, pay_s, overflow = jax.vmap(one_job)(cols, vtype, idx,
                                                    tomb_hi, tomb_lo)

        # --- halo: previous shard's last row (key cols + stripe) ---
        # Recompute stripe needs snapshots; do mask per job via vmap with halo.
        perm = [(i, (i + 1) % r) for i in range(r)]

        def job_mask(cols1, pay1):
            # Halo values: the last REAL (non-pad) row of this shard → next
            # shard. Pad rows sort to the shard's tail, so index by count.
            valid = pay1[:, 0] >= 0
            n_real = jnp.sum(valid.astype(jnp.int32))
            last_idx = jnp.maximum(n_real - 1, 0)
            last_cols = jnp.where(n_real > 0, cols1[last_idx],
                                  jnp.full((c,), INT32MAX, dtype=jnp.int32))
            u = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
            w = c - 3
            inv_hi = u(last_cols[w + 1]) ^ jnp.uint32(_SIGN)
            packed_hi = ~inv_hi
            inv_lo = u(last_cols[w + 2]) ^ jnp.uint32(_SIGN)
            packed_lo = ~inv_lo
            seq_hi = packed_hi >> 8
            seq_lo = (packed_hi << 24) | (packed_lo >> 8)
            lt = (snap_hi < seq_hi) | ((snap_hi == seq_hi) & (snap_lo < seq_lo))
            last_stripe = jnp.sum(lt).astype(jnp.int32)
            return last_cols, last_stripe

        last_cols, last_stripe = jax.vmap(job_mask)(cols_s, pay_s)
        if r > 1:
            prev_cols = jax.lax.ppermute(last_cols, "range", perm)
            prev_stripe = jax.lax.ppermute(last_stripe, "range", perm)
            shard_idx = jax.lax.axis_index("range")
            prev_valid = shard_idx > 0
        else:
            prev_cols = jnp.full_like(last_cols, INT32MAX)
            prev_stripe = jnp.zeros_like(last_stripe)
            prev_valid = jnp.array(False)

        def job_final(cols1, pay1, pcols, pstripe):
            keep, zero_seq, stripe, is_cx = _gc_mask_local(
                cols1, pay1[:, 0], pay1[:, 2], pay1[:, 3], pcols, pstripe,
                prev_valid, snap_hi, snap_lo, bottommost,
            )
            return keep, zero_seq, pay1[:, 1], is_cx

        keep, zero_seq, sidx, is_cx = jax.vmap(job_final)(
            cols_s, pay_s, prev_cols, prev_stripe
        )
        # Total overflow per job across all source shards (psum over ICI).
        total_overflow = jax.lax.psum(overflow, "range")
        return keep, zero_seq, sidx, is_cx, total_overflow

    sharded = shard_map(
        step, mesh=mesh,
        in_specs=(
            P("jobs", "range", None), P("jobs", "range"), P("jobs", "range"),
            P("jobs", "range"), P("jobs", "range"),
            P(), P(),
        ),
        out_specs=(
            P("jobs", "range"), P("jobs", "range"), P("jobs", "range"),
            P("jobs", "range"), P("jobs"),
        ),
        check_rep=False,
    )
    return jax.jit(sharded)


def run_distributed_gc(mesh: Mesh, jobs: list, snapshots: list[int],
                       bottommost: bool):
    """Host driver: jobs = list of padded column dicts (ck.pad_columns).
    All jobs must share the padded length and word count; the jobs list is
    padded to the 'jobs' mesh dim. Jobs may carry a "tomb_cover" uint64
    array (per-row max covering tombstone seqno). Returns per-job
    (keep, zero_seq, sorted_idx, is_complex) numpy arrays in global
    sorted order; complex rows (MERGE/SINGLE_DELETE) are flagged per row —
    group-level resolution is the host's job (groups can span shards)."""
    from toplingdb_tpu.ops.compaction_kernels import _split_snapshots

    jdim = mesh.shape["jobs"]
    rdim = mesh.shape["range"]
    w = jobs[0]["w"]
    p = jobs[0]["key_words"].shape[0]
    p = max(p, rdim)  # at least one row per shard
    nj = len(jobs)
    jpad = -(-nj // jdim) * jdim
    cols = np.full((jpad, p, w + 3), INT32MAX, dtype=np.int32)
    vtype = np.full((jpad, p), -1, dtype=np.int32)
    # -1 marks pad rows even on range=1 meshes (no all_to_all refill).
    idx = np.full((jpad, p), -1, dtype=np.int32)
    tomb_hi = np.zeros((jpad, p), dtype=np.int32)
    tomb_lo = np.zeros((jpad, p), dtype=np.int32)
    for i, job in enumerate(jobs):
        n = job["key_words"].shape[0]
        cols[i, :n, :w] = job["key_words"]
        cols[i, :n, w] = job["key_len"]
        cols[i, :n, w + 1] = job["inv_hi"]
        cols[i, :n, w + 2] = job["inv_lo"]
        vtype[i, :n] = job["vtype"]
        n_real = job["n"]
        idx[i, :n_real] = np.arange(n_real, dtype=np.int32)
        cv = job.get("tomb_cover")
        if cv is not None and len(cv):
            from toplingdb_tpu.ops.compaction_kernels import _split_cover

            # Per ORIGINAL row (uint64): rides the sort as payload words.
            hi_w, lo_w = _split_cover(np.asarray(cv, dtype=np.uint64), p)
            tomb_hi[i] = hi_w.view(np.int32)
            tomb_lo[i] = lo_w.view(np.int32)
    snap_hi, snap_lo = _split_snapshots(snapshots)  # pow2 bucket pad >= 64

    step = make_distributed_gc_step(mesh, w, bottommost)
    keep, zero_seq, sidx, is_cx, overflow = step(
        cols, vtype, idx, tomb_hi, tomb_lo, snap_hi, snap_lo)
    if int(np.max(np.asarray(overflow))) > 0:
        from toplingdb_tpu.utils.status import TryAgain

        raise TryAgain("bucket overflow in distributed sort; retry 1-chip")
    return (
        np.asarray(keep)[:nj], np.asarray(zero_seq)[:nj],
        np.asarray(sidx)[:nj], np.asarray(is_cx)[:nj],
    )
