"""MergingIterator: the k-way merge over child iterators.

Same role as the reference's MergingIterator (table/merging_iterator.cc:476-1019
in /root/reference): children expose the standard iterator protocol
(valid/key/value/seek/seek_to_first/seek_to_last/next/prev); the merger
presents their union in internal-key order. The CPU implementation keeps a
binary heap of valid children; the TPU compaction path replaces this whole
structure with a device sort-merge (toplingdb_tpu/ops), so this class is the
correctness reference for that kernel.
"""

from __future__ import annotations

import heapq


class _HeapItem:
    __slots__ = ("key", "idx", "cmp", "reverse")

    def __init__(self, key, idx, cmp, reverse):
        self.key = key
        self.idx = idx
        self.cmp = cmp
        self.reverse = reverse

    def __lt__(self, other):
        r = self.cmp(self.key, other.key)
        if r == 0:
            # Stable tie-break: earlier child = newer source wins first.
            r = self.idx - other.idx
        return r > 0 if self.reverse else r < 0


class MergingIterator:
    def __init__(self, cmp, children: list):
        self._cmp = cmp
        self._children = children
        self._heap: list[_HeapItem] = []
        self._direction_forward = True
        self._current = None  # child index

    # ------------------------------------------------------------------

    def _rebuild_heap(self, forward: bool) -> None:
        self._direction_forward = forward
        self._heap = [
            _HeapItem(c.key(), i, self._cmp, not forward)
            for i, c in enumerate(self._children)
            if c.valid()
        ]
        heapq.heapify(self._heap)
        self._current = self._heap[0].idx if self._heap else None

    def valid(self) -> bool:
        return self._current is not None

    def key(self):
        return self._children[self._current].key()

    def value(self):
        return self._children[self._current].value()

    def current_child(self) -> int:
        """Index of the child supplying the current entry (the 'source rank':
        lower = newer source, used for MVCC tie-breaks)."""
        return self._current

    def prefetch_counts(self) -> tuple[int, int]:
        """Summed FilePrefetchBuffer (hits, misses) of every child that
        has one — DBIter banks the deltas into the PREFETCH_* tickers."""
        h = m = 0
        for c in self._children:
            pc = getattr(c, "prefetch_counts", None)
            if pc is not None:
                ch, cm = pc()
                h += ch
                m += cm
        return h, m

    def seek_to_first(self) -> None:
        for c in self._children:
            c.seek_to_first()
        self._rebuild_heap(forward=True)

    def seek_to_last(self) -> None:
        for c in self._children:
            c.seek_to_last()
        self._rebuild_heap(forward=False)

    def seek(self, target) -> None:
        for c in self._children:
            c.seek(target)
        self._rebuild_heap(forward=True)

    def seek_for_prev(self, target) -> None:
        for c in self._children:
            c.seek_for_prev(target)
        self._rebuild_heap(forward=False)

    def next(self) -> None:
        assert self.valid()
        if not self._direction_forward:
            # Direction switch: re-seek all other children after current key.
            key = self.key()
            for i, c in enumerate(self._children):
                if i != self._current:
                    c.seek(key)
                    if c.valid() and self._cmp(c.key(), key) == 0:
                        c.next()
            self._direction_forward = True
            child = self._children[self._current]
            child.next()
            self._rebuild_heap(forward=True)
            return
        item = heapq.heappop(self._heap)
        child = self._children[item.idx]
        child.next()
        if child.valid():
            heapq.heappush(self._heap, _HeapItem(child.key(), item.idx, self._cmp, False))
        self._current = self._heap[0].idx if self._heap else None

    def prev(self) -> None:
        assert self.valid()
        if self._direction_forward:
            key = self.key()
            for i, c in enumerate(self._children):
                if i != self._current:
                    c.seek_for_prev(key)
                    if c.valid() and self._cmp(c.key(), key) == 0:
                        c.prev()
            self._direction_forward = False
            child = self._children[self._current]
            child.prev()
            self._rebuild_heap(forward=False)
            return
        item = heapq.heappop(self._heap)
        child = self._children[item.idx]
        child.prev()
        if child.valid():
            heapq.heappush(self._heap, _HeapItem(child.key(), item.idx, self._cmp, True))
        self._current = self._heap[0].idx if self._heap else None

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()
