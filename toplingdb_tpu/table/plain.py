"""PlainTable: flat all-in-RAM format with a PREFIX hash index.

The analogue of the reference's PlainTable (table/plain/ in
/root/reference: plain_table_factory.h, plain_table_index.h): an mmap'd
no-block format where point lookups hash the key's PREFIX
(Options.prefix_extractor) to a bucket holding the start of that prefix's
entry group, then binary-search inside the group. Reuses the single_fast
flat region/offset-array machinery (table/single_fast.py) — the difference
is purely the index discipline:

- single_fast: optional whole-key open-addressed index, one slot per user key;
- plain: prefix-bucket index, one slot per DISTINCT PREFIX (smaller index,
  natural fit for prefix-scan workloads), out-of-domain keys fall back to
  total-order binary search.

Reference restrictions kept: bytewise comparator + a prefix extractor are
required (plain_table_factory.h notes the format is hash-based).
"""

from __future__ import annotations

import numpy as np

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.single_fast import (
    SingleFastTableBuilder,
    SingleFastTableReader,
)
from toplingdb_tpu.utils import crc32c
from toplingdb_tpu.utils.status import Corruption, InvalidArgument

METAINDEX_PREFIX_INDEX = b"tpulsm.pt.prefix_index"


class PlainTableBuilder(SingleFastTableBuilder):
    """Flat region + prefix-bucket hash index."""

    FOOTER_MAGIC = fmt.PLAIN_MAGIC

    def __init__(self, wfile, icmp: InternalKeyComparator, options=None,
                 **kw):
        super().__init__(wfile, icmp, options, **kw)
        if getattr(self.opts, "prefix_extractor", None) is None:
            raise InvalidArgument(
                "plain table format requires TableOptions.prefix_extractor"
            )
        if icmp.user_comparator.name() != dbformat.BYTEWISE.name():
            raise InvalidArgument(
                "plain table format requires the bytewise comparator "
                "(prefix groups must be byte-contiguous)"
            )

    def _hash_index_block(self) -> tuple[bytes, bytes] | None:
        # One bucket per distinct prefix: 1 + ordinal of the FIRST entry of
        # the prefix group (the newest version of the group's smallest key).
        # Out-of-domain keys are indexed nowhere; lookups for them fall back
        # to binary search.
        n = len(self._offsets)
        if n == 0:
            return None
        pe = self.opts.prefix_extractor
        firsts: list[tuple[bytes, int]] = []  # (prefix, first ordinal)
        prev = None
        for i in range(n):
            uk = self._entry_user_key(i)
            if not pe.in_domain(uk):
                continue
            p = pe.transform(uk)
            if p != prev:
                firsts.append((p, i))
                prev = p
        if not firsts:
            return None
        nb = 1
        while nb < (len(firsts) * 10) // 7 + 1:
            nb <<= 1
        buckets = np.zeros(nb, dtype="<u4")
        mask = nb - 1
        for p, i in firsts:
            h = crc32c.xxh64(p) & mask
            while buckets[h]:
                h = (h + 1) & mask
            buckets[h] = i + 1
        return METAINDEX_PREFIX_INDEX, buckets.tobytes()


class PlainTableReader(SingleFastTableReader):
    FOOTER_MAGIC = fmt.PLAIN_MAGIC

    def _load_hash_index(self) -> None:
        self._hash_buckets = None
        hh = self._meta_handles.get(METAINDEX_PREFIX_INDEX)
        if hh is not None:
            self._hash_buckets = np.frombuffer(
                fmt.read_block(_mem(self._data), hh,
                               self.opts.verify_checksums),
                dtype="<u4",
            )
        self._pe = self._resolved_pe  # resolved by SingleFastTableReader
        # has_hash_index drives the DB Get fast path; the fallback inside
        # hash_probe keeps the contract for out-of-domain keys.
        self.has_hash_index = True

    def _newest_ordinal(self, user_key: bytes, lo: int = 0) -> int | None:
        """Ordinal of the newest version of user_key at or after `lo`, or
        None when absent."""
        i = self._lower_bound_from(
            dbformat.make_internal_key(
                user_key, dbformat.MAX_SEQUENCE_NUMBER,
                dbformat.VALUE_TYPE_FOR_SEEK,
            ),
            lo,
        )
        if i < self.n and self._entry(i)[0][:-8] == user_key:
            return i
        return None

    def _lower_bound_from(self, target: bytes, lo: int) -> int:
        hi = self.n
        cmp = self._icmp.compare
        while lo < hi:
            mid = (lo + hi) // 2
            if cmp(self._entry(mid)[0], target) < 0:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def hash_probe(self, user_key: bytes) -> int | None:
        if self._pe is None or not self._pe.in_domain(user_key):
            return self._newest_ordinal(user_key)
        if self._hash_buckets is None:
            return self._newest_ordinal(user_key)
        prefix = self._pe.transform(user_key)
        buckets = self._hash_buckets
        mask = len(buckets) - 1
        h = crc32c.xxh64(prefix) & mask
        for _ in range(len(buckets)):  # bounded: corrupt blocks can't hang
            v = int(buckets[h])
            if v == 0:
                return None  # no such prefix group → key absent
            start = v - 1
            if start >= self.n:
                raise Corruption("plain table prefix bucket out of range")
            uk = self._entry(start)[0][:-8]
            if self._pe.in_domain(uk) and self._pe.transform(uk) == prefix:
                return self._newest_ordinal(user_key, start)
            h = (h + 1) & mask
        raise Corruption("plain table prefix index has no empty buckets")

    def prefix_seek_start(self, prefix: bytes) -> int | None:
        """Ordinal of the first entry whose key has `prefix`, or None when
        no such group exists (prefix-scan entry point)."""
        if self._hash_buckets is None:
            return None
        buckets = self._hash_buckets
        mask = len(buckets) - 1
        h = crc32c.xxh64(prefix) & mask
        for _ in range(len(buckets)):
            v = int(buckets[h])
            if v == 0:
                return None
            start = v - 1
            if start >= self.n:
                raise Corruption("plain table prefix bucket out of range")
            uk = self._entry(start)[0][:-8]
            if (self._pe is not None and self._pe.in_domain(uk)
                    and self._pe.transform(uk) == prefix):
                return start
            h = (h + 1) & mask
        raise Corruption("plain table prefix index has no empty buckets")


def _mem(data: bytes):
    from toplingdb_tpu.table.single_fast import _Mem

    return _Mem(data)
