"""FilePrefetchBuffer: sequential readahead for table iteration.

The reference's file/file_prefetch_buffer.h:63 (in /root/reference) role:
block-at-a-time iteration over a cold file otherwise pays one pread per
~4KB block. This buffer detects a sequential access pattern and reads
ahead with a doubling window (8KB → 256KB), so a long scan does one
pread per window instead of per block. Random access passes straight
through (no cost, no pollution). One instance per iterator — readahead
state is a property of the scan, not the file.
"""

from __future__ import annotations
from toplingdb_tpu.utils import errors as _errors


class FilePrefetchBuffer:
    """Wraps a RandomAccessFile with auto-readahead. Presents the same
    read(offset, n) surface, so fmt.read_block can consume it directly.

    `initial_readahead` + `arm_immediately` configure the KNOWN-sequential
    mode used by the compaction input scan (the reference's fixed
    compaction readahead, CompactionOptions::compaction_readahead_size
    role): the very first read already fetches a full window instead of
    waiting for the doubling ramp."""

    __slots__ = ("_f", "_buf", "_buf_off", "_readahead", "_init_ra", "_max",
                 "_next_expected", "_seq_reads", "_arm0", "hits", "misses",
                 "_ring", "_pending")

    MIN_READAHEAD = 8 * 1024
    MAX_READAHEAD = 256 * 1024
    # Sequential reads before readahead arms (reference
    # BlockBasedTable::kMinNumFileReadsToStartAutoReadahead).
    ARM_AFTER = 2

    def __init__(self, rfile, max_readahead: int = MAX_READAHEAD,
                 initial_readahead: int | None = None,
                 arm_immediately: bool = False, aio_ring=None):
        self._f = rfile
        self._buf = b""
        self._buf_off = 0
        self._init_ra = min(initial_readahead or self.MIN_READAHEAD,
                            max_readahead)
        self._readahead = self._init_ra
        self._max = max_readahead
        self._next_expected = -1
        self._arm0 = arm_immediately
        self._seq_reads = self.ARM_AFTER if arm_immediately else 0
        self.hits = 0      # reads served from the buffer
        self.misses = 0    # reads that went to the file
        # Async readahead (env/env.py AsyncIORing — the write plane's
        # submit ring doubles as a prefetch I/O lane): when armed, the
        # NEXT window's pread is submitted to the ring as the current one
        # is returned, so the scan's compute overlaps its I/O.
        self._ring = aio_ring
        self._pending = None  # (offset, AioToken) of the in-flight window

    def reset(self) -> None:
        """Back to the initial state (a seek): drop the window and the
        readahead ramp so the next sequential run re-arms from
        `initial_readahead` — the auto-scaling window doubles on
        sequential refills and resets here. hit/miss counters survive
        (they are cumulative scan accounting)."""
        self._buf = b""
        self._buf_off = 0
        self._readahead = self._init_ra
        self._next_expected = -1
        self._seq_reads = self.ARM_AFTER if self._arm0 else 0
        self._pending = None

    def _schedule_next(self) -> None:
        """Submit the window after the current one through the ring."""
        nxt = self._buf_off + len(self._buf)
        want = self._readahead
        f = self._f
        self._pending = (nxt, self._ring.submit_task(
            lambda: f.read(nxt, want)))

    def read(self, offset: int, n: int) -> bytes:
        end = offset + n
        if self._buf and offset >= self._buf_off \
                and end <= self._buf_off + len(self._buf):
            self.hits += 1
            o = offset - self._buf_off
            self._track(end)
            return self._buf[o: o + n]
        if self._pending is not None:
            # Adopt the async window if the read landed in/at it.
            p_off, tok = self._pending
            if offset >= p_off and self._seq_reads >= self.ARM_AFTER:
                self._pending = None
                try:
                    data = tok.wait()
                except Exception as e:
                    _errors.swallow(reason="prefetch-wait-failed", exc=e)
                    data = b""
                if data and end <= p_off + len(data):
                    self.hits += 1
                    self._buf = data
                    self._buf_off = p_off
                    self._readahead = min(self._readahead * 2, self._max)
                    if self._ring is not None:
                        self._schedule_next()
                    self._track(end)
                    o = offset - p_off
                    return self._buf[o: o + n]
            elif offset < p_off:
                self._pending = None  # seek backwards: drop the window
        self.misses += 1
        if offset == self._next_expected:
            self._seq_reads += 1
        elif self._next_expected >= 0:
            # Random access mid-stream: back to the cold state. (A first
            # read keeps any pre-armed window instead of resetting it.)
            self._seq_reads = 0
            self._readahead = self._init_ra
        if self._seq_reads >= self.ARM_AFTER:
            want = max(n, self._readahead)
            self._buf = self._f.read(offset, want)
            self._buf_off = offset
            self._readahead = min(self._readahead * 2, self._max)
            if self._ring is not None:
                self._schedule_next()
            self._track(end)
            return self._buf[:n]
        self._track(end)
        return self._f.read(offset, n)

    def _track(self, end: int) -> None:
        self._next_expected = end

    def size(self) -> int:
        return self._f.size()
