"""On-disk SST framing: block handles, trailers, footer, compression.

Structure follows the reference's BlockBasedTable framing (table/format.h:39-133
in /root/reference): every block is written as
    payload' | compression_type(1B) | masked_crc32c(4B over payload'+type)
and the file ends with a fixed-size footer
    checksum_type(1B) | metaindex_handle | index_handle | padding | version(4B) | magic(8B)
Handles are (offset, size) varint64 pairs. The magic number is our own — this
is a new format ("tpulsm SST v1"), structured like BlockBasedTable but not
byte-identical to it.
"""

from __future__ import annotations

import bz2
import lzma
import zlib
from dataclasses import dataclass

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption, NotSupported

MAGIC = 0x7470756C736D5354  # "tpulsmST" big-endian spelling, stored fixed64 LE
SINGLE_FAST_MAGIC = 0x7470756C736D4654  # "tpulsmFT": the flat L0/L1 format
CUCKOO_MAGIC = 0x7470756C736D4354  # "tpulsmCT": cuckoo-hash point-lookup format
PLAIN_MAGIC = 0x7470756C736D5054  # "tpulsmPT": plain table w/ prefix hash index
ZIP_MAGIC = 0x7470756C736D5A54  # "tpulsmZT": searchable-compression L2+ format
FOOTER_VERSION = 1
BLOCK_TRAILER_SIZE = 5  # type byte + crc32
MAX_HANDLE_LEN = 20     # two varint64s
FOOTER_LEN = 1 + 2 * MAX_HANDLE_LEN + 4 + 8

# Compression type byte (values chosen to match the reference's enum where the
# codec exists in both — include/rocksdb/compression_type.h:22-28:
# kNoCompression=0, kSnappyCompression=1, kZlibCompression=2, kBZip2=3,
# kLZ4=4, kLZ4HC=5, kZSTD=7; kLZMA has no reference equivalent and takes a
# private value).
NO_COMPRESSION = 0
SNAPPY_COMPRESSION = 1
ZLIB_COMPRESSION = 2
BZIP2_COMPRESSION = 3
LZ4_COMPRESSION = 4
LZ4HC_COMPRESSION = 5
ZSTD_COMPRESSION = 7
LZMA_COMPRESSION = 0x21

CHECKSUM_CRC32C = 1


@dataclass(frozen=True)
class BlockHandle:
    offset: int
    size: int  # payload size, excluding the 5-byte trailer

    def encode(self) -> bytes:
        return coding.encode_varint64(self.offset) + coding.encode_varint64(self.size)

    @staticmethod
    def decode(buf: bytes, off: int = 0) -> tuple["BlockHandle", int]:
        o, off = coding.decode_varint64(buf, off)
        s, off = coding.decode_varint64(buf, off)
        return BlockHandle(o, s), off

    @staticmethod
    def decode_exact(buf: bytes) -> "BlockHandle":
        h, _ = BlockHandle.decode(buf, 0)
        return h


@dataclass(frozen=True)
class Footer:
    metaindex_handle: BlockHandle
    index_handle: BlockHandle
    checksum_type: int = CHECKSUM_CRC32C
    version: int = FOOTER_VERSION
    magic: int = MAGIC

    def encode(self) -> bytes:
        out = bytearray()
        out.append(self.checksum_type)
        out += self.metaindex_handle.encode()
        out += self.index_handle.encode()
        out += b"\x00" * (1 + 2 * MAX_HANDLE_LEN - len(out))
        out += coding.encode_fixed32(self.version)
        out += coding.encode_fixed64(self.magic)
        assert len(out) == FOOTER_LEN
        return bytes(out)

    @staticmethod
    def read_magic(buf: bytes) -> int:
        """Format dispatch (the reference's adaptive table, table/adaptive/)."""
        if len(buf) < FOOTER_LEN:
            raise Corruption("footer too short")
        return coding.decode_fixed64(buf, len(buf) - 8)

    @staticmethod
    def decode(buf: bytes, expected_magic: int = MAGIC) -> "Footer":
        if len(buf) < FOOTER_LEN:
            raise Corruption("footer too short")
        tail = buf[-FOOTER_LEN:]
        magic = coding.decode_fixed64(tail, FOOTER_LEN - 8)
        if magic != expected_magic:
            raise Corruption(f"bad SST magic: {magic:#x}")
        version = coding.decode_fixed32(tail, FOOTER_LEN - 12)
        checksum_type = tail[0]
        mih, off = BlockHandle.decode(tail, 1)
        ih, _ = BlockHandle.decode(tail, off)
        return Footer(mih, ih, checksum_type, version, magic)


def compress(data: bytes, ctype: int, level: int | None = None,
             dict_: bytes = b"") -> bytes:
    if ctype == NO_COMPRESSION:
        return data
    if ctype == SNAPPY_COMPRESSION:
        from toplingdb_tpu.utils import codecs

        return codecs.snappy_compress(data)
    if ctype == ZLIB_COMPRESSION:
        return zlib.compress(data, 6 if level is None else level)
    if ctype == BZIP2_COMPRESSION:
        return bz2.compress(data)
    if ctype == LZ4_COMPRESSION:
        from toplingdb_tpu.utils import codecs

        return codecs.lz4_compress(data)
    if ctype == LZ4HC_COMPRESSION:
        from toplingdb_tpu.utils import codecs

        return codecs.lz4_compress(data, hc=True, level=level or 9)
    if ctype == ZSTD_COMPRESSION:
        from toplingdb_tpu.utils import codecs

        return codecs.zstd_compress(data, 3 if level is None else level, dict_)
    if ctype == LZMA_COMPRESSION:
        return lzma.compress(data)
    raise NotSupported(f"compression type {ctype}")


def decompress(data: bytes, ctype: int, dict_: bytes = b"") -> bytes:
    if ctype == NO_COMPRESSION:
        return data
    if ctype == SNAPPY_COMPRESSION:
        from toplingdb_tpu.utils import codecs

        return codecs.snappy_decompress(data)
    if ctype == ZLIB_COMPRESSION:
        return zlib.decompress(data)
    if ctype == BZIP2_COMPRESSION:
        return bz2.decompress(data)
    if ctype in (LZ4_COMPRESSION, LZ4HC_COMPRESSION):
        from toplingdb_tpu.utils import codecs

        return codecs.lz4_decompress(data)
    if ctype == ZSTD_COMPRESSION:
        from toplingdb_tpu.utils import codecs

        return codecs.zstd_decompress(data, dict_)
    if ctype == LZMA_COMPRESSION:
        return lzma.decompress(data)
    raise Corruption(f"unknown compression type {ctype}")


def compress_for_block(raw: bytes, ctype: int, level: int | None = None,
                       dict_: bytes = b"") -> tuple[bytes, int]:
    """The CPU half of write_block: (payload, effective_type) with the
    <12.5%-gain fallback to uncompressed — safe to run on worker threads
    (all codecs release the GIL under ctypes/stdlib)."""
    if ctype != NO_COMPRESSION:
        c = compress(raw, ctype, level, dict_)
        if len(c) < len(raw) - len(raw) // 8:
            return c, ctype
    return raw, NO_COMPRESSION


def write_compressed_block(wfile, payload: bytes, out_type: int) -> BlockHandle:
    """The IO half of write_block: frame with trailer, append, handle."""
    offset = wfile.file_size()
    crc = crc32c.value(payload + bytes([out_type]))
    wfile.append(payload)
    wfile.append(bytes([out_type]))
    wfile.append(coding.encode_fixed32(crc32c.mask(crc)))
    return BlockHandle(offset, len(payload))


def write_block(wfile, raw: bytes, ctype: int, level: int | None = None,
                dict_: bytes = b"") -> BlockHandle:
    """Compress (if profitable), frame with trailer, append. Returns handle.

    Mirrors BlockBasedTableBuilder::WriteBlock (reference
    table/block_based/block_based_table_builder.cc:1092-1150): fall back to
    uncompressed when compression gains <12.5%.
    """
    payload, out_type = compress_for_block(raw, ctype, level, dict_)
    return write_compressed_block(wfile, payload, out_type)


def read_block(rfile, handle: BlockHandle, verify_checksums: bool = True,
               dict_: bytes = b"") -> bytes:
    """Read, verify trailer CRC, decompress."""
    buf = rfile.read(handle.offset, handle.size + BLOCK_TRAILER_SIZE)
    if len(buf) != handle.size + BLOCK_TRAILER_SIZE:
        raise Corruption(
            f"truncated block read at {handle.offset}: "
            f"got {len(buf)}, want {handle.size + BLOCK_TRAILER_SIZE}"
        )
    payload = buf[: handle.size]
    ctype = buf[handle.size]
    if verify_checksums:
        stored = crc32c.unmask(coding.decode_fixed32(buf, handle.size + 1))
        actual = crc32c.value(payload + bytes([ctype]))
        if stored != actual:
            raise Corruption(
                f"block checksum mismatch at {handle.offset}: "
                f"stored {stored:#x} != computed {actual:#x}"
            )
    return decompress(payload, ctype, dict_)
