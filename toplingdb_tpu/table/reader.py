"""SST reader: open, point lookup support, two-level iteration.

Counterpart of the reference's BlockBasedTable reader
(table/block_based/block_based_table_reader.cc:2095 `Get`,
block_based_table_iterator in /root/reference): footer → metaindex →
{filter, properties, range-del} blocks, single-level index in memory,
data blocks fetched (and optionally cached) per seek.
"""

from __future__ import annotations

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.block import BlockIter
from toplingdb_tpu.table.builder import (
    METAINDEX_COMPRESSION_DICT,
    METAINDEX_FILTER,
    METAINDEX_FILTER_PARTS,
    METAINDEX_PROPERTIES,
    METAINDEX_RANGE_DEL,
    TableOptions,
)
from toplingdb_tpu.table.filter import filter_policy_from_name, filter_probe
from toplingdb_tpu.table.properties import TableProperties


import itertools as _it

_NGET_ID = _it.count(1)  # atomic process-global cache-namespace allocator


class TableReader:
    def __init__(self, rfile, icmp: InternalKeyComparator, options: TableOptions | None = None,
                 block_cache=None, cache_key_prefix: bytes = b""):
        self.opts = options or TableOptions()
        self._f = rfile
        self._icmp = icmp
        self._cache = block_cache
        self._cache_prefix = cache_key_prefix
        size = rfile.size()
        footer_buf = rfile.read(max(0, size - fmt.FOOTER_LEN), fmt.FOOTER_LEN)
        self.footer = fmt.Footer.decode(footer_buf)
        self._index_data = fmt.read_block(
            rfile, self.footer.index_handle, self.opts.verify_checksums
        )
        meta = fmt.read_block(
            rfile, self.footer.metaindex_handle, self.opts.verify_checksums
        )
        self._meta_handles: dict[bytes, fmt.BlockHandle] = {}
        mit = BlockIter(meta, dbformat.BYTEWISE.compare)
        mit.seek_to_first()
        for k, v in mit.entries():
            self._meta_handles[k] = fmt.BlockHandle.decode_exact(v)

        self.properties = TableProperties()
        ph = self._meta_handles.get(METAINDEX_PROPERTIES)
        if ph is not None:
            self.properties = TableProperties.decode_block(
                fmt.read_block(rfile, ph, self.opts.verify_checksums)
            )

        self._filter_data: bytes | None = None
        self._filter_policy = None
        fh = self._meta_handles.get(METAINDEX_FILTER)
        if fh is not None:
            self._filter_data = fmt.read_block(rfile, fh, self.opts.verify_checksums)
            self._filter_policy = filter_policy_from_name(
                self.properties.filter_policy_name
            )
        # Partitioned filter (reference PartitionedFilterBlockReader): the
        # small top index (last user key -> partition handle) loads now;
        # partitions load lazily through the block cache on probes.
        self._filter_top: bytes | None = None
        self._filter_part_memo: dict[int, bytes] = {}
        th = self._meta_handles.get(METAINDEX_FILTER_PARTS)
        if th is not None:
            self._filter_top = fmt.read_block(rfile, th,
                                              self.opts.verify_checksums)
            self._filter_policy = filter_policy_from_name(
                self.properties.filter_policy_name
            )

        # The extractor this FILE's prefix structures were built with,
        # resolved once (hot Get path must not reconstruct it per probe).
        from toplingdb_tpu.utils.slice_transform import resolve_file_extractor

        self._resolved_pe = resolve_file_extractor(
            getattr(self.opts, "prefix_extractor", None),
            self.properties.prefix_extractor_name,
        )

        self._range_del_data: bytes | None = None
        self._range_del_cache: list[tuple[bytes, bytes]] | None = None
        rh = self._meta_handles.get(METAINDEX_RANGE_DEL)
        if rh is not None:
            self._range_del_data = fmt.read_block(rfile, rh, self.opts.verify_checksums)

        # ZSTD dictionary the data blocks were compressed with (reference
        # kCompressionDictBlockName / UncompressionDict).
        self._compression_dict = b""
        dh = self._meta_handles.get(METAINDEX_COMPRESSION_DICT)
        if dh is not None:
            self._compression_dict = fmt.read_block(
                rfile, dh, self.opts.verify_checksums)

        # Partitioned index: _index_data is the small top-level index; the
        # partition blocks load lazily through the block cache (reference
        # partitioned index readers, table/block_based/partitioned_index_*).
        self._partitioned_index = self.properties.index_type == "two_level"
        # Data/index block seeks may run the native C scan when raw
        # bytewise order == comparator order (bytewise and u64ts — the ts
        # encoding bakes its order into the bytes).
        self._native_seek = icmp.user_comparator.name() in (
            "tpulsm.BytewiseComparator", "tpulsm.BytewiseComparator.u64ts")

    # ------------------------------------------------------------------

    def native_get_handle(self, smallest_uk: bytes, largest_uk: bytes):
        """Handle for the native point-read engine (tpulsm_db_get), built
        lazily and owned by this reader (freed at GC; the native side dups
        the fd, so reader close doesn't invalidate it). Ineligible tables
        (partitioned index/filter, range tombstones, dict compression,
        non-posix file, non-bytewise comparator) get an eligible=0 handle:
        the chain walk returns FALLBACK on contact, keeping the Python
        state machine authoritative for everything it must see."""
        h = getattr(self, "_nget_handle", False)
        if h is not False:
            return h
        import ctypes
        import weakref

        from toplingdb_tpu import native

        cl = native.lib()
        if cl is None or not hasattr(cl, "tpulsm_table_handle_new"):
            self._nget_handle = None
            return None
        fd = -1
        try:
            fd = self._f._f.fileno()  # posix random-access file only
        except AttributeError:
            fd = -1
        eligible = (
            fd >= 0
            and not self._partitioned_index
            and self._filter_top is None
            and self._range_del_data is None
            and not self._compression_dict
            and self._icmp.user_comparator.name()
            == "tpulsm.BytewiseComparator"
        )
        filt = b""
        filter_kind = 0
        fname = str(self.properties.filter_policy_name)
        if (eligible and self._filter_data is not None
                and self.properties.whole_key_filtering):
            if fname.startswith("tpulsm.BloomFilter"):
                filt = self._filter_data
            elif fname.startswith("tpulsm.BlockedBloom"):
                filt = self._filter_data
                filter_kind = 1
        idx = self._index_data if eligible else b""
        u8 = ctypes.POINTER(ctypes.c_uint8)

        def buf(b):
            return ctypes.cast(ctypes.c_char_p(bytes(b)), u8)

        # Cache-key namespace: a process-global id, NOT the file number —
        # the native block cache is process-wide and two DBs' file numbers
        # collide (the Python block cache solves this with a per-open
        # session prefix; a fresh id per handle is the same guarantee).
        h = cl.tpulsm_table_handle_new(
            fd if eligible else -1,
            next(_NGET_ID),
            (1 | (filter_kind << 1)) if eligible else 0,
            buf(idx), len(idx), buf(filt), len(filt),
            buf(smallest_uk), len(smallest_uk),
            buf(largest_uk), len(largest_uk),
        )
        h = h or None
        self._nget_handle = h
        if h:
            weakref.finalize(self, cl.tpulsm_table_handle_free, h)
        return h

    def close(self) -> None:
        self._f.close()

    def key_may_match(self, user_key: bytes) -> bool:
        if self._filter_top is not None:
            return self._partitioned_filter_probe(user_key)
        return filter_probe(
            self._filter_policy, self._filter_data,
            bool(self.properties.whole_key_filtering),
            self._resolved_pe, user_key,
        )

    def _partitioned_filter_probe(self, user_key: bytes) -> bool:
        """Binary-search the filter-top index, load (and cache) ONE filter
        partition, probe it. Fails open (like filter_probe) when the
        policy can't be reconstructed from its recorded name."""
        if self._filter_policy is None:
            return True
        it = BlockIter(self._filter_top, dbformat.BYTEWISE.compare)
        it.seek(user_key)  # first partition whose last key >= user_key
        if not it.valid():
            return False  # past every partition's range: definitely absent
        handle = fmt.BlockHandle.decode_exact(it.value())
        if self._cache is not None:
            fdata = self._read_data_block(handle, kind="filter")
        else:
            # No shared block cache: memoize per reader (bounded by the
            # partition count) — a probe must stay cheaper than the block
            # read it exists to avoid.
            fdata = self._filter_part_memo.get(handle.offset)
            if fdata is None:
                fdata = self._read_data_block(handle, kind="filter")
                self._filter_part_memo[handle.offset] = fdata
        return self._filter_policy.key_may_match(user_key, fdata)

    def prefix_may_match(self, prefix: bytes) -> bool:
        """Probe the filter with an already-extracted prefix (prefix Seek
        short-circuit, reference FilterBlockReader::PrefixMayMatch). Only
        meaningful when the file was built with a prefix_extractor."""
        if (self._filter_policy is None or self._filter_data is None
                or not self.properties.prefix_extractor_name):
            return True
        return self._filter_policy.key_may_match(prefix, self._filter_data)

    def _read_data_block(self, handle: fmt.BlockHandle, pf=None,
                         kind: str = "") -> bytes:
        """`pf`: optional FilePrefetchBuffer (per-iterator readahead;
        reference FilePrefetchBuffer, file/file_prefetch_buffer.h:63).
        `kind`: "filter"/"index" routes PerfContext cache counters to the
        typed fields; "" counts as a data block."""
        from toplingdb_tpu.utils import statistics as st

        src = pf if pf is not None else self._f
        if self._cache is not None:
            ckey = self._cache_prefix + handle.encode()
            data = self._cache.lookup(ckey)
            if data is not None:
                if st.perf_level:
                    ctx = st.perf_context()
                    if kind == "filter":
                        ctx.block_cache_filter_hit_count += 1
                    elif kind == "index":
                        ctx.block_cache_index_hit_count += 1
                    else:
                        ctx.block_cache_hit_count += 1
                return data
            data = fmt.read_block(src, handle, self.opts.verify_checksums,
                                  self._compression_dict)
            self._cache.insert(ckey, data, len(data))
            if st.perf_level:
                ctx = st.perf_context()
                if not kind:
                    ctx.block_cache_miss_count += 1
                ctx.block_read_count += 1
                ctx.block_read_byte += len(data)
            return data
        data = fmt.read_block(src, handle, self.opts.verify_checksums,
                              self._compression_dict)
        if st.perf_level:
            ctx = st.perf_context()
            ctx.block_read_count += 1
            ctx.block_read_byte += len(data)
        return data

    def new_iterator(self, readahead_size: int = 0, preread=None,
                     aio_ring=None) -> "TableIterator":
        """`readahead_size`: ReadOptions.readahead_size — a fixed,
        immediately-armed prefetch window for this iterator; 0 keeps the
        auto-scaling default. `preread`: a PrereadSpans-style overlay
        (env/async_reads.py) replacing the prefetch buffer — the async
        read plane's batched block fetches serve this iterator's loads.
        `aio_ring`: AsyncIORing for the prefetch buffer's readahead
        windows (they become ring tasks instead of inline preads)."""
        return TableIterator(self, readahead_size=readahead_size,
                             preread=preread, aio_ring=aio_ring)

    def plan_block_reads(self, seek_ikeys) -> list[tuple[int, int]]:
        """Async read plane planner: the (offset, length) byte ranges the
        data blocks landed on by seeking each internal key would pread —
        deduplicated, block-cache-resident handles skipped. The length
        covers the block trailer, exactly what `fmt.read_block` consumes,
        so a prefetched range serves `_read_data_block` byte-for-byte."""
        idx = self.new_index_iterator()
        seen: set[int] = set()
        out: list[tuple[int, int]] = []
        for ik in seek_ikeys:
            idx.seek(ik)
            if not idx.valid():
                continue
            h = fmt.BlockHandle.decode_exact(idx.value())
            if h.offset in seen:
                continue
            seen.add(h.offset)
            if self._cache is not None and self._cache.lookup(
                    self._cache_prefix + h.encode()) is not None:
                continue  # resident: the probe will hit the cache
            out.append((h.offset, h.size + fmt.BLOCK_TRAILER_SIZE))
        return out

    def new_index_iterator(self):
        """Iterator over (separator_key, data BlockHandle bytes) — flat or
        partition-hopping depending on the file's index_type."""
        if self._partitioned_index:
            return _PartitionedIndexIter(self)
        return BlockIter(self._index_data, self._icmp.compare,
                         native_icmp_seek=self._native_seek)

    def range_del_entries(self) -> list[tuple[bytes, bytes]]:
        """Raw (begin_internal_key, end_user_key) tombstones in this file
        (parsed once, cached)."""
        if self._range_del_data is None:
            return []
        if self._range_del_cache is None:
            it = BlockIter(self._range_del_data, self._icmp.compare)
            it.seek_to_first()
            self._range_del_cache = list(it.entries())
        return self._range_del_cache

    def approximate_offset_of(self, ikey: bytes) -> int:
        """Approximate file offset of ikey (reference TableReader::
        ApproximateOffsetOf) — used for subcompaction boundary sizing."""
        idx = self.new_index_iterator()
        idx.seek(ikey)
        if idx.valid():
            return fmt.BlockHandle.decode_exact(idx.value()).offset
        return self.footer.metaindex_handle.offset

    def anchors(self, max_anchors: int = 32) -> list[bytes]:
        """Sampled keys for subcompaction boundary picking (reference
        TableReader::Anchors, used by GenSubcompactionBoundaries,
        compaction_job.cc:604-640)."""
        idx = self.new_index_iterator()
        idx.seek_to_first()
        keys = [k for k, _ in idx.entries()]
        if len(keys) <= max_anchors:
            return keys
        step = len(keys) / max_anchors
        return [keys[int(i * step)] for i in range(max_anchors)]


class _PartitionedIndexIter:
    """BlockIter-shaped view over a two-level (partitioned) index: the
    in-memory top block maps last-separator → partition handle; partition
    blocks load on demand through the reader's block cache."""

    def __init__(self, reader: TableReader):
        self._r = reader
        self._cmp = reader._icmp.compare
        self._top = BlockIter(reader._index_data, self._cmp)
        self._sub: BlockIter | None = None

    def _load(self) -> None:
        if not self._top.valid():
            self._sub = None
            return
        h = fmt.BlockHandle.decode_exact(self._top.value())
        self._sub = BlockIter(self._r._read_data_block(h, kind="index"),
                              self._cmp,
                              native_icmp_seek=self._r._native_seek)

    def valid(self) -> bool:
        return self._sub is not None and self._sub.valid()

    def key(self) -> bytes:
        return self._sub.key()

    def value(self) -> bytes:
        return self._sub.value()

    def seek_to_first(self) -> None:
        self._top.seek_to_first()
        self._load()
        if self._sub is not None:
            self._sub.seek_to_first()

    def seek_to_last(self) -> None:
        self._top.seek_to_last()
        self._load()
        if self._sub is not None:
            self._sub.seek_to_last()

    def seek(self, target: bytes) -> None:
        self._top.seek(target)
        self._load()
        if self._sub is not None:
            # Each top key is its partition's LAST separator, so the landed
            # partition always contains a separator >= target.
            self._sub.seek(target)

    def seek_for_prev(self, target: bytes) -> None:
        self.seek(target)
        if not self.valid():
            self.seek_to_last()
            return
        if self._cmp(self.key(), target) > 0:
            self.prev()

    def next(self) -> None:
        self._sub.next()
        if not self._sub.valid():
            self._top.next()
            self._load()
            if self._sub is not None:
                self._sub.seek_to_first()

    def prev(self) -> None:
        self._sub.prev()
        if not self._sub.valid():
            self._top.prev()
            self._load()
            if self._sub is not None:
                self._sub.seek_to_last()

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()


class TableIterator:
    """Two-level iterator: index (flat or partitioned) → data block."""

    def __init__(self, reader: TableReader, readahead_size: int = 0,
                 preread=None, aio_ring=None):
        from toplingdb_tpu.table.prefetch import FilePrefetchBuffer

        self._r = reader
        self._cmp = reader._icmp.compare
        self._idx = reader.new_index_iterator()
        self._data: BlockIter | None = None
        # Per-iterator auto-readahead: sequential block loads escalate to
        # windowed preads; random seeks pass through untouched. A nonzero
        # ReadOptions.readahead_size pins a pre-armed fixed window
        # instead of the auto-scaling ramp. A `preread` overlay (async
        # read plane batched fetches) replaces the buffer outright; an
        # `aio_ring` moves the buffer's readahead windows onto a reader
        # ring thread.
        if preread is not None:
            self._pf = preread
        elif readahead_size > 0:
            self._pf = FilePrefetchBuffer(
                reader._f, max_readahead=readahead_size,
                initial_readahead=readahead_size, arm_immediately=True,
                aio_ring=aio_ring)
        else:
            self._pf = FilePrefetchBuffer(reader._f, aio_ring=aio_ring)

    def prefetch_counts(self) -> tuple[int, int]:
        """(hits, misses) of this iterator's readahead buffer — exported
        as PREFETCH_* tickers by the compaction input scan."""
        return self._pf.hits, self._pf.misses

    def _load_data_block(self) -> None:
        if not self._idx.valid():
            self._data = None
            return
        handle = fmt.BlockHandle.decode_exact(self._idx.value())
        self._data = BlockIter(
            self._r._read_data_block(handle, pf=self._pf), self._cmp,
            native_icmp_seek=self._r._native_seek)

    def valid(self) -> bool:
        return self._data is not None and self._data.valid()

    def key(self) -> bytes:
        return self._data.key()

    def value(self) -> bytes:
        return self._data.value()

    def seek_to_first(self) -> None:
        self._idx.seek_to_first()
        self._load_data_block()
        if self._data is not None:
            self._data.seek_to_first()
            self._skip_forward_empty()

    def seek_to_last(self) -> None:
        self._idx.seek_to_last()
        self._load_data_block()
        if self._data is not None:
            self._data.seek_to_last()
            self._skip_backward_empty()

    def seek(self, target: bytes) -> None:
        self._idx.seek(target)
        self._load_data_block()
        if self._data is not None:
            self._data.seek(target)
            self._skip_forward_empty()

    def seek_for_prev(self, target: bytes) -> None:
        self.seek(target)
        if not self.valid():
            self.seek_to_last()
            return
        if self._cmp(self.key(), target) > 0:
            self.prev()

    def next(self) -> None:
        assert self.valid()
        self._data.next()
        self._skip_forward_empty()

    def prev(self) -> None:
        assert self.valid()
        self._data.prev()
        self._skip_backward_empty()

    def _skip_forward_empty(self) -> None:
        while self._data is not None and not self._data.valid():
            self._idx.next()
            self._load_data_block()
            if self._data is not None:
                self._data.seek_to_first()

    def _skip_backward_empty(self) -> None:
        while self._data is not None and not self._data.valid():
            self._idx.prev()
            self._load_data_block()
            if self._data is not None:
                self._data.seek_to_last()

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()
