"""Table format factory + adaptive reader dispatch.

The pluggable-SST seam (reference TableFactory registry,
table/table_factory.cc:18-40, and the adaptive reader, table/adaptive/ in
/root/reference): builders are chosen by `TableOptions.format`; readers are
dispatched by footer magic, so a DB can hold a mix of formats (e.g.
single_fast at L0/L1, block at L2+) and always open every file.
"""

from __future__ import annotations

from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import TableBuilder, TableOptions
from toplingdb_tpu.table.cuckoo import CuckooTableBuilder, CuckooTableReader
from toplingdb_tpu.table.plain import PlainTableBuilder, PlainTableReader
from toplingdb_tpu.table.reader import TableReader
from toplingdb_tpu.table.single_fast import (
    SingleFastTableBuilder,
    SingleFastTableReader,
)
from toplingdb_tpu.table.zip_table import ZipTableBuilder, ZipTableReader
from toplingdb_tpu.utils.status import Corruption, InvalidArgument

FORMATS = ("block", "single_fast", "cuckoo", "plain", "zip")


def new_table_builder(wfile, icmp, options: TableOptions | None = None,
                      **kw):
    options = options or TableOptions()
    f = getattr(options, "format", "block")
    if getattr(options, "auto_sort", False) and f != "single_fast":
        raise InvalidArgument(
            "auto_sort is a single_fast-format feature (the block builder "
            "requires sorted adds)"
        )
    if f == "block":
        return TableBuilder(wfile, icmp, options, **kw)
    if f == "single_fast":
        return SingleFastTableBuilder(wfile, icmp, options, **kw)
    if f == "cuckoo":
        return CuckooTableBuilder(wfile, icmp, options, **kw)
    if f == "plain":
        return PlainTableBuilder(wfile, icmp, options, **kw)
    if f == "zip":
        return ZipTableBuilder(wfile, icmp, options, **kw)
    raise InvalidArgument(f"unknown table format {f!r}")


def open_table(rfile, icmp, options: TableOptions | None = None,
               block_cache=None, cache_key_prefix: bytes = b""):
    """Adaptive open: dispatch on the footer magic."""
    size = rfile.size()
    tail = rfile.read(max(0, size - fmt.FOOTER_LEN), fmt.FOOTER_LEN)
    magic = fmt.Footer.read_magic(tail)
    if magic == fmt.MAGIC:
        return TableReader(rfile, icmp, options, block_cache=block_cache,
                           cache_key_prefix=cache_key_prefix)
    if magic == fmt.SINGLE_FAST_MAGIC:
        return SingleFastTableReader(rfile, icmp, options)
    if magic == fmt.CUCKOO_MAGIC:
        return CuckooTableReader(rfile, icmp, options)
    if magic == fmt.PLAIN_MAGIC:
        return PlainTableReader(rfile, icmp, options)
    if magic == fmt.ZIP_MAGIC:
        return ZipTableReader(rfile, icmp, options)
    raise Corruption(f"unknown SST magic {magic:#x}")
