"""SST builder: block-based table writer.

The default table format, structured like the reference's
BlockBasedTableBuilder (table/block_based/block_based_table_builder.cc:961-1150
in /root/reference): data blocks cut at `block_size`, a single-level index of
shortest separators, a whole-file bloom filter over user keys, a range-deletion
meta block, a properties meta block, a metaindex, and the fixed footer.

Keys added must be internal keys in InternalKeyComparator order. Range
tombstones go to their own meta block via `add_tombstone` (internal begin key →
end user key), mirroring the reference's kRangeDelBlockName handling.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.block import BlockBuilder
from toplingdb_tpu.table.filter import (
    BlockedBloomFilterPolicy,
    BloomFilterPolicy,
    FilterPolicy,
)
from toplingdb_tpu.table.properties import TableProperties

METAINDEX_FILTER = b"filter.fullfilter"
METAINDEX_FILTER_PARTS = b"filter.partitioned"
METAINDEX_PROPERTIES = b"tpulsm.properties"
METAINDEX_RANGE_DEL = b"tpulsm.range_del"
METAINDEX_COMPRESSION_DICT = b"tpulsm.compression_dict"


@dataclass
class CompressionOptions:
    """Per-codec tuning (reference CompressionOptions,
    include/rocksdb/advanced_options.h): `level` feeds the codec,
    `max_dict_bytes` > 0 enables ZSTD dictionary compression (the dict is
    trained from the file's first `zstd_max_train_bytes` of raw blocks —
    default 100x the dict size — stored in a metaindex block, and applied
    to every data block; reference util/compression.h:1435-1476)."""

    level: int | None = None
    max_dict_bytes: int = 0
    zstd_max_train_bytes: int = 0

    def train_budget(self) -> int:
        return self.zstd_max_train_bytes or self.max_dict_bytes * 100


@dataclass
class TableOptions:
    format: str = "block"           # 'block' | 'single_fast' (table/factory.py)
    block_size: int = 4096
    restart_interval: int = 16
    index_restart_interval: int = 1
    # 'binary' = one in-memory index block; 'two_level' = partitioned index
    # (reference kTwoLevelIndexSearch / partitioned index-filter): index
    # entries split into metadata_block_size partitions behind a small top
    # index, loaded lazily and block-cached — the big-SST memory saver.
    index_type: str = "binary"
    metadata_block_size: int = 4096
    # Partitioned filters (reference PartitionedFilterBlockBuilder,
    # table/block_based/partitioned_filter_block.h:27): the bloom splits
    # into ~metadata_block_size partitions behind a small top index, so a
    # point lookup loads/caches ONE partition instead of the whole filter.
    # Whole-key filtering only (prefix probes could span partitions).
    partition_filters: bool = False
    # single_fast only: also write an open-addressed hash bucket index for
    # O(1) point lookups (the CuckooTable / PlainTable prefix-hash role).
    hash_index: bool = False
    # single_fast only: accept UNSORTED adds and sort at finish (the Topling
    # VecAutoSortTable role — bulk loads without pre-sorting); exact
    # duplicate internal keys dedup last-write-wins.
    auto_sort: bool = False
    # >1 enables the producer/consumer compression pipeline (reference
    # CompressionOptions.parallel_threads / ParallelCompressionRep,
    # block_based_table_builder.cc:818-825): data blocks compress on worker
    # threads (zlib/bz2/lzma release the GIL) and write in order.
    compression_parallel_threads: int = 1
    compression: int = fmt.NO_COMPRESSION
    compression_opts: CompressionOptions = field(
        default_factory=CompressionOptions)
    # Blocked (cache-line) bloom by default: one DRAM access per probe
    # (reference FastLocalBloom default since format_version 5).
    filter_policy: FilterPolicy | None = field(
        default_factory=lambda: BlockedBloomFilterPolicy())
    whole_key_filtering: bool = True
    # SliceTransform (utils/slice_transform.py) or None. When set, key
    # prefixes ALSO go into the bloom filter (reference prefix bloom,
    # FullFilterBlockBuilder), readers can probe prefix_may_match(), and the
    # 'plain' format builds its prefix hash index from it.
    prefix_extractor: object | None = None
    verify_checksums: bool = True
    # User TablePropertiesCollectorFactory list (reference
    # table_properties_collector_factories); a fresh collector per SST.
    properties_collector_factories: list = field(default_factory=list)
    # Per-entry protection info (Options.protection_bytes_per_key,
    # propagated here at DB.open so the flush/compaction/scan data planes
    # see it without signature plumbing). 0 = off.
    protection_bytes_per_key: int = 0


class TableBuilder:
    def __init__(
        self,
        wfile,
        icmp: InternalKeyComparator,
        options: TableOptions | None = None,
        column_family_id: int = 0,
        column_family_name: str = "",
        creation_time: int = 0,
    ):
        self.opts = options or TableOptions()
        self._w = wfile
        self._icmp = icmp
        self._data_block = BlockBuilder(self.opts.restart_interval)
        self._two_level_index = self.opts.index_type == "two_level"
        # Flat index builds incrementally (prefix-compressed as we go); only
        # the partitioned mode needs the entries buffered for chunking.
        self._index_block = (
            None if self._two_level_index
            else BlockBuilder(self.opts.index_restart_interval)
        )
        self._index_entries: list[tuple[bytes, bytes]] = []  # two-level only
        self._filter_keys: list[bytes] = []
        self._last_filter_prefix: bytes | None = None
        self._filter_parts: list[tuple[bytes, list[bytes]]] = []
        self._partition_filters = bool(
            getattr(self.opts, "partition_filters", False)
            and self.opts.filter_policy is not None
        )
        if self._partition_filters and self.opts.prefix_extractor is not None:
            from toplingdb_tpu.utils.status import InvalidArgument

            raise InvalidArgument(
                "partition_filters supports whole-key filtering only "
                "(prefix probes could span filter partitions)"
            )
        self._range_del_block = BlockBuilder(restart_interval=1)
        self.props = TableProperties(
            comparator_name=icmp.user_comparator.name(),
            filter_policy_name=(
                self.opts.filter_policy.name() if self.opts.filter_policy else ""
            ),
            compression_name=str(self.opts.compression),
            prefix_extractor_name=(
                self.opts.prefix_extractor.name()
                if self.opts.prefix_extractor else ""
            ),
            column_family_id=column_family_id,
            column_family_name=column_family_name,
            creation_time=creation_time,
            smallest_seqno=dbformat.MAX_SEQUENCE_NUMBER,
            whole_key_filtering=1 if self.opts.whole_key_filtering else 0,
        )
        self._last_key: bytes | None = None
        self._pending_index_entry = False
        self._pending_handle: fmt.BlockHandle | None = None
        self._smallest: bytes | None = None
        self._largest: bytes | None = None
        self._finished = False
        self._collectors = [
            f.create() for f in self.opts.properties_collector_factories
        ]
        self.need_compaction = False
        # Parallel-compression pipeline state (active only when compressing
        # with >1 threads): blocks compress out-of-band, write in order, and
        # the index is assembled at finish from recorded block boundaries.
        self._par_pool = None
        self._par_blocks: list = []  # (future, first_key, last_key, raw_len)
        self._par_meta: list = []    # (first_key, last_key, BlockHandle)
        self._block_first_key: bytes | None = None
        if (self.opts.compression != fmt.NO_COMPRESSION
                and self.opts.compression_parallel_threads > 1):
            from concurrent.futures import ThreadPoolExecutor

            self._par_pool = ThreadPoolExecutor(
                max_workers=self.opts.compression_parallel_threads
            )
        # ZSTD dictionary state: None = disabled, b"" = training pending
        # (raw blocks buffer in _dict_samples until the train budget),
        # non-empty = trained and applied to every subsequent data block.
        copts = self.opts.compression_opts
        self._dict: bytes | None = (
            b"" if (self.opts.compression == fmt.ZSTD_COMPRESSION
                    and copts.max_dict_bytes > 0) else None
        )
        self._dict_samples: list = []   # (raw, first_key, last_key)
        self._dict_sample_bytes = 0
        self._force_deferred = False    # set when dict training fails

    # ------------------------------------------------------------------

    @property
    def num_entries(self) -> int:
        return self.props.num_entries + self.props.num_range_deletions

    def file_size(self) -> int:
        size = self._w.file_size()
        if self._par_blocks:
            # Count queued-but-unwritten blocks (raw size upper bound) so
            # compaction's output-cut trigger doesn't lag the pipeline.
            size += sum(b[3] for b in self._par_blocks)
        size += self._dict_sample_bytes  # dict-training buffer, same reason
        return size

    @property
    def smallest_key(self) -> bytes | None:
        return self._smallest

    @property
    def largest_key(self) -> bytes | None:
        return self._largest

    def _track_bounds(self, ikey: bytes) -> None:
        if self._smallest is None or self._icmp.compare(ikey, self._smallest) < 0:
            self._smallest = ikey
        if self._largest is None or self._icmp.compare(ikey, self._largest) > 0:
            self._largest = ikey
        seq = dbformat.extract_seqno(ikey)
        self.props.smallest_seqno = min(self.props.smallest_seqno, seq)
        self.props.largest_seqno = max(self.props.largest_seqno, seq)

    def add(self, ikey: bytes, value: bytes) -> None:
        assert not self._finished
        if self._last_key is not None:
            assert self._icmp.compare(self._last_key, ikey) < 0, (
                f"keys out of order: {self._last_key!r} >= {ikey!r}"
            )
        if self._pending_index_entry:
            sep = self._icmp.find_shortest_separator(self._last_key, ikey)
            self._index_add(sep, self._pending_handle.encode())
            self._pending_index_entry = False
        if self._data_block.empty():
            self._block_first_key = ikey
        uk, seq_, t = dbformat.split_internal_key(ikey)
        if self.opts.filter_policy:
            if self.opts.whole_key_filtering:
                self._filter_keys.append(uk)
            pe = self.opts.prefix_extractor
            if pe is not None and pe.in_domain(uk):
                p = pe.transform(uk)
                if p != self._last_filter_prefix:
                    self._filter_keys.append(p)
                    self._last_filter_prefix = p
        for c in self._collectors:
            c.add_user_key(uk, value, t, seq_, self._w.file_size())
        self._data_block.add(ikey, value)
        self._last_key = ikey
        self._track_bounds(ikey)
        self.props.num_entries += 1
        self.props.raw_key_size += len(ikey)
        self.props.raw_value_size += len(value)
        if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
            self.props.num_deletions += 1
        elif t == ValueType.MERGE:
            self.props.num_merge_operands += 1
        if self._data_block.current_size_estimate() >= self.opts.block_size:
            self._flush_data_block()
            if self._partition_filters and self._filter_keys:
                bp = self.opts.filter_policy
                est = len(self._filter_keys) \
                    * getattr(bp, "bits_per_key", 10.0) / 8
                if est >= self.opts.metadata_block_size:
                    # Cut at the data-block boundary: uk ranges of sibling
                    # partitions stay disjoint except possibly the boundary
                    # key, which lands in both (probe finds the first).
                    self._filter_parts.append((uk, self._filter_keys))
                    self._filter_keys = []

    def add_tombstone(self, begin_ikey: bytes, end_user_key: bytes) -> None:
        """Range tombstone: begin internal key (type RANGE_DELETION) → end user
        key (exclusive)."""
        assert not self._finished
        self._range_del_block.add(begin_ikey, end_user_key)
        self.props.num_range_deletions += 1
        self._track_bounds(begin_ikey)
        # The tombstone covers up to end_user_key exclusive; widen largest.
        end_ikey = dbformat.make_internal_key(
            end_user_key, dbformat.MAX_SEQUENCE_NUMBER, dbformat.VALUE_TYPE_FOR_SEEK
        )
        if self._largest is None or self._icmp.compare(end_ikey, self._largest) > 0:
            self._largest = end_ikey

    def _index_add(self, key: bytes, handle_bytes: bytes) -> None:
        if self._index_block is not None:
            self._index_block.add(key, handle_bytes)
        else:
            self._index_entries.append((key, handle_bytes))

    def _flush_data_block(self) -> None:
        if self._data_block.empty():
            return
        raw = self._data_block.finish()
        if self._dict == b"":
            # Dictionary training pending: buffer raw blocks until the
            # train budget (reference buffers data_begin the same way,
            # block_based_table_builder.cc EnterUnbuffered).
            self._dict_samples.append(
                (raw, self._block_first_key, self._last_key))
            self._dict_sample_bytes += len(raw)
            if (self._dict_sample_bytes
                    >= self.opts.compression_opts.train_budget()):
                self._train_dict_and_flush()
        elif (self._par_pool is not None or self._dict is not None
                or self._force_deferred):
            self._emit_deferred(raw, self._block_first_key, self._last_key)
        else:
            self._pending_handle = fmt.write_block(
                self._w, raw, self.opts.compression,
                self.opts.compression_opts.level,
            )
            self._pending_index_entry = True
            self.props.data_size += len(raw)
            self.props.num_data_blocks += 1
        self._data_block.reset()

    def _emit_deferred(self, raw: bytes, first: bytes, last: bytes) -> None:
        """Deferred-index block emission (parallel pipeline and/or dict
        mode): compressed out-of-band or inline, index assembled at finish
        from recorded boundaries."""
        copts = self.opts.compression_opts
        if self._par_pool is not None:
            fut = self._par_pool.submit(
                fmt.compress_for_block, raw, self.opts.compression,
                copts.level, self._dict or b"",
            )
            self._par_blocks.append((fut, first, last, len(raw)))
            self._drain_parallel(wait=False)
        else:
            payload, out_type = fmt.compress_for_block(
                raw, self.opts.compression, copts.level, self._dict or b"")
            h = fmt.write_compressed_block(self._w, payload, out_type)
            self._par_meta.append((first, last, h))
            self.props.data_size += len(raw)
            self.props.num_data_blocks += 1

    def _train_dict_and_flush(self) -> None:
        from toplingdb_tpu.utils import codecs

        self._dict = codecs.zstd_train_dictionary(
            [r for r, _, _ in self._dict_samples],
            self.opts.compression_opts.max_dict_bytes,
        )
        if self._dict == b"":
            # Training failed: disable the dict (don't re-buffer), but stay
            # in deferred-emission mode so index entries keep accumulating
            # in _par_meta in file order with the replayed blocks below.
            self._dict = None
            self._force_deferred = True
        for raw, first, last in self._dict_samples:
            self._emit_deferred(raw, first, last)
        self._dict_samples = []
        self._dict_sample_bytes = 0

    def _drain_parallel(self, wait: bool) -> None:
        """Write completed compressed blocks in submission order (bounds
        memory during the build; `wait` drains everything at finish)."""
        while self._par_blocks and (wait or self._par_blocks[0][0].done()):
            fut, first, last, raw_len = self._par_blocks.pop(0)
            payload, out_type = fut.result()
            h = fmt.write_compressed_block(self._w, payload, out_type)
            self._par_meta.append((first, last, h))
            self.props.data_size += raw_len
            self.props.num_data_blocks += 1

    def finish(self) -> TableProperties:
        assert not self._finished
        for c in self._collectors:
            self.props.user_collected.update(c.finish())
            if c.need_compact():
                self.need_compaction = True
        self._flush_data_block()
        if self._dict == b"":
            self._train_dict_and_flush()  # small file: train from the lot
        if self._par_pool is not None:
            self._drain_parallel(wait=True)
            self._par_pool.shutdown()
        if self._par_meta:
            # Index from recorded block boundaries — same separators as the
            # sequential path computes incrementally.
            for i, (first, last, h) in enumerate(self._par_meta):
                if i + 1 < len(self._par_meta):
                    sep = self._icmp.find_shortest_separator(
                        last, self._par_meta[i + 1][0]
                    )
                else:
                    sep = self._icmp.find_short_successor(last)
                self._index_add(sep, h.encode())
        if self._pending_index_entry:
            succ = self._icmp.find_short_successor(self._last_key)
            self._index_add(succ, self._pending_handle.encode())
            self._pending_index_entry = False

        metaindex = BlockBuilder(restart_interval=1)
        meta_entries: list[tuple[bytes, fmt.BlockHandle]] = []

        if self._partition_filters and (self._filter_parts
                                        or self._filter_keys):
            if self._filter_keys:
                last_uk = dbformat.extract_user_key(self._last_key) \
                    if self._last_key else b""
                self._filter_parts.append((last_uk, self._filter_keys))
                self._filter_keys = []
            top = BlockBuilder(restart_interval=1)
            total = 0
            for last_uk, keys in self._filter_parts:
                fdata = self.opts.filter_policy.create_filter(keys)
                fh = fmt.write_block(self._w, fdata, fmt.NO_COMPRESSION)
                top.add(last_uk, fh.encode())
                total += len(fdata)
            th = fmt.write_block(self._w, top.finish(), fmt.NO_COMPRESSION)
            self.props.filter_size = total
            meta_entries.append((METAINDEX_FILTER_PARTS, th))
        elif self.opts.filter_policy and self._filter_keys:
            fdata = self.opts.filter_policy.create_filter(self._filter_keys)
            fh = fmt.write_block(self._w, fdata, fmt.NO_COMPRESSION)
            self.props.filter_size = len(fdata)
            meta_entries.append((METAINDEX_FILTER, fh))

        if not self._range_del_block.empty():
            rd = self._range_del_block.finish()
            rh = fmt.write_block(self._w, rd, fmt.NO_COMPRESSION)
            meta_entries.append((METAINDEX_RANGE_DEL, rh))

        if self._dict:
            dh = fmt.write_block(self._w, self._dict, fmt.NO_COMPRESSION)
            meta_entries.append((METAINDEX_COMPRESSION_DICT, dh))

        # Index size must be known before the properties block is serialized.
        two_level = self._two_level_index and len(self._index_entries) > 1
        self.props.index_type = "two_level" if two_level else "binary"
        if two_level:
            # Partition blocks go to the file now; the footer's index handle
            # points at the small top-level index over them.
            top = BlockBuilder(self.opts.index_restart_interval)
            part = BlockBuilder(self.opts.index_restart_interval)
            part_size = 0
            last_key = None
            total = 0
            for k, v in self._index_entries:
                part.add(k, v)
                part_size += len(k) + len(v) + 8
                last_key = k
                if part_size >= self.opts.metadata_block_size:
                    raw = part.finish()
                    ph = fmt.write_block(self._w, raw, self.opts.compression)
                    top.add(last_key, ph.encode())
                    total += len(raw)
                    part = BlockBuilder(self.opts.index_restart_interval)
                    part_size = 0
            if part_size:
                raw = part.finish()
                ph = fmt.write_block(self._w, raw, self.opts.compression)
                top.add(last_key, ph.encode())
                total += len(raw)
            iraw = top.finish()
            self.props.index_size = total + len(iraw)
        elif self._index_block is not None:
            iraw = self._index_block.finish()
            self.props.index_size = len(iraw)
        else:
            # two_level requested but 0-1 index entries: flat degenerate.
            flat = BlockBuilder(self.opts.index_restart_interval)
            for k, v in self._index_entries:
                flat.add(k, v)
            iraw = flat.finish()
            self.props.index_size = len(iraw)

        pblock = self.props.encode_block()
        ph = fmt.write_block(self._w, pblock, fmt.NO_COMPRESSION)
        meta_entries.append((METAINDEX_PROPERTIES, ph))

        for name, handle in sorted(meta_entries):
            metaindex.add(name, handle.encode())
        mih = fmt.write_block(self._w, metaindex.finish(), fmt.NO_COMPRESSION)

        ih = fmt.write_block(self._w, iraw, self.opts.compression)

        self._w.append(fmt.Footer(mih, ih).encode())
        self._w.flush()
        self._finished = True
        return self.props
