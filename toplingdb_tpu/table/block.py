"""Restart-point block: builder and iterator.

Same entry layout as the reference's Block (table/block_based/block_builder.cc,
block.cc in /root/reference): each entry is
    varint32 shared_key_len | varint32 non_shared_key_len | varint32 value_len
    | key_delta | value
with full keys at restart points every `restart_interval` entries; the block
ends with a fixed32 array of restart offsets and a fixed32 restart count.
Seek = binary search over restarts, then linear delta-decode.
"""

from __future__ import annotations

from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.status import Corruption


class BlockBuilder:
    def __init__(self, restart_interval: int = 16):
        self.restart_interval = restart_interval
        self._buf = bytearray()
        self._restarts: list[int] = [0]
        self._counter = 0
        self._last_key = b""
        self._num_entries = 0

    def reset(self) -> None:
        self._buf.clear()
        self._restarts = [0]
        self._counter = 0
        self._last_key = b""
        self._num_entries = 0

    def empty(self) -> bool:
        return self._num_entries == 0

    @property
    def num_entries(self) -> int:
        return self._num_entries

    def current_size_estimate(self) -> int:
        return len(self._buf) + 4 * len(self._restarts) + 4

    def add(self, key: bytes, value: bytes) -> None:
        shared = 0
        if self._counter < self.restart_interval:
            lk = self._last_key
            n = min(len(lk), len(key))
            while shared < n and lk[shared] == key[shared]:
                shared += 1
        else:
            self._restarts.append(len(self._buf))
            self._counter = 0
        non_shared = len(key) - shared
        self._buf += coding.encode_varint32(shared)
        self._buf += coding.encode_varint32(non_shared)
        self._buf += coding.encode_varint32(len(value))
        self._buf += key[shared:]
        self._buf += value
        self._last_key = key
        self._counter += 1
        self._num_entries += 1

    def finish(self) -> bytes:
        out = bytearray(self._buf)
        for r in self._restarts:
            out += coding.encode_fixed32(r)
        out += coding.encode_fixed32(len(self._restarts))
        return bytes(out)


class BlockIter:
    """Iterator over a finished block. Comparator `cmp(a, b) -> int` orders
    the keys stored in the block (internal-key order for data/index blocks)."""

    def __init__(self, contents: bytes, cmp, native_icmp_seek: bool = False):
        """`native_icmp_seek`: keys are internal keys under the STANDARD
        comparator (bytewise user keys, seq desc) — seek() may run the
        native C scan (one ctypes call instead of ~25 Python decodes)."""
        if len(contents) < 4:
            raise Corruption("block too small")
        self._data = contents
        self._cmp = cmp
        self._native_seek = native_icmp_seek
        self._num_restarts = coding.decode_fixed32(contents, len(contents) - 4)
        if self._num_restarts == 0:
            raise Corruption("block has no restarts")
        self._restart_off = len(contents) - 4 - 4 * self._num_restarts
        if self._restart_off < 0:
            raise Corruption("block restart array overflows block")
        self._limit = self._restart_off
        self._cur = self._limit  # invalid
        self._key = b""
        self._val_off = 0
        self._val_len = 0
        self._restart_idx = 0

    # -- parsing --------------------------------------------------------

    def _restart_point(self, i: int) -> int:
        return coding.decode_fixed32(self._data, self._restart_off + 4 * i)

    def _decode_at(self, off: int, prev_key: bytes) -> tuple[int, bytes]:
        """Decode entry at `off` given previous key; returns (next_off, key)
        and sets value span."""
        d = self._data
        shared, p = coding.decode_varint32(d, off)
        non_shared, p = coding.decode_varint32(d, p)
        vlen, p = coding.decode_varint32(d, p)
        if shared > len(prev_key) or p + non_shared + vlen > self._limit:
            raise Corruption("bad block entry")
        key = prev_key[:shared] + bytes(d[p : p + non_shared])
        self._val_off = p + non_shared
        self._val_len = vlen
        return p + non_shared + vlen, key

    # -- iterator interface --------------------------------------------

    def valid(self) -> bool:
        return self._cur < self._limit

    def key(self) -> bytes:
        return self._key

    def value(self) -> bytes:
        return bytes(self._data[self._val_off : self._val_off + self._val_len])

    def seek_to_first(self) -> None:
        self._restart_idx = 0
        self._cur = 0
        if self._limit == 0:
            return
        self._next_off, self._key = self._decode_at(0, b"")

    def seek_to_last(self) -> None:
        if self._limit == 0:
            self._cur = self._limit
            return
        self._restart_idx = self._num_restarts - 1
        off = self._restart_point(self._restart_idx)
        key = b""
        while True:
            self._cur = off
            nxt, key = self._decode_at(off, key)
            if nxt >= self._limit:
                self._key = key
                self._next_off = nxt
                return
            off = nxt

    def seek(self, target: bytes) -> None:
        """Position at first entry with key >= target."""
        if self._native_seek and self._try_native_seek(target):
            return
        # Binary search restarts: find last restart whose key < target.
        lo, hi = 0, self._num_restarts - 1
        while lo < hi:
            mid = (lo + hi + 1) // 2
            off = self._restart_point(mid)
            _, key = self._decode_at(off, b"")
            if self._cmp(key, target) < 0:
                lo = mid
            else:
                hi = mid - 1
        off = self._restart_point(lo)
        key = b""
        self._restart_idx = lo
        while off < self._limit:
            self._cur = off
            nxt, key = self._decode_at(off, key)
            if self._cmp(key, target) >= 0:
                self._key = key
                self._next_off = nxt
                return
            off = nxt
        self._cur = self._limit  # all keys < target

    _seek_out = None  # lazily-built per-iterator ctypes scratch

    def _try_native_seek(self, target: bytes) -> bool:
        """One-call native seek; False = run the Python path (no lib, or
        the native scan refused — it re-raises proper errors there)."""
        import ctypes

        from toplingdb_tpu import native

        lib = native.lib()
        if lib is None or not hasattr(lib, "tpulsm_block_seek"):
            self._native_seek = False
            return False
        if self._seek_out is None:
            self._seek_out = (ctypes.c_int32 * 6)()
            self._seek_key = ctypes.create_string_buffer(4096)
        rc = lib.tpulsm_block_seek(
            self._data, len(self._data), target, len(target),
            ctypes.cast(self._seek_key,
                        ctypes.POINTER(ctypes.c_ubyte)), 4096,
            self._seek_out,
        )
        if rc < 0:
            return False  # oversized key / corrupt: Python path decides
        if rc == 0:
            self._cur = self._limit  # all keys < target
            return True
        o = self._seek_out
        self._cur = o[0]
        self._next_off = o[1]
        self._val_off = o[2]
        self._val_len = o[3]
        self._key = self._seek_key[: o[4]]  # slice copies only the key
        self._restart_idx = o[5]
        return True

    def seek_for_prev(self, target: bytes) -> None:
        """Position at last entry with key <= target."""
        self.seek(target)
        if not self.valid():
            self.seek_to_last()
            return
        if self._cmp(self._key, target) > 0:
            self.prev()

    def next(self) -> None:
        assert self.valid()
        if self._next_off >= self._limit:
            self._cur = self._limit
            return
        self._cur = self._next_off
        self._next_off, self._key = self._decode_at(self._cur, self._key)

    def prev(self) -> None:
        assert self.valid()
        target = self._cur
        if target == 0:
            self._cur = self._limit
            return
        # Find restart <= previous entry.
        while self._restart_idx > 0 and self._restart_point(self._restart_idx) >= target:
            self._restart_idx -= 1
        off = self._restart_point(self._restart_idx)
        key = b""
        prev_off = None
        while off < target:
            prev_off = off
            off, key = self._decode_at(off, key)
        if prev_off is None:
            self._cur = self._limit
            return
        # Re-decode at prev_off to set value span correctly.
        self._cur = prev_off
        # key currently holds the key at prev_off? No: loop decoded up to
        # `target`, and `key` is the key of the *last decoded* entry, which is
        # the one starting at prev_off.
        self._key = key
        # _decode_at already set value span during the final decode.
        self._next_off = target

    def entries(self):
        """Yield (key, value) from current position to end."""
        while self.valid():
            yield self._key, self.value()
            self.next()
