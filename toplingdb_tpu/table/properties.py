"""TableProperties: per-SST metadata stored in a meta block.

Analogue of the reference's TableProperties / meta_blocks.cc
(table/table_properties.cc in /root/reference). `raw_key_size` /
`raw_value_size` feed compaction stats and the distributed-compaction
result accounting (reference compaction_executor.h:120-158).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from toplingdb_tpu.table.block import BlockBuilder, BlockIter


@dataclass
class TableProperties:
    num_entries: int = 0
    num_deletions: int = 0
    num_merge_operands: int = 0
    num_range_deletions: int = 0
    raw_key_size: int = 0
    raw_value_size: int = 0
    data_size: int = 0
    index_size: int = 0
    filter_size: int = 0
    num_data_blocks: int = 0
    comparator_name: str = ""
    filter_policy_name: str = ""
    prefix_extractor_name: str = ""
    compression_name: str = ""
    creation_time: int = 0
    smallest_seqno: int = 0
    largest_seqno: int = 0
    column_family_id: int = 0
    column_family_name: str = ""
    # 1 when the filter block holds whole user keys (it may ALSO hold
    # prefixes when prefix_extractor_name is set); 0 = prefix-only filter.
    whole_key_filtering: int = 1
    index_type: str = "binary"  # 'binary' | 'two_level' (partitioned)
    user_collected: dict[str, bytes] = field(default_factory=dict)

    _INT_FIELDS = (
        "num_entries", "num_deletions", "num_merge_operands",
        "num_range_deletions", "raw_key_size", "raw_value_size", "data_size",
        "index_size", "filter_size", "num_data_blocks", "creation_time",
        "smallest_seqno", "largest_seqno", "column_family_id",
        "whole_key_filtering",
    )
    _STR_FIELDS = ("comparator_name", "filter_policy_name",
                   "prefix_extractor_name", "compression_name",
                   "column_family_name", "index_type")

    def encode_block(self) -> bytes:
        b = BlockBuilder(restart_interval=1)
        items: list[tuple[bytes, bytes]] = []
        for f in self._INT_FIELDS:
            items.append((f"tpulsm.{f}".encode(), str(getattr(self, f)).encode()))
        for f in self._STR_FIELDS:
            items.append((f"tpulsm.{f}".encode(), getattr(self, f).encode()))
        for k, v in self.user_collected.items():
            items.append((f"user.{k}".encode(), v))
        for k, v in sorted(items):
            b.add(k, v)
        return b.finish()

    @staticmethod
    def decode_block(data: bytes) -> "TableProperties":
        from toplingdb_tpu.db.dbformat import BYTEWISE
        from toplingdb_tpu.utils.status import Corruption

        props = TableProperties()
        it = BlockIter(data, BYTEWISE.compare)
        it.seek_to_first()
        for k, v in it.entries():
            ks = k.decode(errors="replace")
            if ks.startswith("tpulsm."):
                name = ks[len("tpulsm."):]
                if name in TableProperties._INT_FIELDS:
                    try:
                        setattr(props, name, int(v))
                    except ValueError as e:
                        raise Corruption(f"bad table property {ks}: {v!r}") from e
                elif name in TableProperties._STR_FIELDS:
                    setattr(props, name, v.decode(errors="replace"))
            elif ks.startswith("user."):
                props.user_collected[ks[len("user."):]] = v
        return props
