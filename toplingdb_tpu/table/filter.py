"""Filter policies: bloom and ribbon-style.

Role matches the reference's FullFilterBlock bloom/ribbon
(util/bloom_impl.h, util/ribbon_* in /root/reference): a whole-file filter
over user keys, probed before any index/data-block IO on point lookups.
Implementation is our own: cache-line-free simple bloom with double hashing
derived from xxh64 (filters are built once per SST and probed on Get).
"""

from __future__ import annotations

import math

from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.crc32c import xxh64
from toplingdb_tpu.utils import errors as _errors


class FilterPolicy:
    def name(self) -> str:
        raise NotImplementedError

    def create_filter(self, keys: list[bytes]) -> bytes:
        raise NotImplementedError

    def key_may_match(self, key: bytes, filter_data: bytes) -> bool:
        raise NotImplementedError


class BloomFilterPolicy(FilterPolicy):
    """Classic bloom with k probes via double hashing.

    Layout: varint32 num_bits | 1B num_probes | bit array.
    """

    def __init__(self, bits_per_key: float = 10.0):
        self.bits_per_key = bits_per_key
        self.num_probes = max(1, min(30, int(round(bits_per_key * math.log(2)))))

    def name(self) -> str:
        return f"tpulsm.BloomFilter:{self.bits_per_key}"

    def _hashes(self, key: bytes, num_bits: int, num_probes: int):
        h = xxh64(key, 0xA0761D64)
        h1 = h & 0xFFFFFFFFFFFFFFFF
        h2 = ((h >> 33) | (h << 31)) & 0xFFFFFFFFFFFFFFFF | 1
        for i in range(num_probes):
            yield ((h1 + i * h2) & 0xFFFFFFFFFFFFFFFF) % num_bits

    def create_filter(self, keys: list[bytes]) -> bytes:
        n = max(1, len(keys))
        num_bits = max(64, int(n * self.bits_per_key))
        num_bytes = (num_bits + 7) // 8
        num_bits = num_bytes * 8
        bits = bytearray(num_bytes)
        for k in keys:
            for b in self._hashes(k, num_bits, self.num_probes):
                bits[b >> 3] |= 1 << (b & 7)
        out = bytearray()
        out += coding.encode_varint32(num_bits)
        out.append(self.num_probes)
        out += bits
        return bytes(out)

    def key_may_match(self, key: bytes, filter_data: bytes) -> bool:
        if not filter_data:
            return True
        try:
            num_bits, off = coding.decode_varint32(filter_data, 0)
            num_probes = filter_data[off]
            bits = memoryview(filter_data)[off + 1 :]
            if num_bits == 0 or len(bits) * 8 < num_bits:
                return True
            for b in self._hashes(key, num_bits, num_probes):
                if not (bits[b >> 3] >> (b & 7)) & 1:
                    return False
            return True
        except Exception as e:
            _errors.swallow(reason="bloom-corrupt-fail-open", exc=e)
            return True  # corrupt filter: fail open


class BlockedBloomFilterPolicy(FilterPolicy):
    """Cache-line blocked bloom (the reference's FastLocalBloom role,
    util/bloom_impl.h:FastLocalBloomImpl): every key's probes land in ONE
    64-byte line, so a filter check costs one DRAM access instead of
    num_probes scattered ones — the standard bloom's ~6 random misses
    dominated the hot Get chain at bench scale.

    Layout: varint32 num_lines | 1B num_probes | num_lines * 64B lines.
    Line = h % num_lines; in-line bits via double hashing mod 512.
    """

    LINE_BYTES = 64
    LINE_BITS = 512

    def __init__(self, bits_per_key: float = 10.0):
        self.bits_per_key = bits_per_key
        self.num_probes = max(1, min(30,
                                     int(round(bits_per_key * math.log(2)))))

    def name(self) -> str:
        return f"tpulsm.BlockedBloom:{self.bits_per_key}"

    def _line_and_bits(self, key: bytes, num_lines: int, num_probes: int):
        h = xxh64(key, 0xA0761D64)
        h1 = h & 0xFFFFFFFFFFFFFFFF
        h2 = ((h >> 33) | (h << 31)) & 0xFFFFFFFFFFFFFFFF | 1
        line = h1 % num_lines
        bits = [((h1 + (i + 1) * h2) & 0xFFFFFFFFFFFFFFFF) % self.LINE_BITS
                for i in range(num_probes)]
        return line, bits

    def create_filter(self, keys: list[bytes]) -> bytes:
        n = max(1, len(keys))
        num_lines = max(1, (int(n * self.bits_per_key) + self.LINE_BITS - 1)
                        // self.LINE_BITS)
        data = bytearray(num_lines * self.LINE_BYTES)
        for k in keys:
            line, bits = self._line_and_bits(k, num_lines, self.num_probes)
            base = line * self.LINE_BYTES
            for b in bits:
                data[base + (b >> 3)] |= 1 << (b & 7)
        out = bytearray()
        out += coding.encode_varint32(num_lines)
        out.append(self.num_probes)
        out += data
        return bytes(out)

    def key_may_match(self, key: bytes, filter_data: bytes) -> bool:
        if not filter_data:
            return True
        try:
            num_lines, off = coding.decode_varint32(filter_data, 0)
            num_probes = filter_data[off]
            data = memoryview(filter_data)[off + 1:]
            if num_lines == 0 or len(data) < num_lines * self.LINE_BYTES:
                return True
            line, bits = self._line_and_bits(key, num_lines, num_probes)
            base = line * self.LINE_BYTES
            for b in bits:
                if not (data[base + (b >> 3)] >> (b & 7)) & 1:
                    return False
            return True
        except Exception as e:
            _errors.swallow(reason="blocked-bloom-corrupt-fail-open", exc=e)
            return True  # corrupt filter: fail open


def filter_probe(policy: FilterPolicy | None, filter_data: bytes | None,
                 whole_key_filtering: bool, prefix_extractor,
                 user_key: bytes) -> bool:
    """The point-lookup filter probe shared by every table reader: whole-key
    probe normally; prefix probe when the file holds a prefix-only filter
    (whole_key_filtering=0 in its properties). Fails open when the filter or
    a needed extractor is unavailable."""
    if policy is None or filter_data is None:
        return True
    if not whole_key_filtering:
        pe = prefix_extractor
        if pe is None or not pe.in_domain(user_key):
            return True
        return policy.key_may_match(pe.transform(user_key), filter_data)
    return policy.key_may_match(user_key, filter_data)


def build_filter_block_native(lib, bp: FilterPolicy, key_buf, offs,
                              uk_lens, n: int) -> bytes:
    """The filter-block bytes for n user keys held columnar (numpy
    buffers) — ONE implementation of the wire layout shared by the
    columnar and zip writers. Native fast path per policy kind; the
    Python fallback builds the SAME layout via the policy itself, so the
    data can never mismatch the recorded filter_policy_name (a classic
    layout under a BlockedBloom name would silently fail open on every
    probe)."""
    import numpy as np

    from toplingdb_tpu import native

    name = bp.name()
    if lib is not None and n:
        o = np.ascontiguousarray(offs, dtype=np.int32)
        ln = np.ascontiguousarray(uk_lens, dtype=np.int32)
        if name.startswith("tpulsm.BlockedBloom") and \
                hasattr(lib, "tpulsm_bloom_build_blocked"):
            num_lines = max(1, (int(n * bp.bits_per_key) + 511) // 512)
            bits = np.zeros(num_lines * 64, dtype=np.uint8)
            lib.tpulsm_bloom_build_blocked(
                native.np_u8p(key_buf), native.np_i32p(o),
                native.np_i32p(ln), n, num_lines, bp.num_probes,
                native.np_u8p(bits))
            return (coding.encode_varint32(num_lines)
                    + bytes([bp.num_probes]) + bits.tobytes())
        if name.startswith("tpulsm.BloomFilter") and \
                hasattr(lib, "tpulsm_bloom_build"):
            num_bits = max(64, int(n * bp.bits_per_key))
            num_bytes = (num_bits + 7) // 8
            num_bits = num_bytes * 8
            bits = np.zeros(num_bytes, dtype=np.uint8)
            lib.tpulsm_bloom_build(
                native.np_u8p(key_buf), native.np_i32p(o),
                native.np_i32p(ln), n, num_bits, bp.num_probes,
                native.np_u8p(bits))
            return (coding.encode_varint32(num_bits)
                    + bytes([bp.num_probes]) + bits.tobytes())
    keys = [bytes(key_buf[int(offs[i]): int(offs[i]) + int(uk_lens[i])])
            for i in range(n)]
    return bp.create_filter(keys)


def filter_policy_from_name(name: str) -> FilterPolicy | None:
    if name.startswith("tpulsm.BloomFilter:"):
        return BloomFilterPolicy(float(name.split(":", 1)[1]))
    if name.startswith("tpulsm.BlockedBloom:"):
        return BlockedBloomFilterPolicy(float(name.split(":", 1)[1]))
    return None
