"""Filter policies: bloom and ribbon-style.

Role matches the reference's FullFilterBlock bloom/ribbon
(util/bloom_impl.h, util/ribbon_* in /root/reference): a whole-file filter
over user keys, probed before any index/data-block IO on point lookups.
Implementation is our own: cache-line-free simple bloom with double hashing
derived from xxh64 (filters are built once per SST and probed on Get).
"""

from __future__ import annotations

import math

from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.crc32c import xxh64


class FilterPolicy:
    def name(self) -> str:
        raise NotImplementedError

    def create_filter(self, keys: list[bytes]) -> bytes:
        raise NotImplementedError

    def key_may_match(self, key: bytes, filter_data: bytes) -> bool:
        raise NotImplementedError


class BloomFilterPolicy(FilterPolicy):
    """Classic bloom with k probes via double hashing.

    Layout: varint32 num_bits | 1B num_probes | bit array.
    """

    def __init__(self, bits_per_key: float = 10.0):
        self.bits_per_key = bits_per_key
        self.num_probes = max(1, min(30, int(round(bits_per_key * math.log(2)))))

    def name(self) -> str:
        return f"tpulsm.BloomFilter:{self.bits_per_key}"

    def _hashes(self, key: bytes, num_bits: int, num_probes: int):
        h = xxh64(key, 0xA0761D64)
        h1 = h & 0xFFFFFFFFFFFFFFFF
        h2 = ((h >> 33) | (h << 31)) & 0xFFFFFFFFFFFFFFFF | 1
        for i in range(num_probes):
            yield ((h1 + i * h2) & 0xFFFFFFFFFFFFFFFF) % num_bits

    def create_filter(self, keys: list[bytes]) -> bytes:
        n = max(1, len(keys))
        num_bits = max(64, int(n * self.bits_per_key))
        num_bytes = (num_bits + 7) // 8
        num_bits = num_bytes * 8
        bits = bytearray(num_bytes)
        for k in keys:
            for b in self._hashes(k, num_bits, self.num_probes):
                bits[b >> 3] |= 1 << (b & 7)
        out = bytearray()
        out += coding.encode_varint32(num_bits)
        out.append(self.num_probes)
        out += bits
        return bytes(out)

    def key_may_match(self, key: bytes, filter_data: bytes) -> bool:
        if not filter_data:
            return True
        try:
            num_bits, off = coding.decode_varint32(filter_data, 0)
            num_probes = filter_data[off]
            bits = memoryview(filter_data)[off + 1 :]
            if num_bits == 0 or len(bits) * 8 < num_bits:
                return True
            for b in self._hashes(key, num_bits, num_probes):
                if not (bits[b >> 3] >> (b & 7)) & 1:
                    return False
            return True
        except Exception:
            return True  # corrupt filter: fail open


def filter_probe(policy: FilterPolicy | None, filter_data: bytes | None,
                 whole_key_filtering: bool, prefix_extractor,
                 user_key: bytes) -> bool:
    """The point-lookup filter probe shared by every table reader: whole-key
    probe normally; prefix probe when the file holds a prefix-only filter
    (whole_key_filtering=0 in its properties). Fails open when the filter or
    a needed extractor is unavailable."""
    if policy is None or filter_data is None:
        return True
    if not whole_key_filtering:
        pe = prefix_extractor
        if pe is None or not pe.in_domain(user_key):
            return True
        return policy.key_may_match(pe.transform(user_key), filter_data)
    return policy.key_may_match(user_key, filter_data)


def filter_policy_from_name(name: str) -> FilterPolicy | None:
    if name.startswith("tpulsm.BloomFilter:"):
        return BloomFilterPolicy(float(name.split(":", 1)[1]))
    return None
