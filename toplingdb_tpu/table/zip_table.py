"""ZipTable: the searchable-compression SST format for cold levels.

The analogue of the reference's ToplingZipTable (the L2+ format of the
absent topling-rocks submodule; /root/reference/README.md:50-56 bills it as
"searchable compression": an FSA/succinct-trie key index + entropy-coded
values, so point lookups never decompress a 4KB block). This re-design
keeps the property that made it the reference's headline readrandom format
(4.28M ops/s vs 376K for BlockBasedTable, BASELINE.md rows 19-22) with
array-friendly structures instead of a trie:

  keys    a front-coded dictionary in groups of G: each group's head key is
          stored whole, followers as (shared-prefix len, suffix). Lookup =
          binary search over group heads + a <=G-entry in-group decode —
          no data blocks, no restart arrays, the whole dictionary stays
          resident as flat numpy arrays.
  values  compressed in mini-groups of VG with one ZSTD dictionary trained
          over the file's values (util/compression dict training role), so
          a point read decompresses ~1-4KB ONCE per group (cached) rather
          than a block per miss; groups that don't shrink are stored raw
          (per-group flag bit).

Shares filter / properties / range-del meta blocks and the footer shape
with the other formats; dispatched by footer magic ("tpulsmZT") through
table/factory.py. Builder surface matches TableBuilder (build_outputs /
flush compatible); target it at the bottommost level via
Options.bottommost_format = "zip".
"""

from __future__ import annotations

import os

import numpy as np

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.block import BlockBuilder, BlockIter
from toplingdb_tpu.table.builder import (
    METAINDEX_FILTER,
    METAINDEX_PROPERTIES,
    METAINDEX_RANGE_DEL,
    CompressionOptions,
    TableOptions,
)
from toplingdb_tpu.table.filter import filter_policy_from_name
from toplingdb_tpu.table.properties import TableProperties
from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption, NotSupported
from toplingdb_tpu.utils import errors as _errors

METAINDEX_PARAMS = b"tpulsm.zt.params"
METAINDEX_KEY_META = b"tpulsm.zt.k.meta"
METAINDEX_KEY_SFX = b"tpulsm.zt.k.sfx"
METAINDEX_KEY_GSO = b"tpulsm.zt.k.gso"
METAINDEX_VAL_LENS = b"tpulsm.zt.v.lens"
METAINDEX_VAL_GO = b"tpulsm.zt.v.go"
METAINDEX_VAL_FLAGS = b"tpulsm.zt.v.flags"
METAINDEX_VAL_DICT = b"tpulsm.zt.v.dict"
METAINDEX_VAL_BLOB = b"tpulsm.zt.v.blob"

_VERSION = 1
_FLAG_LENS32 = 1
_FLAG_HAS_DICT = 2
_FLAG_META16 = 4  # key meta is u16 pairs (some internal key > 255 bytes)

# Key-group width: binary search lands on a head, then decodes <= G-1
# follower suffixes. 16 balances in-group decode cost vs head overhead.
GROUP = 16
# Value mini-group target: ~2KB of raw value bytes per compressed unit.
VALUE_GROUP_TARGET = 2048


def zip_plane_enabled() -> bool:
    """TPULSM_ZIP_PLANE=0 restores the pure-Python zip paths everywhere:
    the numpy builder in write_tables_zip_columnar (and with it the
    pipeline's serial-zip fallback), PlaneIneligible scans, and
    Python-only Get (no native table handle)."""
    return os.environ.get("TPULSM_ZIP_PLANE", "1") != "0"


class ZipTableBuilder:
    """Same surface as TableBuilder (build_outputs/flush compatible)."""

    FOOTER_MAGIC = fmt.ZIP_MAGIC

    def __init__(self, wfile, icmp: InternalKeyComparator,
                 options: TableOptions | None = None,
                 column_family_id: int = 0, column_family_name: str = "",
                 creation_time: int = 0):
        self.opts = options or TableOptions()
        self._w = wfile
        self._icmp = icmp
        self._keys: list[bytes] = []
        self._vals: list[bytes] = []
        self._approx_bytes = 0
        self._filter_keys: list[bytes] = []
        self._last_filter_prefix: bytes | None = None
        self._range_del_block = BlockBuilder(restart_interval=1)
        self.props = TableProperties(
            comparator_name=icmp.user_comparator.name(),
            filter_policy_name=(
                self.opts.filter_policy.name() if self.opts.filter_policy
                else ""
            ),
            compression_name="zip",
            prefix_extractor_name=(
                self.opts.prefix_extractor.name()
                if getattr(self.opts, "prefix_extractor", None) else ""
            ),
            column_family_id=column_family_id,
            column_family_name=column_family_name,
            creation_time=creation_time,
            smallest_seqno=dbformat.MAX_SEQUENCE_NUMBER,
            whole_key_filtering=1 if self.opts.whole_key_filtering else 0,
        )
        self._last_key: bytes | None = None
        self._smallest: bytes | None = None
        self._largest: bytes | None = None
        self._finished = False
        self._collectors = [
            f.create() for f in self.opts.properties_collector_factories
        ]
        self.need_compaction = False

    @property
    def num_entries(self) -> int:
        return self.props.num_entries + self.props.num_range_deletions

    def file_size(self) -> int:
        return self._w.file_size() + self._approx_bytes

    @property
    def smallest_key(self) -> bytes | None:
        return self._smallest

    @property
    def largest_key(self) -> bytes | None:
        return self._largest

    def _track_bounds(self, ikey: bytes) -> None:
        if self._smallest is None or \
                self._icmp.compare(ikey, self._smallest) < 0:
            self._smallest = ikey
        if self._largest is None or \
                self._icmp.compare(ikey, self._largest) > 0:
            self._largest = ikey
        seq = dbformat.extract_seqno(ikey)
        self.props.smallest_seqno = min(self.props.smallest_seqno, seq)
        self.props.largest_seqno = max(self.props.largest_seqno, seq)

    def add(self, ikey: bytes, value: bytes) -> None:
        assert not self._finished
        if self._last_key is not None:
            assert self._icmp.compare(self._last_key, ikey) < 0
        if len(ikey) >= 1 << 16:
            raise NotSupported(
                "zip table keys are capped at 64KiB (front-coding meta "
                "is u16 at most); use the block format"
            )
        self._keys.append(ikey)
        self._vals.append(value)
        self._approx_bytes += len(ikey) + len(value) + 4
        self._last_key = ikey
        self._track_bounds(ikey)
        uk, seq_, t = dbformat.split_internal_key(ikey)
        if self.opts.filter_policy:
            if self.opts.whole_key_filtering:
                self._filter_keys.append(uk)
            pe = getattr(self.opts, "prefix_extractor", None)
            if pe is not None and pe.in_domain(uk):
                p = pe.transform(uk)
                if p != self._last_filter_prefix:
                    self._filter_keys.append(p)
                    self._last_filter_prefix = p
        for c in self._collectors:
            c.add_user_key(uk, value, t, seq_, self._approx_bytes)
        self.props.num_entries += 1
        self.props.raw_key_size += len(ikey)
        self.props.raw_value_size += len(value)
        if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
            self.props.num_deletions += 1
        elif t == ValueType.MERGE:
            self.props.num_merge_operands += 1

    def add_tombstone(self, begin_ikey: bytes, end_user_key: bytes) -> None:
        assert not self._finished
        self._range_del_block.add(begin_ikey, end_user_key)
        self.props.num_range_deletions += 1
        self._track_bounds(begin_ikey)
        end_ikey = dbformat.make_internal_key(
            end_user_key, dbformat.MAX_SEQUENCE_NUMBER,
            dbformat.VALUE_TYPE_FOR_SEEK,
        )
        if self._largest is None or \
                self._icmp.compare(end_ikey, self._largest) > 0:
            self._largest = end_ikey

    def _encode_keys(self) -> tuple[bytes, bytes, bytes, bool]:
        """(meta (plen,slen) pairs, sfx blob, gso u32[nG], meta16) —
        the front-coded key dictionary. Meta pairs are u8 unless any key
        exceeds 255 bytes (then u16, flagged in params)."""
        meta16 = any(len(k) > 255 for k in self._keys)
        cap = 0xFFFF if meta16 else 0xFF
        meta: list[int] = []
        sfx = bytearray()
        gso = []
        prev = b""
        for i, k in enumerate(self._keys):
            if i % GROUP == 0:
                gso.append(len(sfx))
                plen = 0
            else:
                mx = min(len(prev), len(k))
                plen = 0
                while plen < mx and prev[plen] == k[plen]:
                    plen += 1
                plen = min(plen, cap)
            meta.append(plen)
            meta.append(len(k) - plen)
            sfx += k[plen:]
            prev = k
        mraw = np.asarray(meta, dtype="<u2" if meta16 else np.uint8).tobytes()
        return (mraw, bytes(sfx),
                np.asarray(gso, dtype="<u4").tobytes(), meta16)

    def _encode_values(self):
        """(lens bytes, go u32[nVG+1], flags bitmask, dict, blob, vg,
        lens32)"""
        from toplingdb_tpu.utils import codecs

        n = len(self._vals)
        avg = (self.props.raw_value_size // n) if n else 1
        vg = max(1, min(256, VALUE_GROUP_TARGET // max(1, avg)))
        copts = getattr(self.opts, "compression_opts", None) \
            or CompressionOptions()
        compress = (self.opts.compression != fmt.NO_COMPRESSION
                    and codecs.available("zstd"))
        groups = [b"".join(self._vals[i:i + vg]) for i in range(0, n, vg)]
        zdict = b""
        if compress and copts.max_dict_bytes > 0 and len(groups) >= 8:
            zdict = codecs.zstd_train_dictionary(
                groups[:: max(1, len(groups) // 256)] or groups,
                copts.max_dict_bytes,
            )
        blob = bytearray()
        go = [0]
        flags = bytearray((len(groups) + 7) // 8)
        for gi, raw in enumerate(groups):
            payload = raw
            if compress and len(raw) >= 32:
                z = codecs.zstd_compress(
                    raw, copts.level if copts.level is not None else 3,
                    zdict)
                if len(z) < len(raw):
                    payload = z
                    flags[gi // 8] |= 1 << (gi % 8)
            blob += payload
            go.append(len(blob))
        lens32 = any(len(v) >= 1 << 16 for v in self._vals)
        lens = np.asarray([len(v) for v in self._vals],
                          dtype="<u4" if lens32 else "<u2").tobytes()
        if compress:
            self.props.compression_name = "zip+zstd"
        return (lens, np.asarray(go, dtype="<u4").tobytes(), bytes(flags),
                zdict, bytes(blob), vg, lens32)

    def finish(self) -> TableProperties:
        assert not self._finished
        for c in self._collectors:
            self.props.user_collected.update(c.finish())
            if c.need_compact():
                self.need_compaction = True
        kmeta, ksfx, kgso, meta16 = self._encode_keys()
        vlens, vgo, vflags, vdict, vblob, vg, lens32 = self._encode_values()
        n = len(self._keys)
        self._keys = []
        self._vals = []
        fdata = None
        if self.opts.filter_policy and self._filter_keys:
            fdata = self.opts.filter_policy.create_filter(self._filter_keys)
        rd_raw = None if self._range_del_block.empty() \
            else self._range_del_block.finish()
        _write_zip_file(
            self._w, self.props, n, vg, meta16, lens32,
            kmeta, ksfx, kgso, vlens, vgo, vflags, vdict, vblob,
            fdata, rd_raw,
        )
        self._finished = True
        return self.props


def _write_zip_file(w, props, n, vg, meta16, lens32, kmeta, ksfx, kgso,
                    vlens, vgo, vflags, vdict, vblob, filter_data,
                    range_del_raw) -> None:
    """Write the zip-file sections + metaindex + footer (shared by the
    per-entry builder and the vectorized columnar writer, so the two can't
    diverge byte-wise). Mutates props size fields."""
    meta_entries = []
    metaindex = BlockBuilder(restart_interval=1)
    flags = (_FLAG_LENS32 if lens32 else 0) | \
        (_FLAG_HAS_DICT if vdict else 0) | \
        (_FLAG_META16 if meta16 else 0)
    params = b"".join(coding.encode_fixed32(x) for x in (
        _VERSION, GROUP, vg, n, flags,
    ))
    for name, payload in (
        (METAINDEX_PARAMS, params),
        (METAINDEX_KEY_META, kmeta),
        (METAINDEX_KEY_SFX, ksfx),
        (METAINDEX_VAL_LENS, vlens),
        (METAINDEX_VAL_GO, vgo),
        (METAINDEX_VAL_FLAGS, vflags),
        (METAINDEX_VAL_DICT, vdict),
        (METAINDEX_VAL_BLOB, vblob),
    ):
        if name == METAINDEX_VAL_DICT and not vdict:
            continue
        h = fmt.write_block(w, payload, fmt.NO_COMPRESSION)
        meta_entries.append((name, h))
        if name == METAINDEX_VAL_BLOB:
            props.data_size = len(vblob)
    props.num_data_blocks = (n + vg - 1) // vg if n else 0
    if filter_data is not None:
        fh = fmt.write_block(w, filter_data, fmt.NO_COMPRESSION)
        props.filter_size = len(filter_data)
        meta_entries.append((METAINDEX_FILTER, fh))
    if range_del_raw is not None:
        rh = fmt.write_block(w, range_del_raw, fmt.NO_COMPRESSION)
        meta_entries.append((METAINDEX_RANGE_DEL, rh))
    props.index_size = len(kgso)
    pblock = props.encode_block()
    ph = fmt.write_block(w, pblock, fmt.NO_COMPRESSION)
    meta_entries.append((METAINDEX_PROPERTIES, ph))
    for name, handle in sorted(meta_entries):
        metaindex.add(name, handle.encode())
    mih = fmt.write_block(w, metaindex.finish(), fmt.NO_COMPRESSION)
    ih = fmt.write_block(w, kgso, fmt.NO_COMPRESSION)
    w.append(fmt.Footer(mih, ih, magic=fmt.ZIP_MAGIC).encode())
    w.flush()


from toplingdb_tpu.table.single_fast import _Mem  # shared in-memory file view


class ZipTableReader:
    """Same surface as the other readers; the key dictionary and value
    directory stay resident, value groups decompress lazily (cached)."""

    FOOTER_MAGIC = fmt.ZIP_MAGIC

    def __init__(self, rfile, icmp: InternalKeyComparator,
                 options: TableOptions | None = None, block_cache=None,
                 cache_key_prefix: bytes = b""):
        self.opts = options or TableOptions()
        self._icmp = icmp
        size = rfile.size()
        # The file bytes live only for this constructor: every section is
        # copied out below, so keeping them would double resident memory.
        data = rfile.read(0, size)
        rfile.close()
        mem = _Mem(data)
        self.footer = fmt.Footer.decode(data, self.FOOTER_MAGIC)
        meta = fmt.read_block(mem, self.footer.metaindex_handle,
                              self.opts.verify_checksums)
        mit = BlockIter(meta, dbformat.BYTEWISE.compare)
        mit.seek_to_first()
        self._meta_handles = {
            k: fmt.BlockHandle.decode_exact(v) for k, v in mit.entries()
        }
        vc = self.opts.verify_checksums

        def sect(name, required=True):
            h = self._meta_handles.get(name)
            if h is None:
                if required:
                    raise Corruption(f"zip table missing section {name!r}")
                return b""
            return fmt.read_block(mem, h, vc)

        params = sect(METAINDEX_PARAMS)
        if len(params) < 20:
            raise Corruption("zip table params truncated")
        ver = coding.decode_fixed32(params, 0)
        if ver != _VERSION:
            raise Corruption(f"zip table version {ver} unsupported")
        self.G = coding.decode_fixed32(params, 4)
        self.VG = coding.decode_fixed32(params, 8)
        self.n = coding.decode_fixed32(params, 12)
        flags = coding.decode_fixed32(params, 16)
        self._kmeta = np.frombuffer(
            sect(METAINDEX_KEY_META),
            dtype="<u2" if flags & _FLAG_META16 else np.uint8,
        )
        self._ksfx = sect(METAINDEX_KEY_SFX)
        # Group head offsets double as the footer's index block.
        self._kgso = np.frombuffer(
            fmt.read_block(mem, self.footer.index_handle, vc), dtype="<u4")
        self._vlens = np.frombuffer(
            sect(METAINDEX_VAL_LENS),
            dtype="<u4" if flags & _FLAG_LENS32 else "<u2",
        )
        self._vgo = np.frombuffer(sect(METAINDEX_VAL_GO), dtype="<u4")
        self._vflags = np.frombuffer(sect(METAINDEX_VAL_FLAGS),
                                     dtype=np.uint8)
        self._vdict = sect(METAINDEX_VAL_DICT, required=False) \
            if flags & _FLAG_HAS_DICT else b""
        self._vblob = sect(METAINDEX_VAL_BLOB)
        # Per-group suffix start offsets; entry suffix offsets derive from
        # one global exclusive cumsum of slen (kmeta odd bytes).
        slen = self._kmeta[1::2].astype(np.int64)
        self._soff = np.cumsum(slen) - slen
        self.properties = TableProperties()
        ph = self._meta_handles.get(METAINDEX_PROPERTIES)
        if ph is not None:
            self.properties = TableProperties.decode_block(
                fmt.read_block(mem, ph, vc))
        self._filter_data = None
        self._filter_policy = None
        fh = self._meta_handles.get(METAINDEX_FILTER)
        if fh is not None:
            self._filter_data = fmt.read_block(mem, fh, vc)
            self._filter_policy = filter_policy_from_name(
                self.properties.filter_policy_name)
        rh = self._meta_handles.get(METAINDEX_RANGE_DEL)
        self._range_del_data = fmt.read_block(mem, rh, vc) \
            if rh is not None else None
        self._nG = len(self._kgso)
        from toplingdb_tpu.utils.slice_transform import resolve_file_extractor

        self._resolved_pe = resolve_file_extractor(
            getattr(self.opts, "prefix_extractor", None),
            self.properties.prefix_extractor_name,
        )

    # --- key access ---

    def _head(self, g: int) -> bytes:
        o = int(self._kgso[g])
        return self._ksfx[o: o + int(self._kmeta[2 * g * self.G + 1])]

    def key_at(self, i: int) -> bytes:
        """Decode entry i's internal key (walks its group prefix chain)."""
        g = i // self.G
        base = g * self.G
        k = self._head(g)
        for j in range(base + 1, i + 1):
            pl = int(self._kmeta[2 * j])
            o = int(self._soff[j])
            k = k[:pl] + self._ksfx[o: o + int(self._kmeta[2 * j + 1])]
        return k

    def group_keys(self, g: int) -> list[bytes]:
        """All internal keys of group g, decoded in one pass."""
        base = g * self.G
        end = min(base + self.G, self.n)
        k = self._head(g)
        out = [k]
        for j in range(base + 1, end):
            pl = int(self._kmeta[2 * j])
            o = int(self._soff[j])
            k = k[:pl] + self._ksfx[o: o + int(self._kmeta[2 * j + 1])]
            out.append(k)
        return out

    def _group_for(self, target: bytes) -> int:
        """Last group whose head <= target (internal order), or 0."""
        lo, hi = 0, self._nG - 1
        cmp = self._icmp.compare
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if cmp(self._head(mid), target) <= 0:
                lo = mid
            else:
                hi = mid - 1
        return lo

    # --- value access ---

    def _value_group(self, vg: int) -> tuple[bytes, np.ndarray]:
        """(decoded group payload, in-group exclusive offsets). Stateless —
        the reader is shared across threads via TableCache, so caching
        lives in each (single-threaded) iterator instead."""
        payload = self._vblob[int(self._vgo[vg]): int(self._vgo[vg + 1])]
        if len(self._vflags) and self._vflags[vg // 8] & (1 << (vg % 8)):
            from toplingdb_tpu.utils import codecs

            payload = codecs.zstd_decompress(bytes(payload), self._vdict)
        base = vg * self.VG
        ls = self._vlens[base: base + self.VG].astype(np.int64)
        return payload, np.concatenate([[0], np.cumsum(ls)])

    def value_at(self, i: int) -> bytes:
        """Uncached single-value decode (prefer iterator.value(), which
        caches the group across adjacent reads)."""
        payload, offs = self._value_group(i // self.VG)
        off = int(offs[i % self.VG])
        return bytes(payload[off: off + int(self._vlens[i])])

    # --- reader surface ---

    def key_may_match(self, user_key: bytes) -> bool:
        if self._filter_data is None or self._filter_policy is None:
            return True
        if self.properties.whole_key_filtering:
            return self._filter_policy.key_may_match(user_key,
                                                     self._filter_data)
        pe = self._resolved_pe
        if pe is not None and pe.in_domain(user_key):
            return self._filter_policy.key_may_match(pe.transform(user_key),
                                                     self._filter_data)
        return True

    def new_iterator(self, preread=None) -> "ZipTableIterator":
        """`preread`: async read plane preload — {value-group ordinal →
        completion token} whose wait() returns `_value_group(vg)`'s
        result, so mini-group zstd inflates ran on a reader ring while
        the request thread was elsewhere (env/async_reads.py)."""
        return ZipTableIterator(self, preload=preread)

    def plan_value_groups(self, seek_ikeys) -> list[int]:
        """Async read plane planner: the value-group ordinals the entries
        landed on by each internal seek key live in — deduplicated, only
        groups whose decode is non-trivial (compressed) included."""
        out: list[int] = []
        seen: set[int] = set()
        for ik in seek_ikeys:
            i = self.entry_lower_bound(ik)
            if not 0 <= i < self.n:
                continue
            vg = i // self.VG
            if vg in seen:
                continue
            seen.add(vg)
            if len(self._vflags) and self._vflags[vg // 8] & (1 << (vg % 8)):
                out.append(vg)
        return out

    def range_del_entries(self):
        if self._range_del_data is None:
            return []
        it = BlockIter(self._range_del_data, self._icmp.compare)
        it.seek_to_first()
        return list(it.entries())

    def approximate_offset_of(self, ikey: bytes) -> int:
        if not self.n:
            return 0
        g = self._group_for(ikey)
        return int(self._vgo[min(g * self.G // self.VG,
                                 len(self._vgo) - 1)])

    def anchors(self, max_anchors: int = 32):
        if not self.n:
            return []
        step = max(1, self.n // max_anchors)
        return [self.key_at(i)
                for i in range(0, self.n, step)][:max_anchors]

    # --- batched data-plane surface (native kernels) ---

    def scan_native_ready(self) -> bool:
        """True when scan_columnar can serve the scan plane (native bulk
        decoders present and the zip plane not knob-disabled)."""
        if not (zip_plane_enabled() and self.n):
            return False
        from toplingdb_tpu import native

        lib = native.lib()
        return (
            lib is not None
            and getattr(lib, "tpulsm_zip_decode_keys", None) is not None
            and getattr(lib, "tpulsm_zip_group_decode", None) is not None
        )

    def _scan_sections(self):
        """Zero-copy u8 views over the resident sections plus per-entry
        key-length cumsums — the operands the native kernels take. Built
        once; the views pin the backing bytes for the handle's lifetime."""
        s = getattr(self, "_scan_sect", None)
        if s is None:
            def u8(b):
                a = (b.view(np.uint8) if isinstance(b, np.ndarray)
                     else np.frombuffer(b, dtype=np.uint8))
                return a if len(a) else np.zeros(1, dtype=np.uint8)

            kl = (self._kmeta[0::2].astype(np.int64)
                  + self._kmeta[1::2].astype(np.int64))
            s = {
                "kmeta": u8(self._kmeta), "ksfx": u8(self._ksfx),
                "kgso": u8(self._kgso), "vlens": u8(self._vlens),
                "vgo": u8(self._vgo), "vflags": u8(self._vflags),
                "vdict": u8(self._vdict), "vblob": u8(self._vblob),
                "kcum": np.concatenate([[0], np.cumsum(kl)]),
            }
            self._scan_sect = s
        return s

    def entry_lower_bound(self, target: bytes) -> int:
        """First entry index whose internal key >= target (n past end)."""
        if not self.n:
            return 0
        g = self._group_for(target)
        base = g * self.G
        cmp = self._icmp.compare
        for j, k in enumerate(self.group_keys(g)):
            if cmp(k, target) >= 0:
                return base + j
        return min(base + self.G, self.n)

    def scan_columnar(self, e0: int, e1: int):
        """Bulk-decode entries [e0, e1) into columnar slabs: (key_buf,
        key_offs, key_lens, val_buf, val_offs, val_lens), int64 offsets
        into the two uint8 slabs. Values come straight out of compressed
        groups via tpulsm_zip_group_decode — no whole-file inflate, no
        per-entry Python. Callers gate on scan_native_ready()."""
        from toplingdb_tpu import native
        from toplingdb_tpu.utils import telemetry as tele

        lib = native.lib()
        s = self._scan_sections()
        e0 = max(0, int(e0))
        e1 = min(self.n, int(e1))
        cnt = e1 - e0
        if cnt <= 0:
            z8 = np.zeros(0, dtype=np.uint8)
            z64 = np.zeros(0, dtype=np.int64)
            return z8, z64, z64, z8, z64, z64
        kcap = int(s["kcum"][e1] - s["kcum"][e0])
        key_out = np.empty(kcap, dtype=np.uint8)
        key_offs = np.empty(cnt, dtype=np.int64)
        key_lens = np.empty(cnt, dtype=np.int64)
        rc = lib.tpulsm_zip_decode_keys(
            native.np_u8p(s["kmeta"]), self._kmeta.nbytes,
            1 if self._kmeta.dtype.itemsize == 2 else 0,
            native.np_u8p(s["ksfx"]), len(self._ksfx),
            native.np_u8p(s["kgso"]), self._kgso.nbytes, self.n, self.G,
            e0, e1, native.np_u8p(key_out), kcap, native.np_i64p(key_offs),
            native.np_i64p(key_lens), 0)
        if rc != kcap:
            raise Corruption(f"zip key decode failed (rc={rc})")
        g0 = e0 // self.VG
        g1 = (e1 + self.VG - 1) // self.VG
        first = g0 * self.VG
        last = min(g1 * self.VG, self.n)
        ls = self._vlens[first:last].astype(np.int64)
        gsz = np.add.reduceat(ls, np.arange(0, len(ls), self.VG))
        raw_offs = np.ascontiguousarray(
            np.concatenate([[0], np.cumsum(gsz)]), dtype=np.int64)
        vcap = int(raw_offs[-1])
        val_out = np.empty(max(1, vcap), dtype=np.uint8)
        with tele.span("zip.group_decode", groups=g1 - g0, nbytes=vcap):
            rc2 = lib.tpulsm_zip_group_decode(
                native.np_u8p(s["vblob"]), len(self._vblob),
                native.np_u8p(s["vgo"]), self._vgo.nbytes,
                native.np_u8p(s["vflags"]), self._vflags.nbytes,
                native.np_u8p(s["vdict"]), len(self._vdict), g0, g1,
                native.np_i64p(raw_offs), native.np_u8p(val_out), vcap)
        if rc2 != vcap:
            raise Corruption(f"zip group decode failed (rc={rc2})")
        voff_all = np.cumsum(ls) - ls
        val_offs = np.ascontiguousarray(voff_all[e0 - first: e1 - first])
        val_lens = np.ascontiguousarray(ls[e0 - first: e1 - first])
        return (key_out, key_offs, key_lens, val_out[:vcap], val_offs,
                val_lens)

    def native_get_handle(self, smallest_uk: bytes, largest_uk: bytes):
        """Handle for the native point-read engine. Unlike the block
        reader (which hands C an index copy + fd), the zip sections are
        BORROWED by C — the finalize closure pins them until
        tpulsm_table_handle_free runs. Ineligible tables (plane disabled,
        range tombstones, non-bytewise comparator, empty file) get an
        eligible=0 handle so the chain walk FALLBACKs on contact, same
        contract as reader.py."""
        h = getattr(self, "_nget_handle", False)
        if h is not False:
            return h
        import ctypes
        import weakref

        from toplingdb_tpu import native
        from toplingdb_tpu.table.reader import _NGET_ID

        cl = native.lib()
        if cl is None or not hasattr(cl, "tpulsm_zip_table_handle_new"):
            self._nget_handle = None
            return None
        eligible = (
            zip_plane_enabled()
            and self.n > 0
            and self._range_del_data is None
            and self._icmp.user_comparator.name()
            == "tpulsm.BytewiseComparator"
        )
        filt = b""
        filter_kind = 0
        fname = str(self.properties.filter_policy_name)
        if (eligible and self._filter_data is not None
                and self.properties.whole_key_filtering):
            if fname.startswith("tpulsm.BloomFilter"):
                filt = self._filter_data
            elif fname.startswith("tpulsm.BlockedBloom"):
                filt = self._filter_data
                filter_kind = 1
        u8 = ctypes.POINTER(ctypes.c_uint8)

        def buf(b):
            return ctypes.cast(ctypes.c_char_p(bytes(b)), u8)

        keep = None
        if eligible:
            s = self._scan_sections()
            keep = (s, filt)
            h = cl.tpulsm_zip_table_handle_new(
                next(_NGET_ID), 1 | (filter_kind << 1), self.G, self.VG,
                self.n, 1 if self._kmeta.dtype.itemsize == 2 else 0,
                1 if self._vlens.dtype.itemsize == 4 else 0,
                native.np_u8p(s["kmeta"]), self._kmeta.nbytes,
                native.np_u8p(s["ksfx"]), len(self._ksfx),
                native.np_u8p(s["kgso"]), self._kgso.nbytes,
                native.np_u8p(s["vlens"]), self._vlens.nbytes,
                native.np_u8p(s["vgo"]), self._vgo.nbytes,
                native.np_u8p(s["vflags"]), self._vflags.nbytes,
                native.np_u8p(s["vdict"]), len(self._vdict),
                native.np_u8p(s["vblob"]), len(self._vblob),
                buf(filt), len(filt),
                buf(smallest_uk), len(smallest_uk),
                buf(largest_uk), len(largest_uk),
            )
        else:
            h = cl.tpulsm_zip_table_handle_new(
                next(_NGET_ID), 0, 0, 0, 0, 0, 0,
                None, 0, None, 0, None, 0, None, 0, None, 0, None, 0,
                None, 0, None, 0, None, 0,
                buf(smallest_uk), len(smallest_uk),
                buf(largest_uk), len(largest_uk),
            )
        h = h or None
        self._nget_handle = h
        if h:
            weakref.finalize(self, _zip_handle_free,
                             cl.tpulsm_table_handle_free, h, keep)
        return h

    def close(self) -> None:
        pass


def _zip_handle_free(free_fn, h, _sections):
    # _sections pins the buffers C borrowed until the handle dies with it
    free_fn(h)


class ZipTableIterator:
    """Forward/backward iterator over one ZipTable (TableIterator shape)."""

    def __init__(self, r: ZipTableReader, preload: dict | None = None):
        self._r = r
        self._i = r.n
        self._gkeys: list[bytes] = []
        self._g = -1
        self._vg = -1
        self._vg_payload: bytes = b""
        self._vg_offs: np.ndarray | None = None
        # {vg → token} of ring-side _value_group decodes (async plane);
        # consumed once, then the sync decode path takes over.
        self._preload = preload

    def _load(self, g: int) -> None:
        if g != self._g:
            self._gkeys = self._r.group_keys(g)
            self._g = g

    def valid(self) -> bool:
        return 0 <= self._i < self._r.n

    def key(self) -> bytes:
        self._load(self._i // self._r.G)
        return self._gkeys[self._i % self._r.G]

    def value(self) -> bytes:
        r = self._r
        vg = self._i // r.VG
        if vg != self._vg:
            tok = self._preload.pop(vg, None) if self._preload else None
            if tok is not None:
                self._vg_payload, self._vg_offs = tok.wait()
            else:
                self._vg_payload, self._vg_offs = r._value_group(vg)
            self._vg = vg
        off = int(self._vg_offs[self._i % r.VG])
        return bytes(
            self._vg_payload[off: off + int(r._vlens[self._i])])

    def seek_to_first(self) -> None:
        self._i = 0

    def seek_to_last(self) -> None:
        self._i = self._r.n - 1

    def seek(self, target: bytes) -> None:
        r = self._r
        if not r.n:
            self._i = 0
            return
        g = r._group_for(target)
        self._load(g)
        cmp = r._icmp.compare
        base = g * r.G
        lo, hi = 0, len(self._gkeys)
        while lo < hi:
            mid = (lo + hi) // 2
            if cmp(self._gkeys[mid], target) < 0:
                lo = mid + 1
            else:
                hi = mid
        # lo == len(gkeys) lands on the next group's head ordinal, which is
        # > target by _group_for's choice; head(0) > target leaves i at 0.
        self._i = base + lo

    def seek_for_prev(self, target: bytes) -> None:
        self.seek(target)
        if not self.valid():
            self.seek_to_last()
            return
        if self._r._icmp.compare(self.key(), target) > 0:
            self.prev()

    def seek_ordinal(self, i: int) -> None:
        self._i = i

    def next(self) -> None:
        self._i += 1

    def prev(self) -> None:
        self._i -= 1

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()


def _zip_encode_segment_native(lib, kv, rows, ko_seg, ov_seg, fvl, K, n, vg,
                               compress, copts, meta16):
    """One output segment through the tpulsm_zip_* kernels. Returns the
    encoded sections (kmeta, ksfx, kgso, vlens, vgo, vblob, vflags, zdict,
    lens32) bit-identical to the numpy encoder below (parity-tested), or
    None when a kernel declines — the caller then re-encodes in Python."""
    from toplingdb_tpu import native
    from toplingdb_tpu.utils import telemetry as tele

    ko_seg = np.ascontiguousarray(ko_seg, dtype=np.int64)
    ov_seg = np.ascontiguousarray(ov_seg, dtype=np.int64)
    fvl = np.ascontiguousarray(fvl, dtype=np.int64)
    meta_out = np.empty(n * (4 if meta16 else 2), dtype=np.uint8)
    sfx_cap = n * K
    sfx_out = np.empty(max(1, sfx_cap), dtype=np.uint8)
    ngk = (n + GROUP - 1) // GROUP
    gso_out = np.empty(4 * ngk, dtype=np.uint8)
    with tele.span("zip.index_build", rows=n, groups=ngk):
        rc = lib.tpulsm_zip_encode_keys(
            native.np_u8p(kv.key_buf), len(kv.key_buf),
            native.np_i64p(ko_seg), n, K, native.np_i64p(ov_seg), GROUP,
            1 if meta16 else 0, native.np_u8p(meta_out),
            native.np_u8p(sfx_out), sfx_cap, native.np_u8p(gso_out))
    if rc < 0:
        return None
    voffs = np.ascontiguousarray(kv.val_offs[rows], dtype=np.int64)
    total_v = int(fvl.sum())
    ngv = (n + vg - 1) // vg
    mdb = int(getattr(copts, "max_dict_bytes", 0) or 0)
    lvl = copts.level if copts.level is not None else 3
    dict_out = np.zeros(max(1, mdb), dtype=np.uint8)
    blob_out = np.empty(max(1, total_v), dtype=np.uint8)
    go_out = np.empty(4 * (ngv + 1), dtype=np.uint8)
    flags_out = np.zeros((ngv + 7) // 8, dtype=np.uint8)
    om = np.zeros(2, dtype=np.int64)
    vb = kv.val_buf if len(kv.val_buf) else np.zeros(1, dtype=np.uint8)
    with tele.span("zip.encode", rows=n, groups=ngv,
                   compress=1 if compress else 0):
        rc2 = lib.tpulsm_zip_encode_values(
            native.np_u8p(vb), len(kv.val_buf), native.np_i64p(voffs),
            native.np_i64p(fvl), n, vg, 1 if compress else 0, int(lvl),
            mdb, native.np_u8p(dict_out), len(dict_out),
            native.np_u8p(blob_out), total_v, native.np_u8p(go_out),
            native.np_u8p(flags_out), native.np_i64p(om))
    if rc2 != ngv:
        return None
    lens32 = bool((fvl >= 1 << 16).any())
    vlens = fvl.astype("<u4" if lens32 else "<u2").tobytes()
    return (meta_out.tobytes(), sfx_out[:rc].tobytes(), gso_out.tobytes(),
            vlens, go_out.tobytes(), blob_out[: int(om[0])].tobytes(),
            flags_out.tobytes(), dict_out[: int(om[1])].tobytes(),
            lens32)


def write_tables_zip_columnar(env, dbname, new_file_number, icmp, options,
                              kv, order, trailer_override, vtypes, seqs,
                              tombstones, creation_time: int,
                              max_output_file_size: int = 2 ** 62,
                              column_family=(0, "default")):
    """Vectorized ZipTable emission from columnar buffers + a survivor
    order — the zip-format counterpart of write_tables_columnar, so device
    compactions emit searchable-compressed bottommost files without a
    per-entry Python loop. Byte-identical to feeding ZipTableBuilder the
    same stream through build_outputs (cut rule included; parity-tested).
    Uniform key length only; raises NotSupported otherwise (callers fall
    back to the per-entry path)."""
    from toplingdb_tpu import native
    from toplingdb_tpu.db import filename as _fn
    from toplingdb_tpu.utils import codecs
    from toplingdb_tpu.utils.status import NotSupported

    if getattr(options, "prefix_extractor", None) is not None:
        raise NotSupported("zip columnar writer: prefix extractors use the "
                           "per-entry path")
    if getattr(options, "properties_collector_factories", None):
        raise NotSupported("zip columnar writer: collectors use the "
                           "per-entry path")
    if not isinstance(order, np.ndarray):
        # Pipelined callers stream order chunks; the zip encoders work on
        # whole segments, so drain the feed first (the scan/merge stages
        # upstream still overlap with THIS call's encode work).
        chunks = [np.asarray(c, dtype=np.int64) for c in order]
        order = (np.concatenate(chunks) if chunks
                 else np.empty(0, np.int64))
    order = np.ascontiguousarray(order, dtype=np.int64)
    m = len(order)
    if m == 0 and not tombstones:
        return []
    lib = native.lib()
    use_native = (
        zip_plane_enabled() and lib is not None
        and getattr(lib, "tpulsm_zip_encode_keys", None) is not None
    )
    mat = None

    def _build_mat():
        # internal-key matrix with trailer overrides applied (Python
        # encoder path only; the native kernels patch trailers on the fly)
        nonlocal mat
        if mat is not None:
            return mat
        mat = kv.key_buf[ko[:, None] + np.arange(K)]
        has_ov = ov >= 0
        if has_ov.any():
            tb = (ov[:, None] >> (8 * np.arange(8))) & 0xFF
            mat[has_ov, K - 8:] = tb[has_ov].astype(np.uint8)
        return mat

    if m:
        if int(kv.key_lens.min()) != int(kv.key_lens.max()):
            raise NotSupported("zip columnar writer requires uniform keys")
        K = int(kv.key_lens[0])
        if K >= 1 << 16:
            raise NotSupported("zip table keys are capped at 64KiB")
        ko = kv.key_offs[order].astype(np.int64)
        ov = trailer_override[order]
        vl = kv.val_lens[order].astype(np.int64)
        cum = np.cumsum(K + vl + 4)  # builder.file_size() approximation
        newkey = np.ones(m, dtype=bool)
        if m > 1:
            nk_done = False
            if use_native:
                nk8 = np.empty(m, dtype=np.uint8)
                rc = lib.tpulsm_zip_newkey(
                    native.np_u8p(kv.key_buf), len(kv.key_buf),
                    native.np_i64p(ko), m, K - 8, native.np_u8p(nk8))
                if rc == m:
                    newkey = nk8.view(bool)
                    nk_done = True
                else:
                    use_native = False
            if not nk_done:
                _build_mat()
                newkey[1:] = (mat[1:, : K - 8]
                              != mat[:-1, : K - 8]).any(axis=1)
        nk_pos = np.flatnonzero(newkey)
    else:
        K = 0

    can_cut = m > 0 and not tombstones
    cuts = [0]
    if can_cut:
        s = 0
        while True:
            base = cum[s - 1] if s else 0
            i0 = int(np.searchsorted(cum, base + max_output_file_size,
                                     side="left")) + 1
            if i0 >= m:
                break
            j = int(np.searchsorted(nk_pos, i0, side="left"))
            if j >= len(nk_pos):
                break
            s = int(nk_pos[j])
            cuts.append(s)
    cuts.append(m)

    results = []
    written = []
    try:
        for fi in range(len(cuts) - 1):
            lo, hi = cuts[fi], cuts[fi + 1]
            rows = order[lo:hi]
            seg = slice(lo, hi)
            n = hi - lo
            props = TableProperties(
                comparator_name=icmp.user_comparator.name(),
                filter_policy_name=(
                    options.filter_policy.name() if options.filter_policy
                    else ""
                ),
                compression_name="zip",
                column_family_id=column_family[0],
                column_family_name=column_family[1],
                creation_time=creation_time,
                smallest_seqno=dbformat.MAX_SEQUENCE_NUMBER,
                whole_key_filtering=1 if options.whole_key_filtering else 0,
            )
            if n:
                fvl = vl[seg]
                meta16 = K > 255
                total_v = int(fvl.sum())
                props.raw_key_size = n * K
                props.raw_value_size = total_v
                avg = total_v // n
                vg = max(1, min(256, VALUE_GROUP_TARGET // max(1, avg)))
                copts = getattr(options, "compression_opts", None) \
                    or CompressionOptions()
                compress = (options.compression != fmt.NO_COMPRESSION
                            and codecs.available("zstd"))
                enc = None
                if use_native:
                    enc = _zip_encode_segment_native(
                        lib, kv, rows, ko[seg], ov[seg], fvl, K, n, vg,
                        compress, copts, meta16)
                if enc is not None:
                    (kmeta, ksfx, kgso_b, vlens, vgo, vblob, vflags_b,
                     zdict, lens32) = enc
                    smallest = kv.key_buf[
                        int(ko[lo]): int(ko[lo]) + K].tobytes()
                    largest = kv.key_buf[
                        int(ko[hi - 1]): int(ko[hi - 1]) + K].tobytes()
                    t0, tn = int(ov[lo]), int(ov[hi - 1])
                    if t0 >= 0:
                        smallest = (smallest[: K - 8]
                                    + t0.to_bytes(8, "little"))
                    if tn >= 0:
                        largest = (largest[: K - 8]
                                   + tn.to_bytes(8, "little"))
                else:
                    fmat = _build_mat()[seg]
                    # --- keys: front-coded groups of GROUP ---
                    pl = np.zeros(n, dtype=np.int64)
                    if n > 1:
                        eq = fmat[1:] == fmat[:-1]
                        all_eq = eq.all(axis=1)
                        pl[1:] = np.where(all_eq, K,
                                          np.argmin(eq, axis=1))
                    pl[np.arange(0, n, GROUP)] = 0
                    slen = K - pl
                    meta = np.empty(2 * n,
                                    dtype="<u2" if meta16 else np.uint8)
                    meta[0::2] = pl
                    meta[1::2] = slen
                    sfx = fmat[np.arange(K)[None, :] >= pl[:, None]]
                    soff = np.cumsum(slen) - slen
                    kgso = soff[::GROUP].astype("<u4")
                    # --- values (order-gathered flat bytes, VG groups) ---
                    if total_v:
                        vpos = np.repeat(
                            kv.val_offs[rows].astype(np.int64), fvl
                        ) + (np.arange(total_v)
                             - np.repeat(np.cumsum(fvl) - fvl, fvl))
                        ordered_v = kv.val_buf[vpos]
                    else:
                        ordered_v = np.zeros(0, dtype=np.uint8)
                    gb = np.concatenate([[0], np.cumsum(np.add.reduceat(
                        fvl, np.arange(0, n, vg)))]).astype(np.int64) \
                        if n else np.zeros(1, np.int64)
                    groups = [
                        ordered_v[gb[i]: gb[i + 1]].tobytes()
                        for i in range(len(gb) - 1)
                    ]
                    zdict = b""
                    if (compress and copts.max_dict_bytes > 0
                            and len(groups) >= 8):
                        zdict = codecs.zstd_train_dictionary(
                            groups[:: max(1, len(groups) // 256)]
                            or groups,
                            copts.max_dict_bytes,
                        )
                    blob = bytearray()
                    go = [0]
                    vflags = bytearray((len(groups) + 7) // 8)
                    if compress:
                        from concurrent.futures import ThreadPoolExecutor

                        lvl = copts.level if copts.level is not None else 3
                        with ThreadPoolExecutor(8) as ex:
                            zs = list(ex.map(
                                lambda raw: codecs.zstd_compress(
                                    raw, lvl, zdict)
                                if len(raw) >= 32 else None, groups))
                    else:
                        zs = [None] * len(groups)
                    for gi, raw in enumerate(groups):
                        payload = raw
                        z = zs[gi]
                        if z is not None and len(z) < len(raw):
                            payload = z
                            vflags[gi // 8] |= 1 << (gi % 8)
                        blob += payload
                        go.append(len(blob))
                    lens32 = bool((fvl >= 1 << 16).any())
                    vlens = fvl.astype(
                        "<u4" if lens32 else "<u2").tobytes()
                    smallest = fmat[0].tobytes()
                    largest = fmat[-1].tobytes()
                    kmeta = meta.tobytes()
                    ksfx = sfx.tobytes()
                    kgso_b = kgso.tobytes()
                    vgo = np.asarray(go, dtype="<u4").tobytes()
                    vblob = bytes(blob)
                    vflags_b = bytes(vflags)
                if compress:
                    props.compression_name = "zip+zstd"
                # --- stats ---
                vt = vtypes[rows]
                props.num_entries = n
                props.num_deletions = int(np.count_nonzero(
                    (vt == int(ValueType.DELETION))
                    | (vt == int(ValueType.SINGLE_DELETION))))
                props.num_merge_operands = int(np.count_nonzero(
                    vt == int(ValueType.MERGE)))
                sq = seqs[rows]
                props.smallest_seqno = int(sq.min())
                props.largest_seqno = int(sq.max())
                # --- bloom (native build, byte-identical to the python
                # policy per the block-format parity tests) ---
                fdata = None
                bp = options.filter_policy
                if bp is not None and options.whole_key_filtering and lib:
                    from toplingdb_tpu.table.filter import (
                        build_filter_block_native,
                    )

                    fdata = build_filter_block_native(
                        lib, bp, kv.key_buf, kv.key_offs[rows],
                        np.full(n, K - 8, dtype=np.int32), n)
            else:
                # Parity with ZipTableBuilder on an entry-less file: its
                # _encode_values computes avg=1 -> vg=256, and its seqno
                # bounds stay at the MAX sentinel until add_tombstone
                # narrows them (finish leaves them if tombstones exist).
                meta16 = lens32 = False
                vg = 256
                kmeta = ksfx = kgso_b = vblob = vflags_b = b""
                vlens = b""
                vgo = np.asarray([0], dtype="<u4").tobytes()
                zdict = b""
                fdata = None
                smallest = largest = None
                props.smallest_seqno = dbformat.MAX_SEQUENCE_NUMBER
                props.largest_seqno = 0
                if (options.compression != fmt.NO_COMPRESSION
                        and codecs.available("zstd")):
                    props.compression_name = "zip+zstd"
            # tombstones ride the LAST file (single output when present)
            rd_raw = None
            file_tombs = tombstones if fi == len(cuts) - 2 else []
            if file_tombs:
                rdb = BlockBuilder(restart_interval=1)
                for frag in file_tombs:
                    b, e = frag.to_table_entry()
                    rdb.add(b, e)
                    props.num_range_deletions += 1
                    if smallest is None or icmp.compare(b, smallest) < 0:
                        smallest = b
                    end_ikey = dbformat.make_internal_key(
                        e, dbformat.MAX_SEQUENCE_NUMBER,
                        dbformat.VALUE_TYPE_FOR_SEEK)
                    if largest is None or icmp.compare(end_ikey, largest) > 0:
                        largest = end_ikey
                    props.smallest_seqno = min(props.smallest_seqno,
                                               frag.seq)
                    props.largest_seqno = max(props.largest_seqno, frag.seq)
                rd_raw = rdb.finish()
            if n == 0 and rd_raw is None:
                continue
            fnum = new_file_number()
            path = _fn.table_file_name(dbname, fnum)
            w = env.new_writable_file(path)
            written.append(path)
            _write_zip_file(w, props, n, vg, meta16, lens32,
                            kmeta, ksfx, kgso_b, vlens, vgo, vflags_b,
                            zdict, vblob, fdata, rd_raw)
            w.sync()
            w.close()
            results.append((fnum, path, props, smallest, largest,
                            rows if n else np.empty(0, np.int64)))
        return results
    except BaseException:
        for p in written:
            try:
                env.delete_file(p)
            except Exception as e:
                _errors.swallow(reason="sst-abort-cleanup", exc=e)
        raise
