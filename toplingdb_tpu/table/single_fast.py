"""SingleFastTable: the flat, all-in-RAM SST format for hot levels.

The analogue of the reference's Topling SingleFastTable (the L0/L1 format of
the absent topling-sst submodule; README.md:50 claims it as a headline) and
of PlainTable (table/plain/): no blocks, no prefix compression — entries are
a flat [varint klen | varint vlen | ikey | value] region, the index is a raw
fixed32 offset array, and the reader holds the whole file in memory, so a
point lookup is a pure binary search (no per-block linear scan) and a scan
is a linear decode. Shares the bloom filter / properties / range-del meta
blocks and the footer shape with the block format; dispatched by footer
magic (table/factory.py — the adaptive-table mechanism).
"""

from __future__ import annotations

import numpy as np

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.block import BlockBuilder, BlockIter
from toplingdb_tpu.table.builder import (
    METAINDEX_FILTER,
    METAINDEX_PROPERTIES,
    METAINDEX_RANGE_DEL,
    TableOptions,
)
from toplingdb_tpu.table.filter import filter_policy_from_name
from toplingdb_tpu.table.properties import TableProperties
from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils.status import Corruption

METAINDEX_DATA_CRC = b"tpulsm.sf.data_crc"
METAINDEX_HASH_INDEX = b"tpulsm.sf.hash_index"


class SingleFastTableBuilder:
    """Same surface as TableBuilder (build_outputs/flush compatible)."""

    FOOTER_MAGIC = fmt.SINGLE_FAST_MAGIC

    def __init__(self, wfile, icmp: InternalKeyComparator,
                 options: TableOptions | None = None,
                 column_family_id: int = 0, column_family_name: str = "",
                 creation_time: int = 0):
        self.opts = options or TableOptions()
        self._w = wfile
        self._icmp = icmp
        self._buf = bytearray()
        self._offsets: list[int] = []
        self._filter_keys: list[bytes] = []
        self._range_del_block = BlockBuilder(restart_interval=1)
        self.props = TableProperties(
            comparator_name=icmp.user_comparator.name(),
            filter_policy_name=(
                self.opts.filter_policy.name() if self.opts.filter_policy else ""
            ),
            compression_name="single_fast",
            prefix_extractor_name=(
                self.opts.prefix_extractor.name()
                if getattr(self.opts, "prefix_extractor", None) else ""
            ),
            column_family_id=column_family_id,
            column_family_name=column_family_name,
            creation_time=creation_time,
            smallest_seqno=dbformat.MAX_SEQUENCE_NUMBER,
            whole_key_filtering=1 if self.opts.whole_key_filtering else 0,
        )
        self._last_key: bytes | None = None
        self._smallest: bytes | None = None
        self._largest: bytes | None = None
        self._finished = False
        self._last_filter_prefix: bytes | None = None
        self._collectors = [
            f.create() for f in self.opts.properties_collector_factories
        ]
        self.need_compaction = False
        self._unsorted: list[tuple[bytes, bytes]] = []  # auto_sort buffer
        self._unsorted_bytes = 0

    @property
    def num_entries(self) -> int:
        return self.props.num_entries + self.props.num_range_deletions

    def file_size(self) -> int:
        # _unsorted_bytes: output-cutting must see buffered auto_sort adds.
        return self._w.file_size() + len(self._buf) + self._unsorted_bytes

    @property
    def smallest_key(self) -> bytes | None:
        return self._smallest

    @property
    def largest_key(self) -> bytes | None:
        return self._largest

    def _track_bounds(self, ikey: bytes) -> None:
        if self._smallest is None or self._icmp.compare(ikey, self._smallest) < 0:
            self._smallest = ikey
        if self._largest is None or self._icmp.compare(ikey, self._largest) > 0:
            self._largest = ikey
        seq = dbformat.extract_seqno(ikey)
        self.props.smallest_seqno = min(self.props.smallest_seqno, seq)
        self.props.largest_seqno = max(self.props.largest_seqno, seq)

    def add(self, ikey: bytes, value: bytes) -> None:
        assert not self._finished
        if self.opts.auto_sort:
            # VecAutoSortTable mode: buffer now, sort at finish.
            self._unsorted.append((ikey, value))
            self._unsorted_bytes += len(ikey) + len(value) + 10
            return
        self._add_sorted(ikey, value)

    def _add_sorted(self, ikey: bytes, value: bytes) -> None:
        if self._last_key is not None:
            assert self._icmp.compare(self._last_key, ikey) < 0
        if len(self._buf) + len(ikey) + len(value) + 10 > 0xFFFFFF00:
            # Offsets are fixed32: refuse before appending (no torn region)
            # rather than overflow into a corrupt index at finish().
            from toplingdb_tpu.utils.status import NotSupported

            raise NotSupported(
                "single_fast table data region exceeds 4GiB; use the block "
                "format or a smaller max_output_file_size"
            )
        self._offsets.append(len(self._buf))
        self._buf += coding.encode_varint32(len(ikey))
        self._buf += coding.encode_varint32(len(value))
        self._buf += ikey
        self._buf += value
        self._last_key = ikey
        self._track_bounds(ikey)
        uk, seq_, t = dbformat.split_internal_key(ikey)
        if self.opts.filter_policy:
            if self.opts.whole_key_filtering:
                self._filter_keys.append(uk)
            pe = getattr(self.opts, "prefix_extractor", None)
            if pe is not None and pe.in_domain(uk):
                p = pe.transform(uk)
                if p != self._last_filter_prefix:
                    self._filter_keys.append(p)
                    self._last_filter_prefix = p
        for c in self._collectors:
            c.add_user_key(uk, value, t, seq_, len(self._buf))
        self.props.num_entries += 1
        self.props.raw_key_size += len(ikey)
        self.props.raw_value_size += len(value)
        if t in (ValueType.DELETION, ValueType.SINGLE_DELETION):
            self.props.num_deletions += 1
        elif t == ValueType.MERGE:
            self.props.num_merge_operands += 1

    def add_tombstone(self, begin_ikey: bytes, end_user_key: bytes) -> None:
        assert not self._finished
        self._range_del_block.add(begin_ikey, end_user_key)
        self.props.num_range_deletions += 1
        self._track_bounds(begin_ikey)
        end_ikey = dbformat.make_internal_key(
            end_user_key, dbformat.MAX_SEQUENCE_NUMBER,
            dbformat.VALUE_TYPE_FOR_SEEK,
        )
        if self._largest is None or self._icmp.compare(end_ikey, self._largest) > 0:
            self._largest = end_ikey

    def _entry_user_key(self, i: int) -> bytes:
        off = self._offsets[i]
        klen, o = coding.decode_varint32(self._buf, off)
        _, o = coding.decode_varint32(self._buf, o)
        return bytes(self._buf[o: o + klen - 8])

    def _hash_index_block(self) -> tuple[bytes, bytes] | None:
        """(metaindex name, raw block bytes) of the point-lookup index, or
        None. Subclass hook — the cuckoo format swaps in its own table."""
        if not (self.opts.hash_index and self._offsets
                and self._icmp.user_comparator.name()
                == dbformat.BYTEWISE.name()):
            # Bytewise comparator only: the hash dedups/matches by BYTE
            # equality, which must coincide with comparator equality.
            return None
        # O(1) point-lookup bucket array (the PlainTable prefix-hash role,
        # reference table/plain/): open-addressed xxh64 buckets at <=0.7
        # load, each holding 1 + the ordinal of the NEWEST version of one
        # user key.
        n = len(self._offsets)
        nb = 1
        while nb < (n * 10) // 7 + 1:
            nb <<= 1
        buckets = np.zeros(nb, dtype="<u4")
        mask = nb - 1
        prev_uk = None
        for i in range(n):
            uk = self._entry_user_key(i)
            if uk == prev_uk:
                continue  # hash maps to the first (newest) version
            prev_uk = uk
            h = crc32c.xxh64(uk) & mask
            while buckets[h]:
                h = (h + 1) & mask
            buckets[h] = i + 1
        return METAINDEX_HASH_INDEX, buckets.tobytes()

    def finish(self) -> TableProperties:
        assert not self._finished
        if self.opts.auto_sort and self._unsorted:
            # Reverse + STABLE sort: among exact-duplicate internal keys the
            # latest add comes first, so dedup keeps last-write-wins.
            ents = sorted(reversed(self._unsorted),
                          key=lambda kv: self._icmp.sort_key(kv[0]))
            self._unsorted = []
            self._unsorted_bytes = 0
            prev = None
            for k, v in ents:
                if prev is not None and self._icmp.compare(prev, k) == 0:
                    continue  # older duplicate
                self._add_sorted(k, v)
                prev = k
        for c in self._collectors:
            self.props.user_collected.update(c.finish())
            if c.need_compact():
                self.need_compaction = True
        data = bytes(self._buf)
        self._w.append(data)  # flat data region at offset 0, unframed
        self.props.data_size = len(data)
        self.props.num_data_blocks = 1

        metaindex = BlockBuilder(restart_interval=1)
        meta_entries = []
        # Whole-region checksum (entries have no per-block trailers).
        crc = crc32c.mask(crc32c.value(data))
        ch = fmt.write_block(self._w, coding.encode_fixed32(crc),
                             fmt.NO_COMPRESSION)
        meta_entries.append((METAINDEX_DATA_CRC, ch))

        if self.opts.filter_policy and self._filter_keys:
            fdata = self.opts.filter_policy.create_filter(self._filter_keys)
            fh = fmt.write_block(self._w, fdata, fmt.NO_COMPRESSION)
            self.props.filter_size = len(fdata)
            meta_entries.append((METAINDEX_FILTER, fh))
        hash_block = self._hash_index_block()
        if hash_block is not None:
            name, hdata = hash_block
            hh = fmt.write_block(self._w, hdata, fmt.NO_COMPRESSION)
            meta_entries.append((name, hh))
        if not self._range_del_block.empty():
            rh = fmt.write_block(self._w, self._range_del_block.finish(),
                                 fmt.NO_COMPRESSION)
            meta_entries.append((METAINDEX_RANGE_DEL, rh))

        # Raw fixed32 offset array as the "index block".
        iraw = np.asarray(self._offsets, dtype="<u4").tobytes()
        self.props.index_size = len(iraw)

        pblock = self.props.encode_block()
        ph = fmt.write_block(self._w, pblock, fmt.NO_COMPRESSION)
        meta_entries.append((METAINDEX_PROPERTIES, ph))
        for name, handle in sorted(meta_entries):
            metaindex.add(name, handle.encode())
        mih = fmt.write_block(self._w, metaindex.finish(), fmt.NO_COMPRESSION)
        ih = fmt.write_block(self._w, iraw, fmt.NO_COMPRESSION)
        self._w.append(fmt.Footer(mih, ih, magic=self.FOOTER_MAGIC).encode())
        self._w.flush()
        self._finished = True
        return self.props


class SingleFastTableReader:
    """Same surface as TableReader. The whole file is resident in memory."""

    FOOTER_MAGIC = fmt.SINGLE_FAST_MAGIC

    def __init__(self, rfile, icmp: InternalKeyComparator,
                 options: TableOptions | None = None, block_cache=None,
                 cache_key_prefix: bytes = b""):
        self.opts = options or TableOptions()
        self._icmp = icmp
        size = rfile.size()
        self._data = rfile.read(0, size)
        rfile.close()
        self.footer = fmt.Footer.decode(self._data, self.FOOTER_MAGIC)
        iraw = fmt.read_block(_Mem(self._data), self.footer.index_handle,
                              self.opts.verify_checksums)
        self._offsets = np.frombuffer(iraw, dtype="<u4")
        meta = fmt.read_block(_Mem(self._data), self.footer.metaindex_handle,
                              self.opts.verify_checksums)
        mit = BlockIter(meta, dbformat.BYTEWISE.compare)
        mit.seek_to_first()
        self._meta_handles = {
            k: fmt.BlockHandle.decode_exact(v) for k, v in mit.entries()
        }
        self.properties = TableProperties()
        ph = self._meta_handles.get(METAINDEX_PROPERTIES)
        if ph is not None:
            self.properties = TableProperties.decode_block(
                fmt.read_block(_Mem(self._data), ph, self.opts.verify_checksums)
            )
        if self.opts.verify_checksums:
            ch = self._meta_handles.get(METAINDEX_DATA_CRC)
            if ch is not None:
                stored = crc32c.unmask(coding.decode_fixed32(
                    fmt.read_block(_Mem(self._data), ch, True), 0
                ))
                data_len = self.properties.data_size
                if crc32c.value(self._data[:data_len]) != stored:
                    raise Corruption("single_fast data region checksum mismatch")
        self._filter_data = None
        self._filter_policy = None
        fh = self._meta_handles.get(METAINDEX_FILTER)
        if fh is not None:
            self._filter_data = fmt.read_block(
                _Mem(self._data), fh, self.opts.verify_checksums
            )
            self._filter_policy = filter_policy_from_name(
                self.properties.filter_policy_name
            )
        self._range_del_cache = None
        rh = self._meta_handles.get(METAINDEX_RANGE_DEL)
        self._range_del_data = (
            fmt.read_block(_Mem(self._data), rh, self.opts.verify_checksums)
            if rh is not None else None
        )
        self.n = len(self._offsets)
        from toplingdb_tpu.utils.slice_transform import resolve_file_extractor

        # Resolved once: the hot probe path must not rebuild the extractor.
        self._resolved_pe = resolve_file_extractor(
            getattr(self.opts, "prefix_extractor", None),
            self.properties.prefix_extractor_name,
        )
        self._load_hash_index()

    def _load_hash_index(self) -> None:
        self._hash_buckets = None
        hh = self._meta_handles.get(METAINDEX_HASH_INDEX)
        if hh is not None:
            self._hash_buckets = np.frombuffer(
                fmt.read_block(_Mem(self._data), hh,
                               self.opts.verify_checksums),
                dtype="<u4",
            )
        self.has_hash_index = self._hash_buckets is not None

    # -- entry decode ---------------------------------------------------

    def _entry(self, i: int) -> tuple[bytes, bytes]:
        off = int(self._offsets[i])
        klen, off = coding.decode_varint32(self._data, off)
        vlen, off = coding.decode_varint32(self._data, off)
        k = self._data[off : off + klen]
        v = self._data[off + klen : off + klen + vlen]
        return k, v

    def _lower_bound(self, target: bytes) -> int:
        lo, hi = 0, self.n
        cmp = self._icmp.compare
        while lo < hi:
            mid = (lo + hi) // 2
            if cmp(self._entry(mid)[0], target) < 0:
                lo = mid + 1
            else:
                hi = mid
        return lo

    # -- TableReader surface -------------------------------------------

    def close(self) -> None:
        pass

    def key_may_match(self, user_key: bytes) -> bool:
        from toplingdb_tpu.table.filter import filter_probe

        return filter_probe(
            self._filter_policy, self._filter_data,
            bool(self.properties.whole_key_filtering),
            self._resolved_pe, user_key,
        )

    def hash_probe(self, user_key: bytes) -> int | None:
        """O(1) lookup: ordinal of the NEWEST version of user_key, or None
        when the key is definitively absent from this file. Only meaningful
        when has_hash_index (bytewise-comparator files only)."""
        buckets = self._hash_buckets
        if buckets is None:
            return None
        mask = len(buckets) - 1
        h = crc32c.xxh64(user_key) & mask
        for _ in range(len(buckets)):  # bounded: corrupt blocks can't hang
            v = int(buckets[h])
            if v == 0:
                return None
            i = v - 1
            if i >= self.n:
                raise Corruption("single_fast hash index bucket out of range")
            k = self._entry(i)[0]
            if k[:-8] == user_key:
                return i
            h = (h + 1) & mask
        raise Corruption("single_fast hash index has no empty buckets")

    def new_iterator(self) -> "SingleFastIterator":
        return SingleFastIterator(self)

    def range_del_entries(self):
        if self._range_del_data is None:
            return []
        if self._range_del_cache is None:
            it = BlockIter(self._range_del_data, self._icmp.compare)
            it.seek_to_first()
            self._range_del_cache = list(it.entries())
        return self._range_del_cache

    def approximate_offset_of(self, ikey: bytes) -> int:
        i = self._lower_bound(ikey)
        return int(self._offsets[i]) if i < self.n else self.properties.data_size

    def anchors(self, max_anchors: int = 32):
        if self.n == 0:
            return []
        step = max(1, self.n // max_anchors)
        return [self._entry(i)[0] for i in range(0, self.n, step)][:max_anchors]


class _Mem:
    """RandomAccessFile view over an in-memory bytes object."""

    def __init__(self, data: bytes):
        self._d = data

    def read(self, offset: int, n: int) -> bytes:
        return self._d[offset : offset + n]

    def size(self) -> int:
        return len(self._d)


class SingleFastIterator:
    def __init__(self, r: SingleFastTableReader):
        self._r = r
        self._i = r.n  # invalid

    def valid(self) -> bool:
        return 0 <= self._i < self._r.n

    def key(self) -> bytes:
        return self._r._entry(self._i)[0]

    def value(self) -> bytes:
        return self._r._entry(self._i)[1]

    def seek_to_first(self) -> None:
        self._i = 0

    def seek_to_last(self) -> None:
        self._i = self._r.n - 1

    def seek(self, target: bytes) -> None:
        self._i = self._r._lower_bound(target)

    def seek_ordinal(self, i: int) -> None:
        """Position directly at entry ordinal i (hash_probe fast path)."""
        self._i = i

    def seek_for_prev(self, target: bytes) -> None:
        i = self._r._lower_bound(target)
        if i < self._r.n and self._r._icmp.compare(
            self._r._entry(i)[0], target
        ) == 0:
            self._i = i
        else:
            self._i = i - 1

    def next(self) -> None:
        assert self.valid()
        self._i += 1

    def prev(self) -> None:
        assert self.valid()
        self._i -= 1

    def entries(self):
        while self.valid():
            yield self.key(), self.value()
            self.next()
