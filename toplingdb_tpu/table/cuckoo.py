"""CuckooTable: hash-table SST for point-lookup-dominated workloads.

The analogue of the reference's CuckooTable (table/cuckoo/
cuckoo_table_builder.cc, cuckoo_table_reader.cc): every user key lives in
one of exactly TWO buckets, so a point lookup is at most two entry
comparisons — O(1) worst case, unlike the open-addressed single_fast index
whose probe chains grow with load. Buckets are placed by cuckoo
displacement at build time (kick the resident, re-place it in its
alternate bucket, bounded walk, grow + rebuild on failure).

Re-design notes vs the reference: the data region stays the SORTED flat
[varint klen | varint vlen | ikey | value] region of the single_fast
format rather than the reference's hash-ordered buckets, so ordered
iteration, anchors, and approximate offsets come for free and only the
index block differs; both hash values derive from one xxh64 (low/high
halves), matching the reference's use of a single base hash family.
Restrictions mirror the reference (cuckoo_table_builder.cc): unique user
keys (one version per key — last-level files) and no range deletions;
violations raise NotSupported, which fails the surrounding job cleanly
(build_outputs deletes partial and completed outputs on any mid-stream
error) — choose this format only for workloads meeting the restrictions.
"""

from __future__ import annotations

import numpy as np

from toplingdb_tpu.db import dbformat
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.single_fast import (
    SingleFastTableBuilder,
    SingleFastTableReader,
    _Mem,
)
from toplingdb_tpu.utils import crc32c
from toplingdb_tpu.utils.status import Corruption, NotSupported

METAINDEX_CUCKOO_INDEX = b"tpulsm.cuckoo.index"

# Bounded displacement walk; beyond this the table grows and rebuilds.
_MAX_KICKS = 500


def _bucket_pair_from_hash(h: int, mask: int) -> tuple[int, int]:
    """Two bucket candidates from one xxh64 (low/high halves). When both
    halves collide onto one bucket the alternate is the adjacent one so
    displacement always has somewhere to go."""
    b1 = h & mask
    b2 = (h >> 32) & mask
    if b2 == b1:
        b2 = (b1 + 1) & mask
    return b1, b2


def _bucket_pair(user_key: bytes, mask: int) -> tuple[int, int]:
    return _bucket_pair_from_hash(crc32c.xxh64(user_key), mask)


class CuckooTableBuilder(SingleFastTableBuilder):
    """Same surface as TableBuilder; data region identical to single_fast,
    index block replaced by the cuckoo bucket array."""

    FOOTER_MAGIC = fmt.CUCKOO_MAGIC

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # Fail fast, before any bytes are written: hash equality must
        # coincide with comparator equality.
        if self._icmp.user_comparator.name() != dbformat.BYTEWISE.name():
            raise NotSupported(
                "cuckoo tables require the bytewise comparator"
            )

    def _add_sorted(self, ikey: bytes, value: bytes) -> None:
        if self._last_key is not None:
            prev_uk = self._last_key[:-8]
            if prev_uk == ikey[:-8]:
                raise NotSupported(
                    "cuckoo tables require unique user keys (one version "
                    "per key); use single_fast or the block format"
                )
        super()._add_sorted(ikey, value)

    def add_tombstone(self, begin_ikey: bytes, end_user_key: bytes) -> None:
        raise NotSupported("cuckoo tables do not support range deletions")

    def _hash_index_block(self) -> tuple[bytes, bytes] | None:
        if not self._offsets:
            return None
        n = len(self._offsets)
        # Hash each key ONCE; displacement kicks and grow retries then cost
        # two mask ops per step instead of a fresh xxh64.
        hashes = [
            crc32c.xxh64(self._entry_user_key(i)) for i in range(n)
        ]
        # 2-choice single-slot cuckoo hashing is only reliably placeable
        # below ~0.5 load; sizing at >= 2n skips doomed placement passes.
        nb = 4
        while nb < 2 * n:
            nb <<= 1
        while True:
            buckets = self._try_place(hashes, nb)
            if buckets is not None:
                return METAINDEX_CUCKOO_INDEX, buckets.tobytes()
            nb <<= 1

    @staticmethod
    def _try_place(hashes: list[int], nb: int) -> np.ndarray | None:
        mask = nb - 1
        buckets = np.zeros(nb, dtype="<u4")  # ordinal + 1; 0 = empty
        for i, h in enumerate(hashes):
            cur = i
            b1, b2 = _bucket_pair_from_hash(h, mask)
            pos = b1 if not buckets[b1] else b2
            for _ in range(_MAX_KICKS):
                if not buckets[pos]:
                    buckets[pos] = cur + 1
                    break
                victim = int(buckets[pos]) - 1
                buckets[pos] = cur + 1
                cur = victim
                v1, v2 = _bucket_pair_from_hash(hashes[cur], mask)
                pos = v2 if pos == v1 else v1
            else:
                return None  # displacement cycle: grow
        return buckets


class CuckooTableReader(SingleFastTableReader):
    """Same surface as TableReader/SingleFastTableReader; point lookups
    probe at most two buckets."""

    FOOTER_MAGIC = fmt.CUCKOO_MAGIC

    def _load_hash_index(self) -> None:
        hh = self._meta_handles.get(METAINDEX_CUCKOO_INDEX)
        if hh is None:
            if self.n == 0:
                # Tombstone-only / empty file: a valid empty index.
                self._buckets = np.zeros(0, dtype="<u4")
                self.has_hash_index = True
                return
            raise Corruption("cuckoo table missing its index block")
        self._buckets = np.frombuffer(
            fmt.read_block(_Mem(self._data), hh, self.opts.verify_checksums),
            dtype="<u4",
        )
        if len(self._buckets) & (len(self._buckets) - 1):
            raise Corruption("cuckoo index size is not a power of two")
        self.has_hash_index = True

    def hash_probe(self, user_key: bytes) -> int | None:
        if not len(self._buckets):
            return None
        mask = len(self._buckets) - 1
        for b in _bucket_pair(user_key, mask):
            v = int(self._buckets[b])
            if not v:
                continue
            i = v - 1
            if i >= self.n:
                raise Corruption("cuckoo index bucket out of range")
            k = self._entry(i)[0]
            if k[:-8] == user_key:
                return i
        return None
