"""Filesystem/Env implementations: posix and in-memory.

Interface mirrors the reference's FileSystem surface that the LSM engine
actually uses (new_*_file, rename, list, lock), not its full breadth.
File handles expose explicit append/read-at/sync so WAL durability and
SST reads have the same contract as the reference's WritableFileWriter /
RandomAccessFileReader (file/ in /root/reference).
"""

from __future__ import annotations

import io
import os
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.utils import statistics as _stats_mod
from toplingdb_tpu.utils.status import IOError_, NotFound
from toplingdb_tpu.utils import errors as _errors


class WritableFile:
    def append(self, data: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    def file_size(self) -> int:
        raise NotImplementedError

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RandomAccessFile:
    def read(self, offset: int, n: int) -> bytes:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SequentialFile:
    def read(self, n: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Env:
    """Abstract Env: files + clock + misc (reference include/rocksdb/env.h:151)."""

    def new_writable_file(self, path: str) -> WritableFile:
        raise NotImplementedError

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        raise NotImplementedError

    def new_sequential_file(self, path: str) -> SequentialFile:
        raise NotImplementedError

    def file_exists(self, path: str) -> bool:
        raise NotImplementedError

    def get_file_size(self, path: str) -> int:
        raise NotImplementedError

    def delete_file(self, path: str) -> None:
        raise NotImplementedError

    def rename_file(self, src: str, dst: str) -> None:
        raise NotImplementedError

    def reuse_writable_file(self, old_path: str, new_path: str) -> WritableFile:
        """Rename old_path to new_path and open it for OVERWRITE from
        offset 0 WITHOUT truncating (WAL recycling, reference
        Env::ReuseWritableFile: the already-allocated blocks are rewritten
        in place; the recyclable log format makes the stale tail safe)."""
        self.rename_file(old_path, new_path)
        return self.new_writable_file(new_path)  # fallback: truncates

    def get_file_mtime(self, path: str) -> float | None:
        """Last-modification time (reference Env::GetFileModificationTime);
        None when the env doesn't track one (callers must not purge)."""
        return None

    def create_dir(self, path: str) -> None:
        raise NotImplementedError

    def get_children(self, path: str) -> list[str]:
        raise NotImplementedError

    def now_micros(self) -> int:
        return int(time.time() * 1e6)

    def read_file(self, path: str) -> bytes:
        f = self.new_random_access_file(path)
        try:
            return f.read(0, f.size())
        finally:
            f.close()

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        f = self.new_writable_file(path)
        try:
            f.append(data)
            if sync:
                f.sync()
        finally:
            f.close()

    def get_free_space(self, path: str) -> int:
        """Free bytes on the filesystem holding `path`.

        Envs with no real capacity notion (pure wrappers, in-memory stores
        without a configured size) report effectively-infinite space so
        pressure logic stays dormant until someone sets a budget."""
        return 1 << 62


# ---------------------------------------------------------------------------
# Async batched I/O (the Env-level submit ring)
# ---------------------------------------------------------------------------


class AioToken:
    """Completion handle for one submitted ring operation. wait() blocks
    until the writer thread settled it and re-raises any error; `result`
    carries a task submission's return value."""

    __slots__ = ("_ev", "error", "result")

    def __init__(self):
        self._ev = threading.Event()
        self.error: BaseException | None = None
        self.result = None

    def done(self, err: BaseException | None = None, result=None) -> None:
        self.error = err
        self.result = result
        self._ev.set()

    def ready(self) -> bool:
        return self._ev.is_set()

    def wait(self):
        self._ev.wait()
        if self.error is not None:
            raise self.error
        return self.result


class AsyncIORing:
    """Bounded submit ring with ONE dedicated I/O thread — the Env's async
    batched-I/O primitive (the fiber/io_uring surgery of the reference
    fork, PAPER.md item 4, expressed as a thread + ring). Producers submit
    appends, fsync barriers, generic read tasks (FilePrefetchBuffer
    readahead, IntegrityScrubber chunk reads), and drain barriers;
    submission is cheap and non-blocking until the ring is full.

    The crucial write-plane property is FSYNC COALESCING: the worker
    drains the queue in batches, executes every pending append in submit
    order, then performs ONE fsync per file that has >= 1 pending sync
    request and completes every such sync token — concurrent group-commit
    leaders' sync=True barriers merge into shared fsyncs. This is sound
    because a sync token only promises durability of the bytes submitted
    BEFORE it, and the shared fsync covers a superset.

    Error propagation: an append failure settles its own token AND parks
    per-file; the file's next sync/append-barrier waiter receives it
    (durability unknown past a failed append) and the park clears — a
    clean resume, not a poisoned ring. `fault_hook(kind, nbytes)` is the
    seeded injection seam (env/fault_injection.py WalWriterFaultInjector).
    """

    def __init__(self, capacity: int = 256, coalesce_cb=None,
                 fault_hook=None, name: str = "tpulsm-aio",
                 task_capacity: int | None = None):
        self._cap = max(1, int(capacity))
        # Reads (submit_task) get their OWN cap: a miss storm must not fill
        # the shared queue and starve WAL appends of their capacity slots,
        # and appends must not let tasks pile up unbounded (ISSUE 18).
        self._task_cap = max(1, int(task_capacity if task_capacity is not None
                                    else capacity))
        self._q: list = []
        self._n_task = 0
        self._cv = ccy.Condition("env.AsyncIORing._cv")
        self._closed = False
        self.coalesce_cb = coalesce_cb     # callable(n_merged_fsyncs)
        self.fault_hook = fault_hook       # callable(kind, nbytes) -> None
        self.appends = 0
        self.syncs = 0
        self.fsyncs = 0
        self.fsyncs_coalesced = 0
        self._pending_err: dict[int, BaseException] = {}
        self._thread = ccy.spawn(f"aio-{name}", self._run, owner=self,
                                 stop=self.close)

    # -- submission ----------------------------------------------------

    def _submit(self, kind: str, f, data) -> AioToken:
        tok = AioToken()
        with self._cv:
            if self._closed:
                raise IOError_("async IO ring is closed")
            while not self._closed and (
                    (kind == "append" and len(self._q) >= self._cap)
                    or (kind == "task" and self._n_task >= self._task_cap)):
                self._cv.wait()  # bounded: back-pressure the producer
            if self._closed:
                raise IOError_("async IO ring is closed")
            if kind == "task":
                self._n_task += 1
            self._q.append((kind, f, data, tok))
            self._cv.notify_all()
        return tok

    def submit_append(self, wfile, data) -> AioToken:
        return self._submit("append", wfile, data)

    def submit_sync(self, wfile) -> AioToken:
        return self._submit("sync", wfile, None)

    def submit_barrier(self, wfile) -> AioToken:
        """Completes when every append for `wfile` submitted before it has
        been written (and the file flushed); carries any parked error."""
        return self._submit("fbarrier", wfile, None)

    def submit_task(self, fn) -> AioToken:
        """Generic async work on the I/O thread (prefetch window reads,
        scrubber chunk reads); token.wait() returns fn()'s result."""
        return self._submit("task", None, fn)

    def drain(self) -> None:
        """Global barrier: every previously submitted op is settled."""
        self._submit("barrier", None, None).wait()

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
        self.drain()
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)

    # -- the worker ----------------------------------------------------

    def _exec(self, kind: str, fn, nbytes: int):
        try:
            if self.fault_hook is not None:
                self.fault_hook(kind, nbytes)
            return fn()
        except BaseException as e:  # noqa: BLE001
            return _AIO_ERR, e

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q and self._closed:
                    return
                batch = self._q
                self._q = []
                self._n_task = 0
                self._cv.notify_all()
            per_file: dict[int, list] = {}  # id -> [f, appended, syncs, fbars]
            global_bars: list[AioToken] = []

            def state(f):
                st = per_file.get(id(f))
                if st is None:
                    st = per_file[id(f)] = [f, False, [], []]
                return st

            for kind, f, data, tok in batch:
                if kind == "append":
                    r = self._exec("append", lambda: f.append(data), len(data))
                    if type(r) is tuple and r and r[0] is _AIO_ERR:
                        self._pending_err.setdefault(id(f), r[1])
                        tok.done(r[1])
                    else:
                        self.appends += 1
                        state(f)[1] = True
                        tok.done()
                elif kind == "task":
                    r = self._exec("task", data, 0)
                    if type(r) is tuple and r and r[0] is _AIO_ERR:
                        tok.done(r[1])
                    else:
                        tok.done(result=r)
                elif kind == "sync":
                    self.syncs += 1
                    state(f)[2].append(tok)
                elif kind == "fbarrier":
                    state(f)[3].append(tok)
                else:  # barrier
                    global_bars.append(tok)
            for f, appended, sync_toks, fbar_toks in per_file.values():
                err = self._pending_err.pop(id(f), None)
                if sync_toks and err is None:
                    r = self._exec("sync", f.sync, 0)
                    if type(r) is tuple and r and r[0] is _AIO_ERR:
                        err = r[1]
                    else:
                        self.fsyncs += 1
                        if len(sync_toks) > 1:
                            merged = len(sync_toks) - 1
                            self.fsyncs_coalesced += merged
                            if self.coalesce_cb is not None:
                                with _errors.guard(
                                        listener=self.coalesce_cb):
                                    self.coalesce_cb(merged)
                elif appended and err is None:
                    # No fsync requested: hand the bytes to the OS so a
                    # process crash behaves like the inline write path.
                    r = self._exec("flush", f.flush, 0)
                    if type(r) is tuple and r and r[0] is _AIO_ERR:
                        err = r[1]
                waiters = sync_toks + fbar_toks
                for tok in waiters:
                    tok.done(err)
                if err is not None and not waiters:
                    # Nobody to tell yet: park for the file's next barrier.
                    self._pending_err[id(f)] = err
            for tok in global_bars:
                tok.done()


_AIO_ERR = object()  # sentinel tag for _exec error returns


class AsyncWritableFile(WritableFile):
    """Write-behind WritableFile: append() submits to an AsyncIORing and
    returns immediately; sync() is a blocking coalesced-fsync barrier;
    sync_async()/append_barrier() return AioTokens so a group-commit
    leader can overlap WAL durability with its memtable phase and wait
    outside the commit critical section (db.py _group_wal_durability)."""

    def __init__(self, base: WritableFile, ring: AsyncIORing):
        self._base = base
        self._ring = ring
        self._size = base.file_size()

    def append(self, data) -> None:
        self._size += len(data)
        self._ring.submit_append(self._base, data)

    def flush(self) -> None:
        pass  # the ring flushes after each drained append run

    def sync(self) -> None:
        self.sync_async().wait()

    def sync_async(self) -> AioToken:
        return self._ring.submit_sync(self._base)

    def append_barrier(self) -> AioToken:
        return self._ring.submit_barrier(self._base)

    def close(self) -> None:
        self.append_barrier().wait()  # surface parked errors before close
        self._base.close()

    def file_size(self) -> int:
        return self._size


# ---------------------------------------------------------------------------
# Posix
# ---------------------------------------------------------------------------


class _PosixWritable(WritableFile):
    def __init__(self, path: str, reuse: bool = False):
        try:
            # reuse: overwrite in place from offset 0 without truncating
            # (the recycled file's preallocated blocks are rewritten).
            self._f = open(path, "r+b" if reuse else "wb")
        except OSError as e:
            raise IOError_(f"open {path}: {e}") from e
        if reuse:
            self._f.seek(0)
        self._size = 0

    def append(self, data: bytes) -> None:
        # IOStatsContext twin of PerfContext (reference iostats_context.h):
        # byte counts at perf_level >= 1, wall timings at >= 2 — level 0
        # pays one module-attribute read.
        lvl = _stats_mod.perf_level
        if lvl >= 2:
            t0 = time.perf_counter()
            self._f.write(data)
            ctx = _stats_mod.iostats_context()
            ctx.write_nanos += int((time.perf_counter() - t0) * 1e9)
            ctx.bytes_written += len(data)
        else:
            self._f.write(data)
            if lvl:
                _stats_mod.iostats_context().bytes_written += len(data)
        self._size += len(data)

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        lvl = _stats_mod.perf_level
        t0 = time.perf_counter() if lvl >= 2 else 0.0
        self._f.flush()
        os.fsync(self._f.fileno())
        if lvl >= 2:
            _stats_mod.iostats_context().fsync_nanos += int(
                (time.perf_counter() - t0) * 1e9)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def file_size(self) -> int:
        return self._size


class _PosixRandomAccess(RandomAccessFile):
    def __init__(self, path: str):
        try:
            self._f = open(path, "rb")
        except FileNotFoundError as e:
            raise NotFound(f"{path}") from e
        except OSError as e:
            raise IOError_(f"open {path}: {e}") from e
        self._size = os.fstat(self._f.fileno()).st_size

    def read(self, offset: int, n: int) -> bytes:
        lvl = _stats_mod.perf_level
        if lvl >= 2:
            t0 = time.perf_counter()
            data = os.pread(self._f.fileno(), n, offset)
            ctx = _stats_mod.iostats_context()
            ctx.read_nanos += int((time.perf_counter() - t0) * 1e9)
            ctx.bytes_read += len(data)
            return data
        data = os.pread(self._f.fileno(), n, offset)
        if lvl:
            _stats_mod.iostats_context().bytes_read += len(data)
        return data

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class _PosixSequential(SequentialFile):
    def __init__(self, path: str):
        try:
            self._f = open(path, "rb")
        except FileNotFoundError as e:
            raise NotFound(f"{path}") from e
        except OSError as e:
            raise IOError_(f"open {path}: {e}") from e

    def read(self, n: int) -> bytes:
        data = self._f.read(n)
        if _stats_mod.perf_level:
            _stats_mod.iostats_context().bytes_read += len(data)
        return data

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class PosixEnv(Env):
    def new_writable_file(self, path: str) -> WritableFile:
        return _PosixWritable(path)

    def get_free_space(self, path: str) -> int:
        p = path
        while p and not os.path.exists(p):
            parent = os.path.dirname(p)
            if parent == p:
                break
            p = parent
        try:
            st = os.statvfs(p or "/")
        except OSError as e:
            raise IOError_(f"statvfs {path}: {e}") from e
        return st.f_bavail * st.f_frsize

    def reuse_writable_file(self, old_path: str, new_path: str) -> WritableFile:
        os.replace(old_path, new_path)
        return _PosixWritable(new_path, reuse=True)

    def get_file_mtime(self, path: str) -> float | None:
        try:
            return os.path.getmtime(path)
        except FileNotFoundError as e:
            raise NotFound(path) from e

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        return _PosixRandomAccess(path)

    def new_sequential_file(self, path: str) -> SequentialFile:
        return _PosixSequential(path)

    def file_exists(self, path: str) -> bool:
        return os.path.exists(path)

    def get_file_size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except FileNotFoundError as e:
            raise NotFound(path) from e

    def delete_file(self, path: str) -> None:
        try:
            os.remove(path)
        except FileNotFoundError as e:
            raise NotFound(path) from e

    def rename_file(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def create_dir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def get_children(self, path: str) -> list[str]:
        try:
            return sorted(os.listdir(path))
        except FileNotFoundError as e:
            raise NotFound(path) from e


# ---------------------------------------------------------------------------
# In-memory (reference env/mock_env.cc analogue)
# ---------------------------------------------------------------------------


class _MemFileState:
    __slots__ = ("data", "synced_len", "mtime")

    def __init__(self):
        import time as _time

        self.data = bytearray()
        self.synced_len = 0
        self.mtime = _time.time()


class _MemWritable(WritableFile):
    def __init__(self, st: _MemFileState):
        self._st = st

    def append(self, data: bytes) -> None:
        self._st.data += data

    def sync(self) -> None:
        self._st.synced_len = len(self._st.data)

    def close(self) -> None:
        pass

    def file_size(self) -> int:
        return len(self._st.data)


class _MemRandomAccess(RandomAccessFile):
    def __init__(self, st: _MemFileState):
        self._st = st

    def read(self, offset: int, n: int) -> bytes:
        return bytes(self._st.data[offset : offset + n])

    def size(self) -> int:
        return len(self._st.data)


class _MemSequential(SequentialFile):
    def __init__(self, st: _MemFileState):
        self._buf = io.BytesIO(bytes(st.data))

    def read(self, n: int) -> bytes:
        return self._buf.read(n)


class MemEnv(Env):
    """In-memory Env for tests. `drop_unsynced()` simulates a crash that loses
    un-synced bytes (the core trick of the reference's FaultInjectionTestFS,
    utilities/fault_injection_fs.h:204)."""

    def __init__(self):
        self._files: dict[str, _MemFileState] = {}
        self._dirs: set[str] = {"/"}
        self._lock = ccy.Lock("env.MemEnv._lock")
        self._capacity = 0  # 0 = unlimited (get_free_space reports huge)

    def _norm(self, path: str) -> str:
        return os.path.normpath(path)

    def new_writable_file(self, path: str) -> WritableFile:
        with self._lock:
            st = _MemFileState()
            self._files[self._norm(path)] = st
            return _MemWritable(st)

    def get_file_mtime(self, path: str) -> float | None:
        with self._lock:
            st = self._files.get(self._norm(path))
            if st is None:
                raise NotFound(path)
            return st.mtime

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        with self._lock:
            st = self._files.get(self._norm(path))
            if st is None:
                raise NotFound(path)
            return _MemRandomAccess(st)

    def new_sequential_file(self, path: str) -> SequentialFile:
        with self._lock:
            st = self._files.get(self._norm(path))
            if st is None:
                raise NotFound(path)
            return _MemSequential(st)

    def file_exists(self, path: str) -> bool:
        p = self._norm(path)
        return p in self._files or p in self._dirs

    def get_file_size(self, path: str) -> int:
        st = self._files.get(self._norm(path))
        if st is None:
            raise NotFound(path)
        return len(st.data)

    def delete_file(self, path: str) -> None:
        with self._lock:
            if self._files.pop(self._norm(path), None) is None:
                raise NotFound(path)

    def rename_file(self, src: str, dst: str) -> None:
        with self._lock:
            st = self._files.pop(self._norm(src), None)
            if st is None:
                raise NotFound(src)
            self._files[self._norm(dst)] = st

    def create_dir(self, path: str) -> None:
        self._dirs.add(self._norm(path))

    def get_children(self, path: str) -> list[str]:
        p = self._norm(path)
        out = set()
        for f in self._files.keys() | self._dirs:
            if f != p and os.path.dirname(f) == p:
                out.add(os.path.basename(f))
        return sorted(out)

    def drop_unsynced(self) -> None:
        """Crash simulation: truncate every file to its last synced length."""
        with self._lock:
            for st in self._files.values():
                del st.data[st.synced_len :]

    def set_capacity(self, nbytes: int) -> None:
        """Simulated filesystem size; get_free_space = capacity - stored."""
        with self._lock:
            self._capacity = int(nbytes)

    def get_free_space(self, path: str) -> int:
        with self._lock:
            if self._capacity <= 0:
                return 1 << 62
            used = sum(len(st.data) for st in self._files.values())
            return max(0, self._capacity - used)


_default = PosixEnv()


def default_env() -> Env:
    return _default
