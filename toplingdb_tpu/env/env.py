"""Filesystem/Env implementations: posix and in-memory.

Interface mirrors the reference's FileSystem surface that the LSM engine
actually uses (new_*_file, rename, list, lock), not its full breadth.
File handles expose explicit append/read-at/sync so WAL durability and
SST reads have the same contract as the reference's WritableFileWriter /
RandomAccessFileReader (file/ in /root/reference).
"""

from __future__ import annotations

import io
import os
import threading
import time

from toplingdb_tpu.utils.status import IOError_, NotFound


class WritableFile:
    def append(self, data: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def sync(self) -> None:
        pass

    def close(self) -> None:
        pass

    def file_size(self) -> int:
        raise NotImplementedError

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RandomAccessFile:
    def read(self, offset: int, n: int) -> bytes:
        raise NotImplementedError

    def size(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SequentialFile:
    def read(self, n: int) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Env:
    """Abstract Env: files + clock + misc (reference include/rocksdb/env.h:151)."""

    def new_writable_file(self, path: str) -> WritableFile:
        raise NotImplementedError

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        raise NotImplementedError

    def new_sequential_file(self, path: str) -> SequentialFile:
        raise NotImplementedError

    def file_exists(self, path: str) -> bool:
        raise NotImplementedError

    def get_file_size(self, path: str) -> int:
        raise NotImplementedError

    def delete_file(self, path: str) -> None:
        raise NotImplementedError

    def rename_file(self, src: str, dst: str) -> None:
        raise NotImplementedError

    def reuse_writable_file(self, old_path: str, new_path: str) -> WritableFile:
        """Rename old_path to new_path and open it for OVERWRITE from
        offset 0 WITHOUT truncating (WAL recycling, reference
        Env::ReuseWritableFile: the already-allocated blocks are rewritten
        in place; the recyclable log format makes the stale tail safe)."""
        self.rename_file(old_path, new_path)
        return self.new_writable_file(new_path)  # fallback: truncates

    def get_file_mtime(self, path: str) -> float | None:
        """Last-modification time (reference Env::GetFileModificationTime);
        None when the env doesn't track one (callers must not purge)."""
        return None

    def create_dir(self, path: str) -> None:
        raise NotImplementedError

    def get_children(self, path: str) -> list[str]:
        raise NotImplementedError

    def now_micros(self) -> int:
        return int(time.time() * 1e6)

    def read_file(self, path: str) -> bytes:
        f = self.new_random_access_file(path)
        try:
            return f.read(0, f.size())
        finally:
            f.close()

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        f = self.new_writable_file(path)
        try:
            f.append(data)
            if sync:
                f.sync()
        finally:
            f.close()


# ---------------------------------------------------------------------------
# Posix
# ---------------------------------------------------------------------------


class _PosixWritable(WritableFile):
    def __init__(self, path: str, reuse: bool = False):
        try:
            # reuse: overwrite in place from offset 0 without truncating
            # (the recycled file's preallocated blocks are rewritten).
            self._f = open(path, "r+b" if reuse else "wb")
        except OSError as e:
            raise IOError_(f"open {path}: {e}") from e
        if reuse:
            self._f.seek(0)
        self._size = 0

    def append(self, data: bytes) -> None:
        self._f.write(data)
        self._size += len(data)

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def file_size(self) -> int:
        return self._size


class _PosixRandomAccess(RandomAccessFile):
    def __init__(self, path: str):
        try:
            self._f = open(path, "rb")
        except FileNotFoundError as e:
            raise NotFound(f"{path}") from e
        except OSError as e:
            raise IOError_(f"open {path}: {e}") from e
        self._size = os.fstat(self._f.fileno()).st_size

    def read(self, offset: int, n: int) -> bytes:
        return os.pread(self._f.fileno(), n, offset)

    def size(self) -> int:
        return self._size

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class _PosixSequential(SequentialFile):
    def __init__(self, path: str):
        try:
            self._f = open(path, "rb")
        except FileNotFoundError as e:
            raise NotFound(f"{path}") from e
        except OSError as e:
            raise IOError_(f"open {path}: {e}") from e

    def read(self, n: int) -> bytes:
        return self._f.read(n)

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class PosixEnv(Env):
    def new_writable_file(self, path: str) -> WritableFile:
        return _PosixWritable(path)

    def reuse_writable_file(self, old_path: str, new_path: str) -> WritableFile:
        os.replace(old_path, new_path)
        return _PosixWritable(new_path, reuse=True)

    def get_file_mtime(self, path: str) -> float | None:
        try:
            return os.path.getmtime(path)
        except FileNotFoundError as e:
            raise NotFound(path) from e

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        return _PosixRandomAccess(path)

    def new_sequential_file(self, path: str) -> SequentialFile:
        return _PosixSequential(path)

    def file_exists(self, path: str) -> bool:
        return os.path.exists(path)

    def get_file_size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except FileNotFoundError as e:
            raise NotFound(path) from e

    def delete_file(self, path: str) -> None:
        try:
            os.remove(path)
        except FileNotFoundError as e:
            raise NotFound(path) from e

    def rename_file(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def create_dir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def get_children(self, path: str) -> list[str]:
        try:
            return sorted(os.listdir(path))
        except FileNotFoundError as e:
            raise NotFound(path) from e


# ---------------------------------------------------------------------------
# In-memory (reference env/mock_env.cc analogue)
# ---------------------------------------------------------------------------


class _MemFileState:
    __slots__ = ("data", "synced_len", "mtime")

    def __init__(self):
        import time as _time

        self.data = bytearray()
        self.synced_len = 0
        self.mtime = _time.time()


class _MemWritable(WritableFile):
    def __init__(self, st: _MemFileState):
        self._st = st

    def append(self, data: bytes) -> None:
        self._st.data += data

    def sync(self) -> None:
        self._st.synced_len = len(self._st.data)

    def close(self) -> None:
        pass

    def file_size(self) -> int:
        return len(self._st.data)


class _MemRandomAccess(RandomAccessFile):
    def __init__(self, st: _MemFileState):
        self._st = st

    def read(self, offset: int, n: int) -> bytes:
        return bytes(self._st.data[offset : offset + n])

    def size(self) -> int:
        return len(self._st.data)


class _MemSequential(SequentialFile):
    def __init__(self, st: _MemFileState):
        self._buf = io.BytesIO(bytes(st.data))

    def read(self, n: int) -> bytes:
        return self._buf.read(n)


class MemEnv(Env):
    """In-memory Env for tests. `drop_unsynced()` simulates a crash that loses
    un-synced bytes (the core trick of the reference's FaultInjectionTestFS,
    utilities/fault_injection_fs.h:204)."""

    def __init__(self):
        self._files: dict[str, _MemFileState] = {}
        self._dirs: set[str] = {"/"}
        self._lock = threading.Lock()

    def _norm(self, path: str) -> str:
        return os.path.normpath(path)

    def new_writable_file(self, path: str) -> WritableFile:
        with self._lock:
            st = _MemFileState()
            self._files[self._norm(path)] = st
            return _MemWritable(st)

    def get_file_mtime(self, path: str) -> float | None:
        with self._lock:
            st = self._files.get(self._norm(path))
            if st is None:
                raise NotFound(path)
            return st.mtime

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        with self._lock:
            st = self._files.get(self._norm(path))
            if st is None:
                raise NotFound(path)
            return _MemRandomAccess(st)

    def new_sequential_file(self, path: str) -> SequentialFile:
        with self._lock:
            st = self._files.get(self._norm(path))
            if st is None:
                raise NotFound(path)
            return _MemSequential(st)

    def file_exists(self, path: str) -> bool:
        p = self._norm(path)
        return p in self._files or p in self._dirs

    def get_file_size(self, path: str) -> int:
        st = self._files.get(self._norm(path))
        if st is None:
            raise NotFound(path)
        return len(st.data)

    def delete_file(self, path: str) -> None:
        with self._lock:
            if self._files.pop(self._norm(path), None) is None:
                raise NotFound(path)

    def rename_file(self, src: str, dst: str) -> None:
        with self._lock:
            st = self._files.pop(self._norm(src), None)
            if st is None:
                raise NotFound(src)
            self._files[self._norm(dst)] = st

    def create_dir(self, path: str) -> None:
        self._dirs.add(self._norm(path))

    def get_children(self, path: str) -> list[str]:
        p = self._norm(path)
        out = set()
        for f in self._files.keys() | self._dirs:
            if f != p and os.path.dirname(f) == p:
                out.add(os.path.basename(f))
        return sorted(out)

    def drop_unsynced(self) -> None:
        """Crash simulation: truncate every file to its last synced length."""
        with self._lock:
            for st in self._files.values():
                del st.data[st.synced_len :]


_default = PosixEnv()


def default_env() -> Env:
    return _default
