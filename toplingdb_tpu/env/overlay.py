"""OverlayEnv: a writable overlay on top of a read-only base Env.

Analogue of the reference's CatFileSystem (env/fs_cat.cc:33-60 in
/root/reference), which concatenates a local overlay over a read-only base
filesystem — how dcompact workers mount the DB dir: input SSTs are read from
the (shared, read-only) base; all new files land in the overlay. Deletes of
base files are masked with in-memory whiteouts (the worker never really
deletes primary data).
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.env.env import Env
from toplingdb_tpu.utils.status import NotFound


class OverlayEnv(Env):
    def __init__(self, base: Env, overlay: Env):
        self.base = base
        self.overlay = overlay
        self._whiteouts: set[str] = set()
        self._mu = ccy.Lock("overlay.OverlayEnv._mu")

    def _hidden(self, path: str) -> bool:
        with self._mu:
            return path in self._whiteouts

    def _unhide(self, path: str) -> None:
        with self._mu:
            self._whiteouts.discard(path)

    def get_free_space(self, path: str) -> int:
        # New bytes land in the overlay; its filesystem is the one filling.
        return self.overlay.get_free_space(path)

    # -- reads: overlay first, then base --------------------------------

    def new_random_access_file(self, path: str):
        if self.overlay.file_exists(path):
            return self.overlay.new_random_access_file(path)
        if self._hidden(path):
            raise NotFound(path)
        return self.base.new_random_access_file(path)

    def new_sequential_file(self, path: str):
        if self.overlay.file_exists(path):
            return self.overlay.new_sequential_file(path)
        if self._hidden(path):
            raise NotFound(path)
        return self.base.new_sequential_file(path)

    def read_file(self, path: str) -> bytes:
        if self.overlay.file_exists(path):
            return self.overlay.read_file(path)
        if self._hidden(path):
            raise NotFound(path)
        return self.base.read_file(path)

    def file_exists(self, path: str) -> bool:
        if self.overlay.file_exists(path):
            return True
        return not self._hidden(path) and self.base.file_exists(path)

    def get_file_size(self, path: str) -> int:
        if self.overlay.file_exists(path):
            return self.overlay.get_file_size(path)
        if self._hidden(path):
            raise NotFound(path)
        return self.base.get_file_size(path)

    def get_children(self, path: str) -> list[str]:
        out = set()
        try:
            out.update(self.overlay.get_children(path))
        except NotFound:
            pass
        try:
            import os

            for child in self.base.get_children(path):
                if not self._hidden(os.path.join(path, child)):
                    out.add(child)
        except NotFound:
            pass
        return sorted(out)

    # -- writes: overlay only -------------------------------------------

    def new_writable_file(self, path: str):
        self._unhide(path)
        return self.overlay.new_writable_file(path)

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        self._unhide(path)
        self.overlay.write_file(path, data, sync=sync)

    def create_dir(self, path: str) -> None:
        self.overlay.create_dir(path)

    def delete_file(self, path: str) -> None:
        deleted = False
        if self.overlay.file_exists(path):
            self.overlay.delete_file(path)
            deleted = True
        if self.base.file_exists(path):
            with self._mu:
                self._whiteouts.add(path)  # mask, never touch the base
            deleted = True
        if not deleted:
            raise NotFound(path)

    def rename_file(self, src: str, dst: str) -> None:
        if self.overlay.file_exists(src):
            self._unhide(dst)
            self.overlay.rename_file(src, dst)
            return
        if not self._hidden(src) and self.base.file_exists(src):
            # Copy-up: materialize the base file into the overlay under the
            # new name; whiteout the source.
            self._unhide(dst)
            self.overlay.write_file(dst, self.base.read_file(src))
            with self._mu:
                self._whiteouts.add(src)
            return
        raise NotFound(src)
