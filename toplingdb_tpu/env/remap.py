"""RemapEnv: path-prefix remapping over a base Env.

Analogue of the reference's fs_remap (env/fs_remap.cc in /root/reference):
a dcompact worker sees the DB's canonical paths (as serialized in
CompactionParams) even when the shared storage is mounted somewhere else —
e.g. the DB records `/data/db` but the worker mounts it at `/mnt/nfs/db`.
Every Env call translates the longest matching source prefix before
delegating; paths outside every mapping pass through unchanged.
"""

from __future__ import annotations

from toplingdb_tpu.env.env import Env


class RemapEnv(Env):
    def __init__(self, base: Env, mappings: dict[str, str]):
        """mappings: {source_prefix: target_prefix}, longest prefix wins."""
        self.base = base
        # Normalize: no trailing slash, longest first for greedy matching.
        self._maps = sorted(
            ((src.rstrip("/"), dst.rstrip("/"))
             for src, dst in mappings.items()),
            key=lambda p: -len(p[0]),
        )

    def remap(self, path: str) -> str:
        for src, dst in self._maps:
            if path == src or path.startswith(src + "/"):
                return dst + path[len(src):]
        return path

    # -- delegation ------------------------------------------------------

    def new_writable_file(self, path: str):
        return self.base.new_writable_file(self.remap(path))

    def new_random_access_file(self, path: str):
        return self.base.new_random_access_file(self.remap(path))

    def new_sequential_file(self, path: str):
        return self.base.new_sequential_file(self.remap(path))

    def file_exists(self, path: str) -> bool:
        return self.base.file_exists(self.remap(path))

    def get_file_size(self, path: str) -> int:
        return self.base.get_file_size(self.remap(path))

    def get_free_space(self, path: str) -> int:
        return self.base.get_free_space(self.remap(path))

    def delete_file(self, path: str) -> None:
        self.base.delete_file(self.remap(path))

    def rename_file(self, src: str, dst: str) -> None:
        self.base.rename_file(self.remap(src), self.remap(dst))

    def create_dir(self, path: str) -> None:
        self.base.create_dir(self.remap(path))

    def get_children(self, path: str) -> list[str]:
        return self.base.get_children(self.remap(path))

    def now_micros(self) -> int:
        return self.base.now_micros()

    def read_file(self, path: str) -> bytes:
        return self.base.read_file(self.remap(path))

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        self.base.write_file(self.remap(path), data, sync=sync)
