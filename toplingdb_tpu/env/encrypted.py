"""Encrypted Env: transparent at-rest encryption of every file.

Analogue of the reference's EncryptedEnv (env/env_encryption.cc in
/root/reference): a BlockAccessCipherStream seam — any byte-addressable
stream cipher works because reads/writes XOR a position-derived keystream,
so random access never needs block alignment. Ships with CTRCipher (a
counter-mode keystream built on the project's xxh64, standing in for the
reference's example ROT13/CTR providers; swap in a real AES provider via
the same seam for production)."""

from __future__ import annotations

from toplingdb_tpu.env.env import Env
from toplingdb_tpu.utils import crc32c


class CipherStream:
    """Position-addressable keystream: encrypt/decrypt = XOR(keystream)."""

    def keystream(self, offset: int, n: int) -> bytes:
        raise NotImplementedError

    def crypt(self, data: bytes, offset: int) -> bytes:
        ks = self.keystream(offset, len(data))
        return bytes(a ^ b for a, b in zip(data, ks))


class CTRCipher(CipherStream):
    """Counter-mode keystream: block i = xxh64(key, seed=i) — deterministic,
    position-addressable, zero state (the provider seam; NOT a vetted
    production cipher)."""

    BLOCK = 8

    def __init__(self, key: bytes):
        self._key = key

    def keystream(self, offset: int, n: int) -> bytes:
        first = offset // self.BLOCK
        last = (offset + n + self.BLOCK - 1) // self.BLOCK
        out = bytearray()
        for blk in range(first, last):
            out += crc32c.xxh64(self._key, seed=blk).to_bytes(8, "little")
        skip = offset - first * self.BLOCK
        return bytes(out[skip : skip + n])


class _EncWritable:
    def __init__(self, f, cipher: CipherStream):
        self._f = f
        self._c = cipher

    def append(self, data: bytes) -> None:
        self._f.append(self._c.crypt(data, self._f.file_size()))

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        self._f.sync()

    def close(self) -> None:
        self._f.close()

    def file_size(self) -> int:
        return self._f.file_size()


class _EncRandomAccess:
    def __init__(self, f, cipher: CipherStream):
        self._f = f
        self._c = cipher

    def read(self, offset: int, n: int) -> bytes:
        return self._c.crypt(self._f.read(offset, n), offset)

    def size(self) -> int:
        return self._f.size()

    def close(self) -> None:
        self._f.close()


class _EncSequential:
    def __init__(self, f, cipher: CipherStream):
        self._f = f
        self._c = cipher
        self._pos = 0

    def read(self, n: int) -> bytes:
        data = self._c.crypt(self._f.read(n), self._pos)
        self._pos += len(data)
        return data

    def close(self) -> None:
        self._f.close()


class EncryptedEnv(Env):
    """Wraps any Env; file BYTES on the base Env are ciphertext."""

    def __init__(self, base: Env, cipher: CipherStream):
        self.base = base
        self.cipher = cipher

    def new_writable_file(self, path: str):
        return _EncWritable(self.base.new_writable_file(path), self.cipher)

    def new_random_access_file(self, path: str):
        return _EncRandomAccess(
            self.base.new_random_access_file(path), self.cipher
        )

    def new_sequential_file(self, path: str):
        return _EncSequential(
            self.base.new_sequential_file(path), self.cipher
        )

    def get_free_space(self, path: str) -> int:
        return self.base.get_free_space(path)

    def read_file(self, path: str) -> bytes:
        return self.cipher.crypt(self.base.read_file(path), 0)

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        self.base.write_file(path, self.cipher.crypt(data, 0), sync=sync)

    def file_exists(self, path: str) -> bool:
        return self.base.file_exists(path)

    def get_file_size(self, path: str) -> int:
        return self.base.get_file_size(path)

    def delete_file(self, path: str) -> None:
        self.base.delete_file(path)

    def rename_file(self, src: str, dst: str) -> None:
        self.base.rename_file(src, dst)

    def create_dir(self, path: str) -> None:
        self.base.create_dir(path)

    def get_children(self, path: str) -> list[str]:
        return self.base.get_children(path)
