"""Fault-injection Env: the crash/IO-error test harness seam
(reference utilities/fault_injection_fs.h:204 FaultInjectionTestFS in
/root/reference): wraps any Env; can drop unsynced writes ("crash"), inject
errors on the Nth operation or per-operation-type, and count IO."""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.env.env import Env, RandomAccessFile, SequentialFile, WritableFile
from toplingdb_tpu.utils.status import IOError_


class FaultInjectionEnv(Env):
    def __init__(self, base: Env):
        self.base = base
        self._mu = ccy.Lock("fault_injection.FaultInjectionEnv._mu")
        self._unsynced: dict[str, int] = {}   # path → synced length
        self._files: dict[str, "_FIWritable"] = {}
        self.fail_after_ops: int | None = None
        self.fail_ops: set[str] = set()       # e.g. {"append", "sync", "read"}
        self.op_count = 0
        self.io_counts: dict[str, int] = {}
        self._filesystem_active = True
        # Read-side corruption rules (corrupt_reads): the file on disk
        # stays intact; returned READ bytes are deterministically damaged.
        self._corrupt_rules: list[dict] = []
        self._corrupt_tick = 0  # transient-mode read counter
        self.corruptions_injected: list[tuple[str, int, int]] = []
        # Disk-full injection (set_disk_budget): fnmatch pattern →
        # remaining writable bytes. Appends charge the first matching
        # budget; exhaustion writes the affordable PREFIX (a torn short
        # write, exactly what a real disk does) then raises genuine
        # OSError(ENOSPC). delete_file refunds the deleted size, so
        # trash-deleter / GC reclamation genuinely restores headroom.
        self._disk_budgets: dict[str, int] = {}
        self.enospc_injected = 0

    # ------------------------------------------------------------------

    # -- read-side corruption injection (`corrupt_read` kind) ----------

    def corrupt_reads(self, pattern: str = "*", rate: float = 1e-5,
                      seed: int = 0,
                      kinds: tuple = ("bitflip", "byteswap"),
                      transient: bool = False) -> None:
        """Inject seeded read-side corruption: every read whose file's
        BASENAME matches `pattern` (fnmatch; e.g. '*.sst', '000012.*')
        has each returned byte independently damaged with probability
        `rate`. Deterministic in (seed, basename, offset, length) — the
        same read corrupts the same way every time, so integrity soaks
        reproduce from a seed without hand-editing files. `kinds`:
        'bitflip' XORs one random bit, 'byteswap' swaps adjacent bytes.
        `transient=True` additionally mixes a running read counter into
        the seed (still seeded, but a RETRY of the same read draws fresh
        randomness — models transient bus/DMA flips, so detect-and-retry
        paths like compaction can eventually make progress)."""
        with self._mu:
            self._corrupt_rules.append({
                "pattern": pattern, "rate": float(rate), "seed": int(seed),
                "kinds": tuple(kinds), "transient": bool(transient),
            })

    def clear_corrupt_reads(self) -> None:
        with self._mu:
            self._corrupt_rules = []

    def _maybe_corrupt(self, path: str, offset: int, data: bytes) -> bytes:
        if not self._corrupt_rules or not data:
            return data
        import fnmatch
        import hashlib
        import math
        import random

        name = path.rsplit("/", 1)[-1]
        out = None
        for rule in self._corrupt_rules:
            if not fnmatch.fnmatch(name, rule["pattern"]):
                continue
            rate = rule["rate"]
            if rate <= 0:
                continue
            # Stable digest seed (not hash(): per-process salt would break
            # cross-process reproducibility of a corruption scenario).
            tick = ""
            if rule.get("transient"):
                with self._mu:
                    self._corrupt_tick += 1
                    tick = f"|{self._corrupt_tick}"
            material = (f"{rule['seed']}|{name}|{offset}|{len(data)}{tick}"
                        .encode())
            rng = random.Random(int.from_bytes(
                hashlib.blake2s(material, digest_size=8).digest(),
                "little"))
            buf = bytearray(data if out is None else out)
            n_hit = 0
            # Geometric gap sampling: O(corrupted bytes), not O(length).
            log1m = math.log1p(-rate) if rate < 1.0 else None
            pos = 0
            while True:
                if log1m is None:
                    gap = 0
                else:
                    gap = int(math.log(max(rng.random(), 1e-300)) / log1m)
                pos += gap
                if pos >= len(buf):
                    break
                kind = rule["kinds"][rng.randrange(len(rule["kinds"]))] \
                    if rule["kinds"] else "bitflip"
                if kind == "byteswap" and pos + 1 < len(buf):
                    buf[pos], buf[pos + 1] = buf[pos + 1], buf[pos]
                else:
                    buf[pos] ^= 1 << rng.randrange(8)
                n_hit += 1
                pos += 1
                if log1m is None:
                    break
            if n_hit:
                out = bytes(buf)
                with self._mu:
                    self.corruptions_injected.append((name, offset, n_hit))
        return data if out is None else out

    # -- disk-full injection (`set_disk_budget` kind) ------------------

    def set_disk_budget(self, pattern: str, budget_bytes: int) -> None:
        """Cap the bytes writable to files matching `pattern` (fnmatch
        against the full path OR the basename — use '*' for a whole-disk
        budget, '*.sst' to starve only table writes). Writing past the
        budget injects a torn short write + genuine OSError(ENOSPC);
        deleting a matching file refunds its size. get_free_space()
        reports the remaining budget, so the SstFileManager poller sees
        the same full disk the writers hit."""
        with self._mu:
            self._disk_budgets[pattern] = int(budget_bytes)

    def add_disk_budget(self, pattern: str, delta: int) -> None:
        """Grow (or shrink) an existing budget — 'the operator freed
        space' move in a disk-full soak."""
        with self._mu:
            if pattern in self._disk_budgets:
                self._disk_budgets[pattern] += int(delta)

    def clear_disk_budgets(self) -> None:
        with self._mu:
            self._disk_budgets.clear()

    def disk_budget_remaining(self, pattern: str = "*") -> int | None:
        with self._mu:
            return self._disk_budgets.get(pattern)

    @staticmethod
    def _disk_match(path: str, pattern: str) -> bool:
        import fnmatch

        return (fnmatch.fnmatch(path, pattern)
                or fnmatch.fnmatch(path.rsplit("/", 1)[-1], pattern))

    def _charge_disk(self, path: str, nbytes: int) -> int:
        """Charge `nbytes` against the first matching budget; returns the
        affordable byte count (== nbytes when no budget matches)."""
        if nbytes <= 0:
            return nbytes
        with self._mu:
            for pat, rem in self._disk_budgets.items():
                if self._disk_match(path, pat):
                    afford = max(0, min(nbytes, rem))
                    self._disk_budgets[pat] = rem - afford
                    if afford < nbytes:
                        self.enospc_injected += 1
                    return afford
        return nbytes

    def _refund_disk(self, path: str, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._mu:
            for pat in self._disk_budgets:
                if self._disk_match(path, pat):
                    self._disk_budgets[pat] += nbytes
                    return

    def _disk_exhausted(self, path: str) -> bool:
        with self._mu:
            for pat, rem in self._disk_budgets.items():
                if self._disk_match(path, pat):
                    return rem <= 0
        return False

    def _op(self, kind: str) -> None:
        with self._mu:
            self.op_count += 1
            self.io_counts[kind] = self.io_counts.get(kind, 0) + 1
            if not self._filesystem_active:
                raise IOError_(f"injected: filesystem inactive ({kind})")
            if kind in self.fail_ops:
                raise IOError_(f"injected {kind} error")
            if self.fail_after_ops is not None and self.op_count > self.fail_after_ops:
                raise IOError_(f"injected error after {self.fail_after_ops} ops")

    def drop_unsynced_and_deactivate(self) -> None:
        """Simulate a crash: future IO fails until reactivate(); unsynced
        data in tracked writables is lost (truncate on reactivate)."""
        with self._mu:
            self._filesystem_active = False

    def reactivate_and_truncate(self) -> None:
        """Come back from the crash: truncate files to their synced length."""
        with self._mu:
            self._filesystem_active = True
            import os

            for path, synced in self._unsynced.items():
                try:
                    with open(path, "rb+") as f:
                        f.truncate(synced)
                except OSError:
                    pass
            self._unsynced.clear()

    # -- Env interface --------------------------------------------------

    def new_writable_file(self, path: str) -> WritableFile:
        self._op("open_w")
        f = self.base.new_writable_file(path)
        wrapped = _FIWritable(self, path, f)
        with self._mu:
            self._unsynced[path] = 0
        return wrapped

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        self._op("open_r")
        return _FIRandom(self, self.base.new_random_access_file(path), path)

    def new_sequential_file(self, path: str) -> SequentialFile:
        self._op("open_s")
        return _FISequential(self, self.base.new_sequential_file(path), path)

    def file_exists(self, path: str) -> bool:
        return self.base.file_exists(path)

    def get_file_size(self, path: str) -> int:
        return self.base.get_file_size(path)

    def delete_file(self, path: str) -> None:
        self._op("delete")
        freed = 0
        if self._disk_budgets:
            try:
                freed = self.base.get_file_size(path)
            except Exception as e:
                from toplingdb_tpu.utils import errors as _errors

                _errors.swallow(reason="fi-delete-size-probe", exc=e)
        self.base.delete_file(path)
        self._refund_disk(path, freed)

    def get_free_space(self, path: str) -> int:
        free = self.base.get_free_space(path)
        with self._mu:
            for pat, rem in self._disk_budgets.items():
                if self._disk_match(path, pat):
                    return min(free, max(0, rem))
        return free

    def rename_file(self, src: str, dst: str) -> None:
        self._op("rename")
        self.base.rename_file(src, dst)

    def create_dir(self, path: str) -> None:
        self.base.create_dir(path)

    def get_children(self, path: str):
        return self.base.get_children(path)


class _FIWritable(WritableFile):
    def __init__(self, env: FaultInjectionEnv, path: str, base: WritableFile):
        self._env = env
        self._path = path
        self._base = base

    def append(self, data: bytes) -> None:
        self._env._op("append")
        afford = self._env._charge_disk(self._path, len(data))
        if afford < len(data):
            import errno
            import os as _os

            if afford > 0:
                # Torn short write: a real disk persists the prefix that
                # fit before failing the call.
                self._base.append(data[:afford])
            raise OSError(errno.ENOSPC, _os.strerror(errno.ENOSPC),
                          self._path)
        self._base.append(data)

    def flush(self) -> None:
        self._base.flush()

    def sync(self) -> None:
        self._env._op("sync")
        if self._env._disk_exhausted(self._path):
            # fsync on a full filesystem fails too (dirty pages can't
            # land); recovers once something refunds the budget.
            import errno
            import os as _os

            raise OSError(errno.ENOSPC, _os.strerror(errno.ENOSPC),
                          self._path)
        self._base.sync()
        with self._env._mu:
            self._env._unsynced[self._path] = self._base.file_size()

    def close(self) -> None:
        self._base.close()

    def file_size(self) -> int:
        return self._base.file_size()


class _FIRandom(RandomAccessFile):
    def __init__(self, env, base, path: str = ""):
        self._env = env
        self._base = base
        self._path = path

    def read(self, offset, n):
        self._env._op("read")
        data = self._base.read(offset, n)
        return self._env._maybe_corrupt(self._path, offset, data)

    def size(self):
        return self._base.size()

    def close(self):
        self._base.close()


class _FISequential(SequentialFile):
    def __init__(self, env, base, path: str = ""):
        self._env = env
        self._base = base
        self._path = path
        self._off = 0  # running offset: deterministic corruption keying

    def read(self, n):
        self._env._op("read")
        data = self._base.read(n)
        off = self._off
        self._off += len(data)
        return self._env._maybe_corrupt(self._path, off, data)

    def close(self):
        self._base.close()


class DelayedReadEnv:
    """Env wrapper whose random-access reads sleep `delay_sec` first.

    Models device read latency on a page-cache-warm box, where real
    preads return in microseconds and I/O overlap is unmeasurable: the
    bench/microbench cold-cache twins run BOTH knob settings of the
    async read plane (env/async_reads.py) on this env, so the 0/1 ratio
    isolates ring fan-out + coalescing. Wrapped file handles also make
    the native get/multiget fast chains ineligible (no raw fd), which
    keeps the two twins on the same Python walk — the comparison never
    mixes native-vs-Python with sync-vs-async.
    """

    def __init__(self, base, delay_sec: float = 0.0002):
        self.base = base
        self.delay_sec = delay_sec
        self.delayed_reads = 0  # benign race: diagnostic counter only

    def new_random_access_file(self, path: str):
        return _DelayedRandom(self, self.base.new_random_access_file(path))

    def __getattr__(self, name):
        return getattr(self.base, name)


class _DelayedRandom(RandomAccessFile):
    def __init__(self, env: DelayedReadEnv, base):
        self._env = env
        self._base = base

    def read(self, offset, n):
        import time as _t

        _t.sleep(self._env.delay_sec)
        self._env.delayed_reads += 1
        return self._base.read(offset, n)

    def size(self):
        return self._base.size()

    def close(self):
        self._base.close()


class WalWriterFaultInjector:
    """Seeded fault points for the async WAL writer's submit ring
    (env/env.py AsyncIORing.fault_hook): each executed ring entry draws a
    plan decided by (seed, op ordinal), so a chaos soak reproduces the
    exact same WAL-writer-thread failures from a seed.

      "fail"   the entry raises IOError_ — the group whose durability
               barrier covers it receives the error (clean resume after)
      "delay"  the writer thread sleeps `delay_sec` first — widens the
               fsync-coalescing window and the publish/durability overlap

    `schedule` pins a plan to a specific executed-op ordinal (0-based);
    `rate` injects pseudo-randomly with plan weights `plans`. `ops`
    restricts injection to those ring op kinds (default: append + sync)."""

    def __init__(self, schedule: dict | None = None, rate: float = 0.0,
                 plans: tuple = ("fail", "delay"), seed: int = 0,
                 delay_sec: float = 0.005,
                 ops: tuple = ("append", "sync")):
        import random

        self.schedule = dict(schedule or {})
        self.rate = rate
        self.plans = tuple(plans)
        self.delay_sec = delay_sec
        self.ops = tuple(ops)
        self._rng = random.Random(seed)
        self._mu = ccy.Lock("fault_injection.WalWriterFaultInjector._mu")
        self._ordinal = 0
        self.injected: list[tuple[int, str, str]] = []  # (ordinal, kind, plan)

    def __call__(self, kind: str, nbytes: int) -> None:
        if kind not in self.ops:
            return
        with self._mu:
            ordinal = self._ordinal
            self._ordinal += 1
            p = self.schedule.get(ordinal)
            if p is None and self.rate > 0 and self.plans:
                if self._rng.random() < self.rate:
                    p = self.plans[self._rng.randrange(len(self.plans))]
            if p:
                self.injected.append((ordinal, kind, p))
        if p == "delay":
            import time as _t

            _t.sleep(self.delay_sec)
        elif p == "fail":
            raise IOError_(
                f"injected WAL-writer {kind} failure at op {ordinal}")

    def injected_counts(self) -> dict:
        with self._mu:
            out: dict[str, int] = {}
            for _o, _k, p in self.injected:
                out[p] = out.get(p, 0) + 1
            return out


class ReadFaultInjector:
    """Seeded fault points for the async read plane's reader rings
    (env/async_reads.py AsyncReadBatcher, plugged in as each ring's
    `fault_hook`): every executed ring entry draws a plan decided by
    (seed, executed-op ordinal), so a read-path chaos soak reproduces
    the exact same ring-thread failures from a seed.

      "fail"   the ring task raises IOError_ — the waiter of THAT block's
               token receives it (error propagation), the ring itself is
               not poisoned, and the next batch runs clean (resume)
      "delay"  the ring thread sleeps `delay_sec` first — models device
               read latency, which is also what the cold-cache bench uses
               to make I/O overlap measurable on a page-cache-warm box

    `schedule` pins a plan to a specific executed-op ordinal (0-based);
    `rate` injects pseudo-randomly with plan weights `plans`. `ops`
    defaults to ("task",) — block reads ride the ring as task entries."""

    def __init__(self, schedule: dict | None = None, rate: float = 0.0,
                 plans: tuple = ("fail", "delay"), seed: int = 0,
                 delay_sec: float = 0.0002, ops: tuple = ("task",)):
        import random

        self.schedule = dict(schedule or {})
        self.rate = rate
        self.plans = tuple(plans)
        self.delay_sec = delay_sec
        self.ops = tuple(ops)
        self._rng = random.Random(seed)
        self._mu = ccy.Lock("fault_injection.ReadFaultInjector._mu")
        self._ordinal = 0
        self.injected: list[tuple[int, str, str]] = []  # (ordinal, kind, plan)

    def __call__(self, kind: str, nbytes: int) -> None:
        if kind not in self.ops:
            return
        with self._mu:
            ordinal = self._ordinal
            self._ordinal += 1
            p = self.schedule.get(ordinal)
            if p is None and self.rate > 0 and self.plans:
                if self._rng.random() < self.rate:
                    p = self.plans[self._rng.randrange(len(self.plans))]
            if p:
                self.injected.append((ordinal, kind, p))
        if p == "delay":
            import time as _t

            _t.sleep(self.delay_sec)
        elif p == "fail":
            raise IOError_(
                f"injected reader-ring {kind} failure at op {ordinal}")

    def injected_counts(self) -> dict:
        with self._mu:
            out: dict[str, int] = {}
            for _o, _k, p in self.injected:
                out[p] = out.get(p, 0) + 1
            return out


class ShipFaultInjector:
    """Deterministic fault points for the replication ship transport
    (replication/log_shipper.py FaultyTransport), mirroring
    DcompactFaultInjector's shape so replication chaos soaks are
    reproducible from a seed. Plans, decided per pull ordinal:

      "drop"      the pulled frames never arrive (follower sees no progress)
      "delay"     the frames arrive after `delay_sec`
      "truncate"  a frame's encoded bytes are cut mid-payload (the follower
                  must detect the bad CRC/short frame and re-pull, never
                  apply a half batch)

    `rate` injects pseudo-randomly from `seed` with plan weights `plans`;
    `schedule` pins a plan to a specific pull ordinal (0-based)."""

    def __init__(self, schedule: dict | None = None, rate: float = 0.0,
                 plans: tuple = ("drop", "delay", "truncate"),
                 seed: int = 0, delay_sec: float = 0.01):
        import random

        self.schedule = dict(schedule or {})
        self.rate = rate
        self.plans = tuple(plans)
        self.delay_sec = delay_sec
        self._rng = random.Random(seed)
        self._mu = ccy.Lock("fault_injection.ShipFaultInjector._mu")
        self._ordinal = 0
        self.injected: list[tuple[int, str]] = []  # (ordinal, plan)

    def plan(self) -> str | None:
        with self._mu:
            ordinal = self._ordinal
            self._ordinal += 1
            p = self.schedule.get(ordinal)
            if p is None and self.rate > 0 and self.plans:
                if self._rng.random() < self.rate:
                    p = self.plans[self._rng.randrange(len(self.plans))]
            if p:
                self.injected.append((ordinal, p))
            return p

    def injected_counts(self) -> dict:
        with self._mu:
            out: dict[str, int] = {}
            for _o, p in self.injected:
                out[p] = out.get(p, 0) + 1
            return out

    def truncate_bytes(self, data: bytes) -> bytes:
        """Cut an encoded frame roughly in half — past the header when
        possible, so the follower exercises the CRC check rather than the
        short-header check every time."""
        if len(data) <= 2:
            return data[:1]
        return data[: max(1, len(data) // 2)]


class PartitionGate:
    """Network-partition switch for HTTP clients (fleet chaos soak): an
    engaged gate makes every guarded call fail fast with IOError_, as a
    dropped route would — the caller sees unreachability, not hangs.
    Thread-safe; `blocked` counts the calls the partition ate."""

    def __init__(self):
        self._mu = ccy.Lock("fault_injection.PartitionGate._mu")
        self._engaged = False
        self.blocked = 0

    def engage(self) -> None:
        with self._mu:
            self._engaged = True

    def heal(self) -> None:
        with self._mu:
            self._engaged = False

    @property
    def engaged(self) -> bool:
        with self._mu:
            return self._engaged

    def check(self, what: str = "call") -> None:
        """Raise IOError_ if the partition is engaged."""
        with self._mu:
            if self._engaged:
                self.blocked += 1
                raise IOError_(f"partitioned: {what}")


class StoreFaultInjector:
    """Seeded fault wrapper for a shared SST object store
    (storage/object_store.py LocalObjectStore or storage/store_server.py
    StoreClient): interposes on the data-plane verbs so storage chaos
    soaks reproduce exactly from a seed. Plans, decided per data op
    ordinal (fetch/put/publish_file):

      "drop"      the op raises IOError_ (an unreachable/refusing store)
      "delay"     the op completes after `delay_sec`
      "corrupt"   a fetch returns payload bytes with one flipped bit —
                  the cache tier's address verification must catch it and
                  re-fetch; a corrupt object must NEVER materialize
      "truncate"  a fetch returns a prefix of the payload (same contract)

    Writes only ever see "drop"/"delay": the store itself verifies
    payloads before making them visible, so a corrupted upload is the
    uploader's bug, not a transport fault. Control verbs (contains, pins,
    list, delete, status) pass through untouched."""

    def __init__(self, store, schedule: dict | None = None,
                 rate: float = 0.0,
                 plans: tuple = ("drop", "delay", "corrupt", "truncate"),
                 seed: int = 0, delay_sec: float = 0.002):
        import random

        self._store = store
        self.schedule = dict(schedule or {})
        self.rate = rate
        self.plans = tuple(plans)
        self.delay_sec = delay_sec
        self._rng = random.Random(seed)
        self._mu = ccy.Lock("fault_injection.StoreFaultInjector._mu")
        self._ordinal = 0
        self.injected: list[tuple[int, str, str]] = []  # (ordinal, op, plan)

    def _plan(self, op: str) -> str | None:
        with self._mu:
            ordinal = self._ordinal
            self._ordinal += 1
            p = self.schedule.get(ordinal)
            if p is None and self.rate > 0 and self.plans:
                if self._rng.random() < self.rate:
                    p = self.plans[self._rng.randrange(len(self.plans))]
            if p and op != "fetch" and p in ("corrupt", "truncate"):
                p = "drop"  # writes can't lie (the store verifies): drop
            if p:
                self.injected.append((ordinal, op, p))
            return p

    def _apply(self, op: str):
        p = self._plan(op)
        if p == "delay":
            import time as _t

            _t.sleep(self.delay_sec)
        elif p == "drop":
            raise IOError_(f"injected: store {op} dropped")
        return p

    # -- data-plane verbs (faulted) ------------------------------------

    def fetch(self, addr: str) -> bytes:
        p = self._apply("fetch")
        data = self._store.fetch(addr)
        if p == "corrupt" and data:
            i = self._rng.randrange(len(data))
            return data[:i] + bytes([data[i] ^ 0x40]) + data[i + 1:]
        if p == "truncate":
            return data[: len(data) // 2]
        return data

    def put(self, addr: str, payload: bytes) -> bool:
        self._apply("put")
        return self._store.put(addr, payload)

    def publish_file(self, src_path: str, addr: str, src_env=None) -> bool:
        self._apply("publish")
        return self._store.publish_file(src_path, addr, src_env=src_env)

    # -- control verbs (clean) -----------------------------------------

    def __getattr__(self, name):
        return getattr(self._store, name)

    def injected_counts(self) -> dict:
        with self._mu:
            out: dict[str, int] = {}
            for _o, _op, p in self.injected:
                out[p] = out.get(p, 0) + 1
            return out
