"""Fault-injection Env: the crash/IO-error test harness seam
(reference utilities/fault_injection_fs.h:204 FaultInjectionTestFS in
/root/reference): wraps any Env; can drop unsynced writes ("crash"), inject
errors on the Nth operation or per-operation-type, and count IO."""

from __future__ import annotations

import threading

from toplingdb_tpu.env.env import Env, RandomAccessFile, SequentialFile, WritableFile
from toplingdb_tpu.utils.status import IOError_


class FaultInjectionEnv(Env):
    def __init__(self, base: Env):
        self.base = base
        self._mu = threading.Lock()
        self._unsynced: dict[str, int] = {}   # path → synced length
        self._files: dict[str, "_FIWritable"] = {}
        self.fail_after_ops: int | None = None
        self.fail_ops: set[str] = set()       # e.g. {"append", "sync", "read"}
        self.op_count = 0
        self.io_counts: dict[str, int] = {}
        self._filesystem_active = True

    # ------------------------------------------------------------------

    def _op(self, kind: str) -> None:
        with self._mu:
            self.op_count += 1
            self.io_counts[kind] = self.io_counts.get(kind, 0) + 1
            if not self._filesystem_active:
                raise IOError_(f"injected: filesystem inactive ({kind})")
            if kind in self.fail_ops:
                raise IOError_(f"injected {kind} error")
            if self.fail_after_ops is not None and self.op_count > self.fail_after_ops:
                raise IOError_(f"injected error after {self.fail_after_ops} ops")

    def drop_unsynced_and_deactivate(self) -> None:
        """Simulate a crash: future IO fails until reactivate(); unsynced
        data in tracked writables is lost (truncate on reactivate)."""
        with self._mu:
            self._filesystem_active = False

    def reactivate_and_truncate(self) -> None:
        """Come back from the crash: truncate files to their synced length."""
        with self._mu:
            self._filesystem_active = True
            import os

            for path, synced in self._unsynced.items():
                try:
                    with open(path, "rb+") as f:
                        f.truncate(synced)
                except OSError:
                    pass
            self._unsynced.clear()

    # -- Env interface --------------------------------------------------

    def new_writable_file(self, path: str) -> WritableFile:
        self._op("open_w")
        f = self.base.new_writable_file(path)
        wrapped = _FIWritable(self, path, f)
        with self._mu:
            self._unsynced[path] = 0
        return wrapped

    def new_random_access_file(self, path: str) -> RandomAccessFile:
        self._op("open_r")
        return _FIRandom(self, self.base.new_random_access_file(path))

    def new_sequential_file(self, path: str) -> SequentialFile:
        self._op("open_s")
        return _FISequential(self, self.base.new_sequential_file(path))

    def file_exists(self, path: str) -> bool:
        return self.base.file_exists(path)

    def get_file_size(self, path: str) -> int:
        return self.base.get_file_size(path)

    def delete_file(self, path: str) -> None:
        self._op("delete")
        self.base.delete_file(path)

    def rename_file(self, src: str, dst: str) -> None:
        self._op("rename")
        self.base.rename_file(src, dst)

    def create_dir(self, path: str) -> None:
        self.base.create_dir(path)

    def get_children(self, path: str):
        return self.base.get_children(path)


class _FIWritable(WritableFile):
    def __init__(self, env: FaultInjectionEnv, path: str, base: WritableFile):
        self._env = env
        self._path = path
        self._base = base

    def append(self, data: bytes) -> None:
        self._env._op("append")
        self._base.append(data)

    def flush(self) -> None:
        self._base.flush()

    def sync(self) -> None:
        self._env._op("sync")
        self._base.sync()
        with self._env._mu:
            self._env._unsynced[self._path] = self._base.file_size()

    def close(self) -> None:
        self._base.close()

    def file_size(self) -> int:
        return self._base.file_size()


class _FIRandom(RandomAccessFile):
    def __init__(self, env, base):
        self._env = env
        self._base = base

    def read(self, offset, n):
        self._env._op("read")
        return self._base.read(offset, n)

    def size(self):
        return self._base.size()

    def close(self):
        self._base.close()


class _FISequential(SequentialFile):
    def __init__(self, env, base):
        self._env = env
        self._base = base

    def read(self, n):
        self._env._op("read")
        return self._base.read(n)

    def close(self):
        self._base.close()


class ShipFaultInjector:
    """Deterministic fault points for the replication ship transport
    (replication/log_shipper.py FaultyTransport), mirroring
    DcompactFaultInjector's shape so replication chaos soaks are
    reproducible from a seed. Plans, decided per pull ordinal:

      "drop"      the pulled frames never arrive (follower sees no progress)
      "delay"     the frames arrive after `delay_sec`
      "truncate"  a frame's encoded bytes are cut mid-payload (the follower
                  must detect the bad CRC/short frame and re-pull, never
                  apply a half batch)

    `rate` injects pseudo-randomly from `seed` with plan weights `plans`;
    `schedule` pins a plan to a specific pull ordinal (0-based)."""

    def __init__(self, schedule: dict | None = None, rate: float = 0.0,
                 plans: tuple = ("drop", "delay", "truncate"),
                 seed: int = 0, delay_sec: float = 0.01):
        import random

        self.schedule = dict(schedule or {})
        self.rate = rate
        self.plans = tuple(plans)
        self.delay_sec = delay_sec
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self._ordinal = 0
        self.injected: list[tuple[int, str]] = []  # (ordinal, plan)

    def plan(self) -> str | None:
        with self._mu:
            ordinal = self._ordinal
            self._ordinal += 1
            p = self.schedule.get(ordinal)
            if p is None and self.rate > 0 and self.plans:
                if self._rng.random() < self.rate:
                    p = self.plans[self._rng.randrange(len(self.plans))]
            if p:
                self.injected.append((ordinal, p))
            return p

    def injected_counts(self) -> dict:
        with self._mu:
            out: dict[str, int] = {}
            for _o, p in self.injected:
                out[p] = out.get(p, 0) + 1
            return out

    def truncate_bytes(self, data: bytes) -> bytes:
        """Cut an encoded frame roughly in half — past the header when
        possible, so the follower exercises the CRC check rather than the
        short-header check every time."""
        if len(data) <= 2:
            return data[:1]
        return data[: max(1, len(data) // 2)]
