"""IO tracing Env wrapper.

Analogue of the reference's IO tracer (trace_replay/io_tracer.cc +
env/file_system_tracer.{h,cc}, parsed by tools/io_tracer_parser_tool.cc in
/root/reference): every file operation through the wrapped Env is recorded
as a JSONL line {ts_us, op, path, offset, len, latency_us}. Thread-safe;
records go to a plain local file (the trace must not recurse through the
traced Env).
"""

from __future__ import annotations

import json
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

from toplingdb_tpu.env.env import Env


class IOTracer:
    def __init__(self, trace_path: str):
        self._f = open(trace_path, "a", buffering=1)
        self._mu = ccy.Lock("io_tracer.IOTracer._mu")
        self.num_records = 0

    def record(self, op: str, path: str, offset: int = 0, length: int = 0,
               latency_us: int = 0) -> None:
        line = json.dumps({
            "ts_us": int(time.time() * 1e6), "op": op, "path": path,
            "offset": offset, "len": length, "latency_us": latency_us,
        })
        with self._mu:
            self._f.write(line + "\n")
            self.num_records += 1

    def close(self) -> None:
        with self._mu:
            self._f.close()


def parse_io_trace(trace_path: str) -> dict:
    """Aggregate an IO trace (the io_tracer_parser role): per-op counts,
    bytes, and latency totals. Delegates to the CLI parser so there is
    exactly ONE parse loop (tools/io_tracer_parser.py)."""
    from toplingdb_tpu.tools.io_tracer_parser import parse

    return parse(trace_path)["per_op"]


class _TracedWritable:
    def __init__(self, f, path: str, tracer: IOTracer):
        self._f = f
        self._path = path
        self._t = tracer

    def append(self, data: bytes) -> None:
        t0 = time.time()
        self._f.append(data)
        self._t.record("append", self._path, self._f.file_size() - len(data),
                       len(data), int((time.time() - t0) * 1e6))

    def flush(self) -> None:
        self._f.flush()

    def sync(self) -> None:
        t0 = time.time()
        self._f.sync()
        self._t.record("sync", self._path, 0, 0,
                       int((time.time() - t0) * 1e6))

    def close(self) -> None:
        self._f.close()
        self._t.record("close", self._path)

    def file_size(self) -> int:
        return self._f.file_size()


class _TracedRandomAccess:
    def __init__(self, f, path: str, tracer: IOTracer):
        self._f = f
        self._path = path
        self._t = tracer

    def read(self, offset: int, n: int) -> bytes:
        t0 = time.time()
        out = self._f.read(offset, n)
        self._t.record("read", self._path, offset, len(out),
                       int((time.time() - t0) * 1e6))
        return out

    def size(self) -> int:
        return self._f.size()

    def close(self) -> None:
        self._f.close()


class IOTracingEnv(Env):
    """Wraps any Env; file handles record their IO into the tracer."""

    def __init__(self, base: Env, tracer: IOTracer):
        self.base = base
        self.tracer = tracer

    def new_writable_file(self, path: str):
        self.tracer.record("new_writable", path)
        return _TracedWritable(self.base.new_writable_file(path), path,
                               self.tracer)

    def new_random_access_file(self, path: str):
        self.tracer.record("open_random", path)
        return _TracedRandomAccess(
            self.base.new_random_access_file(path), path, self.tracer
        )

    def new_sequential_file(self, path: str):
        self.tracer.record("open_sequential", path)
        return self.base.new_sequential_file(path)

    def file_exists(self, path: str) -> bool:
        return self.base.file_exists(path)

    def get_free_space(self, path: str) -> int:
        return self.base.get_free_space(path)

    def get_file_size(self, path: str) -> int:
        return self.base.get_file_size(path)

    def delete_file(self, path: str) -> None:
        self.tracer.record("delete", path)
        self.base.delete_file(path)

    def rename_file(self, src: str, dst: str) -> None:
        self.tracer.record("rename", src)
        self.base.rename_file(src, dst)

    def create_dir(self, path: str) -> None:
        self.base.create_dir(path)

    def get_children(self, path: str) -> list[str]:
        return self.base.get_children(path)

    def read_file(self, path: str) -> bytes:
        t0 = time.time()
        out = self.base.read_file(path)
        self.tracer.record("read_file", path, 0, len(out),
                           int((time.time() - t0) * 1e6))
        return out

    def write_file(self, path: str, data: bytes, sync: bool = False) -> None:
        self.tracer.record("write_file", path, 0, len(data))
        self.base.write_file(path, data, sync=sync)
