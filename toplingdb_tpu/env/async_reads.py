"""Async read plane: batched block-fetch fan-out over AsyncIORing.

The reference fork's biggest read-path win is the fiber/io_uring MultiGet
(PAPER.md item 4, db_impl.cc:3026-3227): every block fetch in a batch is
submitted up front and overlapped, instead of serializing preads in the
request thread. `AsyncReadBatcher` is that surgery expressed on top of
the Env's AsyncIORing primitive (env/env.py):

  * callers submit a BATCH of (file, offset, length) block requests;
  * requests are coalesced per file — adjacent/overlapping ranges merge
    into one pread, bounded by `max_span` so a long run of neighbouring
    blocks cannot balloon into an arbitrarily large read;
  * each coalesced range becomes one ring `submit_task` pread, fanned
    round-robin across N rings (N I/O threads) so a cold-cache miss
    storm overlaps rather than serializes;
  * every ORIGINAL request gets back a completion token whose `wait()`
    returns exactly the bytes a synchronous `f.read(offset, n)` would
    have returned — the sync path stays the byte-parity oracle.

`PrereadSpans` adapts a set of tokens back into the `read(offset, n)`
shape `table/format.py read_block` consumes, so the block decode/verify
path is untouched: the overlay slots in as the `pf` source argument of
`TableReader._read_data_block` and falls through to the real file for
anything that was not prefetched.

After `close()` the batcher degrades, it does not poison: submissions
are served synchronously inline (tokens come back pre-completed) and
`READ_ASYNC_FALLBACKS` ticks — a shutdown race costs latency, never
correctness.
"""

from __future__ import annotations

from toplingdb_tpu.env.env import AioToken, AsyncIORing
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils.status import IOError_
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils import telemetry as _tm

# One coalesced pread never exceeds this many bytes (matches the upper
# readahead window of FilePrefetchBuffer: big enough to merge a run of
# ~4K blocks + trailers, small enough to keep ring tasks short).
DEFAULT_MAX_SPAN = 1 << 20


class ReadToken:
    """Completion token for ONE submitted (offset, length) request.

    `wait()` returns the same bytes `rfile.read(offset, length)` would:
    the coalesced carrier read is sliced back down, and a short read at
    EOF shortens the slice exactly like the sync pread would.
    """

    __slots__ = ("_tok", "_base", "_off", "_n")

    def __init__(self, tok: AioToken, base: int, off: int, n: int):
        self._tok = tok
        self._base = base   # carrier range start offset
        self._off = off     # this request's absolute offset
        self._n = n

    def ready(self) -> bool:
        return self._tok.ready()

    def wait(self) -> bytes:
        data = self._tok.wait()
        lo = self._off - self._base
        return bytes(data[lo:lo + self._n])


class PrereadSpans:
    """`read(offset, n)` view over a file's prefetched ranges.

    FilePrefetchBuffer-compatible surface (read + hits/misses) so it can
    be passed as the `pf` source of `TableReader._read_data_block`; any
    range that was not prefetched falls through to the real file — a
    correctness backstop, counted as a miss.
    """

    __slots__ = ("_f", "_spans", "hits", "misses")

    def __init__(self, rfile, spans: list[tuple[int, int, ReadToken]]):
        self._f = rfile
        self._spans = sorted(spans, key=lambda s: s[0])
        self.hits = 0
        self.misses = 0

    def read(self, offset: int, n: int) -> bytes:
        spans = self._spans
        lo, hi = 0, len(spans)
        while lo < hi:
            mid = (lo + hi) // 2
            if spans[mid][0] <= offset:
                lo = mid + 1
            else:
                hi = mid
        if lo:
            start, end, tok = spans[lo - 1]
            if offset >= start and offset + n <= end:
                self.hits += 1
                data = tok.wait()
                return bytes(data[offset - start:offset - start + n])
        self.misses += 1
        return self._f.read(offset, n)


class AsyncReadBatcher:
    """Fan a batch of block reads across N AsyncIORings.

    Thread-safe: submission holds `_mu` only for ring round-robin and
    the closed check; the preads themselves run on the ring threads
    (os.pread releases the GIL, so N rings genuinely overlap I/O).
    """

    def __init__(self, rings: int = 2, ring_capacity: int = 256,
                 task_capacity: int | None = None, stats=None,
                 fault_hook=None, name: str = "read"):
        n = max(1, int(rings))
        self._rings = [
            AsyncIORing(capacity=ring_capacity, name=f"{name}-{i}",
                        task_capacity=task_capacity, fault_hook=fault_hook)
            for i in range(n)
        ]
        self._mu = ccy.Lock("async_reads.AsyncReadBatcher._mu")
        self._rr = 0
        self._closed = False
        self.stats = stats
        self.max_span = DEFAULT_MAX_SPAN
        self.batches = 0
        self.coalesced = 0
        self.fallbacks = 0

    @property
    def n_rings(self) -> int:
        return len(self._rings)

    # -- submission ----------------------------------------------------

    def _next_ring(self) -> AsyncIORing | None:
        with self._mu:
            if self._closed:
                return None
            i = self._rr
            self._rr = (i + 1) % len(self._rings)
            return self._rings[i]

    def submit_batch(self, requests) -> list[ReadToken]:
        """requests: iterable of (rfile, offset, length). Returns one
        ReadToken per request, in order. Adjacent/overlapping ranges of
        the same file are coalesced into shared carrier preads."""
        reqs = list(requests)
        with _tm.span("read.async.batch", requests=len(reqs),
                      rings=len(self._rings)):
            by_file: dict[int, list[tuple[int, int, int]]] = {}
            files: dict[int, object] = {}
            for i, (f, off, n) in enumerate(reqs):
                by_file.setdefault(id(f), []).append((int(off), int(n), i))
                files[id(f)] = f
            out: list[ReadToken | None] = [None] * len(reqs)
            ranges = 0
            for fid, lst in by_file.items():
                f = files[fid]
                lst.sort()
                run: list[tuple[int, int, int]] = []
                run_end = -1
                for off, n, i in lst:
                    if (run and off <= run_end
                            and max(run_end, off + n) - run[0][0]
                            <= self.max_span):
                        run.append((off, n, i))
                        run_end = max(run_end, off + n)
                    else:
                        if run:
                            ranges += 1
                            self._dispatch(f, run, run_end, out)
                        run = [(off, n, i)]
                        run_end = off + n
                if run:
                    ranges += 1
                    self._dispatch(f, run, run_end, out)
            self.batches += 1
            self.coalesced += len(reqs) - ranges
            if self.stats is not None:
                self.stats.record_tick(st.READ_ASYNC_BATCHES, 1)
                if len(reqs) > ranges:
                    self.stats.record_tick(st.READ_ASYNC_COALESCED,
                                           len(reqs) - ranges)
            return out

    def _dispatch(self, f, run, run_end, out) -> None:
        base = run[0][0]
        ring = self._next_ring()
        if ring is not None:
            try:
                tok = ring.submit_task(
                    lambda f=f, base=base, n=run_end - base:
                    f.read(base, n))
            except IOError_:
                tok = None
        else:
            tok = None
        if tok is None:
            # Closed (or closing) batcher: serve inline, stay correct.
            self.fallbacks += 1
            if self.stats is not None:
                self.stats.record_tick(st.READ_ASYNC_FALLBACKS, 1)
            tok = AioToken()
            try:
                tok.done(result=f.read(base, run_end - base))
            except BaseException as e:  # noqa: BLE001
                tok.done(err=e)
        for off, n, i in run:
            out[i] = ReadToken(tok, base, off, n)

    def preread(self, rfile, ranges) -> PrereadSpans:
        """Submit one file's (offset, length) ranges and hand back the
        overlay `_read_data_block` can consume as its `pf` source."""
        toks = self.submit_batch([(rfile, off, n) for off, n in ranges])
        return PrereadSpans(
            rfile,
            [(off, off + n, t) for (off, n), t in zip(ranges, toks)])

    def submit_task(self, fn) -> AioToken:
        """Generic async work round-robined onto a reader ring (zip
        mini-group decodes, iterator readahead windows)."""
        ring = self._next_ring()
        if ring is not None:
            try:
                return ring.submit_task(fn)
            except IOError_:
                pass
        self.fallbacks += 1
        if self.stats is not None:
            self.stats.record_tick(st.READ_ASYNC_FALLBACKS, 1)
        tok = AioToken()
        try:
            tok.done(result=fn())
        except BaseException as e:  # noqa: BLE001
            tok.done(err=e)
        return tok

    def ring_for(self, seq: int) -> AsyncIORing | None:
        """Stable ring handle for long-lived consumers (an iterator's
        FilePrefetchBuffer keeps ONE ring so its windows stay ordered).

        Lock-free on purpose: `_rings` is immutable after construction
        and `_closed` only flips False→True, so the worst race hands
        out a closing ring — whose submits fall back inline. Taking
        `_mu` here would create a sideways rank-2 edge under
        `db.DB._mutex` (DB.new_iterator builds children under it)."""
        if self._closed:
            return None
        return self._rings[seq % len(self._rings)]

    # -- lifecycle -----------------------------------------------------

    def drain(self) -> None:
        for r in self._rings:
            r.drain()

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
        for r in self._rings:
            r.close()
