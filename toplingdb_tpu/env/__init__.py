"""Env / FileSystem abstraction.

The reference splits OS access behind Env/FileSystem (include/rocksdb/env.h:151,
include/rocksdb/file_system.h:257 in /root/reference) so tests can substitute
in-memory and fault-injecting filesystems. We keep the same seam: PosixEnv is
the real thing; MemEnv backs unit tests; wrappers can interpose for fault
injection and IO counting.
"""

from toplingdb_tpu.env.async_reads import (  # noqa: F401
    AsyncReadBatcher,
    PrereadSpans,
    ReadToken,
)
from toplingdb_tpu.env.env import (  # noqa: F401
    AioToken,
    AsyncIORing,
    AsyncWritableFile,
    Env,
    PosixEnv,
    MemEnv,
    WritableFile,
    RandomAccessFile,
    SequentialFile,
    default_env,
)
