"""Device kernels: sort-merge + MVCC GC masking.

The k-way merge + CompactionIterator state machine (reference
table/merging_iterator.cc + db/compaction/compaction_iterator.cc:475),
re-expressed as two jitted array programs:

  pad_columns(...) + device_sort(...)   one multi-operand `jax.lax.sort`
      realizes internal-key order over all input runs at once (the whole
      merge); sorted columns stay on device for the GC kernel.
  gc_mask(...)   survivor decisions as shifted/segment comparisons over the
      sorted stream — no data-dependent control flow.

Shapes are padded to the next power of two so XLA compiles one program per
size bucket, not per job. All lanes are 32-bit (TPU-native); 64-bit packed
(seqno,type) values travel as hi/lo uint32 word pairs.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.utils.status import NotSupported

_SIGN = 0x80000000
# Stripe computation is an [N, S] broadcast compare, linear in the padded
# snapshot count; pad to pow2 buckets (>=64) so the jit cache stays small
# and typical jobs pay the 64-wide compare. Above the cap the scheduler
# falls back to the host path.
MAX_SNAPSHOTS = 1024
_MIN_SNAP_BUCKET = 64



def _split_snapshots(snapshots: list[int]) -> tuple[np.ndarray, np.ndarray]:
    """Sorted snapshot seqnos padded to the next pow2 bucket (>=64) with the
    2^56 sentinel, split into (hi, lo) uint32 word arrays for the device
    kernels."""
    pad_snap = 1 << 56
    bucket = _MIN_SNAP_BUCKET
    while bucket < len(snapshots):
        bucket *= 2
    snaps = sorted(snapshots) + [pad_snap] * (bucket - len(snapshots))
    snap_hi = np.array([x >> 32 for x in snaps], dtype=np.uint32)
    snap_lo = np.array([x & 0xFFFFFFFF for x in snaps], dtype=np.uint32)
    return snap_hi, snap_lo


def _split_cover(cover: np.ndarray, p: int):
    """uint64 per-row max-covering-tombstone seqnos → (hi, lo) u32 word
    arrays padded to p rows (shared by the single-chip and mesh drivers)."""
    tc = np.zeros(p, dtype=np.uint64)
    tc[: len(cover)] = cover
    return ((tc >> np.uint64(32)).astype(np.uint32),
            (tc & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _tomb_covered(seq_hi, seq_lo, tomb_hi, tomb_lo, snap_hi, snap_lo,
                  stripe):
    """Same-stripe range-tombstone shadowing (traced; shared by the
    single-chip GC mask and the mesh kernel so they cannot diverge)."""
    has_tomb = (tomb_hi | tomb_lo) != 0
    tomb_newer = (tomb_hi > seq_hi) | ((tomb_hi == seq_hi)
                                       & (tomb_lo > seq_lo))
    tsnap_lt = (snap_hi[None, :] < tomb_hi[:, None]) | (
        (snap_hi[None, :] == tomb_hi[:, None])
        & (snap_lo[None, :] < tomb_lo[:, None])
    )
    tomb_stripe = jnp.sum(tsnap_lt, axis=1).astype(jnp.int32)
    return has_tomb & tomb_newer & (tomb_stripe == stripe)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _want_pallas_gc() -> bool:
    """Use the Pallas GC-row kernel inside _gc_mask_impl. Decided at TRACE
    time (the jit cache does not key on this): default ON for accelerator
    backends, OFF on cpu (where interpret mode would crawl);
    TPULSM_PALLAS_GC=1/0 forces. Flip the env var before first use."""
    import os

    env = os.environ.get("TPULSM_PALLAS_GC", "")
    if env in ("0", "1"):
        return env == "1"
    return jax.default_backend() != "cpu"


def pad_columns(col) -> dict:
    """Pad a ColumnarEntries to the next power of two. Sentinel rows sort
    last (int32 max keys) and carry vtype=-1."""
    n = col.n
    p = _next_pow2(max(1, n))
    w = col.key_words.shape[1]
    int32max = np.iinfo(np.int32).max
    out = {
        "n": n, "w": w,
        "key_words": np.full((p, w), int32max, dtype=np.int32),
        "key_len": np.full(p, int32max, dtype=np.int32),
        "inv_hi": np.full(p, int32max, dtype=np.int32),
        "inv_lo": np.full(p, int32max, dtype=np.int32),
        "vtype": np.full(p, -1, dtype=np.int32),
    }
    out["key_words"][:n] = col.key_words
    out["key_len"][:n] = col.key_len
    out["inv_hi"][:n] = col.inv_hi
    out["inv_lo"][:n] = col.inv_lo
    out["vtype"][:n] = col.vtype
    return out


# ---------------------------------------------------------------------------
# Sort
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("num_key_words",))
def _sort_impl(key_words, key_len, inv_hi, inv_lo, vtype, idx, num_key_words):
    operands = tuple(key_words[:, w] for w in range(num_key_words)) + (
        key_len, inv_hi, inv_lo, vtype, idx,
    )
    out = jax.lax.sort(operands, num_keys=num_key_words + 3)
    key_words_sorted = jnp.stack(out[:num_key_words], axis=1)
    key_len_s, inv_hi_s, inv_lo_s, vtype_s, perm = out[num_key_words:]
    return key_words_sorted, key_len_s, inv_hi_s, inv_lo_s, vtype_s, perm


def device_sort(padded: dict):
    """Sort padded columns into internal-key order on device. Returns a dict
    of SORTED on-device columns (padding rows last) plus the permutation of
    original indices as np.ndarray[:n]."""
    p = padded["key_words"].shape[0]
    idx = np.arange(p, dtype=np.int32)
    kw, kl, ih, il, vt, perm = _sort_impl(
        padded["key_words"], padded["key_len"], padded["inv_hi"],
        padded["inv_lo"], padded["vtype"], idx, padded["w"],
    )
    sorted_cols = {
        "n": padded["n"], "w": padded["w"],
        "key_words": kw, "key_len": kl, "inv_hi": ih, "inv_lo": il,
        "vtype": vt,
    }
    return sorted_cols, np.asarray(perm)[: padded["n"]]


# ---------------------------------------------------------------------------
# Segmented merge of presorted runs
#
# The inputs of a compaction are ALREADY sorted runs (one per input SST
# slice); a full lax.sort re-derives that order with O(N log^2 N)
# compare-exchange stages. The reference merges K runs with a binary heap
# (table/merging_iterator.cc:476-506, util/heap.h:43) — O(N log K). The
# TPU-honest equivalent: hierarchical pairwise RANK merges. Each round
# merges run pairs by computing every row's rank in its partner run with a
# vectorized binary search (static ~log2(P) trip count, lexicographic
# folded compare over the key columns), then applies the resulting
# permutation — log2(R) rounds total, O(N log R log P) compares instead of
# the sort network, and the non-key columns move once per round instead of
# once per stage.
# ---------------------------------------------------------------------------


def _rows_less(cols, ai, bi):
    """Lexicographic a < b over priority-ordered int32 column tuples,
    folded from the least-significant column up (no data-dependent
    control flow)."""
    lt = jnp.zeros(ai.shape, dtype=bool)
    for c in reversed(cols):
        a = c[ai]
        b = c[bi]
        lt = (a < b) | ((a == b) & lt)
    return lt


def _partner_bound(cols, probe_idx, lo0, hi0, strict, steps):
    """Vectorized binary search: for each probe row, the insertion point in
    its partner run [lo0, hi0) — lower bound when strict (run[mid] < probe
    moves right), upper bound otherwise (run[mid] <= probe moves right)."""
    lo, hi = lo0, hi0
    for _ in range(steps):
        mid = (lo + hi) >> 1
        midc = jnp.clip(mid, 0, cols[0].shape[0] - 1)
        if strict:
            right = _rows_less(cols, midc, probe_idx)
        else:
            right = ~_rows_less(cols, probe_idx, midc)
        open_ = lo < hi
        lo = jnp.where(open_ & right, mid + 1, lo)
        hi = jnp.where(open_ & ~right, mid, hi)
    return lo


def _merge_runs_perm(cols, run_starts, n_rounds):
    """Permutation (new row -> old row) realizing the merge of the R
    presorted runs bounded by run_starts ([R+1] int32, R a power of two,
    empty runs allowed). `cols`: priority-ordered int32 key columns.
    Stability: ties place even-run rows before their odd partner's."""
    p = cols[0].shape[0]
    steps = max(1, p.bit_length())
    iota = jnp.arange(p, dtype=jnp.int32)
    perm = iota
    starts = run_starts
    for _ in range(n_rounds):
        c = tuple(col[perm] for col in cols)
        r = jnp.searchsorted(starts, iota, side="right").astype(
            jnp.int32) - 1
        partner = r ^ 1
        pc = jnp.clip(partner, 0, starts.shape[0] - 2)
        lo_p = starts[pc]
        hi_p = starts[pc + 1]
        even = (r & 1) == 0
        lb = _partner_bound(c, iota, lo_p, hi_p, True, steps)
        ub = _partner_bound(c, iota, lo_p, hi_p, False, steps)
        bound = jnp.where(even, lb, ub)
        base = starts[jnp.clip(r & ~1, 0, starts.shape[0] - 2)]
        new_pos = base + (iota - starts[r]) + (bound - lo_p)
        inv_round = jnp.zeros(p, dtype=jnp.int32).at[new_pos].set(iota)
        perm = perm[inv_round]
        starts = starts[::2]
    return perm


@functools.partial(jax.jit, static_argnames=("num_key_words", "bottommost"))
def _gc_mask_impl(key_words, key_len, inv_hi, inv_lo, vtype,
                  snap_hi, snap_lo, tomb_hi, tomb_lo,
                  num_key_words, bottommost):
    """All inputs are SORTED columns (internal-key order, padded).
    tomb_hi/lo: per-entry max covering tombstone seqno words (0 = none).
    Returns keep, zero_seq, host_resolve, group_id (all padded length)."""
    n = key_words.shape[0]
    u = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)

    # --- group boundaries: user key change ---
    prev_words = jnp.roll(key_words, 1, axis=0)
    same_words = jnp.all(key_words == prev_words, axis=1)
    same_len = key_len == jnp.roll(key_len, 1)
    same_key = (same_words & same_len).at[0].set(False)
    new_key = ~same_key
    group_id = jnp.cumsum(new_key.astype(jnp.int32)) - 1

    # --- seqno recovery: packed = ~inv (64-bit), seq = packed >> 8 ---
    inv_hi_u = u(inv_hi) ^ jnp.uint32(_SIGN)
    inv_lo_u = u(inv_lo) ^ jnp.uint32(_SIGN)
    packed_hi = ~inv_hi_u
    packed_lo = ~inv_lo_u
    seq_hi = packed_hi >> 8                                   # top 24 bits
    seq_lo = (packed_hi << 24) | (packed_lo >> 8)             # low 32 bits

    if _want_pallas_gc() and n % 1024 == 0 and tomb_hi.shape[0] == n:
        # Pallas VPU kernel for the per-row mask core (stripe /
        # first-in-stripe / tombstone shadowing / complex flag); the
        # group-complex segment reduction below stays in lax.
        from toplingdb_tpu.ops import pallas_kernels as _pk

        stripe, first_in_stripe, covered, is_complex = _pk.gc_rows(
            seq_hi, seq_lo, jnp.roll(seq_hi, 1), jnp.roll(seq_lo, 1),
            new_key, tomb_hi, tomb_lo, vtype, snap_hi, snap_lo,
        )
        first_in_stripe = first_in_stripe | new_key
    else:
        # --- snapshot stripe: count of snapshots strictly below seq ---
        # snap arrays sorted ascending, padded with 2^56 (never < any seq).
        s_hi = snap_hi[None, :]
        s_lo = snap_lo[None, :]
        e_hi = seq_hi[:, None]
        e_lo = seq_lo[:, None]
        snap_lt = (s_hi < e_hi) | ((s_hi == e_hi) & (s_lo < e_lo))
        stripe = jnp.sum(snap_lt, axis=1).astype(jnp.int32)

        # --- first-in-(group, stripe): the only candidate survivor ---
        prev_stripe = jnp.roll(stripe, 1)
        first_in_stripe = new_key | (stripe != prev_stripe)

        # --- tombstone coverage (same-stripe shadowing) ---
        covered = _tomb_covered(seq_hi, seq_lo, tomb_hi, tomb_lo,
                                snap_hi, snap_lo, stripe)

        # --- complex groups: MERGE or SINGLE_DELETION → host resolves ---
        is_complex = (vtype == int(ValueType.MERGE)) | (
            vtype == int(ValueType.SINGLE_DELETION)
        )
    group_complex = jax.ops.segment_max(
        is_complex.astype(jnp.int32), group_id, num_segments=n,
        indices_are_sorted=True,
    )
    host_resolve = group_complex[group_id] > 0

    # --- survivor rules (simple groups) ---
    is_pad = vtype < 0
    keep = first_in_stripe & ~covered & ~is_pad
    drop_bottom_del = (
        bool(bottommost)
        & (stripe == 0)
        & (vtype == int(ValueType.DELETION))
    )
    keep = keep & ~drop_bottom_del
    zero_seq = (
        keep
        & bool(bottommost)
        & (stripe == 0)
        & (vtype == int(ValueType.VALUE))
    )
    keep = keep & ~host_resolve
    return keep, zero_seq, host_resolve & ~is_pad, group_id



def _sort_gc_compact_tail(key_words, key_len, inv_hi, inv_lo, vtype,
                          snap_hi, snap_lo, num_key_words, bottommost,
                          tomb_hi_orig=None, tomb_lo_orig=None):
    """Traced tail shared by the fused kernels: sort → GC mask → survivors
    compacted to the front in sorted order. Rows of complex groups (MERGE /
    SINGLE_DELETE present) are INCLUDED in the output stream, flagged via
    cx_flags, so the host can fold them without abandoning the columnar
    path. tomb_*_orig: per-ORIGINAL-index max covering tombstone seqno
    words (None = tombstone-free job)."""
    n = key_words.shape[0]
    idxs = jnp.arange(n, dtype=jnp.int32)
    kw, kl, ih, il, vt, perm = _sort_impl(
        key_words, key_len, inv_hi, inv_lo, vtype, idxs, num_key_words
    )
    if tomb_hi_orig is None:
        tomb_hi = tomb_lo = jnp.zeros(n, dtype=jnp.uint32)
    else:
        tomb_hi = tomb_hi_orig[perm]
        tomb_lo = tomb_lo_orig[perm]
    keep, zero_seq, host_resolve, _ = _gc_mask_impl(
        kw, kl, ih, il, vt, snap_hi, snap_lo, tomb_hi, tomb_lo,
        num_key_words, bottommost,
    )
    out = keep | host_resolve
    take = jnp.argsort(~out, stable=True)
    order = perm[take]
    zero_flags = zero_seq[take]
    cx_flags = host_resolve[take]
    count = jnp.sum(out.astype(jnp.int32))
    has_complex = jnp.any(host_resolve)
    return order, zero_flags, cx_flags, count, has_complex


@functools.partial(jax.jit, static_argnames=("num_key_words", "bottommost"))
def _fused_sort_gc_impl(key_words, key_len, inv_hi, inv_lo, vtype, idx,
                        snap_hi, snap_lo, num_key_words, bottommost):
    """Sort + GC mask in ONE device program (single host round trip for
    tombstone-free jobs). Returns (order, zero_flags, cx_flags, count,
    has_complex): order[i] for i < count = original indices of survivors
    (incl. complex-group rows, flagged) in output order."""
    return _sort_gc_compact_tail(
        key_words, key_len, inv_hi, inv_lo, vtype, snap_hi, snap_lo,
        num_key_words, bottommost,
    )


def fused_sort_gc(padded: dict, snapshots: list[int], bottommost: bool):
    """Host wrapper for the fused kernel (no range tombstones).
    Returns (order np[count], zero_flags np[count], cx_flags np[count],
    has_complex bool)."""
    if len(snapshots) > MAX_SNAPSHOTS:
        raise NotSupported(
            f"device GC supports <= {MAX_SNAPSHOTS} live snapshots"
        )
    p = padded["key_words"].shape[0]
    snap_hi, snap_lo = _split_snapshots(snapshots)
    idx = np.arange(p, dtype=np.int32)
    order, zero_flags, cx_flags, count, has_complex = _fused_sort_gc_impl(
        padded["key_words"], padded["key_len"], padded["inv_hi"],
        padded["inv_lo"], padded["vtype"], idx, snap_hi, snap_lo,
        padded["w"], bool(bottommost),
    )
    for a in (order, zero_flags, cx_flags, count, has_complex):
        if hasattr(a, "copy_to_host_async"):
            a.copy_to_host_async()
    c = int(count)
    return (np.asarray(order)[:c], np.asarray(zero_flags)[:c],
            np.asarray(cx_flags)[:c], bool(has_complex))


def host_encode_sort(key_buf: np.ndarray, key_offs: np.ndarray,
                     key_lens: np.ndarray, max_key_bytes: int):
    """NumPy half-twin: columnar encode + np.lexsort into internal-key
    order. Returns (s, words, uk_len, seq, vtype) with s = sorted→original
    permutation and the UNSORTED per-entry columns."""
    n = len(key_offs)
    offs = key_offs.astype(np.int64)
    lens = key_lens.astype(np.int64)

    seq, vtype = _trailer_seq_vtype(key_buf, key_offs, key_lens)
    packed = (seq << np.uint64(8)) | vtype.astype(np.uint64)
    inv = ~packed  # descending seq under an ascending sort

    # Big-endian user-key words, zero-masked past each key's length.
    w = (max_key_bytes + 3) // 4
    span = w * 4
    uk_len = lens - 8
    idx = offs[:, None] + np.arange(span)[None, :]
    np.clip(idx, 0, max(len(key_buf) - 1, 0), out=idx)
    kb = key_buf[idx].astype(np.uint32)
    kb *= np.arange(span)[None, :] < uk_len[:, None]
    kbw = kb.reshape(n, w, 4)
    words = ((kbw[:, :, 0] << 24) | (kbw[:, :, 1] << 16)
             | (kbw[:, :, 2] << 8) | kbw[:, :, 3])

    # lexsort: LAST column is primary — mirror the device operand order
    # (key words..., key_len, inv): stable, so duplicate internal keys keep
    # input order (the device sort has no key ties for distinct seqnos).
    s = np.lexsort((inv, uk_len) + tuple(
        words[:, j] for j in range(w - 1, -1, -1)
    ))
    return s, words, uk_len, seq, vtype


def host_sort_order(key_buf: np.ndarray, key_offs: np.ndarray,
                    key_lens: np.ndarray, run_starts=None):
    """(order, new_key, packed) via the native byte-span comparator —
    same order as the device sort; `packed` = per-ORIGINAL-index
    (seq<<8|type) trailers so callers skip re-gathering them in numpy.
    With `run_starts` ([R+1] boundaries of PRESORTED input runs), the
    multi-threaded k-way run merge replaces the full sort (the host twin
    of the device segmented merge; the reference's heap-merge role).
    None when the native lib is unavailable."""
    import ctypes

    from toplingdb_tpu import native

    lib = native.lib()
    if lib is None or not hasattr(lib, "tpulsm_sort_entries"):
        return None
    n = len(key_offs)
    offs = np.ascontiguousarray(key_offs, dtype=np.int64)
    lens = np.ascontiguousarray(key_lens, dtype=np.int64)
    kb = np.ascontiguousarray(key_buf)
    order = np.empty(n, dtype=np.int32)
    new_key = np.empty(n, dtype=np.uint8)
    # Sentinel prefill: a stale 6-arg .so would leave packed unwritten —
    # (seq=MAX, type=0xFF) is not a valid trailer, so survival means stale.
    packed = np.full(n, np.uint64(0xFFFFFFFFFFFFFFFF), dtype=np.uint64)
    rc = -1
    if (run_starts is not None and len(run_starts) > 1 and n
            and hasattr(lib, "tpulsm_merge_runs")
            and os.environ.get("TPULSM_HOST_MERGE", "1") != "0"):
        rs = np.ascontiguousarray(run_starts, dtype=np.int64)
        # Malformed boundaries would leave output rows unmerged (silent
        # corruption) or index past the entry array in C: validate here,
        # falling back to the full sort.
        if (int(rs[0]) != 0 or int(rs[-1]) != n
                or not np.all(np.diff(rs) >= 0)):
            rs = None
    else:
        rs = None
    if rs is not None:
        rc = lib.tpulsm_merge_runs(
            native.np_u8p(kb), native.np_i64p(offs), native.np_i64p(lens),
            n, native.np_i64p(rs), len(rs) - 1,
            native.np_i32p(order), native.np_u8p(new_key),
            packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
    if rc != 0:
        rc = lib.tpulsm_sort_entries(
            native.np_u8p(kb), native.np_i64p(offs), native.np_i64p(lens),
            n, native.np_i32p(order), native.np_u8p(new_key),
            packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
    if rc != 0:
        return None
    if n and packed[0] == np.uint64(0xFFFFFFFFFFFFFFFF):
        # Old binary ignored packed_out: derive trailers in numpy instead.
        seq, vtype = _trailer_seq_vtype(kb, offs, lens)
        packed = (seq << np.uint64(8)) | vtype.astype(np.uint64)
    return order, new_key.astype(bool), packed


def host_merge_gc(key_buf, key_offs, key_lens, snapshots, bottommost,
                  cover, run_starts):
    """ONE native pass: k-way merge of presorted runs + inline GC mask —
    returns the host_fused_full 6-tuple, or None when the native fused
    routine is unavailable/ineligible (then the two-pass path runs)."""
    import ctypes

    from toplingdb_tpu import native

    lib = native.lib()
    if (lib is None or not hasattr(lib, "tpulsm_merge_gc_runs")
            or os.environ.get("TPULSM_HOST_MERGE", "1") == "0"):
        return None
    if run_starts is None or len(run_starts) < 2:
        return None
    n = len(key_offs)
    rs = np.ascontiguousarray(run_starts, dtype=np.int64)
    if int(rs[0]) != 0 or int(rs[-1]) != n or not np.all(np.diff(rs) >= 0):
        return None
    offs = np.ascontiguousarray(key_offs, dtype=np.int64)
    lens = np.ascontiguousarray(key_lens, dtype=np.int64)
    kb = np.ascontiguousarray(key_buf)
    order = np.empty(n, dtype=np.int32)
    zero = np.empty(n, dtype=np.uint8)
    cx = np.empty(n, dtype=np.uint8)
    packed = np.empty(n, dtype=np.uint64)
    hc = np.zeros(1, dtype=np.int32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    snaps = np.asarray(sorted(snapshots), dtype=np.uint64)
    cov = (np.ascontiguousarray(cover, dtype=np.uint64)
           if cover is not None else None)
    n_out = lib.tpulsm_merge_gc_runs(
        native.np_u8p(kb), native.np_i64p(offs), native.np_i64p(lens), n,
        native.np_i64p(rs), len(rs) - 1,
        snaps.ctypes.data_as(u64p) if len(snaps) else None, len(snaps),
        cov.ctypes.data_as(u64p) if cov is not None else None,
        1 if bottommost else 0,
        native.np_i32p(order), native.np_u8p(zero), native.np_u8p(cx),
        packed.ctypes.data_as(u64p), native.np_i32p(hc),
    )
    if n_out < 0:
        return None
    seq = packed >> np.uint64(8)
    vtype = (packed & np.uint64(0xFF)).astype(np.int32)
    return (order[:n_out], zero[:n_out].astype(bool),
            cx[:n_out].astype(bool), bool(hc[0]), seq, vtype)


def host_gc_mask(new_key, sseq, svt, snapshots, cover, bottommost):
    """NumPy twin of the GC mask over SORTED columns; `new_key` marks
    user-key group starts, `cover` is the per-sorted-entry stripe-clamped
    max covering tombstone seq (or None). Returns (keep, zero_seq,
    host_resolve, group_id) like gc_mask."""
    n = len(sseq)
    snaps = np.asarray(sorted(snapshots), dtype=np.uint64)
    stripe = np.searchsorted(snaps, sseq, side="left").astype(np.int64)
    first_in_stripe = new_key.copy()
    if n > 1:
        first_in_stripe[1:] |= stripe[1:] != stripe[:-1]

    is_complex = (svt == int(ValueType.MERGE)) | (
        svt == int(ValueType.SINGLE_DELETION)
    )
    group_id = np.cumsum(new_key) - 1
    starts = np.flatnonzero(new_key)
    group_complex = (np.bitwise_or.reduceat(is_complex, starts)
                     if n else np.zeros(0, dtype=bool))
    host_resolve = group_complex[group_id] if n else is_complex

    covered = np.zeros(n, dtype=bool)
    if cover is not None:
        c = np.asarray(cover, dtype=np.uint64)
        covered = (c != 0) & (c > sseq)  # cover is stripe-clamped already

    keep = first_in_stripe & ~covered
    if bottommost:
        keep &= ~((stripe == 0) & (svt == int(ValueType.DELETION)))
    zero_seq = (
        keep & bool(bottommost) & (stripe == 0)
        & (svt == int(ValueType.VALUE))
    )
    keep &= ~host_resolve
    return keep, zero_seq, host_resolve, group_id


def fused_encode_sort_gc_host(key_buf: np.ndarray, key_offs: np.ndarray,
                              key_lens: np.ndarray, max_key_bytes: int,
                              snapshots: list[int], bottommost: bool,
                              cover: np.ndarray | None = None):
    """Host twin of fused_encode_sort_gc (same 4-tuple contract)."""
    r = host_fused_full(key_buf, key_offs, key_lens, max_key_bytes,
                        snapshots, bottommost, cover)
    return r[0], r[1], r[2], r[3]


def host_fused_full(key_buf: np.ndarray, key_offs: np.ndarray,
                    key_lens: np.ndarray, max_key_bytes: int,
                    snapshots: list[int], bottommost: bool,
                    cover: np.ndarray | None = None, run_starts=None):
    """Host twin of the fused kernel for accelerator-less deployments
    (TPULSM_HOST_SORT=1): native/lexsort order + vectorized GC mask —
    outputs identical to the jax path (parity-tested). `cover`: optional
    per-ORIGINAL-row uint64 max covering tombstone seqno. Returns
    (order, zero_flags, cx_flags, has_complex, seq, vtype) with seq/vtype
    per ORIGINAL index so callers skip their own trailer gather; `order`
    includes complex-group rows, flagged by cx_flags."""
    if len(snapshots) > MAX_SNAPSHOTS:
        raise NotSupported(
            f"device GC supports <= {MAX_SNAPSHOTS} live snapshots"
        )
    n = len(key_offs)
    if n == 0:
        e = np.empty(0, np.uint64)
        return (np.empty(0, np.int32), np.empty(0, bool),
                np.empty(0, bool), False, e, e.astype(np.int32))
    fused = host_merge_gc(key_buf, key_offs, key_lens, snapshots,
                          bottommost, cover, run_starts)
    if fused is not None:
        return fused
    s, new_key, seq, vtype = host_sort_with_boundaries(
        key_buf, key_offs, key_lens, max_key_bytes, run_starts=run_starts
    )
    keep, zero_seq, host_resolve, _ = host_gc_mask(
        new_key, seq[s], vtype[s], snapshots,
        None if cover is None else cover[s], bottommost
    )
    out = keep | host_resolve
    order = s[out].astype(np.int32)
    zero_flags = zero_seq[out]
    cx_flags = host_resolve[out]
    return order, zero_flags, cx_flags, bool(host_resolve.any()), seq, vtype


def host_sort_with_boundaries(key_buf, key_offs, key_lens, max_key_bytes,
                              run_starts=None):
    """Shared host-path front half: (s, new_key, seq, vtype) — the native
    comparator when available, else the lexsort twin."""
    nat = host_sort_order(key_buf, key_offs, key_lens,
                          run_starts=run_starts)
    if nat is not None:
        s, new_key, packed = nat
        seq = packed >> np.uint64(8)
        vtype = (packed & np.uint64(0xFF)).astype(np.int32)
    else:
        s, words, uk_len, seq, vtype = host_encode_sort(
            key_buf, key_offs, key_lens, max_key_bytes
        )
        new_key = _new_key_from_words(words[s], uk_len[s])
    return s, new_key, seq, vtype


def _trailer_seq_vtype(key_buf, key_offs, key_lens):
    offs = key_offs.astype(np.int64)
    lens = key_lens.astype(np.int64)
    tr_idx = (offs + lens - 8)[:, None] + np.arange(8)[None, :]
    tr = key_buf[tr_idx].astype(np.uint64)
    packed = np.zeros(len(offs), dtype=np.uint64)
    for i in range(8):
        packed |= tr[:, i] << np.uint64(8 * i)
    return packed >> np.uint64(8), (packed & np.uint64(0xFF)).astype(np.int32)


def _new_key_from_words(skw, slen):
    n = len(slen)
    same_key = np.zeros(n, dtype=bool)
    if n > 1:
        same_key[1:] = np.all(skw[1:] == skw[:-1], axis=1) & (
            slen[1:] == slen[:-1]
        )
    return ~same_key


def _encode_from_bytes(key_buf, key_offs, key_lens, valid, num_key_words):
    """Shared traced encode from raw internal-key bytes: trailer unpack +
    BE user-key word pack, invalid rows masked to the int32max sentinel.
    Returns (key_words, key_len, inv_hi, inv_lo, vtype)."""
    n = key_lens.shape[0]
    span = num_key_words * 4
    u32 = jnp.uint32
    sign = u32(_SIGN)
    i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    int32max = jnp.int32(2**31 - 1)

    # --- trailer: 8 LE bytes at offs+len-8 → packed (seq<<8|type) ---
    tr_idx = (key_offs + key_lens - 8)[:, None] + jnp.arange(8)[None, :]
    tr = key_buf[jnp.clip(tr_idx, 0, key_buf.shape[0] - 1)].astype(u32)
    packed_lo = tr[:, 0] | (tr[:, 1] << 8) | (tr[:, 2] << 16) | (tr[:, 3] << 24)
    packed_hi = tr[:, 4] | (tr[:, 5] << 8) | (tr[:, 6] << 16) | (tr[:, 7] << 24)
    vtype = jnp.where(valid, (packed_lo & u32(0xFF)).astype(jnp.int32), -1)
    inv_hi = jnp.where(valid, i32(~packed_hi ^ sign), int32max)
    inv_lo = jnp.where(valid, i32(~packed_lo ^ sign), int32max)

    # --- user-key words: gather span bytes, mask past uk_len, pack BE ---
    uk_len = (key_lens - 8).astype(jnp.int32)
    idx = key_offs[:, None] + jnp.arange(span)[None, :]
    kb = key_buf[jnp.clip(idx, 0, key_buf.shape[0] - 1)].astype(u32)
    kb = kb * (jnp.arange(span)[None, :] < uk_len[:, None])
    kb = kb.reshape(n, num_key_words, 4)
    words = (kb[:, :, 0] << 24) | (kb[:, :, 1] << 16) | (kb[:, :, 2] << 8) | kb[:, :, 3]
    key_words = jnp.where(valid[:, None], i32(words ^ sign), int32max)
    key_len = jnp.where(valid, uk_len, int32max)
    return key_words, key_len, inv_hi, inv_lo, vtype



@functools.partial(
    jax.jit, static_argnames=("num_key_words", "bottommost", "has_tombs")
)
def _fused_encode_sort_gc_impl(key_buf, key_lens, valid, tomb_hi, tomb_lo,
                               snap_hi, snap_lo, num_key_words, bottommost,
                               has_tombs):
    """Columnar encode + sort + GC mask, all ON DEVICE: the host uploads raw
    internal-key bytes + lengths only (entries are densely packed, so the
    offsets are an on-device exclusive cumsum) and downloads the survivor
    order. With has_tombs, tomb_hi/lo carry each original row's max
    covering range-tombstone seqno words (the host interval-maps the few
    fragments over the sorted input parts)."""
    key_offs = jnp.cumsum(key_lens) - key_lens  # dense layout: offs from lens
    key_words, key_len, inv_hi, inv_lo, vtype = _encode_from_bytes(
        key_buf, key_offs, key_lens, valid, num_key_words,
    )
    return _sort_gc_compact_tail(
        key_words, key_len, inv_hi, inv_lo, vtype, snap_hi, snap_lo,
        num_key_words, bottommost,
        tomb_hi_orig=tomb_hi if has_tombs else None,
        tomb_lo_orig=tomb_lo if has_tombs else None,
    )


# Per-shard row budget for the 3-byte packed-order download: local row ids
# must fit 22 bits (bit 23 carries the zero-seq flag, bit 22 the
# complex-group flag).
MAX_SHARD_ROWS = 1 << 22


def _uniform_shard_core(kb, pkb, starts, min_his, min_los, tomb_hi, tomb_lo,
                        snap_hi, snap_lo, total, num_key_words, uk_len,
                        bottommost, has_tombs, run_starts=None,
                        merge_mode="sort"):
    """Shared traced core of the uniform-shard kernels: [p, uk_len] u8 key
    matrix in → sort + GC. Returns a dict of per-SORTED-row arrays
    (perm, out, zero_seq, host_resolve, take) plus per-ORIGINAL-row
    packed trailer words, for the packed-download and block-assembly
    tails to consume.

    merge_mode (static): "sort" = full lax.sort; "merge" = segmented merge
    of the presorted runs bounded by run_starts; "skip" = input is one
    presorted run (pads trailing) — no reorder at all."""
    u32 = jnp.uint32
    int32max = jnp.int32(2**31 - 1)
    sign = u32(_SIGN)
    i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    span = num_key_words * 4
    p = pkb.shape[0]
    iota = jnp.arange(p, dtype=jnp.int32)
    valid = iota < total

    kbp = kb
    if span > uk_len:
        kbp = jnp.pad(kbp, ((0, 0), (0, span - uk_len)))
    kbp = kbp.astype(u32).reshape(p, num_key_words, 4)
    words = (
        (kbp[:, :, 0] << 24) | (kbp[:, :, 1] << 16)
        | (kbp[:, :, 2] << 8) | kbp[:, :, 3]
    )
    key_words = jnp.where(valid[:, None], i32(words ^ sign), int32max)

    # Reconstruct full 64-bit packed trailers (seq<<8|type): per-row chunk
    # id via searchsorted over the chunk starts, then add that chunk's
    # 64-bit min seqno to the 24-bit delta. Deltas from different chunks
    # are not comparable; the absolute words are.
    cid = jnp.searchsorted(starts, iota, side="right") - 1
    rel = pkb >> 8
    mlo = min_los[cid]
    seq_lo = mlo + rel
    carry = (seq_lo < mlo).astype(u32)
    seq_hi = min_his[cid] + carry
    vt0 = pkb & u32(0xFF)
    packed_hi = (seq_hi << 8) | (seq_lo >> 24)
    packed_lo = (seq_lo << 8) | vt0
    inv_hi = jnp.where(valid, i32(~packed_hi ^ sign), int32max)
    inv_lo = jnp.where(valid, i32(~packed_lo ^ sign), int32max)
    vtype = jnp.where(valid, vt0.astype(jnp.int32), -1)
    key_len = jnp.where(valid, jnp.int32(uk_len), int32max)

    if merge_mode == "skip":
        # One presorted run (+ trailing pads): already in output order.
        perm = iota
        kw, kl, ih, il, vt = key_words, key_len, inv_hi, inv_lo, vtype
    elif merge_mode == "merge":
        cols = tuple(
            key_words[:, j] for j in range(num_key_words)
        ) + (key_len, inv_hi, inv_lo)
        n_runs = run_starts.shape[0] - 1
        n_rounds = max(0, n_runs.bit_length() - 1)
        perm = _merge_runs_perm(cols, run_starts, n_rounds)
        kw = key_words[perm]
        kl = key_len[perm]
        ih = inv_hi[perm]
        il = inv_lo[perm]
        vt = vtype[perm]
    else:
        kw, kl, ih, il, vt, perm = _sort_impl(
            key_words, key_len, inv_hi, inv_lo, vtype, iota, num_key_words,
        )
    if has_tombs:
        th = tomb_hi[perm]
        tl = tomb_lo[perm]
    else:
        th = tl = jnp.zeros(p, dtype=jnp.uint32)
    keep, zero_seq, host_resolve, _ = _gc_mask_impl(
        kw, kl, ih, il, vt, snap_hi, snap_lo, th, tl,
        num_key_words, bottommost,
    )
    out = keep | host_resolve
    take = jnp.argsort(~out, stable=True)
    return {
        "perm": perm, "take": take, "out": out, "zero_seq": zero_seq,
        "host_resolve": host_resolve,
        "packed_hi": packed_hi, "packed_lo": packed_lo,  # per ORIGINAL row
        "vtype_orig": vt0.astype(jnp.int32),
        "valid": valid,
    }


def _uniform_shard_tail(kb, pkb, starts, min_his, min_los, tomb_hi, tomb_lo,
                        snap_hi, snap_lo, total, num_key_words, uk_len,
                        bottommost, has_tombs, run_starts=None,
                        merge_mode="sort"):
    """Packed-download tail: [p, uk_len] u8 key matrix in → packed survivor
    byte-planes out (see _fused_uniform_shard_impl for the contract)."""
    u32 = jnp.uint32
    core = _uniform_shard_core(
        kb, pkb, starts, min_his, min_los, tomb_hi, tomb_lo,
        snap_hi, snap_lo, total, num_key_words, uk_len, bottommost,
        has_tombs, run_starts=run_starts, merge_mode=merge_mode,
    )
    take = core["take"]
    po = (
        jax.lax.bitcast_convert_type(core["perm"][take], u32)
        | (core["zero_seq"][take].astype(u32) << 23)
        | (core["host_resolve"][take].astype(u32) << 22)
    )
    packed_bytes = jnp.concatenate([
        (po & u32(0xFF)).astype(jnp.uint8),
        ((po >> 8) & u32(0xFF)).astype(jnp.uint8),
        ((po >> 16) & u32(0xFF)).astype(jnp.uint8),
    ])
    meta = jnp.stack([
        jnp.sum(core["out"].astype(jnp.int32)),
        jnp.any(core["host_resolve"]).astype(jnp.int32),
    ])
    return packed_bytes, meta


def _decode_front_coded(plens, sfx, uk_len):
    """Reconstruct the [p, uk_len] u8 key matrix from front-coded uploads
    (shared by the packed-download and block-assembly kernels)."""
    p = plens.shape[0]
    pl = plens.astype(jnp.int32)
    sfx_len = jnp.int32(uk_len) - pl
    sfx_off = jnp.cumsum(sfx_len) - sfx_len
    iota = jnp.arange(p, dtype=jnp.int32)
    col = jnp.arange(uk_len, dtype=jnp.int32)[None, :]
    # Column j of row i inherits from the LAST row i' <= i with
    # plen[i'] <= j; chunk starts have plen 0, so inheritance never
    # crosses a chunk boundary.
    contrib = jnp.where(pl[:, None] <= col, iota[:, None], jnp.int32(-1))
    src = jax.lax.cummax(contrib, axis=0)
    pos = sfx_off[src] + (col - pl[src])
    return sfx[jnp.clip(pos, 0, sfx.shape[0] - 1)]


@functools.partial(
    jax.jit,
    static_argnames=("num_key_words", "uk_len", "bottommost", "has_tombs",
                     "merge_mode"),
)
def _fused_uniform_shard_impl(ukb, pkb, starts, min_his, min_los,
                              tomb_hi, tomb_lo,
                              snap_hi, snap_lo, total, num_key_words, uk_len,
                              bottommost, has_tombs, run_starts=None,
                              merge_mode="sort"):
    """ONE range-shard's encode+sort+GC over ONE uploaded buffer pair:
    `ukb` = trailer-stripped user-key bytes of every chunk packed
    contiguously (padded rows zero), `pkb` = one uint32 per row
    ((seq - chunk_min_seq) << 8 | vtype, deltas < 2^24). Chunk row starts
    arrive as a small DEVICE array `starts` (pow2-padded with sentinel
    2^31-1), so per-row chunk ids come from one searchsorted and the jit
    cache keys only on pow2-padded shapes — arbitrary chunk-size tuples
    reuse one compilation. TWO bulk host→device transfers per shard.
    The result is (packed_bytes u8[3p], meta i32[2]): three
    byte-planes of the 24-bit survivor row ids (bit 23 = zero-seq flag,
    bit 22 = complex-group flag) — 3/4 the download of int32 orders — plus
    [count, has_complex]. With has_tombs, tomb_hi/lo carry each local row's
    max covering range-tombstone seqno words."""
    p = pkb.shape[0]
    kb = ukb.reshape(p, uk_len)
    return _uniform_shard_tail(
        kb, pkb, starts, min_his, min_los, tomb_hi, tomb_lo,
        snap_hi, snap_lo, total, num_key_words, uk_len, bottommost,
        has_tombs, run_starts=run_starts, merge_mode=merge_mode,
    )


@functools.partial(
    jax.jit,
    static_argnames=("num_key_words", "uk_len", "bottommost", "has_tombs",
                     "merge_mode"),
)
def _fused_uniform_shard_fc_impl(plens, sfx, pkb, starts, min_his, min_los,
                                 tomb_hi, tomb_lo, snap_hi, snap_lo, total,
                                 num_key_words, uk_len, bottommost,
                                 has_tombs, run_starts=None,
                                 merge_mode="sort"):
    """Front-coded variant of _fused_uniform_shard_impl: instead of the full
    [p, uk_len] key bytes, the host uploads per-row shared-prefix lengths
    (`plens` u8, 0 at chunk starts) + the concatenated suffix bytes
    (`sfx`) — typically a fraction of the full key bytes for sorted runs.
    The device reconstructs the key matrix with a cummax scan (source row
    of each inherited byte column) + one gather, then runs the shared
    tail. Output is bit-identical to the plain upload (parity-tested)."""
    kb = _decode_front_coded(plens, sfx, uk_len)
    return _uniform_shard_tail(
        kb, pkb, starts, min_his, min_los, tomb_hi, tomb_lo,
        snap_hi, snap_lo, total, num_key_words, uk_len, bottommost,
        has_tombs, run_starts=run_starts, merge_mode=merge_mode,
    )


def prepare_uniform_chunk(key_buf: np.ndarray, n: int, key_len: int):
    """Host half of the uniform upload: strip the 8-byte trailers from one
    dense uniform-length key slice; no device traffic. Returns
    (uk_bytes, pk32, min_seq, n, uk_len). Raises NotSupported when the
    chunk's seqno span exceeds 24 bits (the uint32 packing budget)."""
    import sys as _sys

    kb2 = key_buf[: n * key_len].reshape(n, key_len)
    tr = np.ascontiguousarray(kb2[:, -8:]).view(np.uint64).reshape(n)
    if _sys.byteorder == "big":
        tr = tr.byteswap()
    seq = tr >> np.uint64(8)
    min_seq = int(seq.min()) if n else 0
    rel = seq - np.uint64(min_seq)
    if n and int(rel.max()) >= 1 << 24:
        raise NotSupported("chunk seqno span exceeds the 24-bit delta budget")
    pk32 = ((rel << np.uint64(8)) | (tr & np.uint64(0xFF))).astype(np.uint32)
    uk_len = key_len - 8
    uk = np.ascontiguousarray(kb2[:, :uk_len]).reshape(-1)
    return (uk, pk32, min_seq, n, uk_len)


# Front-coded uploads: on for uniform keys up to this many bytes unless
# TPULSM_FRONT_CODE=0. The decode materializes [p, uk_len] int32
# intermediates (cummax source rows + gather positions), so also cap the
# total element count — beyond it the transient HBM spike would outweigh
# the transfer win.
_FC_MAX_UK_LEN = 32
_FC_MAX_ELEMS = 64 << 20  # ~256 MB of int32 intermediates


def _want_front_code(uk_len: int, total_rows: int) -> bool:
    import os

    if os.environ.get("TPULSM_FRONT_CODE", "1") == "0":
        return False
    return (0 < uk_len <= _FC_MAX_UK_LEN
            and _next_pow2(max(1, total_rows)) * uk_len <= _FC_MAX_ELEMS)


def upload_uniform_shard(chunks, covers=None, front_code=None, device=None):
    """Pack one shard's prepared chunks (prepare_uniform_chunk outputs, in
    row order) into device buffers, pad rows to the next power of two, and
    START the host→device transfers (device_put is async). Tunneled rigs
    pay a fixed ~60ms per transfer regardless of size, so few big
    transfers beat 2-per-chunk small ones.
    `covers`: optional per-chunk uint64 max-covering-tombstone arrays
    (None = tombstone-free); uploaded as two extra u32 planes.
    `front_code` (None = auto): upload per-row shared-prefix lengths +
    suffix bytes instead of full key bytes — sorted runs share long
    prefixes, so this cuts the dominant H2D transfer; the device
    reconstructs the exact key matrix (bit-identical results).
    `device` (None = backend default): COMMIT the shard's buffers to one
    specific chip — the fused program carries no pin of its own, so the
    committed inputs decide where it runs (ops/mesh_compaction.py places
    shards round-robin over a mesh this way)."""
    uk_len = chunks[0][4]
    ns = tuple(int(c[3]) for c in chunks)
    total = sum(ns)
    if total > MAX_SHARD_ROWS:
        raise NotSupported(
            f"shard rows {total} exceed the 24-bit packed-order budget"
        )
    if front_code is None:
        front_code = _want_front_code(uk_len, total)
    if uk_len > 255:
        front_code = False  # plens is uint8; a longer prefix would wrap
    p = _next_pow2(max(1, total))
    pkb = np.zeros(p, dtype=np.uint32)
    has_tombs = covers is not None and any(
        c is not None and np.any(c) for c in covers
    )
    if has_tombs:
        tomb_hi = np.zeros(p, dtype=np.uint32)
        tomb_lo = np.zeros(p, dtype=np.uint32)
    if front_code:
        plens = np.zeros(p, dtype=np.uint8)
        sfx_parts = []
    else:
        ukb = np.zeros(p * uk_len, dtype=np.uint8)
    pos = 0
    for ci, (uk, pk32, _mn, n, _l) in enumerate(chunks):
        if front_code and n:
            kb2 = uk.reshape(n, uk_len)
            eq = kb2[1:] == kb2[:-1]
            pl = np.zeros(n, dtype=np.int32)
            if n > 1:
                all_eq = eq.all(axis=1)
                pl[1:] = np.where(all_eq, uk_len, np.argmin(eq, axis=1))
            plens[pos:pos + n] = pl.astype(np.uint8)
            sfx_parts.append(kb2[np.arange(uk_len)[None, :] >= pl[:, None]])
        elif not front_code:
            ukb[pos * uk_len:(pos + n) * uk_len] = uk
        pkb[pos:pos + n] = pk32
        if has_tombs and covers[ci] is not None:
            cv = covers[ci]
            tomb_hi[pos:pos + n] = (cv >> np.uint64(32)).astype(np.uint32)
            tomb_lo[pos:pos + n] = (cv & np.uint64(0xFFFFFFFF)).astype(
                np.uint32)
        pos += n
    mins = np.array([c[2] for c in chunks], dtype=np.uint64)
    # Chunk starts + per-chunk min seqnos, pow2-padded so the jit cache
    # keys on O(log nchunks) shapes instead of every (n0, n1, ...) tuple.
    nc = _next_pow2(max(1, len(ns)))
    starts = np.full(nc, 2**31 - 1, dtype=np.int32)
    starts[: len(ns)] = np.cumsum([0] + list(ns[:-1]), dtype=np.int64)
    min_his = np.zeros(nc, dtype=np.uint32)
    min_los = np.zeros(nc, dtype=np.uint32)
    min_his[: len(ns)] = (mins >> np.uint64(32)).astype(np.uint32)
    min_los[: len(ns)] = (mins & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    # Segmented-merge run boundaries: each chunk is one presorted run,
    # the padding rows form a final sorted run, empty runs pad the count
    # to a power of two (the merge does log2(R) pairwise rounds).
    n_chunks = len(ns)
    real_runs = n_chunks + (1 if p > total else 0)
    rr = _next_pow2(max(1, real_runs))
    run_starts = np.full(rr + 1, p, dtype=np.int32)
    run_starts[:n_chunks] = np.cumsum([0] + list(ns[:-1]), dtype=np.int64)
    run_starts[n_chunks] = total
    def put(x):
        # A committed transfer (device=) pins the downstream jit program to
        # that chip; the default keeps today's backend-default placement.
        return jax.device_put(x, device) if device is not None \
            else jax.device_put(x)

    h = {
        "pkb": put(pkb), "total": total,
        "starts": put(starts),
        "min_his": put(min_his),
        "min_los": put(min_los), "uk_len": uk_len,
        "tomb_hi": put(tomb_hi) if has_tombs else None,
        "tomb_lo": put(tomb_lo) if has_tombs else None,
        "n_chunks": n_chunks,
        "run_starts": put(run_starts),
    }
    if front_code:
        sfx = (np.concatenate(sfx_parts) if sfx_parts
               else np.zeros(0, dtype=np.uint8))
        # Pad-row columns all "contribute themselves" (plen 0), so the
        # decode's clipped gather needs only a pow2 bucket, not real bytes.
        sb = np.zeros(_next_pow2(max(8, len(sfx))), dtype=np.uint8)
        sb[: len(sfx)] = sfx
        h["plens"] = put(plens)
        h["sfx"] = put(sb)
    else:
        h["ukb"] = put(ukb)
    return h


def shard_merge_mode(handle):
    """Pick the reorder strategy for one uploaded shard: "skip" when the
    whole shard is a single presorted chunk (no reorder at all), the
    segmented merge when run boundaries are available AND the backend is
    an accelerator, else the full lax.sort. Rationale: on TPU, lax.sort
    lowers to an O(log^2 N)-stage bitonic network that moves every operand
    per stage, so the O(log R · log N) rank-merge wins; on the CPU backend
    XLA's sort is already a sequential O(N log N) sort that beats the
    merge's gather-heavy rounds. TPULSM_DEVICE_MERGE=1/0 forces the choice
    either way. Returns (mode, run_starts)."""
    import os

    rs = handle.get("run_starts")
    env = os.environ.get("TPULSM_DEVICE_MERGE", "")
    if rs is None or env == "0":
        return "sort", None
    if handle.get("n_chunks", 0) == 1:
        return "skip", None
    if env != "1" and jax.default_backend() == "cpu":
        return "sort", None
    return "merge", rs


def fused_uniform_shard_start(handle, snapshots: list[int], bottommost: bool):
    """Dispatch one shard's fused program over an upload_uniform_shard
    handle; enqueues the D2H copies so results stream back as the program
    finishes. Decode with fused_uniform_shard_finish."""
    if len(snapshots) > MAX_SNAPSHOTS:
        raise NotSupported(
            f"device GC supports <= {MAX_SNAPSHOTS} live snapshots"
        )
    h = handle
    snap_hi, snap_lo = _split_snapshots(snapshots)
    uk_len = h["uk_len"]
    w = (max(uk_len, 4) + 3) // 4
    has_tombs = h["tomb_hi"] is not None
    t_hi = h["tomb_hi"] if has_tombs else np.zeros(1, dtype=np.uint32)
    t_lo = h["tomb_lo"] if has_tombs else np.zeros(1, dtype=np.uint32)
    merge_mode, run_starts = shard_merge_mode(h)
    if "plens" in h:
        out = _fused_uniform_shard_fc_impl(
            h["plens"], h["sfx"], h["pkb"], h["starts"], h["min_his"],
            h["min_los"], t_hi, t_lo, snap_hi, snap_lo,
            np.int32(h["total"]), w, uk_len, bool(bottommost), has_tombs,
            run_starts=run_starts, merge_mode=merge_mode,
        )
    else:
        out = _fused_uniform_shard_impl(
            h["ukb"], h["pkb"], h["starts"], h["min_his"], h["min_los"],
            t_hi, t_lo, snap_hi, snap_lo,
            np.int32(h["total"]), w, uk_len, bool(bottommost), has_tombs,
            run_starts=run_starts, merge_mode=merge_mode,
        )
    for a in out:
        if hasattr(a, "copy_to_host_async"):
            a.copy_to_host_async()
    return out


def fused_uniform_shard_finish(pending):
    """Block on one shard's result: (order[count] int32 LOCAL shard rows,
    zero_flags[count] bool, cx_flags[count] bool, has_complex)."""
    packed_bytes, meta = pending
    m = np.asarray(meta)
    c = int(m[0])
    has_complex = bool(m[1])
    arr = np.asarray(packed_bytes)
    p = arr.size // 3
    a = arr.reshape(3, p)
    po = (
        a[0, :c].astype(np.uint32)
        | (a[1, :c].astype(np.uint32) << 8)
        | (a[2, :c].astype(np.uint32) << 16)
    )
    order = (po & np.uint32(MAX_SHARD_ROWS - 1)).astype(np.int32)
    zero_flags = (po >> np.uint32(23)).astype(bool)
    cx_flags = ((po >> np.uint32(22)) & np.uint32(1)).astype(bool)
    return order, zero_flags, cx_flags, has_complex


def fused_encode_sort_gc(key_buf: np.ndarray, key_offs: np.ndarray,
                         key_lens: np.ndarray, max_key_bytes: int,
                         snapshots: list[int], bottommost: bool,
                         cover: np.ndarray | None = None):
    """Host wrapper: raw flat key bytes in, survivor order out. `cover`:
    optional per-original-row uint64 max covering tombstone seqno (0 =
    uncovered). Returns (order[count], zero_flags[count], cx_flags[count],
    has_complex)."""
    if len(snapshots) > MAX_SNAPSHOTS:
        raise NotSupported(
            f"device GC supports <= {MAX_SNAPSHOTS} live snapshots"
        )
    n = len(key_offs)
    # The device derives offsets as an exclusive cumsum of the lengths; that
    # requires the dense end-to-end layout ColumnarKV scans produce.
    if n and (int(key_offs[0]) != 0
              or int(key_offs[-1]) + int(key_lens[-1]) != len(key_buf)
              or not np.array_equal(
                  key_offs[1:], (np.cumsum(key_lens) - key_lens)[1:]
              )):
        raise NotSupported("fused encode requires densely packed key buffers")
    p = _next_pow2(max(1, n))
    w = (max_key_bytes + 3) // 4
    lens = np.zeros(p, dtype=np.int32)  # pad rows: zero-length (masked)
    valid = np.zeros(p, dtype=bool)
    lens[:n] = key_lens
    valid[:n] = True
    snap_hi, snap_lo = _split_snapshots(snapshots)
    has_tombs = cover is not None and bool(np.any(cover))
    if has_tombs:
        tomb_hi, tomb_lo = _split_cover(cover, p)
    else:
        tomb_hi = tomb_lo = np.zeros(1, dtype=np.uint32)  # unused dummy
    # Pad the raw byte buffer to a pow2 bucket too: otherwise every distinct
    # total-key-byte count compiles a fresh XLA program (the row count is
    # already bucketed; the gather clips, so over-length is semantically
    # safe).
    blen = _next_pow2(max(8, len(key_buf)))
    kb = np.zeros(blen, dtype=np.uint8)
    kb[: len(key_buf)] = key_buf
    order, zero_flags, cx_flags, count, has_complex = \
        _fused_encode_sort_gc_impl(
            kb, lens, valid, tomb_hi, tomb_lo, snap_hi, snap_lo, w,
            bool(bottommost), has_tombs,
        )
    for a in (order, zero_flags, cx_flags, count, has_complex):
        if hasattr(a, "copy_to_host_async"):
            a.copy_to_host_async()  # stream D2H; sync np.asarray is ~15x
    c = int(count)
    return (np.asarray(order)[:c], np.asarray(zero_flags)[:c],
            np.asarray(cx_flags)[:c], bool(has_complex))


def gc_mask(sorted_cols: dict, snapshots: list[int],
            tomb_cover: np.ndarray | None, bottommost: bool):
    """Host wrapper over sorted on-device columns from device_sort().
    tomb_cover: [n] uint64 max covering tombstone seq per sorted entry
    (None = no tombstones). Returns (keep, zero_seq, host_resolve, group_id)
    as numpy arrays trimmed to n."""
    if len(snapshots) > MAX_SNAPSHOTS:
        # Falling back to the host path is the caller's job; silently
        # truncating would merge stripes and corrupt MVCC.
        raise NotSupported(
            f"device GC supports <= {MAX_SNAPSHOTS} live snapshots, "
            f"got {len(snapshots)}"
        )
    p = sorted_cols["key_words"].shape[0]
    n = sorted_cols["n"]
    snap_hi, snap_lo = _split_snapshots(snapshots)
    if tomb_cover is None:
        tomb_hi = np.zeros(p, dtype=np.uint32)
        tomb_lo = np.zeros(p, dtype=np.uint32)
    else:
        tomb_hi, tomb_lo = _split_cover(tomb_cover, p)
    keep, zero_seq, host_resolve, group_id = _gc_mask_impl(
        sorted_cols["key_words"], sorted_cols["key_len"],
        sorted_cols["inv_hi"], sorted_cols["inv_lo"], sorted_cols["vtype"],
        snap_hi, snap_lo, tomb_hi, tomb_lo,
        sorted_cols["w"], bool(bottommost),
    )
    return (
        np.asarray(keep)[:n], np.asarray(zero_seq)[:n],
        np.asarray(host_resolve)[:n], np.asarray(group_id)[:n],
    )
