"""Pallas TPU kernels.

The block-encoding prep op: shared-prefix lengths between consecutive sorted
keys — the per-entry scalar loop at the heart of the reference's
BlockBuilder::Add (table/block_based/block_builder.cc) re-expressed as a VPU
program: keys live as [N, 128] byte lanes (TPU-native last dim), the kernel
computes `cumprod(eq) → sum` per row against the previous row.

This is the building block for full on-device block assembly (offsets via
prefix sums, then byte scatter); the current output feeds/validates the
native encoder. Runs in interpret mode on CPU tests, compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

KEY_LANES = 128  # last-dim tile width on TPU
# 1024 rows per grid step: XLA lays out 1-D s32 outputs with tile
# T(min(n, 1024)), and the Mosaic block shape must match it exactly.
_BLOCK_ROWS = 1024


def _prefix_kernel(keys_ref, prev_ref, out_ref):
    keys = keys_ref[:]          # [B, 128] int32 (one byte per lane)
    prev = prev_ref[:]
    neq = keys != prev
    # Common prefix = index of the first differing lane (cumprod doesn't
    # lower in Mosaic; iota + reduce-min does).
    lane = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1)
    first_diff = jnp.min(
        jnp.where(neq, lane, jnp.int32(KEY_LANES)), axis=1
    )
    out_ref[:] = first_diff


@functools.partial(jax.jit, static_argnames=("interpret",))
def _shared_prefix_impl(keys, prev, interpret):
    from jax.experimental import pallas as pl

    n = keys.shape[0]
    grid = (n // _BLOCK_ROWS,)
    return pl.pallas_call(
        _prefix_kernel,
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, KEY_LANES), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, KEY_LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS,), lambda i: (i,)),
        interpret=interpret,
    )(keys, prev)


def shared_prefix_lengths(key_bytes: np.ndarray,
                          key_lens: np.ndarray | None = None,
                          interpret: bool | None = None) -> np.ndarray:
    """out[i] = length of the common prefix of row i and row i-1 (out[0]=0).

    key_bytes: [N, K] uint8 (K <= 128), zero-padded rows of SORTED keys.
    key_lens: optional true lengths; the result is clamped to
    min(len[i], len[i-1]) so zero padding can't inflate prefixes.
    """
    n, k = key_bytes.shape
    if k > KEY_LANES:
        raise ValueError(f"keys wider than {KEY_LANES} bytes")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    pad_n = -(-max(n, 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    buf = np.zeros((pad_n, KEY_LANES), dtype=np.int32)
    buf[:n, :k] = key_bytes
    prev = np.zeros_like(buf)
    prev[1:] = buf[:-1]
    prev[0, :] = -1  # row 0 matches nothing
    out = np.asarray(_shared_prefix_impl(buf, prev, interpret))[:n]
    if key_lens is not None and n:
        lens = key_lens.astype(np.int64)
        cap = np.minimum(lens, np.roll(lens, 1))
        cap[0] = 0
        out = np.minimum(out, cap).astype(np.int32)
    return out
