"""Pallas TPU kernels.

The block-encoding prep op: shared-prefix lengths between consecutive sorted
keys — the per-entry scalar loop at the heart of the reference's
BlockBuilder::Add (table/block_based/block_builder.cc) re-expressed as a VPU
program: keys live as [N, 128] byte lanes (TPU-native last dim), the kernel
computes `cumprod(eq) → sum` per row against the previous row.

This is the building block for full on-device block assembly (offsets via
prefix sums, then byte scatter); the current output feeds/validates the
native encoder. Runs in interpret mode on CPU tests, compiled on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

KEY_LANES = 128  # last-dim tile width on TPU
# 1024 rows per grid step: XLA lays out 1-D s32 outputs with tile
# T(min(n, 1024)), and the Mosaic block shape must match it exactly.
_BLOCK_ROWS = 1024


def _prefix_kernel(keys_ref, prev_ref, out_ref):
    keys = keys_ref[:]          # [B, 128] int32 (one byte per lane)
    prev = prev_ref[:]
    neq = keys != prev
    # Common prefix = index of the first differing lane (cumprod doesn't
    # lower in Mosaic; iota + reduce-min does).
    lane = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1)
    first_diff = jnp.min(
        jnp.where(neq, lane, jnp.int32(KEY_LANES)), axis=1
    )
    out_ref[:] = first_diff


@functools.partial(jax.jit, static_argnames=("interpret",))
def _shared_prefix_impl(keys, prev, interpret):
    from jax.experimental import pallas as pl

    n = keys.shape[0]
    grid = (n // _BLOCK_ROWS,)
    return pl.pallas_call(
        _prefix_kernel,
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, KEY_LANES), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK_ROWS, KEY_LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS,), lambda i: (i,)),
        interpret=interpret,
    )(keys, prev)


def _gc_rows_kernel(seq_hi_ref, seq_lo_ref, pseq_hi_ref, pseq_lo_ref,
                    new_key_ref, tomb_hi_ref, tomb_lo_ref, vtype_ref,
                    snap_hi_ref, snap_lo_ref,
                    stripe_ref, fis_ref, covered_ref, cx_ref):
    """Per-row MVCC GC mask core (reference CompactionIterator::
    NextFromInput's visibility decisions, compaction_iterator.cc:475):
    snapshot stripe via a [B, S] broadcast compare against the resident
    snapshot words, first-in-stripe from the previous row's stripe, and
    same-stripe range-tombstone shadowing. All u32 compares run as two
    i32 word compares on the VPU; the group-complex propagation (a
    segment reduction across arbitrary spans) stays in lax."""
    i32 = jnp.int32
    # Signed-compare trick: XOR the sign bit so i32 < == u32 <.
    sign = jnp.int32(-0x80000000)
    sh = seq_hi_ref[:] ^ sign      # [B, 1]
    sl = seq_lo_ref[:] ^ sign
    ph = pseq_hi_ref[:] ^ sign
    pl_ = pseq_lo_ref[:] ^ sign
    th = tomb_hi_ref[:] ^ sign
    tl = tomb_lo_ref[:] ^ sign
    nh = snap_hi_ref[:] ^ sign     # [1, S]
    nl = snap_lo_ref[:] ^ sign

    def stripe_of(hi, lo):
        lt = (nh < hi) | ((nh == hi) & (nl < lo))
        return jnp.sum(lt.astype(i32), axis=1, keepdims=True)

    stripe = stripe_of(sh, sl)
    pstripe = stripe_of(ph, pl_)
    tstripe = stripe_of(th, tl)
    has_tomb = (tomb_hi_ref[:] | tomb_lo_ref[:]) != 0
    tomb_newer = (th > sh) | ((th == sh) & (tl > sl))
    covered = has_tomb & tomb_newer & (tstripe == stripe)
    fis = (new_key_ref[:] != 0) | (stripe != pstripe)
    vt = vtype_ref[:]
    cx = (vt == i32(2)) | (vt == i32(7))   # MERGE | SINGLE_DELETION
    stripe_ref[:] = stripe
    fis_ref[:] = fis.astype(i32)
    covered_ref[:] = covered.astype(i32)
    cx_ref[:] = cx.astype(i32)


_GC_BLOCK_ROWS = 1024


@functools.partial(jax.jit, static_argnames=("interpret",))
def _gc_rows_impl(seq_hi, seq_lo, pseq_hi, pseq_lo, new_key,
                  tomb_hi, tomb_lo, vtype, snap_hi, snap_lo, interpret):
    from jax.experimental import pallas as pl

    n = seq_hi.shape[0]
    s = snap_hi.shape[0]
    grid = (n // _GC_BLOCK_ROWS,)
    row = lambda: pl.BlockSpec((_GC_BLOCK_ROWS, 1), lambda i: (i, 0))
    snap = lambda: pl.BlockSpec((1, s), lambda i: (0, 0))
    col = lambda a: a.reshape(n, 1)
    outs = pl.pallas_call(
        _gc_rows_kernel,
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.int32)] * 4,
        grid=grid,
        in_specs=[row(), row(), row(), row(), row(), row(), row(), row(),
                  snap(), snap()],
        out_specs=[row()] * 4,
        interpret=interpret,
    )(col(seq_hi), col(seq_lo), col(pseq_hi), col(pseq_lo), col(new_key),
      col(tomb_hi), col(tomb_lo), col(vtype),
      snap_hi.reshape(1, s), snap_lo.reshape(1, s))
    stripe, fis, covered, cx = (o.reshape(n) for o in outs)
    return stripe, fis, covered, cx


def gc_rows(seq_hi, seq_lo, pseq_hi, pseq_lo, new_key, tomb_hi, tomb_lo,
            vtype, snap_hi, snap_lo, interpret=None):
    """Traced entry: per-row (stripe, first_in_stripe, covered, complex)
    for SORTED u32 seqno word columns. Inputs may be traced jax arrays
    (called inside the fused compaction jit). Rows must be a multiple of
    1024 (the caller's pow2 padding guarantees >= that when used)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    u = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    stripe, fis, covered, cx = _gc_rows_impl(
        u(seq_hi), u(seq_lo), u(pseq_hi), u(pseq_lo),
        new_key.astype(jnp.int32), u(tomb_hi), u(tomb_lo),
        vtype.astype(jnp.int32), u(snap_hi), u(snap_lo),
        bool(interpret),
    )
    return stripe, fis != 0, covered != 0, cx != 0


def shared_prefix_lengths(key_bytes: np.ndarray,
                          key_lens: np.ndarray | None = None,
                          interpret: bool | None = None) -> np.ndarray:
    """out[i] = length of the common prefix of row i and row i-1 (out[0]=0).

    key_bytes: [N, K] uint8 (K <= 128), zero-padded rows of SORTED keys.
    key_lens: optional true lengths; the result is clamped to
    min(len[i], len[i-1]) so zero padding can't inflate prefixes.
    """
    n, k = key_bytes.shape
    if k > KEY_LANES:
        raise ValueError(f"keys wider than {KEY_LANES} bytes")
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    pad_n = -(-max(n, 1) // _BLOCK_ROWS) * _BLOCK_ROWS
    buf = np.zeros((pad_n, KEY_LANES), dtype=np.int32)
    buf[:n, :k] = key_bytes
    prev = np.zeros_like(buf)
    prev[1:] = buf[:-1]
    prev[0, :] = -1  # row 0 matches nothing
    out = np.asarray(_shared_prefix_impl(buf, prev, interpret))[:n]
    if key_lens is not None and n:
        lens = key_lens.astype(np.int64)
        cap = np.minimum(lens, np.roll(lens, 1))
        cap[0] = 0
        out = np.minimum(out, cap).astype(np.int32)
    return out


# ---------------------------------------------------------------------------
# Segmented-merge kernel: BITONIC pairwise merge of two presorted runs.
#
# The lax formulation of the device segmented merge (_merge_runs_perm in
# compaction_kernels) ranks rows with per-row binary searches — dynamic
# gathers that XLA lowers well but that have no legal Mosaic lowering
# (TPU vector gathers are not expressible in a Pallas kernel). The
# kernelizable formulation is the BITONIC merge network: concat(A,
# reverse(B)) is a bitonic sequence, and each of the log2(P) stages is a
# compare-exchange at a STATIC stride — pure reshapes + elementwise
# min/max on the VPU, exactly what Mosaic lowers. One kernel invocation
# holds the whole pair in VMEM (cap: _BITONIC_MAX_ROWS), so the grid is
# trivial; larger pairs stay on the lax path.
#
# Keys are (hi, lo) u32 word columns (the packed internal-key order the
# device sort already uses: bytewise-ascending user key, then inverted
# (seq<<8|type)); `perm` rides along so the caller gets the merge
# permutation, and ties keep A-before-B (stability) because the compare
# treats equal keys as "no exchange" and A rows precede B rows.
# ---------------------------------------------------------------------------

_BITONIC_MAX_ROWS = 1 << 17  # 128K rows x (2 key cols + perm) fits VMEM


def _bitonic_merge_kernel(*refs, n_stages, n_cols):
    # refs = (col_0..col_{k-1}, tiebreak, perm) inputs then the outputs.
    ins, outs = refs[: n_cols + 2], refs[n_cols + 2:]
    cols = [r[:] for r in ins]  # [1, P] i32 (u32 order via sign-bit XOR)
    p = cols[0].shape[1]
    for s in range(n_stages - 1, -1, -1):
        stride = 1 << s
        # [1, P] -> [P/(2*stride), 2, stride]: partner = other half.
        halves = [c.reshape(p // (2 * stride), 2, stride) for c in cols]
        a = [h[:, 0, :] for h in halves]
        b = [h[:, 1, :] for h in halves]
        # Lexicographic u32 compare over the key columns, then the
        # ORIGINAL-INDEX tiebreak column — bitonic networks are not
        # stable by construction; the explicit tiebreak makes equal keys
        # come out in concat(A, B) order (perm stays pure payload).
        swap = jnp.zeros_like(a[0], dtype=jnp.bool_)
        tie = jnp.ones_like(a[0], dtype=jnp.bool_)
        for c in range(n_cols):
            swap = swap | (tie & (a[c] > b[c]))
            tie = tie & (a[c] == b[c])
        swap = swap | (tie & (a[n_cols] > b[n_cols]))
        nxt = []
        for c in range(n_cols + 2):
            mn = jnp.where(swap, b[c], a[c])
            mx = jnp.where(swap, a[c], b[c])
            nxt.append(jnp.stack([mn, mx], axis=1).reshape(1, p))
        cols = nxt
    for o, c in zip(outs, cols):
        o[:] = c


@functools.partial(jax.jit,
                   static_argnames=("n_stages", "n_cols", "interpret"))
def _bitonic_merge_impl(arrays, n_stages, n_cols, interpret):
    from jax.experimental import pallas as pl

    p = arrays[0].shape[0]
    kern = functools.partial(_bitonic_merge_kernel, n_stages=n_stages,
                             n_cols=n_cols)
    return pl.pallas_call(
        kern,
        out_shape=[jax.ShapeDtypeStruct((1, p), jnp.int32)] * (n_cols + 2),
        interpret=interpret,
    )(*[a.reshape(1, p) for a in arrays])


_SIGN32 = np.uint32(0x80000000)


def bitonic_merge_pair(cols_a, cols_b, interpret=None):
    """Merge two PRESORTED runs keyed by parallel uint32 word columns
    (lexicographic order over the column list — e.g. [key_hi, key_lo,
    inv_hi, inv_lo] for 8B-user-key internal order); returns the
    permutation into concat(A, B) realizing ascending merged order.
    STABLE: equal keys come out in concat(A, B) order (an original-index
    tiebreak column rides the network). Pads to a power of two
    internally; len(A)+len(B) must be <= _BITONIC_MAX_ROWS.
    Parity-tested in tests/test_pallas_kernels.py; compiled-on-TPU
    validation is pending first tunnel contact (interpret elsewhere)."""
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    n_cols = len(cols_a)
    na, nb = (len(cols_a[0]), len(cols_b[0]))
    total = na + nb
    if total == 0:
        return np.empty(0, np.int32)
    if total > _BITONIC_MAX_ROWS:
        raise ValueError(f"pair of {total} rows exceeds the VMEM budget")
    p = 1 << (total - 1).bit_length()
    # Bitonic input: A ascending, max-padding, then B REVERSED
    # (descending) in the tail — ascending prefix + plateau + descending
    # suffix stays bitonic; padding keys (u32 max) drop from the result.
    arrays = []
    for c in range(n_cols):
        col = np.full(p, 0xFFFFFFFF, np.uint32)
        col[:na] = cols_a[c]
        if nb:
            col[p - nb:] = cols_b[c][::-1]
        arrays.append(col)
    perm = np.full(p, -1, np.int32)
    perm[:na] = np.arange(na, dtype=np.int32)
    if nb:
        perm[p - nb:] = np.arange(na + nb - 1, na - 1, -1, np.int32)
    # Stability tiebreak: original concat index, pads sort last.
    tb = np.where(perm >= 0, perm.astype(np.int64),
                  np.int64(0x7FFFFFFF)).astype(np.uint32)
    i32 = lambda x: (x ^ _SIGN32).astype(np.int64).astype(np.int32)
    out = _bitonic_merge_impl(
        tuple(jnp.asarray(i32(a)) for a in arrays)
        + (jnp.asarray(i32(tb)), jnp.asarray(perm)),
        int(p).bit_length() - 1, n_cols, bool(interpret),
    )
    merged_perm = np.asarray(out[n_cols + 1]).reshape(p)
    return merged_perm[merged_perm >= 0][:total]


def bitonic_merge_runs(cols, run_starts, interpret=None):
    """Segmented merge of R presorted runs via log2(R) rounds of pairwise
    bitonic merges (the kernel-backed twin of _merge_runs_perm's lax
    ranking). `cols`: parallel uint32 word columns, lexicographic.
    Returns the permutation old->sorted over the whole array."""
    starts = list(int(s) for s in run_starts)
    runs = [np.arange(starts[i], starts[i + 1], dtype=np.int32)
            for i in range(len(starts) - 1)]
    cols = [np.asarray(c, np.uint32) for c in cols]
    while len(runs) > 1:
        nxt = []
        for i in range(0, len(runs) - 1, 2):
            a, b = runs[i], runs[i + 1]
            ab = np.concatenate([a, b])
            if len(ab) > _BITONIC_MAX_ROWS:
                # Pair exceeds the kernel's VMEM budget: stable host
                # merge for this pair (the documented oversized-pair
                # fallback; the kernel handles the rest).
                pm = np.argsort(
                    np.rec.fromarrays([c[ab] for c in cols]),
                    kind="stable").astype(np.int32)
            else:
                pm = bitonic_merge_pair([c[a] for c in cols],
                                        [c[b] for c in cols],
                                        interpret=interpret)
            nxt.append(ab[pm])
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt
    return runs[0] if runs else np.empty(0, np.int32)
