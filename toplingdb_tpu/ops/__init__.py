"""JAX/XLA/Pallas kernels: the TPU compaction data plane.

This package re-expresses the compute-heavy half of compaction — the k-way
merge (reference table/merging_iterator.cc), the MVCC GC state machine
(reference db/compaction/compaction_iterator.cc:475), and block encoding
prep (reference table/block_based/block_based_table_builder.cc) — as
fixed-shape array programs:

  columnar.py            entries ⇄ fixed-width key words + metadata arrays
  compaction_kernels.py  sort-merge + visibility/tombstone masking (jit)
  pallas_kernels.py      Pallas TPU kernels (shared-prefix lengths for
                         restart-point block building)
  device_compaction.py   host orchestration: run a compaction's data plane
                         on device, bit-identical to the CPU path

Design notes (TPU-first, not a port):
  * Internal-key order is realized as a multi-operand `jax.lax.sort` over
    big-endian key words + inverted (seqno,type) words — the whole k-way
    merge collapses into one device sort, instead of a scalar loser tree.
  * MVCC GC becomes segment ops over the sorted stream: group boundaries by
    vectorized word compare, snapshot stripes by `searchsorted`, survivor
    masks by shifted comparisons — no data-dependent control flow.
  * Groups needing sequential semantics (merge-operand folding with
    user-defined operators, single-delete pairing) are flagged on device and
    resolved on host; everything else never leaves the array program.

int64 note: seqnos are 56-bit; device arrays carry the packed (seqno,type)
as two uint32 words to stay in TPU-native 32-bit lanes.
"""
