"""Pipelined compaction data plane: overlap scan, merge/GC, and encode.

The serial columnar path (ops/device_compaction.py) is a three-phase
chain — scan every input SST into columnar buffers, one fused sort+GC
over the whole job, then encode+write the outputs — so its wall clock is
the SUM of the phases. This module restructures the same work as a
bounded three-stage pipeline at user-key-range shard granularity:

  reader   per input file, decode the blocks of one key-range shard per
           native call (windowed preads through a FilePrefetchBuffer),
           writing into a properties-sized preallocated ColumnarKV —
           independent files scan on parallel threads
  compute  as soon as EVERY file has scanned past shard s, run the
           device (uniform-shard upload + fused kernel) or host-twin
           (native k-way merge + GC) sort+GC over just that shard's rows
  writer   stream each shard's survivor order into the native block
           builder (write_tables_columnar's chunked-order mode) while
           later shards are still being scanned/computed

Key-range shards are cut at user-key boundaries (every version of a user
key lands in exactly one shard), so per-shard GC decisions — snapshot
stripes, tombstone shadowing, bottommost seqno zeroing — equal the
global ones and the concatenated survivor stream is byte-identical to
the serial path's; tests/test_compaction_pipeline.py asserts whole-file
SST equality. Jobs the pipeline does not cover (complex merge /
single-delete groups, non-block formats, missing properties, small
inputs) raise PipelineIneligible and the caller falls back to the serial
path, which computes the same bytes.

`TPULSM_PIPELINE=0` disables the pipeline; `TPULSM_PIPELINE_SHARDS=N`
overrides the shard count.
"""

from __future__ import annotations

import ctypes
import os
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
from queue import Empty, Full, Queue

import numpy as np

from toplingdb_tpu import native
from toplingdb_tpu.db import dbformat
from toplingdb_tpu.utils import telemetry
from toplingdb_tpu.utils.status import Corruption, NotSupported
from toplingdb_tpu.utils import errors as _errors


class PipelineIneligible(Exception):
    """Job shapes the pipeline does not cover; run the serial path."""


class _Done:
    pass


class _Err:
    def __init__(self, exc):
        self.exc = exc


_DONE = _Done()

# Below this row estimate the serial path wins: thread startup plus
# per-shard dispatch overhead cannot be recouped by overlap.
MIN_PIPELINE_ROWS = 1 << 18

# Reader-stage readahead: shard windows are MBs, so the prefetch buffer
# runs with a much larger ceiling than the per-iterator default.
_PF_READAHEAD = 8 << 20

_PU8 = ctypes.POINTER(ctypes.c_uint8)
_PI32 = ctypes.POINTER(ctypes.c_int32)


def pipeline_enabled(table_options=None) -> bool:
    if os.environ.get("TPULSM_PIPELINE", "1") == "0":
        return False
    if os.environ.get("TPULSM_DEVICE_BLOCKS") == "1":
        return False  # on-device block assembly has its own data plane
    if table_options is not None:
        f = getattr(table_options, "format", "block")
        if f == "zip":
            from toplingdb_tpu.table.zip_table import zip_plane_enabled

            # Zip rides the pipeline when the native zip data plane is
            # on: scan/merge overlap with the drain-then-encode writer
            # stage (write_tables_zip_columnar collects the chunk feed).
            return zip_plane_enabled()
        if f != "block":
            return False  # other formats consume whole arrays serially
    return True


def _pipeline_shards(total_rows: int) -> int:
    """Pipeline shard count: finer than the serial device sharding (the
    pipeline wants several shards in flight even at ~1M rows)."""
    env = os.environ.get("TPULSM_PIPELINE_SHARDS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    # ~512K rows per shard: small jobs get 2 shards (enough to overlap,
    # little per-shard dispatch overhead), bench-scale jobs get 16-32.
    target = 1 << 19
    s = 1
    while s < 32 and total_rows // s > target:
        s *= 2
    return s


class _FilePlan:
    """Per-input-file scan plan: block handles grouped by shard, the
    file's slice of the preallocated global buffers, and the row bounds
    of each shard (filled in by the reader as decode progresses)."""

    __slots__ = ("reader", "pf", "block_offs", "block_lens", "groups",
                 "ne", "rk", "rv", "n_base", "k_base", "v_base",
                 "row_bounds", "verify")


class _Progress:
    """Reader→compute coordination: per-file shard watermarks plus the
    first error; any failure stops every stage."""

    def __init__(self, n_files: int):
        self._done = [-1] * n_files
        self._cv = ccy.Condition("pipeline._Progress._cv")
        self.err: BaseException | None = None
        self.stop = False
        self.scan_end = 0.0

    def mark(self, fi: int, s: int) -> None:
        with self._cv:
            self._done[fi] = s
            self._cv.notify_all()

    def finish_file(self, fi: int) -> None:
        with self._cv:
            self.scan_end = max(self.scan_end, time.time())

    def fail(self, exc: BaseException) -> None:
        with self._cv:
            if self.err is None:
                self.err = exc
            self.stop = True
            self._cv.notify_all()

    def abort(self) -> None:
        with self._cv:
            self.stop = True
            self._cv.notify_all()

    def poll_shard(self, s: int) -> bool:
        with self._cv:
            if self.err is not None:
                raise self.err
            return min(self._done) >= s

    def wait_shard(self, s: int) -> None:
        with self._cv:
            while True:
                if self.err is not None:
                    raise self.err
                if self.stop:
                    raise PipelineIneligible("pipeline aborted")
                if min(self._done) >= s:
                    return
                self._cv.wait()


def _uk_at(kv, r: int) -> bytes:
    o = int(kv.key_offs[r])
    return kv.key_buf[o: o + int(kv.key_lens[r]) - 8].tobytes()


def _lower_bound(kv, lo: int, hi: int, key: bytes) -> int:
    """First row in [lo, hi) (internal-key sorted) with user key >= key."""
    while lo < hi:
        mid = (lo + hi) // 2
        if _uk_at(kv, mid) < key:
            lo = mid + 1
        else:
            hi = mid
    return lo


def _range_seq_vtype(kv, lo: int, hi: int):
    """(seq u64, vtype i32) for global rows [lo, hi) — generic trailer
    gather (the rows need not be a dense byte span)."""
    import sys

    offs = kv.key_offs[lo:hi].astype(np.int64)
    lens = kv.key_lens[lo:hi].astype(np.int64)
    tr_idx = (offs + lens - 8)[:, None] + np.arange(8)[None, :]
    trailer = np.ascontiguousarray(kv.key_buf[tr_idx])
    packed = trailer.view(np.uint64).reshape(hi - lo)
    if sys.byteorder == "big":
        packed = packed.byteswap()
    return packed >> np.uint64(8), \
        (packed & np.uint64(0xFF)).astype(np.int32)


def _build_plan(readers):
    """Validate prealloc eligibility, size the global buffers, pick the
    key-range splitters and each file's per-shard block groups. Returns
    (kv, files, splitters) or raises PipelineIneligible."""
    import bisect

    from toplingdb_tpu.ops.columnar_io import ColumnarKV
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.prefetch import FilePrefetchBuffer

    lib = native.lib()
    if lib is None or not hasattr(lib, "tpulsm_scan_blocks"):
        raise PipelineIneligible("native fused scan unavailable")
    infos = []
    tk = tv = tn = 0
    for r in readers:
        if not hasattr(r, "new_index_iterator"):
            raise PipelineIneligible("non-block input format")
        if getattr(r, "_compression_dict", b""):
            raise PipelineIneligible("dict-compressed input")
        p = getattr(r, "properties", None)
        if p is None:
            raise PipelineIneligible("input properties missing")
        ne, rk, rv = int(p.num_entries), int(p.raw_key_size), int(
            p.raw_value_size)
        if ne < 0 or rk < 0 or rv < 0 or (ne > 0 and rk == 0):
            raise PipelineIneligible("implausible input properties")
        idx = r.new_index_iterator()
        idx.seek_to_first()
        handles = []
        sep_uks = []
        for k, enc in idx.entries():
            handles.append(fmt.BlockHandle.decode_exact(enc))
            sep_uks.append(dbformat.extract_user_key(k))
        if ne and not handles:
            raise PipelineIneligible("entries claimed but no data blocks")
        infos.append((ne, rk, rv, handles, sep_uks))
        tk += rk
        tv += rv
        tn += ne
    if tk > 0x7FFFFF00 or tv > 0x7FFFFF00:
        raise PipelineIneligible("inputs exceed the int32 columnar budget")
    if tn < MIN_PIPELINE_ROWS:
        raise PipelineIneligible("job below the pipeline row floor")

    # Splitters: merged per-file index separator user keys (one per data
    # block, so even index spacing approximates even byte spacing), cut
    # into n_shards quantiles.
    n_shards = _pipeline_shards(tn)
    if n_shards < 2:
        raise PipelineIneligible("single-shard job")
    all_seps = sorted(uk for _, _, _, _, uks in infos for uk in uks)
    splitters: list[bytes] = []
    for t in range(1, n_shards):
        cand = all_seps[len(all_seps) * t // n_shards]
        if not splitters or cand > splitters[-1]:
            splitters.append(cand)
    if not splitters:
        raise PipelineIneligible("inputs too uniform to shard")
    n_shards = len(splitters) + 1

    kv = ColumnarKV(
        np.empty(tk, dtype=np.uint8), np.empty(tn, dtype=np.int32),
        np.empty(tn, dtype=np.int32), np.empty(tv, dtype=np.uint8),
        np.empty(tn, dtype=np.int32), np.empty(tn, dtype=np.int32),
    )

    files = []
    nb = kb = vb = 0
    for r, (ne, rk, rv, handles, sep_uks) in zip(readers, infos):
        if ne == 0:
            continue
        fp = _FilePlan()
        fp.reader = r
        fp.pf = FilePrefetchBuffer(r._f, max_readahead=_PF_READAHEAD,
                                   initial_readahead=_PF_READAHEAD,
                                   arm_immediately=True)
        fp.block_offs = np.array([h.offset for h in handles], dtype=np.int64)
        fp.block_lens = np.array([h.size for h in handles], dtype=np.int64)
        # Shard s decodes blocks [groups[s], groups[s+1]); the group ends
        # at (inclusive) the first block whose separator user key reaches
        # the splitter — that block may straddle it, and its tail rows
        # belong to the next shard via the row-bound binary search.
        g = [0]
        for spl in splitters:
            g.append(max(g[-1], min(bisect.bisect_left(sep_uks, spl) + 1,
                                    len(handles))))
        g.append(len(handles))
        fp.groups = g
        fp.ne, fp.rk, fp.rv = ne, rk, rv
        fp.n_base, fp.k_base, fp.v_base = nb, kb, vb
        fp.row_bounds = [nb] * n_shards + [nb + ne]
        fp.verify = bool(r.opts.verify_checksums)
        files.append(fp)
        nb += ne
        kb += rk
        vb += rv
    if not files:
        raise PipelineIneligible("no non-empty inputs")
    return kv, files, splitters


def _scan_file(fi, fp, kv, prog, splitters, stats, stats_mu,
               trace_handle=None):
    """Reader worker: decode one file shard-by-shard into its slice of the
    global buffers, publishing row bounds + progress per shard."""
    lib = native.lib()
    n_shards = len(splitters) + 1
    try:
        rows = 0
        k_used = v_used = 0
        bound = 0  # file-local row bound of the current shard start
        for s in range(n_shards):
            if prog.stop:
                return
            t_sh = time.time() if trace_handle is not None else 0.0
            blo, bhi = fp.groups[s], fp.groups[s + 1]
            if bhi > blo:
                w0 = int(fp.block_offs[blo])
                w1 = int(fp.block_offs[bhi - 1] + fp.block_lens[bhi - 1]) + 5
                raw = fp.pf.read(w0, w1 - w0)
                rawb = np.frombuffer(raw, dtype=np.uint8)
                boffs = np.ascontiguousarray(fp.block_offs[blo:bhi] - w0)
                blens = np.ascontiguousarray(fp.block_lens[blo:bhi])
                rc = lib.tpulsm_scan_blocks(
                    native.np_u8p(rawb), len(rawb),
                    native.np_i64p(boffs), native.np_i64p(blens), bhi - blo,
                    1 if fp.verify else 0,
                    ctypes.cast(kv.key_buf.ctypes.data + fp.k_base + k_used,
                                _PU8), fp.rk - k_used,
                    ctypes.cast(kv.val_buf.ctypes.data + fp.v_base + v_used,
                                _PU8), fp.rv - v_used,
                    ctypes.cast(kv.key_offs.ctypes.data
                                + 4 * (fp.n_base + rows), _PI32),
                    ctypes.cast(kv.key_lens.ctypes.data
                                + 4 * (fp.n_base + rows), _PI32),
                    ctypes.cast(kv.val_offs.ctypes.data
                                + 4 * (fp.n_base + rows), _PI32),
                    ctypes.cast(kv.val_lens.ctypes.data
                                + 4 * (fp.n_base + rows), _PI32),
                    fp.ne - rows, fp.k_base + k_used, fp.v_base + v_used,
                )
                if rc == -6:
                    raise Corruption("block checksum mismatch (pipeline)")
                if rc == -8:
                    raise Corruption("block decode failed (pipeline)")
                if rc < 0:
                    # -1 codec fallback, -2/-3/-4 capacity disagreements
                    # with the properties: the serial path covers these.
                    raise PipelineIneligible(f"native scan rc={rc}")
                if rc > 0:
                    last = fp.n_base + rows + int(rc) - 1
                    k_used = int(kv.key_offs[last]) \
                        + int(kv.key_lens[last]) - fp.k_base
                    v_used = int(kv.val_offs[last]) \
                        + int(kv.val_lens[last]) - fp.v_base
                rows += int(rc)
                if rows > fp.ne:
                    raise PipelineIneligible("more entries than properties")
            if s < n_shards - 1:
                nb = _lower_bound(kv, fp.n_base + bound, fp.n_base + rows,
                                  splitters[s]) - fp.n_base
                fp.row_bounds[s + 1] = fp.n_base + nb
                bound = nb
            if s == n_shards - 1 and (rows != fp.ne or k_used != fp.rk
                                      or v_used != fp.rv):
                raise PipelineIneligible("scan totals disagree with props")
            if trace_handle is not None and bhi > blo:
                telemetry.span_event_under(
                    trace_handle, "pipeline.scan",
                    (time.time() - t_sh) * 1e6, file=fi, shard=s,
                    blocks=bhi - blo)
            prog.mark(fi, s)
        with stats_mu:
            stats.prefetch_hits += fp.pf.hits
            stats.prefetch_misses += fp.pf.misses
        prog.finish_file(fi)
    except BaseException as e:  # noqa: BLE001 — forwarded to the driver
        prog.fail(e)


def _cover_for_ranges(kv, ranges, frags, snaps):
    """Stripe-clamped max covering tombstone seqno per row of the shard's
    (sorted) per-file ranges, concatenated in range order — the pipeline
    twin of device_compaction._cover_for_parts."""
    if not frags:
        return None
    covs = []
    for lo, hi in ranges:
        n = hi - lo
        cov = np.zeros(n, dtype=np.uint64)
        if n:
            seqs, _vt = _range_seq_vtype(kv, lo, hi)
            if len(snaps):
                idx = np.searchsorted(snaps, seqs, side="left")
                upper = np.where(
                    idx < len(snaps),
                    snaps[np.minimum(idx, len(snaps) - 1)],
                    np.uint64(dbformat.MAX_SEQUENCE_NUMBER),
                )
            else:
                upper = np.full(n, dbformat.MAX_SEQUENCE_NUMBER,
                                dtype=np.uint64)
            for frag in frags:
                flo = _lower_bound(kv, lo, hi, frag.begin) - lo
                fhi = _lower_bound(kv, lo + flo, hi, frag.end) - lo
                if flo < fhi:
                    t = np.uint64(frag.seq)
                    sl = slice(flo, fhi)
                    elig = ((t > seqs[sl]) & (t <= upper[sl])
                            & (t > cov[sl]))
                    cov[sl] = np.where(elig, t, cov[sl])
        covs.append(cov)
    return np.concatenate(covs)


def _shard_ranges(files, s):
    return [(fp.row_bounds[s], fp.row_bounds[s + 1]) for fp in files
            if fp.row_bounds[s + 1] > fp.row_bounds[s]]


def _ranges_lmap(ranges) -> np.ndarray:
    if not ranges:
        return np.empty(0, np.int32)
    return np.concatenate([
        np.arange(lo, hi, dtype=np.int32) for lo, hi in ranges
    ])


def _put(outq, prog, item) -> None:
    """Bounded put that gives up once any stage has failed or aborted."""
    while True:
        if prog.stop:
            raise prog.err or PipelineIneligible("pipeline aborted")
        try:
            outq.put(item, timeout=0.1)
            return
        except Full:
            continue


def _host_compute(kv, files, splitters, prog, outq, shared, snapshots,
                  bottommost, frags, max_dev_key):
    """Compute worker, host-twin mode: native k-way merge + GC per shard;
    publishes global-row survivor chunks with zero-seq rows patched."""
    from toplingdb_tpu.ops import compaction_kernels as ck

    n_shards = len(splitters) + 1
    snaps = np.asarray(sorted(snapshots), dtype=np.uint64)
    for s in range(n_shards):
        prog.wait_shard(s)
        t0 = time.time()
        ranges = _shard_ranges(files, s)
        if not ranges:
            continue
        _tsp = telemetry.span_under(shared.trace, "pipeline.merge_gc",
                                    shard=s)
        soffs = np.concatenate(
            [kv.key_offs[lo:hi] for lo, hi in ranges]).astype(np.int64)
        slens = np.concatenate(
            [kv.key_lens[lo:hi] for lo, hi in ranges]).astype(np.int64)
        mx = int(slens.max())
        if mx - 8 > max_dev_key:
            raise PipelineIneligible("keys exceed the device budget")
        rs = np.cumsum([0] + [hi - lo for lo, hi in ranges],
                       dtype=np.int64)
        cover = _cover_for_ranges(kv, ranges, frags, snaps)
        order, zero, _cx, hc, seq_l, vt_l = ck.host_fused_full(
            kv.key_buf, soffs, slens, max(4, mx - 8), snapshots,
            bottommost, cover, run_starts=rs,
        )
        if hc:
            raise PipelineIneligible("complex groups present")
        lmap = _ranges_lmap(ranges)
        og = lmap[order]
        shared.seqs[lmap] = seq_l
        shared.vtypes[lmap] = vt_l
        zg = og[zero]
        shared.trailer_override[zg] = shared.vtypes[zg].astype(np.int64)
        shared.seqs[zg] = 0
        shared.stats.host_compute_usec += int((time.time() - t0) * 1e6)
        _tsp.finish()
        _put(outq, prog, og)
    _put(outq, prog, _DONE)


def _device_compute(kv, files, splitters, prog, outq, shared, snapshots,
                    bottommost, frags, max_dev_key):
    """Compute worker, device mode: upload each shard's uniform chunks as
    soon as its scan lands (async H2D + dispatch), finish in order —
    double-buffered so shard s+1 transfers while shard s computes. Under
    TPULSM_MESH_COMPACT shards round-robin over every chip instead
    (committed uploads pin each program, ops/mesh_compaction.py) and the
    lookahead widens to UPLOAD_DEPTH per chip; a chip that fails mid-job
    demotes the remaining shards to the default device."""
    from toplingdb_tpu.ops import compaction_kernels as ck
    from toplingdb_tpu.ops import mesh_compaction as mc
    from toplingdb_tpu.parallel import mesh_plan as mp
    from toplingdb_tpu.utils.status import NotSupported

    n_shards = len(splitters) + 1
    snaps = np.asarray(sorted(snapshots), dtype=np.uint64)
    mesh_devs = mc.pipeline_devices(n_shards, stats=shared.stats,
                                    trace=shared.trace)
    depth = [mp.UPLOAD_DEPTH * len(mesh_devs) if mesh_devs else 1]
    pendings = []  # (ranges, lmap, pending, s, dev, chunks, covers) | None

    def _demote(exc) -> None:
        # Wedged chip: the rest of the job runs single-device; bytes are
        # unchanged (same kernels), only placement degrades.
        mesh_devs.clear()
        depth[0] = 1
        shared.stats.mesh_chips = 1
        shared.stats.mesh_fallbacks = getattr(
            shared.stats, "mesh_fallbacks", 0) + 1
        telemetry.span_event_under(shared.trace, "compaction.mesh.fallback",
                                   0, reason="chip-wedged",
                                   error=type(exc).__name__)

    def finish_one(item):
        if item is None:
            return
        ranges, lmap, pending, s, dev, chunks, covers = item
        t0 = time.time()
        try:
            o, z, _cx, hc = ck.fused_uniform_shard_finish(pending)
        except Exception as e:
            if dev is None or isinstance(e, NotSupported):
                raise
            _demote(e)  # re-run this shard on the default device
            pending = ck.fused_uniform_shard_start(
                ck.upload_uniform_shard(chunks, covers), snapshots,
                bottommost,
            )
            o, z, _cx, hc = ck.fused_uniform_shard_finish(pending)
        dwait = time.time() - t0
        shared.stats.device_wait_usec += int(dwait * 1e6)
        telemetry.span_event_under(shared.trace, "pipeline.merge_gc",
                                   dwait * 1e6, shard=s, device=True)
        if dev is not None:
            telemetry.span_event_under(shared.trace, "compaction.mesh.shard",
                                       dwait * 1e6, shard=s, chip=str(dev))
        if hc:
            raise PipelineIneligible("complex groups present")
        og = lmap[o]
        for lo, hi in ranges:
            seq_r, vt_r = _range_seq_vtype(kv, lo, hi)
            shared.seqs[lo:hi] = seq_r
            shared.vtypes[lo:hi] = vt_r
        zg = og[z]
        shared.trailer_override[zg] = shared.vtypes[zg].astype(np.int64)
        shared.seqs[zg] = 0
        _put(outq, prog, og)

    for s in range(n_shards):
        prog.wait_shard(s)
        ranges = _shard_ranges(files, s)
        if not ranges:
            pendings.append(None)
        else:
            t0 = time.time()
            chunks = []
            covers = None if not frags else []
            klen = None
            for lo, hi in ranges:
                lens = kv.key_lens[lo:hi]
                if int(lens.min()) != int(lens.max()):
                    raise PipelineIneligible("non-uniform key length")
                if klen is None:
                    klen = int(lens[0])
                elif klen != int(lens[0]):
                    raise PipelineIneligible("non-uniform key length")
                if klen - 8 > max_dev_key:
                    raise PipelineIneligible("keys exceed the device budget")
                b0 = int(kv.key_offs[lo])
                chunks.append(ck.prepare_uniform_chunk(
                    kv.key_buf[b0:b0 + (hi - lo) * klen], hi - lo, klen,
                ))
            if frags:
                cov = _cover_for_ranges(kv, ranges, frags, snaps)
                covers = []
                pos = 0
                for lo, hi in ranges:
                    covers.append(cov[pos:pos + (hi - lo)])
                    pos += hi - lo
            dev = mesh_devs[s % len(mesh_devs)] if mesh_devs else None
            try:
                pending = ck.fused_uniform_shard_start(
                    ck.upload_uniform_shard(chunks, covers, device=dev),
                    snapshots, bottommost,
                )
            except Exception as e:
                if dev is None or isinstance(e, NotSupported):
                    raise
                _demote(e)
                dev = None
                pending = ck.fused_uniform_shard_start(
                    ck.upload_uniform_shard(chunks, covers), snapshots,
                    bottommost,
                )
            shared.stats.transfer_time_usec += int((time.time() - t0) * 1e6)
            pendings.append((ranges, _ranges_lmap(ranges), pending, s, dev,
                             chunks, covers))
        # keep the lookahead window in flight (one upload serially,
        # UPLOAD_DEPTH per chip under the mesh); finish older shards now
        while len(pendings) > depth[0]:
            finish_one(pendings.pop(0))
    while pendings:
        finish_one(pendings.pop(0))
    _put(outq, prog, _DONE)


class _Shared:
    """Arrays shared between compute and the writer (aliased per the
    chunked-order contract of write_tables_columnar) plus the stats and
    the telemetry handle stage workers parent their spans under."""

    __slots__ = ("trailer_override", "seqs", "vtypes", "stats", "trace")


def run_pipelined(env, dbname, icmp, compaction, table_cache, table_options,
                  snapshots, new_file_number, creation_time, stats,
                  max_dev_key, column_family=(0, "default")):
    """Run one compaction through the three-stage pipeline. Returns the
    write_tables_columnar file tuples plus the shared arrays used to
    build output metadata: (files, kv, vtypes, tombs).

    Raises PipelineIneligible for shapes the serial path must take and
    propagates hard errors (Corruption, IO) after partial outputs are
    cleaned up by the writer."""
    from toplingdb_tpu.compaction.compaction_job import (
        surviving_tombstone_fragments,
    )
    from toplingdb_tpu.db.range_del import (
        RangeDelAggregator, RangeTombstone, fragment_tombstones,
    )
    from toplingdb_tpu.ops.columnar_io import write_tables_columnar
    from toplingdb_tpu.ops.compaction_kernels import MAX_SNAPSHOTS

    if not pipeline_enabled(table_options):
        raise PipelineIneligible("pipeline disabled")
    if len(snapshots) > MAX_SNAPSHOTS:
        raise PipelineIneligible("snapshot count exceeds the device cap")
    readers = [
        table_cache.get_reader(f.number) for _, f in compaction.all_inputs()
    ]
    kv, files, splitters = _build_plan(readers)
    stats.input_records = kv.n

    rd = RangeDelAggregator(icmp.user_comparator)
    for r in readers:
        for b, e in r.range_del_entries():
            rd.add(RangeTombstone.from_table_entry(b, e))
    frags = (list(fragment_tombstones(rd.tombstones(),
                                      icmp.user_comparator))
             if not rd.empty() else [])
    tombs = surviving_tombstone_fragments(
        rd, snapshots, compaction.bottommost, icmp.user_comparator,
    )

    shared = _Shared()
    shared.trailer_override = np.full(kv.n, -1, dtype=np.int64)
    shared.seqs = np.zeros(kv.n, dtype=np.uint64)
    shared.vtypes = np.zeros(kv.n, dtype=np.int32)
    shared.stats = stats
    stats.pipelined = True
    # The compaction root span lives on the ORCHESTRATING thread; stage
    # workers parent their per-shard spans under this exported handle.
    shared.trace = telemetry.current_handle()

    prog = _Progress(len(files))
    outq: Queue = Queue(maxsize=4)
    stats_mu = ccy.Lock("pipeline.run_pipelined.stats_mu")

    t_scan0 = time.time()
    rthreads = [
        ccy.spawn(f"pipeline-scan-{fi}", _scan_file, start=False,
                  args=(fi, fp, kv, prog, splitters, stats,
                        stats_mu, shared.trace))
        for fi, fp in enumerate(files)
    ]
    from toplingdb_tpu.ops.device_compaction import _host_sort

    compute_fn = _host_compute if _host_sort() else _device_compute
    cthread = ccy.spawn(
        "pipeline-compute", _compute_guard, start=False,
        args=(compute_fn, kv, files, splitters, prog, outq, shared,
              snapshots, compaction.bottommost, frags, max_dev_key),
    )
    for t in rthreads:
        t.start()
    cthread.start()

    def chunk_stream():
        chunk = 0
        t_resumed = None  # when the writer got control back after a yield
        while True:
            t0 = time.time()
            if t_resumed is not None:
                # Time since the previous chunk was handed over = that
                # chunk's encode+write stage (the writer consumed it
                # before asking for the next one).
                telemetry.span_event_under(
                    shared.trace, "pipeline.encode_write",
                    (t0 - t_resumed) * 1e6, chunk=chunk)
                chunk += 1
            item = outq.get()
            stats.pipeline_stall_usec += int((time.time() - t0) * 1e6)
            if item is _DONE:
                return
            if isinstance(item, _Err):
                raise item.exc
            t_resumed = time.time()
            yield item

    writer = write_tables_columnar
    if getattr(table_options, "format", "block") == "zip":
        from toplingdb_tpu.table.zip_table import write_tables_zip_columnar

        writer = write_tables_zip_columnar
    t_wr = time.time()
    try:
        out_files = writer(
            env, dbname, new_file_number, icmp, table_options, kv,
            chunk_stream(), shared.trailer_override, shared.vtypes,
            shared.seqs, tombs,
            creation_time if creation_time is not None else int(time.time()),
            max_output_file_size=compaction.max_output_file_size,
            column_family=column_family,
        )
    except BaseException:
        prog.abort()
        _drain_join(outq, [cthread] + rthreads)
        raise
    stats.encode_write_usec = max(0, int(
        (time.time() - t_wr) * 1e6) - stats.pipeline_stall_usec)
    for t in rthreads:
        t.join()
    cthread.join()
    if prog.err is not None:
        raise prog.err
    stats.input_scan_usec = int(
        ((prog.scan_end or time.time()) - t_scan0) * 1e6)
    return out_files, kv, shared.vtypes, tombs


def _compute_guard(fn, kv, files, splitters, prog, outq, shared, snapshots,
                   bottommost, frags, max_dev_key):
    try:
        fn(kv, files, splitters, prog, outq, shared, snapshots, bottommost,
           frags, max_dev_key)
    except BaseException as e:  # noqa: BLE001 — forwarded via the queue
        prog.fail(e)
        try:
            outq.put_nowait(_Err(e))
        except Exception as e2:
            # Queue full: the writer will observe prog.err after draining.
            _errors.swallow(reason="producer-error-queue-full", exc=e2)
            try:
                outq.get_nowait()
                outq.put_nowait(_Err(e))
            except Exception as e3:
                _errors.swallow(reason="producer-error-queue-race", exc=e3)


def _drain_join(outq: Queue, threads) -> None:
    """Unblock producers stuck on the bounded queue, then join."""
    deadline = time.time() + 10.0
    while any(t.is_alive() for t in threads) and time.time() < deadline:
        try:
            outq.get(timeout=0.05)
        except Empty:
            pass
    for t in threads:
        t.join(timeout=1.0)
