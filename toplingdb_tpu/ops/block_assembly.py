"""On-device SST block assembly.

The reference's per-entry block build loop
(/root/reference/table/block_based/block_builder.cc:66-180 BlockBuilder::Add,
/root/reference/table/block_based/block_based_table_builder.cc:961-1150) runs
entirely on the device: after the fused sort+GC, ONE jit program computes
restart-point prefix sharing, greedy block cuts, per-entry byte offsets and
scatters finished UNCOMPRESSED block payloads (records + restart arrays)
into a single output buffer. The host only adds the 5-byte block trailers
(type + masked crc32c), the index/meta blocks and the footer — so its CPU
cost per job is O(blocks), not O(entries), and on PCIe-class hosts the
whole data plane is device-bound.

Byte parity: payloads are bit-identical to the native C++ builder
(tpulsm_build_block) — the greedy cut rule `used + 4*num_restarts + 4 >=
block_size` is reproduced exactly with a residue-class searchsorted (block
start j cuts at the first i where a prefix-sum expression crosses the
budget; restart overhead folds into per-residue prefix sums because
restarts sit at i ≡ j (mod R)) followed by pointer-doubling over the
next-cut graph to mark actual block starts. tests/test_block_assembly.py
asserts whole-file byte equality against the CPU path.

Scope (falls back to the packed-order download path otherwise): uniform
key length < 120B, values < 128B (single-byte varints), NO_COMPRESSION,
whole-key (or no) filters, single output file, no complex groups /
blob refs. A survivor bitmap (1 bit/row) rides down so the host builds
the bloom byte-identically without the full order download.
Transfers: values ride UP and finished blocks ride DOWN, so this path
pays ~2x the bytes of the order-download path — it wins where the host
CPU, not the link, is the bottleneck (TPULSM_DEVICE_BLOCKS=1 opts in;
auto-off on tunneled rigs).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from toplingdb_tpu.db.dbformat import ValueType
from toplingdb_tpu.ops import compaction_kernels as ck
from toplingdb_tpu.utils.status import NotSupported
from toplingdb_tpu.utils import errors as _errors

_I32MAX = 2 ** 31 - 1


def _log2ceil(n: int) -> int:
    b = 0
    while (1 << b) < n:
        b += 1
    return b


@functools.partial(jax.jit, static_argnames=(
    "num_key_words", "uk_len", "bottommost", "has_tombs", "front_code",
    "R", "B", "max_rec", "ubp", "nbp",
))
def _assemble_blocks_impl(ukb, plens, sfx, pkb, starts, min_his, min_los,
                          vlens, vflat, tomb_hi, tomb_lo, snap_hi, snap_lo,
                          total, num_key_words, uk_len, bottommost,
                          has_tombs, front_code, R, B, max_rec, ubp, nbp):
    """Sort + GC + FULL block assembly in one device program.

    Returns (out u8[ubp], meta i32[10], bcounts i32[nbp], bpayload i32[nbp],
    bfirst i32[nbp], blast i32[nbp], surv_bitmap u8[ceil(p/8)]):
      out      concatenated block payloads (no trailers)
      meta     [nb, m, total_payload, has_complex, num_deletions,
                raw_value, smin_hi, smin_lo, smax_hi, smax_lo]
      bcounts  entries per block
      bpayload payload bytes per block
      bfirst/blast  original LOCAL row of each block's first/last entry,
                    bit 30 = that entry's seq was zeroed
    """
    u32 = jnp.uint32
    i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    if front_code:
        kb = ck._decode_front_coded(plens, sfx, uk_len)
    else:
        p0 = pkb.shape[0]
        kb = ukb.reshape(p0, uk_len)
    core = ck._uniform_shard_core(
        kb, pkb, starts, min_his, min_los, tomb_hi, tomb_lo,
        snap_hi, snap_lo, total, num_key_words, uk_len, bottommost,
        has_tombs,
    )
    p = pkb.shape[0]
    iota = jnp.arange(p, dtype=jnp.int32)
    K = uk_len + 8

    # --- survivor-ordered arrays (first m rows valid) ---
    take = core["take"]
    sorder = core["perm"][take]                 # original local row
    svalid = core["out"][take]
    m = jnp.sum(svalid.astype(jnp.int32))
    szero = core["zero_seq"][take] & svalid
    sp_hi = jnp.where(szero, u32(0), core["packed_hi"][sorder])
    sp_lo = jnp.where(
        szero, core["vtype_orig"][sorder].astype(u32),
        core["packed_lo"][sorder],
    )
    svt = core["vtype_orig"][sorder]
    svlen = jnp.where(svalid, vlens[sorder].astype(jnp.int32), 0)
    voff_all = jnp.cumsum(vlens.astype(jnp.int32)) - vlens.astype(jnp.int32)
    svoff = voff_all[sorder]

    # --- full internal-key matrix (user key + 8B LE trailer) ---
    skb = kb[sorder]                            # [p, uk_len]
    tcol = jnp.arange(8, dtype=jnp.int32)[None, :]
    tb = jnp.where(
        tcol < 4,
        (sp_lo[:, None] >> (8 * jnp.clip(tcol, 0, 3))) & u32(0xFF),
        (sp_hi[:, None] >> (8 * jnp.clip(tcol - 4, 0, 3))) & u32(0xFF),
    ).astype(jnp.uint8)
    ikey = jnp.concatenate([skb, tb], axis=1)   # [p, K]

    # --- shared-prefix lengths between consecutive survivors ---
    prev = jnp.roll(ikey, 1, axis=0)
    lane = jax.lax.broadcasted_iota(jnp.int32, ikey.shape, 1)
    lcp = jnp.min(jnp.where(ikey != prev, lane, jnp.int32(K)), axis=1)
    lcp = lcp.at[0].set(0)
    lcp = jnp.where(svalid & (iota > 0), lcp, 0)

    # --- per-entry sizes (single-byte varints; host gates K,vlen < 128) ---
    sz_cont = jnp.where(svalid, 3 + (K - lcp) + svlen, 0)
    sz_rst = jnp.where(svalid, 3 + K + svlen, 0)
    delta = sz_rst - sz_cont                    # == lcp for valid rows
    S = jnp.cumsum(sz_cont)                     # inclusive
    S0 = S - sz_cont                            # exclusive

    # --- greedy block cuts: next_start[j] for every possible start j ---
    # total(j, i) = S[i]-S0[j] + D_m[i]-D0_m[j] + 4*floor((i-j)/R) + 8
    # with m = j mod R and D_m = cumsum(delta at positions ≡ m (mod R)).
    nxt = jnp.full(p, p - 1, dtype=jnp.int32)
    for mc in range(R):
        cls = (iota % R) == mc
        D = jnp.cumsum(jnp.where(cls, delta, 0))
        D0 = D - jnp.where(cls, delta, 0)
        rm = (iota - mc) % R
        a = (iota - rm - mc) // R
        U = S + D + 4 * a
        b_j = (iota - mc) // R
        T = jnp.int32(B - 8) + S0 + D0 + 4 * b_j
        cand = jnp.searchsorted(U, T, side="left").astype(jnp.int32)
        nxt = jnp.where(cls, cand, nxt)
    f = jnp.clip(nxt + 1, 1, p)                 # cut AFTER entry nxt[j]
    f_ext = jnp.concatenate([f, jnp.array([p], jnp.int32)])

    # --- mark the orbit of 0 under f (actual block starts) ---
    reach = jnp.zeros(p + 1, dtype=jnp.bool_).at[0].set(True)
    g = f_ext
    for _ in range(_log2ceil(p) + 1):
        reach = reach | jnp.zeros_like(reach).at[g].max(reach)
        g = g[g]
    start = reach[:p] & (iota < m)

    # --- per-entry block geometry ---
    bstart = jax.lax.cummax(jnp.where(start, iota, jnp.int32(-1)))
    q = iota - bstart
    is_rst = (q % R) == 0
    sz = jnp.where(is_rst, sz_rst, sz_cont)
    Csz = jnp.cumsum(sz)
    E0 = Csz - sz                               # exclusive entry offsets
    eoff_in_blk = E0 - E0[jnp.clip(bstart, 0, p - 1)]
    shared = jnp.where(is_rst, 0, lcp)
    nonshared = K - shared

    # --- compact blocks to the front ---
    border = jnp.argsort(~start, stable=True)
    bpos = border[:nbp]                          # block start positions
    nb = jnp.sum(start.astype(jnp.int32))
    bidx = jnp.arange(nbp, dtype=jnp.int32)
    bvalid = bidx < nb
    bnext = jnp.minimum(f_ext[jnp.clip(bpos, 0, p - 1)], m)
    bcnt = jnp.where(bvalid, bnext - bpos, 0)
    blast = jnp.clip(bpos + bcnt - 1, 0, p - 1)
    bentry_bytes = jnp.where(bvalid, Csz[blast] - E0[bpos], 0)
    bnr = jnp.where(bvalid, 1 + (jnp.maximum(bcnt, 1) - 1) // R, 0)
    bpayload = jnp.where(bvalid, bentry_bytes + 4 * bnr + 4, 0)
    bout = jnp.cumsum(bpayload) - bpayload       # block payload start
    total_payload = jnp.sum(bpayload)

    blk_id = jnp.clip(jnp.cumsum(start.astype(jnp.int32)) - 1, 0, nbp - 1)
    entry_global = bout[blk_id] + eoff_in_blk

    # --- emit records: [p, max_rec] byte matrix scattered once ---
    col = jnp.arange(max_rec, dtype=jnp.int32)[None, :]
    keyb = jnp.take_along_axis(
        ikey, jnp.clip(shared[:, None] + col - 3, 0, K - 1), axis=1
    )
    vpos = svoff[:, None] + (col - 3 - nonshared[:, None])
    valb = vflat[jnp.clip(vpos, 0, vflat.shape[0] - 1)]
    rec = jnp.where(
        col == 0, shared[:, None].astype(jnp.uint8),
        jnp.where(
            col == 1, nonshared[:, None].astype(jnp.uint8),
            jnp.where(
                col == 2, svlen[:, None].astype(jnp.uint8),
                jnp.where(col < 3 + nonshared[:, None], keyb, valb),
            ),
        ),
    )
    in_rec = col < sz[:, None]
    flat_idx = jnp.where(
        in_rec & svalid[:, None], entry_global[:, None] + col, jnp.int32(ubp)
    )
    out = jnp.zeros(ubp, dtype=jnp.uint8)
    out = out.at[flat_idx.reshape(-1)].set(rec.reshape(-1), mode="drop")

    # --- emit restart arrays: [nbp, (max_rwords+1)*4] scattered once ---
    max_rwords = B // (3 * R) + 2
    w = jnp.arange(max_rwords + 1, dtype=jnp.int32)[None, :]
    rpos = jnp.clip(bpos[:, None] + w * R, 0, p - 1)
    roffs = E0[rpos] - E0[jnp.clip(bpos, 0, p - 1)][:, None]
    word = jnp.where(w < bnr[:, None], roffs, bnr[:, None])
    wb = jnp.arange((max_rwords + 1) * 4, dtype=jnp.int32)[None, :]
    wsel = wb // 4
    wbyte = wb % 4
    wvals = jnp.take_along_axis(word, wsel, axis=1)
    rbytes = ((wvals >> (8 * wbyte)) & 0xFF).astype(jnp.uint8)
    in_arr = wsel <= bnr[:, None]
    rdst = jnp.where(
        in_arr & bvalid[:, None],
        (bout + bentry_bytes)[:, None] + wb, jnp.int32(ubp),
    )
    out = out.at[rdst.reshape(-1)].set(rbytes.reshape(-1), mode="drop")

    # --- block boundary rows + stats ---
    zbit = jnp.int32(1 << 30)
    bfirst = jnp.where(
        bvalid,
        i32(sorder[jnp.clip(bpos, 0, p - 1)])
        | jnp.where(szero[jnp.clip(bpos, 0, p - 1)], zbit, 0), -1,
    )
    blast_r = jnp.where(
        bvalid,
        i32(sorder[blast]) | jnp.where(szero[blast], zbit, 0), -1,
    )
    # Survivor bitmap over ORIGINAL local rows (1 bit/row): the host
    # derives `sel` from it to build the bloom filter byte-identically to
    # the CPU path (and blob refs) without downloading the full order.
    surv = jnp.zeros(p, dtype=jnp.int32).at[sorder].max(
        svalid.astype(jnp.int32))
    sbytes = (p + 7) // 8
    pad_rows = (-p) % 8
    if pad_rows:
        surv = jnp.pad(surv, (0, pad_rows))
    bits = surv.reshape(sbytes, 8)
    weights = (jnp.uint32(1) << jnp.arange(8, dtype=jnp.uint32))[None, :]
    surv_bitmap = jnp.sum(
        bits.astype(jnp.uint32) * weights, axis=1).astype(jnp.uint8)

    num_del = jnp.sum(
        (svalid & ((svt == int(ValueType.DELETION))
                   | (svt == int(ValueType.SINGLE_DELETION)))
         ).astype(jnp.int32)
    )
    raw_value = jnp.sum(svlen)
    seq_hi = jnp.where(svalid, sp_hi >> 8, u32(0xFFFFFFFF))
    seq_lo = jnp.where(svalid, (sp_lo >> 8) | (sp_hi << 24), u32(0xFFFFFFFF))
    smin_hi = jnp.min(seq_hi)
    smin_lo = jnp.min(jnp.where(seq_hi == smin_hi, seq_lo, u32(0xFFFFFFFF)))
    seq_hi_mx = jnp.where(svalid, sp_hi >> 8, u32(0))
    seq_lo_mx = jnp.where(svalid, (sp_lo >> 8) | (sp_hi << 24), u32(0))
    smax_hi = jnp.max(seq_hi_mx)
    smax_lo = jnp.max(jnp.where(seq_hi_mx == smax_hi, seq_lo_mx, u32(0)))
    meta = jnp.stack([
        nb, m, total_payload,
        jnp.any(core["host_resolve"]).astype(jnp.int32),
        num_del, raw_value,
        i32(smin_hi), i32(smin_lo), i32(smax_hi), i32(smax_lo),
    ])
    return out, meta, bcnt, bpayload, bfirst, blast_r, surv_bitmap


def assembly_supported(table_options, kv, shards, any_complex,
                       max_output_file_size, vtypes) -> bool:
    """Gate for the on-device block-assembly path. Off unless
    TPULSM_DEVICE_BLOCKS=1 (transfers roughly double vs the order
    download, so it is a win only on PCIe-class links). `vtypes`: the
    caller's already-decoded per-row trailer types."""
    from toplingdb_tpu.table import format as fmt

    if os.environ.get("TPULSM_DEVICE_BLOCKS") != "1":
        return False
    if shards is None or len(shards) != 1 or any_complex:
        return False
    if getattr(table_options, "format", "block") != "block":
        return False
    if table_options.compression != fmt.NO_COMPRESSION:
        return False
    if table_options.filter_policy is not None and (
            not table_options.whole_key_filtering
            or getattr(table_options, "prefix_extractor", None) is not None):
        # Prefix filter keys only exist on the per-entry path; building a
        # whole-key-only bloom here would break byte parity.
        return False
    if not kv.n:
        return False
    K = int(kv.key_lens[0])
    if not (0 < K < 128):
        return False
    if int(kv.val_lens.max()) >= 128:
        return False
    # Single output file only (the block layout must match the unsplit
    # build): a generous 2x margin over the raw estimate covers block
    # trailers/restart/index overhead even at tiny block sizes.
    est = int(kv.key_lens.sum()) + int(kv.val_lens.sum()) + 8 * kv.n
    if est * 2 + 65536 >= max_output_file_size or est >= 2 ** 30:
        return False
    if bool(np.any(vtypes == int(ValueType.BLOB_INDEX))):
        return False
    if table_options.block_size < 64 or table_options.restart_interval < 1:
        return False
    return True


def run_block_assembly(env, dbname, icmp, kv, shard, cover, snapshots,
                       bottommost, table_options, new_file_number,
                       creation_time, tombs, column_family=(0, "default")):
    """Drive the device block-assembly program for a single-shard job and
    write the output SST (host: block trailers + index/meta/footer).
    Returns the same (fnum, path, props, smallest, largest, sel) tuples as
    write_tables_columnar; `sel` (from the downloaded survivor bitmap) is
    materialized only when a whole-key bloom must build from it."""
    from toplingdb_tpu import native
    from toplingdb_tpu.ops.columnar_io import _ColumnarSST
    from toplingdb_tpu.ops.device_compaction import _ranges_lmap
    from toplingdb_tpu.utils import crc32c

    if len(snapshots) > ck.MAX_SNAPSHOTS:
        raise NotSupported(
            f"device GC supports <= {ck.MAX_SNAPSHOTS} live snapshots"
        )
    chunks, ranges = shard
    covers_s = None if cover is None else [cover[lo:hi] for lo, hi in ranges]
    h = ck.upload_uniform_shard(chunks, covers_s)
    uk_len = h["uk_len"]
    K = uk_len + 8
    p = int(h["pkb"].shape[0])

    # Values: per-row lengths + dense bytes, in the same local row order.
    vlens = np.zeros(p, dtype=np.uint32)
    vparts = []
    pos = 0
    for lo, hi in ranges:
        vlens[pos:pos + (hi - lo)] = kv.val_lens[lo:hi]
        b0 = int(kv.val_offs[lo])
        b1 = int(kv.val_offs[hi - 1]) + int(kv.val_lens[hi - 1])
        vparts.append(kv.val_buf[b0:b1])
        pos += hi - lo
    vflat = np.concatenate(vparts) if vparts else np.zeros(0, np.uint8)
    vbp = ck._next_pow2(max(8, len(vflat)))
    vf = np.zeros(vbp, dtype=np.uint8)
    vf[: len(vflat)] = vflat

    R = int(table_options.restart_interval)
    B = int(table_options.block_size)
    max_vlen = int(kv.val_lens.max()) if kv.n else 0
    max_rec = 3 + K + max_vlen
    ub0 = int((3 + K) * p + int(vlens.sum()))
    nb_ub = ub0 // B + 2
    ub0 += 4 * (p // R + nb_ub + 2) + 4 * nb_ub
    ubp = ck._next_pow2(ub0)
    nbp = ck._next_pow2(nb_ub)

    snap_hi, snap_lo = ck._split_snapshots(snapshots)
    has_tombs = h["tomb_hi"] is not None
    t_hi = h["tomb_hi"] if has_tombs else np.zeros(1, dtype=np.uint32)
    t_lo = h["tomb_lo"] if has_tombs else np.zeros(1, dtype=np.uint32)
    front_code = "plens" in h
    dummy = np.zeros(1, dtype=np.uint8)
    w = (max(uk_len, 4) + 3) // 4
    (out, meta, bcnt, bpayload, bfirst, blast,
     surv_bitmap) = _assemble_blocks_impl(
        h.get("ukb", dummy), h.get("plens", dummy), h.get("sfx", dummy),
        h["pkb"], h["starts"], h["min_his"], h["min_los"],
        jax.device_put(vlens), jax.device_put(vf), t_hi, t_lo,
        snap_hi, snap_lo, np.int32(h["total"]), w, uk_len,
        bool(bottommost), has_tombs, front_code, R, B, max_rec, ubp, nbp,
    )
    for a in (meta, bcnt, bpayload, bfirst, blast, surv_bitmap):
        if hasattr(a, "copy_to_host_async"):
            a.copy_to_host_async()
    meta = np.asarray(meta)
    nb, mtot, total_payload, has_complex = (
        int(meta[0]), int(meta[1]), int(meta[2]), bool(meta[3]))
    if has_complex:
        raise NotSupported("complex groups reached block assembly")
    if nb > nbp or total_payload > ubp:
        # The static block/byte budgets were undersized for this shape
        # (belt and braces: the emission scatter drops out-of-range
        # writes, so nothing corrupt was produced — just fall back).
        raise NotSupported("block assembly budgets exceeded")
    bcnt = np.asarray(bcnt)[:nb]
    bpayload = np.asarray(bpayload)[:nb]
    bfirst = np.asarray(bfirst)[:nb]
    blast = np.asarray(blast)[:nb]
    # Download the payload in ~8 MiB sections cut at block boundaries,
    # with every section's D2H copy enqueued up front: the host frames
    # (crc + index bookkeeping) section k while sections k+1.. are still
    # streaming back, instead of blocking on one monolithic download.
    bends = np.cumsum(bpayload, dtype=np.int64) if nb else \
        np.zeros(0, np.int64)
    sections = []  # (blk_lo, blk_hi, base_off, device_slice)
    blk_lo = 0
    base_off = 0
    for b in range(nb):
        if int(bends[b]) - base_off >= (8 << 20) or b == nb - 1:
            dev = out[base_off:int(bends[b])]
            if hasattr(dev, "copy_to_host_async"):
                dev.copy_to_host_async()
            sections.append((blk_lo, b + 1, base_off, dev))
            blk_lo = b + 1
            base_off = int(bends[b])

    lmap = _ranges_lmap(ranges)
    want_bloom = (table_options.filter_policy is not None
                  and table_options.whole_key_filtering)
    if want_bloom:
        surv = np.unpackbits(np.asarray(surv_bitmap),
                             bitorder="little")[: len(lmap)]
        sel = lmap[np.flatnonzero(surv)]
    else:
        sel = np.empty(0, dtype=np.int64)  # nothing consumes it

    def boundary_ikey(enc: int) -> bytes:
        row = int(lmap[enc & ((1 << 30) - 1)])
        zero = bool(enc & (1 << 30))
        ik = kv.ikey(row)
        if zero:
            t = int(ik[-8]) & 0xFF  # vtype byte survives in a zeroed trailer
            ik = ik[:-8] + t.to_bytes(8, "little")
        return ik

    lib = native.lib()
    fnum = new_file_number()
    sst = _ColumnarSST(env, dbname, fnum, icmp, table_options, creation_time,
                       column_family)
    try:
        # Frame blocks: payload + type(0) + masked crc32c, one framed run
        # per downloaded section (consumed as its copy completes).
        for s_lo, s_hi, s_base, dev in sections:
            chunk = np.asarray(dev)  # blocks on THIS section's copy only
            section = bytearray()
            blocks = []
            off = 0
            for b in range(s_lo, s_hi):
                pl = int(bpayload[b])
                raw = chunk[off:off + pl].tobytes()
                off += pl
                crc = crc32c.mask(crc32c.extend(0, raw + b"\x00"))
                section += raw + b"\x00" + crc.to_bytes(4, "little")
                blocks.append((pl, pl, boundary_ikey(int(bfirst[b])),
                               boundary_ikey(int(blast[b])), int(bcnt[b])))
            sst.add_framed_section(bytes(section), blocks)
        pre = {
            "num_entries": mtot,
            "raw_key_size": mtot * K,
            "raw_value_size": int(meta[5]),
            "num_deletions": int(meta[4]),
            "num_merge_operands": 0,
            "smallest_seqno": ((int(np.uint32(meta[6])) << 32)
                               | int(np.uint32(meta[7]))) if mtot else 0,
            "largest_seqno": ((int(np.uint32(meta[8])) << 32)
                              | int(np.uint32(meta[9]))) if mtot else 0,
        }
        props, smallest, largest = sst.finish(
            lib, kv, sel, None, None, tombs, precomputed=pre,
        )
        return [(fnum, sst.path, props, smallest, largest, sel)]
    except BaseException:
        try:
            sst.w.close()
            env.delete_file(sst.path)
        except Exception as e:
            _errors.swallow(reason="sst-abort-cleanup", exc=e)
        raise
