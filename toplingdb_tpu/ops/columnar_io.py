"""Columnar SST IO: native bulk decode and native table building.

The host-side halves of the TPU compaction pipeline that the profile showed
dominating (SURVEY.md §7 step 5 "host↔device streaming"): whole-file scans
into flat buffers via the C++ block decoder, and output building via the C++
block builder + bloom fill — no per-entry Python. File framing (compression,
trailers, index/filter/props/metaindex/footer) reuses the same Python pieces
as TableBuilder, and write_tables_columnar replicates build_outputs' output
cutting (user-key boundary after max_output_file_size) exactly, so outputs
are byte-identical to the per-entry path for single- AND multi-output jobs;
tests/test_columnar_writer.py asserts it.
"""

from __future__ import annotations

import time

import numpy as np

from toplingdb_tpu import native
from toplingdb_tpu.db import dbformat
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.block import BlockBuilder, BlockIter
from toplingdb_tpu.table.builder import (
    METAINDEX_COMPRESSION_DICT,
    METAINDEX_FILTER,
    METAINDEX_PROPERTIES,
    METAINDEX_RANGE_DEL,
    CompressionOptions,
)
from toplingdb_tpu.table.properties import TableProperties
from toplingdb_tpu.utils.status import Corruption, NotSupported
from toplingdb_tpu.utils import errors as _errors


# Soft per-native-call output budget for the bulk block builder: bounds the
# section buffer and the transient Python copy on arbitrarily large jobs.
_SECTION_RUN_BYTES = 8 << 20


class ColumnarKV:
    """Flat-buffer view of N (internal_key, value) entries."""

    __slots__ = ("key_buf", "key_offs", "key_lens", "val_buf", "val_offs",
                 "val_lens", "n")

    def __init__(self, key_buf, key_offs, key_lens, val_buf, val_offs, val_lens):
        self.key_buf = key_buf
        self.key_offs = key_offs
        self.key_lens = key_lens
        self.val_buf = val_buf
        self.val_offs = val_offs
        self.val_lens = val_lens
        self.n = len(key_offs)

    def ikey(self, i: int) -> bytes:
        o = self.key_offs[i]
        return self.key_buf[o : o + self.key_lens[i]].tobytes()

    def value(self, i: int) -> bytes:
        o = self.val_offs[i]
        return self.val_buf[o : o + self.val_lens[i]].tobytes()

    def to_entries(self) -> list[tuple[bytes, bytes]]:
        return [(self.ikey(i), self.value(i)) for i in range(self.n)]

    @staticmethod
    def concat(parts: list["ColumnarKV"]) -> "ColumnarKV":
        if len(parts) == 1:
            return parts[0]
        key_buf = np.concatenate([p.key_buf for p in parts])
        val_buf = np.concatenate([p.val_buf for p in parts])
        ko, vo = [], []
        k_shift = 0
        v_shift = 0
        for p in parts:
            ko.append(p.key_offs + k_shift)
            vo.append(p.val_offs + v_shift)
            k_shift += len(p.key_buf)
            v_shift += len(p.val_buf)
        return ColumnarKV(
            key_buf, np.concatenate(ko),
            np.concatenate([p.key_lens for p in parts]),
            val_buf, np.concatenate(vo),
            np.concatenate([p.val_lens for p in parts]),
        )


def _file_scan_prologue(reader):
    """Shared per-file scan setup: the whole raw file image plus the data
    block handles as arrays and objects — (raw, block_offs, block_lens,
    handles), or (None, None, None, []) for an empty file."""
    idx = reader.new_index_iterator()  # flat or partitioned
    idx.seek_to_first()
    handles = [
        fmt.BlockHandle.decode_exact(enc) for _, enc in idx.entries()
    ]
    if not handles:
        return None, None, None, []
    raw = reader._f.read(0, reader._f.size())
    block_offs = np.array([h.offset for h in handles], dtype=np.int64)
    block_lens = np.array([h.size for h in handles], dtype=np.int64)
    return raw, block_offs, block_lens, handles


def scan_tables_columnar_prealloc(readers):
    """Scan EVERY input file into ONE preallocated pair of columnar
    buffers, sized exactly from each file's TableProperties
    (raw_key_size/raw_value_size/num_entries) — the fused native call
    inflates + decodes per block with absolute offsets, so there is no
    synthetic image, no per-file Python copies, and NO ColumnarKV.concat
    (the r04 known debt: ~0.3-0.5s of pure copy at 10M entries).

    Returns (kv, parts) where kv spans all files and parts[i] is a
    ZERO-COPY per-file view (buffer slices + rebased offsets) with the
    layout the shard/cover helpers expect — or None when ineligible
    (native/symbol missing, props absent or wrong, exotic codec, >int32
    buffers); the caller then uses the per-file scan + concat path."""
    lib = native.lib()
    if lib is None or not hasattr(lib, "tpulsm_scan_blocks"):
        return None
    infos = []
    tk = tv = tn = 0
    for r in readers:
        if not hasattr(r, "new_index_iterator"):
            return None
        if getattr(r, "_compression_dict", b""):
            # Dict-compressed frames need the stored dictionary; the
            # native scan decodes without one (would mis-report healthy
            # files as corrupt) — the per-file path carries the dict.
            return None
        p = getattr(r, "properties", None)
        if p is None:
            return None
        ne, rk, rv = int(p.num_entries), int(p.raw_key_size), int(
            p.raw_value_size)
        if ne < 0 or rk < 0 or rv < 0 or (ne > 0 and rk == 0):
            return None
        infos.append((ne, rk, rv))
        tk += rk
        tv += rv
        tn += ne
    if tk > 0x7FFFFF00 or tv > 0x7FFFFF00:
        return None
    key_buf = np.empty(tk, dtype=np.uint8)
    val_buf = np.empty(tv, dtype=np.uint8)
    key_offs = np.empty(tn, dtype=np.int32)
    key_lens = np.empty(tn, dtype=np.int32)
    val_offs = np.empty(tn, dtype=np.int32)
    val_lens = np.empty(tn, dtype=np.int32)

    bases = []
    kb = vb = nb = 0
    for ne, rk, rv in infos:
        bases.append((nb, kb, vb))
        nb += ne
        kb += rk
        vb += rv

    import ctypes as _ct

    def scan_one(i):
        r = readers[i]
        ne, rk, rv = infos[i]
        if ne == 0:
            return 0
        n_base, k_base, v_base = bases[i]
        raw, b_offs, b_lens, _handles = _file_scan_prologue(r)
        if raw is None:
            return -100
        rawb = np.frombuffer(raw, dtype=np.uint8) \
            if not isinstance(raw, np.ndarray) else raw
        rc = lib.tpulsm_scan_blocks(
            native.np_u8p(rawb), len(rawb),
            native.np_i64p(b_offs), native.np_i64p(b_lens), len(b_offs),
            1 if r.opts.verify_checksums else 0,
            _ct.cast(key_buf.ctypes.data + k_base,
                     _ct.POINTER(_ct.c_uint8)), rk,
            _ct.cast(val_buf.ctypes.data + v_base,
                     _ct.POINTER(_ct.c_uint8)), rv,
            _ct.cast(key_offs.ctypes.data + 4 * n_base,
                     _ct.POINTER(_ct.c_int32)),
            _ct.cast(key_lens.ctypes.data + 4 * n_base,
                     _ct.POINTER(_ct.c_int32)),
            _ct.cast(val_offs.ctypes.data + 4 * n_base,
                     _ct.POINTER(_ct.c_int32)),
            _ct.cast(val_lens.ctypes.data + 4 * n_base,
                     _ct.POINTER(_ct.c_int32)),
            ne, k_base, v_base,
        )
        return rc

    if len(readers) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(min(8, len(readers))) as ex:
            rcs = list(ex.map(scan_one, range(len(readers))))
    else:
        rcs = [scan_one(0)]
    for i, rc in enumerate(rcs):
        if rc == -6:
            raise Corruption("block checksum mismatch (fused scan)")
        if rc == -8:
            raise Corruption("block decode/decompress failed (fused scan)")
        if rc != infos[i][0]:
            # Capacity/entry-count disagreement with the properties, codec
            # fallback, or a dict frame: use the compatible path.
            return None
    kv = ColumnarKV(key_buf, key_offs, key_lens, val_buf, val_offs, val_lens)
    parts = []
    for i, (ne, rk, rv) in enumerate(infos):
        n_base, k_base, v_base = bases[i]
        parts.append(ColumnarKV(
            key_buf[k_base:k_base + rk],
            key_offs[n_base:n_base + ne] - np.int32(k_base),
            key_lens[n_base:n_base + ne],
            val_buf[v_base:v_base + rv],
            val_offs[n_base:n_base + ne] - np.int32(v_base),
            val_lens[n_base:n_base + ne],
        ))
    return kv, parts


def scan_table_columnar(reader, ref_values: bool = True) -> ColumnarKV:
    """Whole-file bulk scan through the native block decoder. Uncompressed
    files decode in ONE native call over the raw file bytes — values
    REFERENCED into the file image (tpulsm_scan_blocks_refvals: the image
    stays alive as val_buf, saving the per-entry value memcpy), keys
    copied; compressed files fall back to per-block decompression +
    decode. `ref_values=False` forces the value-copying twin (parity
    tests)."""
    lib = native.lib()
    if lib is None:
        raise NotSupported("native library unavailable")
    if not hasattr(reader, "new_index_iterator"):
        raise NotSupported("bulk columnar scan requires the block format")
    raw, block_offs, block_lens, handles = _file_scan_prologue(reader)
    if raw is None:
        return ColumnarKV(
            np.zeros(0, np.uint8), np.zeros(0, np.int32), np.zeros(0, np.int32),
            np.zeros(0, np.uint8), np.zeros(0, np.int32), np.zeros(0, np.int32),
        )

    if ref_values:
        kv = _refvals_decode(lib, raw, block_offs, block_lens,
                             reader.opts.verify_checksums)
        if kv is not None:
            return kv

    # Bulk path: all blocks in one native call over the raw image.
    kv = _bulk_decode(lib, raw, block_offs, block_lens,
                      reader.opts.verify_checksums)
    if kv is not None:
        return kv

    # Compressed file. Fast path: ONE native call inflates every block in
    # parallel (snappy/zstd dlopen'd in C++) into a synthetic uncompressed
    # image, then the same single-call bulk decode as above — zero
    # per-block Python. Dictionary-compressed and exotic codecs fall to
    # the threaded Python inflate below.
    cdict = getattr(reader, "_compression_dict", b"") or b""
    verify = reader.opts.verify_checksums
    if not cdict and hasattr(lib, "tpulsm_inflate_blocks"):
        rawb = np.frombuffer(bytes(raw), dtype=np.uint8)
        out_cap = 4 * int(block_lens.sum()) + 5 * len(handles) + 4096
        out_offs = np.empty(len(handles), dtype=np.int64)
        out_lens = np.empty(len(handles), dtype=np.int64)
        for _ in range(4):
            out = np.empty(out_cap, dtype=np.uint8)
            rc = lib.tpulsm_inflate_blocks(
                native.np_u8p(rawb), len(rawb),
                native.np_i64p(block_offs), native.np_i64p(block_lens),
                len(handles), 1 if verify else 0,
                native.np_u8p(out), out_cap,
                native.np_i64p(out_offs), native.np_i64p(out_lens),
            )
            if rc == -2:
                out_cap *= 4
                continue
            break
        if rc == -6:
            raise Corruption("block checksum mismatch (native inflate)")
        if rc == -3:
            raise Corruption("block decompression failed (native inflate)")
        if rc > 0 or (rc == 0 and not handles):
            kv = _bulk_decode(lib, out[: int(rc)], out_offs,
                              out_lens, False)
            if kv is not None:
                return kv
        # rc == -1: codec unavailable/dict frame — Python fallback below.
    mv = memoryview(raw)

    def _inflate(handle):
        end = handle.offset + handle.size
        payload = bytes(mv[handle.offset: end])
        ctype = raw[end]
        if verify:
            from toplingdb_tpu.utils import crc32c as _crc

            stored = _crc.unmask(int.from_bytes(raw[end + 1: end + 5],
                                                "little"))
            if stored != _crc.value(payload + bytes([ctype])):
                raise Corruption(
                    f"block checksum mismatch at {handle.offset}")
        return fmt.decompress(payload, ctype, cdict)

    if len(handles) > 8:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(8) as ex:
            blocks = list(ex.map(_inflate, handles))
    else:
        blocks = [_inflate(h) for h in handles]
    trailer = b"\x00" * 5  # type=NO_COMPRESSION + dummy CRC (verify off)
    synth = trailer.join(blocks) + trailer if blocks else b""
    lens = np.array([len(b) for b in blocks], dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(lens + 5)[:-1]]).astype(np.int64) \
        if blocks else np.zeros(0, np.int64)
    kv = _bulk_decode(lib, synth, offs, lens, False)
    if kv is None:
        raise Corruption("decompressed blocks failed native bulk decode")
    return kv


def _refvals_decode(lib, raw, block_offs, block_lens, verify):
    """Values-referenced whole-file scan (tpulsm_scan_blocks_refvals): keys
    decode into a dense buffer; val_offs point INTO the raw file image,
    which becomes val_buf zero-copy. Returns None when ineligible (symbol
    missing, compressed blocks, int32 budget, long keys) — the caller then
    uses the value-copying path, which is also the authority on whether a
    block is actually corrupt."""
    if not hasattr(lib, "tpulsm_scan_blocks_refvals"):
        return None
    rawb = np.frombuffer(bytes(raw), dtype=np.uint8) \
        if not isinstance(raw, np.ndarray) else raw
    file_size = len(rawb)
    if file_size > 0x7FFFFF00:
        return None  # val offsets must fit the int32 columnar budget
    data_bytes = int(block_lens.sum())
    key_cap = 4 * data_bytes + 4096
    max_e = data_bytes // 3 + 64
    while True:
        key_out = np.empty(key_cap, dtype=np.uint8)
        key_offs = np.empty(max_e, dtype=np.int32)
        key_lens = np.empty(max_e, dtype=np.int32)
        val_offs = np.empty(max_e, dtype=np.int32)
        val_lens = np.empty(max_e, dtype=np.int32)
        rc = lib.tpulsm_scan_blocks_refvals(
            native.np_u8p(rawb), file_size,
            native.np_i64p(block_offs), native.np_i64p(block_lens),
            len(block_offs), 1 if verify else 0,
            native.np_u8p(key_out), key_cap,
            native.np_i32p(key_offs), native.np_i32p(key_lens),
            native.np_i32p(val_offs), native.np_i32p(val_lens), max_e,
            0, 0,
        )
        if rc == -2:
            key_cap *= 4
            continue
        if rc == -4:
            max_e *= 4
            continue
        if rc == -6:
            raise Corruption("block checksum mismatch (refvals scan)")
        if rc < 0:
            # -5 compressed, -7 offset budget, -8 long-key/corrupt: let the
            # copying path decide (it supports what this one doesn't and
            # raises the proper error for real corruption).
            return None
        n = int(rc)
        key_used = int(key_offs[n - 1] + key_lens[n - 1]) if n else 0
        return ColumnarKV(
            key_out[:key_used].copy(), key_offs[:n].copy(),
            key_lens[:n].copy(),
            rawb, val_offs[:n].copy(), val_lens[:n].copy(),
        )


def _bulk_decode(lib, raw, block_offs, block_lens, verify):
    """One native call decoding every (uncompressed) block of a file image
    into a dense ColumnarKV. Returns None when a block is compressed (the
    caller inflates and retries over a synthetic image)."""
    file_size = len(raw)
    praw = raw.tobytes() if isinstance(raw, np.ndarray) else bytes(raw)
    data_bytes = int(block_lens.sum())
    key_cap = 4 * data_bytes + 4096
    val_cap = data_bytes + 4096
    max_e = data_bytes // 3 + 64
    while True:
        key_out = np.empty(key_cap, dtype=np.uint8)
        val_out = np.empty(val_cap, dtype=np.uint8)
        key_offs = np.empty(max_e, dtype=np.int32)
        key_lens = np.empty(max_e, dtype=np.int32)
        val_offs = np.empty(max_e, dtype=np.int32)
        val_lens = np.empty(max_e, dtype=np.int32)
        rc = lib.tpulsm_decode_blocks(
            praw, file_size,
            native.np_i64p(block_offs), native.np_i64p(block_lens),
            len(block_offs), 1 if verify else 0,
            native.np_u8p(key_out), key_cap,
            native.np_u8p(val_out), val_cap,
            native.np_i32p(key_offs), native.np_i32p(key_lens),
            native.np_i32p(val_offs), native.np_i32p(val_lens), max_e,
        )
        if rc == -2:
            key_cap *= 4
            continue
        if rc == -3:
            val_cap *= 4
            continue
        if rc == -4:
            max_e *= 4
            continue
        if rc == -5:
            return None  # compressed blocks present
        if rc == -6:
            raise Corruption("block checksum mismatch (native bulk scan)")
        if rc == -7:
            raise NotSupported("input too large for native columnar path")
        if rc < 0:
            raise Corruption(f"native bulk decode failed rc={rc}")
        n = int(rc)
        key_used = int(key_offs[n - 1] + key_lens[n - 1]) if n else 0
        val_used = int(val_offs[n - 1] + val_lens[n - 1]) if n else 0
        return ColumnarKV(
            key_out[:key_used].copy(), key_offs[:n].copy(), key_lens[:n].copy(),
            val_out[:val_used].copy(), val_offs[:n].copy(), val_lens[:n].copy(),
        )


class _ColumnarSST:
    """Framing state for ONE output file of the columnar writer (index,
    props, meta blocks, footer) — the TableBuilder-equivalent file shell."""

    def __init__(self, env, dbname, fnum, icmp, options, creation_time,
                 column_family=(0, "default"), pool=None):
        from toplingdb_tpu.db import filename as _fn

        self.fnum = fnum
        self.path = _fn.table_file_name(dbname, fnum)
        self.w = env.new_writable_file(self.path)
        self._icmp = icmp
        self._options = options
        # Compressed output: blocks compress on `pool` threads (the codecs
        # release the GIL) and write in order; ZSTD dictionary training
        # buffers the first train_budget() of raw blocks, as in
        # TableBuilder (reference parallel compression + dict,
        # block_based_table_builder.cc:818-825, util/compression.h:1435).
        self._pool = pool
        self._copts = getattr(options, "compression_opts", None) \
            or CompressionOptions()
        self._dict: bytes | None = (
            b"" if (options.compression == fmt.ZSTD_COMPRESSION
                    and self._copts.max_dict_bytes > 0) else None
        )
        self._dict_samples: list = []
        self._dict_bytes = 0
        self._pending: list = []  # (future|tuple, raw_len, first, last, n)
        self.index_block = BlockBuilder(options.index_restart_interval)
        self.props = TableProperties(
            comparator_name=icmp.user_comparator.name(),
            filter_policy_name=(
                options.filter_policy.name() if options.filter_policy else ""
            ),
            compression_name=str(options.compression),
            column_family_id=column_family[0],
            column_family_name=column_family[1],
            creation_time=creation_time,
            smallest_seqno=dbformat.MAX_SEQUENCE_NUMBER,
        )
        self.pending_last_key: bytes | None = None
        self.pending_handle = None
        self.first_key: bytes | None = None
        self.last_key: bytes | None = None
        self.num_entries = 0

    def _account_block(self, handle, raw_len: int, block_first: bytes,
                       block_last: bytes, n_entries: int) -> None:
        """Index/props bookkeeping shared by the per-block and bulk paths —
        one implementation so the two can't diverge byte-wise."""
        if self.first_key is None:
            self.first_key = block_first
        if self.pending_last_key is not None:
            sep = self._icmp.find_shortest_separator(
                self.pending_last_key, block_first
            )
            self.index_block.add(sep, self.pending_handle.encode())
        self.pending_handle = handle
        self.pending_last_key = block_last
        self.props.data_size += raw_len
        self.props.num_data_blocks += 1
        self.last_key = block_last
        self.num_entries += n_entries

    def pending_bytes(self) -> int:
        """Raw bytes buffered for dict training / in the compress queue —
        counted into the output-cut size check so it can't lag."""
        return self._dict_bytes + sum(p[1] for p in self._pending)

    def add_block(self, raw: bytes, block_first: bytes, block_last: bytes,
                  n_entries: int) -> None:
        if self._dict == b"":
            self._dict_samples.append((raw, block_first, block_last,
                                       n_entries))
            self._dict_bytes += len(raw)
            if self._dict_bytes >= self._copts.train_budget():
                self._train_dict_and_flush()
            return
        if self._pool is not None \
                and self._options.compression != fmt.NO_COMPRESSION:
            fut = self._pool.submit(
                fmt.compress_for_block, raw, self._options.compression,
                self._copts.level, self._dict or b"",
            )
            self._pending.append((fut, len(raw), block_first, block_last,
                                  n_entries))
            self._drain(wait=False)
            return
        handle = fmt.write_block(self.w, raw, self._options.compression,
                                 self._copts.level, self._dict or b"")
        self._account_block(handle, len(raw), block_first, block_last,
                            n_entries)

    def _train_dict_and_flush(self) -> None:
        from toplingdb_tpu.utils import codecs

        self._dict = codecs.zstd_train_dictionary(
            [r for r, _f, _l, _n in self._dict_samples],
            self._copts.max_dict_bytes,
        )
        if self._dict == b"":
            # Training failed (ZDICT needs enough distinct samples). b"" is
            # the 'training pending' sentinel, so leaving it would make the
            # replay below re-buffer forever; disable the dict instead.
            self._dict = None
        samples, self._dict_samples, self._dict_bytes = \
            self._dict_samples, [], 0
        for raw, first, last, n in samples:
            self.add_block(raw, first, last, n)

    def _drain(self, wait: bool) -> None:
        while self._pending and (wait or self._pending[0][0].done()):
            fut, raw_len, first, last, n = self._pending.pop(0)
            payload, out_type = fut.result()
            h = fmt.write_compressed_block(self.w, payload, out_type)
            self._account_block(h, raw_len, first, last, n)

    def add_framed_section_arrays(self, section, counts, plens, rawlens,
                                  nb: int, start_pos: int,
                                  entry_key_fn) -> None:
        """Bulk form of add_framed_section that DEFERS index building to
        one native call at finish: per-block metadata is kept as numpy
        arrays (no per-block Python at all); only the file's first/last
        keys are materialized here (two entry_key calls per section)."""
        base = self.w.file_size()
        if self.first_key is None:
            self.first_key = entry_key_fn(start_pos)
        cnts = counts[:nb].astype(np.int64, copy=True)
        pls = plens[:nb].astype(np.int64, copy=True)
        if not hasattr(self, "_nat_sections"):
            self._nat_sections = []
        self._nat_sections.append((start_pos, cnts, pls, base))
        self.props.data_size += int(rawlens[:nb].sum())
        self.props.num_data_blocks += nb
        total = int(cnts.sum())
        self.num_entries += total
        self.last_key = entry_key_fn(start_pos + total - 1)
        self.w.append(section)

    def _native_index_raw(self, lib, kv, order, trailer_override) -> bytes:
        """Build this file's whole index block in one native call from the
        deferred section metadata (tpulsm_build_index_block)."""
        pos_parts, cnt_parts, off_parts, plen_parts = [], [], [], []
        for start_pos, cnts, pls, base in self._nat_sections:
            cum = np.concatenate(([0], np.cumsum(cnts)[:-1]))
            pos_parts.append(start_pos + cum)
            cnt_parts.append(cnts)
            offcum = np.concatenate(
                ([0], np.cumsum(pls + fmt.BLOCK_TRAILER_SIZE)[:-1]))
            off_parts.append(base + offcum)
            plen_parts.append(pls)
        bpos = np.ascontiguousarray(np.concatenate(pos_parts))
        bcnt = np.ascontiguousarray(np.concatenate(cnt_parts))
        boff = np.ascontiguousarray(np.concatenate(off_parts))
        bpl = np.ascontiguousarray(np.concatenate(plen_parts))
        nb = len(bpos)
        cap = 64 * nb + 8192
        out_len = np.zeros(1, dtype=np.int64)
        while True:
            out = np.empty(cap, dtype=np.uint8)
            rc = lib.tpulsm_build_index_block(
                native.np_u8p(kv.key_buf), native.np_i32p(kv.key_offs),
                native.np_i32p(kv.key_lens), native.np_i64p(trailer_override),
                native.np_i32p(order),
                native.np_i64p(bpos), native.np_i64p(bcnt),
                native.np_i64p(boff), native.np_i64p(bpl),
                nb, self._options.index_restart_interval,
                native.np_u8p(out), cap, native.np_i64p(out_len),
            )
            if rc == -2:
                cap *= 4
                continue
            if rc != nb:
                raise NotSupported(f"native index build failed rc={rc}")
            return out[: int(out_len[0])].tobytes()

    def add_framed_section(self, section: bytes, blocks) -> None:
        """Bulk form of add_block: `section` is a pre-framed run of blocks
        (payload + type byte + crc trailer, exactly what write_block emits;
        payloads may be compressed) and `blocks` yields
        (payload_len, raw_len, first_key, last_key, n_entries) per block in
        file order. One append for the whole run."""
        offset = self.w.file_size()
        for payload_len, raw_len, block_first, block_last, n_entries \
                in blocks:
            self._account_block(fmt.BlockHandle(offset, payload_len),
                                raw_len, block_first, block_last,
                                n_entries)
            offset += payload_len + fmt.BLOCK_TRAILER_SIZE
        self.w.append(section)

    def finish(self, lib, kv, sel, vtypes, seqs, tombstones,
               precomputed=None):
        """Write meta blocks + footer; `sel` = the original-index selection
        of this file's entries (stats/bloom are vectorized over it).
        `precomputed`: entry stats already reduced elsewhere (the on-device
        block-assembly path; its sel comes from a survivor bitmap and only
        feeds the bloom build below) — a dict with
        num_entries/raw_key_size/raw_value_size/num_deletions/
        num_merge_operands/smallest_seqno/largest_seqno."""
        if self._dict == b"":
            self._train_dict_and_flush()  # small file: train from the lot
        self._drain(wait=True)
        icmp = self._icmp
        options = self._options
        props = self.props
        n = len(sel)
        nat_sections = getattr(self, "_nat_sections", None)
        if nat_sections and self.pending_last_key is not None:
            # Per-block and deferred-index entries would interleave out of
            # order; this cannot happen on the section path — refuse.
            raise NotSupported("mixed index modes in one output file")
        if self.pending_last_key is not None:
            succ = icmp.find_short_successor(self.pending_last_key)
            self.index_block.add(succ, self.pending_handle.encode())
        if precomputed is not None:
            props.num_entries = precomputed["num_entries"]
            props.raw_key_size = precomputed["raw_key_size"]
            props.raw_value_size = precomputed["raw_value_size"]
            props.num_deletions = precomputed["num_deletions"]
            props.num_merge_operands = precomputed["num_merge_operands"]
            props.smallest_seqno = precomputed["smallest_seqno"]
            props.largest_seqno = precomputed["largest_seqno"]
            # stats come precomputed; the bloom (below) still builds from
            # `sel` when the caller materialized one (order-insensitive).
        else:
            props.num_entries = n
            props.raw_key_size = int(kv.key_lens[sel].sum()) if n else 0
            props.raw_value_size = int(kv.val_lens[sel].sum()) if n else 0
            vt = vtypes[sel] if n else vtypes[:0]
            props.num_deletions = int(np.count_nonzero(
                (vt == int(dbformat.ValueType.DELETION))
                | (vt == int(dbformat.ValueType.SINGLE_DELETION))
            ))
            props.num_merge_operands = int(np.count_nonzero(
                vt == int(dbformat.ValueType.MERGE)
            ))
            sq = seqs[sel] if n else seqs[:0]
            props.smallest_seqno = int(sq.min()) if n else 0
            props.largest_seqno = int(sq.max()) if n else 0

        meta_entries = []
        metaindex = BlockBuilder(restart_interval=1)
        if options.filter_policy and options.whole_key_filtering and n:
            from toplingdb_tpu.table.filter import build_filter_block_native

            fdata = build_filter_block_native(
                lib, options.filter_policy, kv.key_buf,
                kv.key_offs[sel], (kv.key_lens[sel] - 8), n)
            fh = fmt.write_block(self.w, fdata, fmt.NO_COMPRESSION)
            props.filter_size = len(fdata)
            meta_entries.append((METAINDEX_FILTER, fh))

        smallest = self.first_key
        largest = self.last_key
        if tombstones:
            rdb = BlockBuilder(restart_interval=1)
            for frag in tombstones:
                b, e = frag.to_table_entry()
                rdb.add(b, e)
                props.num_range_deletions += 1
                if smallest is None or icmp.compare(b, smallest) < 0:
                    smallest = b
                end_ikey = dbformat.make_internal_key(
                    e, dbformat.MAX_SEQUENCE_NUMBER, dbformat.VALUE_TYPE_FOR_SEEK
                )
                if largest is None or icmp.compare(end_ikey, largest) > 0:
                    largest = end_ikey
                props.smallest_seqno = min(props.smallest_seqno, frag.seq)
                props.largest_seqno = max(props.largest_seqno, frag.seq)
            rh = fmt.write_block(self.w, rdb.finish(), fmt.NO_COMPRESSION)
            meta_entries.append((METAINDEX_RANGE_DEL, rh))

        if self._dict:
            dh = fmt.write_block(self.w, self._dict, fmt.NO_COMPRESSION)
            meta_entries.append((METAINDEX_COMPRESSION_DICT, dh))

        if nat_sections:
            iraw = self._native_index_raw(lib, kv, self._idx_order,
                                          self._idx_trailer)
        else:
            iraw = self.index_block.finish()
        props.index_size = len(iraw)
        pblock = props.encode_block()
        ph = fmt.write_block(self.w, pblock, fmt.NO_COMPRESSION)
        meta_entries.append((METAINDEX_PROPERTIES, ph))
        for name, handle in sorted(meta_entries):
            metaindex.add(name, handle.encode())
        mih = fmt.write_block(self.w, metaindex.finish(), fmt.NO_COMPRESSION)
        ih = fmt.write_block(self.w, iraw, options.compression)
        self.w.append(fmt.Footer(mih, ih).encode())
        self.w.flush()
        self.w.sync()
        self.w.close()
        return props, smallest, largest


def write_tables_columnar(env, dbname, new_file_number, icmp, options,
                          kv: ColumnarKV, order: np.ndarray,
                          trailer_override: np.ndarray, vtypes: np.ndarray,
                          seqs: np.ndarray, tombstones, creation_time: int,
                          max_output_file_size: int = 2 ** 62,
                          column_family=(0, "default")):
    """Build output SSTs from `kv` entries in `order`, byte-identical to
    TableBuilder fed the same stream through build_outputs — including the
    output-cutting rule (cut at a user-key boundary once the file's written
    bytes reach max_output_file_size; reference
    CompactionOutputs::ShouldStopBefore). Cutting is disabled while range
    tombstones survive, matching the per-entry path. trailer_override[i]
    (per ORIGINAL entry index) >= 0 replaces the 8-byte key trailer (seqno
    zeroing). Returns a list of (fnum, path, props, smallest, largest, sel)
    where sel is the original-index selection written to that file.
    On any failure every partial output is deleted before re-raising.

    `order` may also be an ITERATOR of int32 chunks (the device-shard
    pipeline: shard s's survivors stream into SSTs while shard s+1 is still
    computing/downloading). Chunks must be key-range-ordered with no user
    key spanning a chunk boundary, and the caller may update
    trailer_override/seqs rows for a chunk any time before yielding it."""
    lib = native.lib()
    if lib is None:
        raise NotSupported("native library unavailable")
    if isinstance(order, np.ndarray):
        # Whole array up front: no copy, no withhold/rebuild of the final
        # block (exhausted from the start).
        chunks = iter(())
        order = np.ascontiguousarray(order, dtype=np.int32)
        start_filled = len(order)
        start_exhausted = True
    else:
        chunks = iter(order)
        # Survivor count unknown until the last chunk arrives; kv.n bounds it.
        order = np.empty(kv.n, dtype=np.int32)
        start_filled = 0
        start_exhausted = False
        # Streaming callers mutate trailer_override/seqs rows right before
        # yielding each chunk; a dtype/layout conversion here would COPY and
        # silently sever that aliasing, so demand the exact form instead.
        if (trailer_override.dtype != np.int64
                or not trailer_override.flags.c_contiguous):
            raise NotSupported(
                "streamed order requires a C-contiguous int64 "
                "trailer_override (mutations must alias the writer's view)"
            )
    trailer_override = np.ascontiguousarray(trailer_override, dtype=np.int64)

    max_entry = int(kv.key_lens.max() if kv.n else 0) + int(
        kv.val_lens.max() if kv.n else 0
    )
    if not start_exhausted:
        # Streamed (pipelined) callers hand over PREALLOCATED kv buffers
        # that reader threads are still filling: the length arrays may
        # hold uninitialized garbage here, so any size derived from them
        # is only a capacity GUESS. Clamp it to a sane window — the
        # rc==-2 grow-and-retry loops below make small guesses correct,
        # and a negative/absurd garbage max must never turn into a
        # negative np.empty (a heap-state-dependent crash).
        max_entry = min(max(max_entry, 0), 4 << 20)
    out_cap = options.block_size * 2 + max_entry + 8192
    out_buf = np.empty(out_cap, dtype=np.uint8)
    out_len = np.zeros(1, dtype=np.int64)

    def entry_key(pos: int) -> bytes:
        e = int(order[pos])
        k = kv.ikey(e)
        t = int(trailer_override[e])
        if t >= 0:
            k = k[:-8] + t.to_bytes(8, "little")
        return k

    def same_user_key(pos_a: int, pos_b: int) -> bool:
        a, b = int(order[pos_a]), int(order[pos_b])
        la, lb = int(kv.key_lens[a]) - 8, int(kv.key_lens[b]) - 8
        if la != lb:
            return False
        oa, ob = int(kv.key_offs[a]), int(kv.key_offs[b])
        return bool(np.array_equal(kv.key_buf[oa:oa + la],
                                   kv.key_buf[ob:ob + lb]))

    # Hoist ctypes pointer conversions out of the per-block loop.
    p_kbuf = native.np_u8p(kv.key_buf)
    p_koff = native.np_i32p(kv.key_offs)
    p_klen = native.np_i32p(kv.key_lens)
    p_vbuf = native.np_u8p(kv.val_buf)
    p_voff = native.np_i32p(kv.val_offs)
    p_vlen = native.np_i32p(kv.val_lens)
    p_tro = native.np_i64p(trailer_override)
    p_order = native.np_i32p(order)
    p_outlen = native.np_i64p(out_len)
    p_out = native.np_u8p(out_buf)

    can_cut = not tombstones  # single output while tombstones survive

    # Bulk framing: emit a whole RUN of framed blocks per native call
    # (payload + type byte + crc trailer, byte-identical to write_block)
    # instead of one block per call — the per-block Python loop dominates
    # the write side at bench scale. Uncompressed output and snappy/zstd
    # (dict-less) both run natively; a stale .so degrades per-block.
    copts0 = getattr(options, "compression_opts", None)
    sec_ctype = 0
    if options.compression == fmt.NO_COMPRESSION:
        use_section = hasattr(lib, "tpulsm_build_data_section")
    elif (options.compression in (fmt.SNAPPY_COMPRESSION,
                                  fmt.ZSTD_COMPRESSION)
          and not (copts0 is not None and copts0.max_dict_bytes > 0)
          and hasattr(lib, "tpulsm_build_data_section_c")):
        use_section = True
        sec_ctype = options.compression
    else:
        use_section = False
    if use_section and kv.n:
        # Upper bound over ALL entries (the survivor set streams in).
        # max(0, ·): under streamed callers the length arrays can still
        # hold uninitialized garbage (see the max_entry clamp above);
        # the sec rc==-2 grow loop recovers from an undersized guess.
        sec_bytes = max(
            0, int(kv.key_lens.sum()) + int(kv.val_lens.sum()))
        # Each native call emits at most ~_SECTION_RUN_BYTES (stopping a run
        # early is free: the next call continues the same file), so the
        # section buffer and the per-call copy stay bounded no matter how
        # large the compaction or the output-file budget is.
        sec_cap = min(sec_bytes + sec_bytes // 4,
                      _SECTION_RUN_BYTES + out_cap) + (1 << 16)
        sec_buf = np.empty(sec_cap, dtype=np.uint8)
        max_blocks = sec_cap // max(1, options.block_size) + 1024
        sec_counts = np.empty(max_blocks, dtype=np.int64)
        sec_plens = np.empty(max_blocks, dtype=np.int64)
        sec_rawlens = np.empty(max_blocks, dtype=np.int64)
        sec_len = np.zeros(1, dtype=np.int64)
        p_sec = native.np_u8p(sec_buf)
        p_counts = native.np_i64p(sec_counts)
        p_plens = native.np_i64p(sec_plens)
        p_rawlens = native.np_i64p(sec_rawlens)
        p_seclen = native.np_i64p(sec_len)
        sec_level = (copts0.level if copts0 is not None
                     and copts0.level is not None else -(2 ** 31))

    use_nat_index = use_section and hasattr(lib, "tpulsm_build_index_block")

    pool = None
    if (options.compression != fmt.NO_COMPRESSION
            and getattr(options, "compression_parallel_threads", 1) > 1):
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(
            max_workers=options.compression_parallel_threads)

    results = []
    cur: _ColumnarSST | None = None
    lo = 0
    start = 0
    filled = start_filled      # rows of `order` received so far
    exhausted = start_exhausted
    try:
        cur = _ColumnarSST(env, dbname, new_file_number(), icmp, options,
                           creation_time, column_family, pool)
        need_fetch = False
        while True:
            if start >= filled or need_fetch:
                need_fetch = False
                if not exhausted:
                    nxt = next(chunks, None)
                    if nxt is None:
                        exhausted = True
                    else:
                        nxt = np.ascontiguousarray(nxt, dtype=np.int32)
                        order[filled:filled + len(nxt)] = nxt
                        filled += len(nxt)
                    continue
                if start >= filled:
                    break
            limit = filled
            if (can_cut and cur.num_entries
                    and cur.w.file_size() + cur.pending_bytes()
                    >= max_output_file_size):
                if not same_user_key(start, start - 1):
                    # Cut HERE (the per-entry path's pre-add check).
                    sel = order[lo:start]
                    results.append((cur.fnum, cur.path) + cur.finish(
                        lib, kv, sel, vtypes, seqs, []
                    ) + (sel,))
                    cur = _ColumnarSST(env, dbname, new_file_number(), icmp,
                                       options, creation_time, column_family,
                                       pool)
                    lo = start
                else:
                    # Same user key spans the boundary: all its versions stay
                    # in this file; bound the block at the end of the run so
                    # the cut re-check happens there.
                    j = start
                    while j < filled and same_user_key(j, j - 1):
                        j += 1
                    limit = j
            if use_section:
                base_size = cur.w.file_size()
                budget = base_size + _SECTION_RUN_BYTES
                if can_cut and max_output_file_size < budget:
                    budget = max_output_file_size
                if sec_ctype:
                    rc = lib.tpulsm_build_data_section_c(
                        p_kbuf, p_koff, p_klen, p_vbuf, p_voff, p_vlen,
                        p_tro, p_order, start, limit,
                        options.block_size, options.restart_interval,
                        sec_ctype, sec_level,
                        base_size, budget,
                        p_counts, p_plens, p_rawlens, max_blocks,
                        p_sec, sec_cap, p_seclen,
                    )
                    if rc == -9:
                        # codec .so unavailable: per-block Python framing
                        use_section = False
                        sec_ctype = 0
                        continue
                else:
                    rc = lib.tpulsm_build_data_section(
                        p_kbuf, p_koff, p_klen, p_vbuf, p_voff, p_vlen,
                        p_tro, p_order, start, limit,
                        options.block_size, options.restart_interval,
                        base_size, budget,
                        p_counts, p_plens, max_blocks,
                        p_sec, sec_cap, p_seclen,
                    )
                    sec_rawlens[:max(0, int(rc))] = \
                        sec_plens[:max(0, int(rc))] if rc > 0 else 0
                if rc == -2:
                    sec_cap *= 4
                    sec_buf = np.empty(sec_cap, dtype=np.uint8)
                    p_sec = native.np_u8p(sec_buf)
                    continue
                if rc == -3 or rc == -8:
                    raise NotSupported(
                        f"native block build unsupported input rc={rc}"
                    )
                if rc <= 0:
                    raise Corruption(f"native section build failed rc={rc}")
                nb = int(rc)
                sec_total = int(sec_len[0])
                pos = start + sum(int(sec_counts[b]) for b in range(nb))
                if not exhausted and pos == filled:
                    # The final block ended at the chunk boundary — it may
                    # have been starved, not full. Withhold it until more
                    # data arrives so block layout matches the
                    # whole-array build byte-for-byte.
                    last_cnt = int(sec_counts[nb - 1])
                    nb -= 1
                    pos -= last_cnt
                    sec_total -= int(sec_plens[nb]) + fmt.BLOCK_TRAILER_SIZE
                    need_fetch = True
                    if nb == 0:
                        continue
                section = sec_buf[:sec_total].tobytes()
                if use_nat_index:
                    # Index entries defer to ONE native call at finish —
                    # zero per-block Python on the section path.
                    cur._idx_order = order
                    cur._idx_trailer = trailer_override
                    cur.add_framed_section_arrays(
                        section, sec_counts, sec_plens, sec_rawlens, nb,
                        start, entry_key)
                else:
                    blocks = []
                    bpos = start
                    for b in range(nb):
                        cnt = int(sec_counts[b])
                        blocks.append((int(sec_plens[b]),
                                       int(sec_rawlens[b]),
                                       entry_key(bpos),
                                       entry_key(bpos + cnt - 1), cnt))
                        bpos += cnt
                    cur.add_framed_section(section, blocks)
                start = pos
                continue
            rc = lib.tpulsm_build_block(
                p_kbuf, p_koff, p_klen, p_vbuf, p_voff, p_vlen, p_tro,
                p_order, start, limit,
                options.block_size, options.restart_interval,
                p_out, out_cap, p_outlen,
            )
            if rc == -2:
                out_cap *= 4
                out_buf = np.empty(out_cap, dtype=np.uint8)
                p_out = native.np_u8p(out_buf)
                continue
            if rc == -3 or rc == -8:
                # Key too long for the native stack buffer / restart table
                # full: the per-entry path handles these.
                raise NotSupported(
                    f"native block build unsupported input rc={rc}"
                )
            if rc <= 0:
                raise Corruption(f"native block build failed rc={rc}")
            if not exhausted and start + int(rc) == filled:
                # Possibly starved at the chunk boundary: rebuild this block
                # once more data arrives (see the section path above).
                need_fetch = True
                continue
            raw = out_buf[: int(out_len[0])].tobytes()
            cur.add_block(raw, entry_key(start),
                          entry_key(start + int(rc) - 1), int(rc))
            start += int(rc)
        sel = order[lo:filled]
        results.append((cur.fnum, cur.path) + cur.finish(
            lib, kv, sel, vtypes, seqs, tombstones
        ) + (sel,))
        cur = None
        return results
    except BaseException:
        if cur is not None:
            cur.w.close()
            try:
                env.delete_file(cur.path)
            except Exception as e:
                _errors.swallow(reason="sst-abort-cleanup", exc=e)
        for r in results:
            try:
                env.delete_file(r[1])
            except Exception as e:
                _errors.swallow(reason="sst-abort-cleanup", exc=e)
        raise
    finally:
        if pool is not None:
            pool.shutdown()
