"""Device (TPU) compaction data plane: host orchestration.

Replaces the CPU heap-merge + CompactionIterator with:
  1. raw sequential reads of every input file (no host merge),
  2. one device sort realizing internal-key order (ops.compaction_kernels),
  3. device GC masking (stripes, visibility, tombstone shadowing),
  4. host resolution of "complex" groups (merge operands / single-delete),
  5. the SAME build_outputs() as the CPU path → byte-identical SSTs.

This is the kernel surface called out in SURVEY.md §3.4/§7 step 5; the
serializable executor boundary (compaction/executor.py) selects it with
device="tpu"|"cpu" (the jax backend).
"""

from __future__ import annotations

import bisect
import os
import time

import numpy as np

from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.compaction.compaction_job import (
    CompactionStats,
    build_outputs,
    surviving_tombstone_fragments,
)
from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone, fragment_tombstones
from toplingdb_tpu.ops import compaction_kernels as ck
from toplingdb_tpu.ops.columnar import ColumnarEntries


def collect_raw_entries(compaction, table_cache, icmp, stats=None):
    """Sequentially read every input file's entries (NO host merge — the
    device sort is the merge). Returns (entries list, RangeDelAggregator);
    `stats` (CompactionStats) accumulates the scan's readahead counters."""
    entries: list[tuple[bytes, bytes]] = []
    rd = RangeDelAggregator(icmp.user_comparator)
    for _, f in compaction.all_inputs():
        r = table_cache.get_reader(f.number)
        it = r.new_iterator()
        it.seek_to_first()
        for k, v in it.entries():
            entries.append((k, v))
        if stats is not None:
            h, m = it.prefetch_counts()
            stats.prefetch_hits += h
            stats.prefetch_misses += m
        for b, e in r.range_del_entries():
            rd.add(RangeTombstone.from_table_entry(b, e))
    return entries, rd


def _tombstone_cover(sorted_user_keys: list[bytes], rd: RangeDelAggregator,
                     ucmp, sorted_seqs, snapshots) -> np.ndarray | None:
    """Per-sorted-entry max covering tombstone seqno (uint64), CLAMPED TO
    EACH ENTRY'S SNAPSHOT STRIPE — a tombstone above the next snapshot must
    not mask an in-stripe one (it can't delete the entry, but the in-stripe
    one does). Interval mapping on host (fragments are few; entries many)."""
    if rd.empty():
        return None
    n = len(sorted_user_keys)
    cover = np.zeros(n, dtype=np.uint64)
    seqs = np.asarray(sorted_seqs, dtype=np.uint64)
    snaps = np.asarray(sorted(snapshots), dtype=np.uint64)
    if len(snaps):
        idx = np.searchsorted(snaps, seqs, side="left")
        upper = np.where(
            idx < len(snaps), snaps[np.minimum(idx, len(snaps) - 1)],
            np.uint64(dbformat.MAX_SEQUENCE_NUMBER),
        )
    else:
        upper = np.full(n, dbformat.MAX_SEQUENCE_NUMBER, dtype=np.uint64)
    for frag in fragment_tombstones(rd.tombstones(), ucmp):
        lo = bisect.bisect_left(sorted_user_keys, frag.begin)
        hi = bisect.bisect_left(sorted_user_keys, frag.end)
        if lo < hi:
            t = np.uint64(frag.seq)
            sl = slice(lo, hi)
            elig = (t > seqs[sl]) & (t <= upper[sl]) & (t > cover[sl])
            cover[sl] = np.where(elig, t, cover[sl])
    return cover


# Longest user key the device paths accept: the sort uses one operand per
# 4 key bytes, and XLA compile time grows with operand count. Longer keys
# route to the host CompactionIterator (scheduler fallback-to-local).
MAX_DEVICE_KEY_BYTES = 128


def _host_sort() -> bool:
    """TPULSM_HOST_SORT=1: no accelerator attached — the numpy twins beat
    running the jax programs on the cpu backend (set by bench's fallback)."""
    return os.environ.get("TPULSM_HOST_SORT") == "1"


def device_gc_entries(entries, icmp, snapshots, bottommost,
                      merge_operator=None, compaction_filter=None,
                      compaction_filter_level=0, rd=None,
                      max_key_bytes=None, blob_resolver=None):
    """Runs the device data plane over raw (unsorted) entries; yields the
    surviving (internal_key, value) stream — semantically identical to
    CompactionIterator.entries() over the merged sorted input."""
    if not entries:
        return
    if max_key_bytes is None:
        longest = max(len(k) for k, _ in entries) - 8
        if longest > MAX_DEVICE_KEY_BYTES:
            from toplingdb_tpu.utils.status import NotSupported

            raise NotSupported(
                f"user keys up to {longest}B exceed the device key budget "
                f"({MAX_DEVICE_KEY_BYTES}B); use the CPU path"
            )
    if icmp.user_comparator.name() != dbformat.BYTEWISE.name():
        # The device sort realizes bytewise-ascending user-key order; other
        # comparators must use the host path (scheduler falls back).
        from toplingdb_tpu.utils.status import NotSupported

        raise NotSupported(
            f"device compaction requires the bytewise comparator, "
            f"got {icmp.user_comparator.name()!r}"
        )
    col = ColumnarEntries.from_entries(entries, max_key_bytes)
    padded = ck.pad_columns(col)
    sorted_cols, perm = ck.device_sort(padded)
    cover = None
    sorted_uks = None
    if rd is not None:
        sorted_uks = [col.user_key(i) for i in perm]
        cover = _tombstone_cover(sorted_uks, rd, icmp.user_comparator,
                                 col.seq[perm], snapshots)
    keep, zero_seq, host_resolve, group_id = ck.gc_mask(
        sorted_cols, snapshots, cover, bottommost
    )

    # Host-side finishing: complex groups through the reference state
    # machine; simple survivors filtered/zeroed to match it exactly.
    helper = CompactionIterator(
        _EmptyIter(), icmp, snapshots, bottommost_level=bottommost,
        merge_operator=merge_operator, compaction_filter=compaction_filter,
        compaction_filter_level=compaction_filter_level, range_del_agg=rd,
        blob_resolver=blob_resolver,
    )
    earliest = min(snapshots) if snapshots else dbformat.MAX_SEQUENCE_NUMBER
    from toplingdb_tpu.utils.compaction_filter import Decision

    n = col.n
    values = col.values
    ikeys = col.ikeys
    fast = compaction_filter is None  # fast path: emit original ikey bytes
    i = 0
    while i < n:
        if host_resolve[i]:
            g = group_id[i]
            j = i
            group = []
            while j < n and group_id[j] == g:
                oi = perm[j]
                group.append((int(col.seq[oi]), int(col.vtype[oi]), values[oi]))
                j += 1
            yield from helper._process_group(col.user_key(perm[i]), group)
            i = j
            continue
        if keep[i]:
            oi = perm[i]
            if fast:
                if zero_seq[i]:
                    yield dbformat.make_internal_key(
                        ikeys[oi][:-8], 0, int(col.vtype[oi])
                    ), values[oi]
                else:
                    yield ikeys[oi], values[oi]
                i += 1
                continue
            seq, t = int(col.seq[oi]), int(col.vtype[oi])
            val = values[oi]
            uk = col.user_key(oi)
            if t == dbformat.ValueType.VALUE and seq <= earliest:
                d, newv = compaction_filter.filter(
                    compaction_filter_level, uk, val
                )
                if d == Decision.REMOVE:
                    i += 1
                    continue
                if d == Decision.CHANGE_VALUE:
                    val = newv if newv is not None else b""
            if zero_seq[i]:
                seq = 0
            yield dbformat.make_internal_key(uk, seq, t), val
        i += 1


class _EmptyIter:
    def valid(self):
        return False


class _FallbackToEntries(Exception):
    """Raised inside the columnar fast path when the job needs per-entry
    semantics (complex groups present)."""


def _kv_seq_vtype(kv):
    """Trailer columns (packed, seq, vtype) from flat buffers — shared by the
    full columnar encode and the cheap post-fused-run subset."""
    import sys
    import types

    n = kv.n
    offs = kv.key_offs.astype(np.int64)
    lens = kv.key_lens.astype(np.int64)
    if n and kv.key_lens.min() == kv.key_lens.max() and len(
            kv.key_buf) == n * int(lens[0]) and int(offs[0]) == 0 and int(
            offs[-1]) == (n - 1) * int(lens[0]) and np.array_equal(
            np.diff(offs), lens[:-1]):
        # Uniform key length over a dense buffer: the trailers are a strided
        # view — no [n,8] gather.
        trailer = np.ascontiguousarray(
            kv.key_buf.reshape(n, int(lens[0]))[:, -8:]
        )
    else:
        tr_idx = (offs + lens - 8)[:, None] + np.arange(8)[None, :]
        trailer = np.ascontiguousarray(kv.key_buf[tr_idx])
    packed = trailer.view(np.uint64).reshape(n)
    if sys.byteorder == "big":  # trailer bytes on disk are LE
        packed = packed.byteswap()
    return types.SimpleNamespace(
        packed=packed,
        seq=packed >> np.uint64(8),
        vtype=(packed & np.uint64(0xFF)).astype(np.int32),
        n=n,
    )


def _part_user_key(part, i: int) -> bytes:
    o = int(part.key_offs[i])
    return part.key_buf[o: o + int(part.key_lens[i]) - 8].tobytes()


def _shard_splitters(part, n_shards: int) -> list[bytes]:
    """Evenly spaced user keys from one sorted part (deduped, ascending)."""
    spl = []
    for s in range(1, n_shards):
        spl.append(_part_user_key(part, part.n * s // n_shards))
    return sorted(set(spl))


def _part_bounds(part, splitters: list[bytes]) -> list[int]:
    """Row bounds [0, b1, ..., n] for one sorted part: b_s = first row whose
    user key >= splitters[s-1] (all copies of a user key land in ONE shard)."""
    b = [0]
    for spl in splitters:
        lo, hi = b[-1], part.n
        while lo < hi:
            mid = (lo + hi) // 2
            if _part_user_key(part, mid) < spl:
                lo = mid + 1
            else:
                hi = mid
        b.append(lo)
    b.append(part.n)
    return b


def _device_shards(total_rows: int) -> int:
    """Range-shard count: TPULSM_DEVICE_SHARDS wins; otherwise size shards
    to ~512K rows (pow2 count, so per-shard padded shapes land in the same
    compile bucket) up to the 24-bit packed-order budget."""
    env = os.environ.get("TPULSM_DEVICE_SHARDS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    try:
        target = max(1 << 16, int(os.environ.get(
            "TPULSM_SHARD_ROWS", str(1 << 20))))
    except ValueError:
        target = 1 << 20
    s = 1
    while s < 16 and total_rows // s > target:
        s *= 2
    return s


# Below this row count a job runs as one shard: the pipeline's transfer/
# compute overlap cannot recoup the extra per-shard dispatch latency.
_SHARD_MIN_ROWS = 1 << 18


def _collect_raw_columnar(compaction, table_cache, icmp, want_uploads=False):
    """Scan every input file into columnar buffers — in parallel threads
    (the native block decoder runs GIL-free under ctypes). With
    want_uploads, ALSO split the sorted parts into user-key-range shards
    and prepare (host-side, no device traffic yet) each shard's uniform
    chunk columns. Returns (kv, rd, shards, parts) where shards is None
    when the sharded uniform device path does not apply (sparse layout,
    non-uniform key lengths, oversized shards); otherwise shards[s] =
    (chunks, row_ranges): prepare_uniform_chunk outputs plus the
    (global_lo, global_hi) row spans into the concatenated kv that each
    chunk covers, in chunk order."""
    from concurrent.futures import ThreadPoolExecutor

    from toplingdb_tpu.ops.columnar_io import (
        ColumnarKV,
        scan_table_columnar,
        scan_tables_columnar_prealloc,
    )

    readers = [
        table_cache.get_reader(f.number) for _, f in compaction.all_inputs()
    ]
    pre = scan_tables_columnar_prealloc(readers)
    if pre is not None:
        kv, parts = pre
    else:
        if len(readers) > 1:
            with ThreadPoolExecutor(min(8, len(readers))) as ex:
                parts = list(ex.map(scan_table_columnar, readers))
        else:
            parts = [scan_table_columnar(r) for r in readers]
        kv = ColumnarKV.concat(parts)
    rd = RangeDelAggregator(icmp.user_comparator)
    for r in readers:
        for b, e in r.range_del_entries():
            rd.add(RangeTombstone.from_table_entry(b, e))

    shards = None
    if want_uploads:
        shards = _prepare_uniform_shards(parts)
    return kv, rd, shards, parts


def _part_lower_bound(part, key: bytes, lo: int = 0) -> int:
    """First row of the (sorted) part whose user key >= key."""
    hi = part.n
    while lo < hi:
        mid = (lo + hi) // 2
        if _part_user_key(part, mid) < key:
            lo = mid + 1
        else:
            hi = mid
    return lo


def _cover_for_parts(parts, rd: RangeDelAggregator, ucmp, snapshots):
    """Per-ORIGINAL-row (concat order) max covering tombstone seqno,
    stripe-clamped exactly like _tombstone_cover — computed per sorted
    input part with interval binary searches (fragments are few, rows are
    many), so the fused device paths can take tombstone-bearing jobs.
    Returns uint64[sum(part.n)] or None when there are no tombstones."""
    frags = list(fragment_tombstones(rd.tombstones(), ucmp))
    if not frags:
        return None
    snaps = np.asarray(sorted(snapshots), dtype=np.uint64)
    covers = []
    for part in parts:
        n = part.n
        cov = np.zeros(n, dtype=np.uint64)
        if n:
            tv = _kv_seq_vtype(part)
            seqs = tv.seq
            if len(snaps):
                idx = np.searchsorted(snaps, seqs, side="left")
                upper = np.where(
                    idx < len(snaps),
                    snaps[np.minimum(idx, len(snaps) - 1)],
                    np.uint64(dbformat.MAX_SEQUENCE_NUMBER),
                )
            else:
                upper = np.full(n, dbformat.MAX_SEQUENCE_NUMBER,
                                dtype=np.uint64)
            for frag in frags:
                lo = _part_lower_bound(part, frag.begin)
                hi = _part_lower_bound(part, frag.end, lo)
                if lo < hi:
                    t = np.uint64(frag.seq)
                    sl = slice(lo, hi)
                    elig = ((t > seqs[sl]) & (t <= upper[sl])
                            & (t > cov[sl]))
                    cov[sl] = np.where(elig, t, cov[sl])
        covers.append(cov)
    return np.concatenate(covers) if covers else None


def _prepare_uniform_shards(parts):
    """Host half of the sharded uniform device path: validate density +
    uniform key length, pick range splitters, slice every part into
    per-shard chunks. Returns shards list or None when ineligible."""
    from toplingdb_tpu.utils.status import NotSupported

    uniform_len = 0
    total_rows = 0
    for part in parts:
        if not part.n:
            continue
        L = int(part.key_lens[0])
        dense_uniform = (
            part.key_lens.min() == part.key_lens.max()
            and len(part.key_buf) == part.n * L
            and int(part.key_offs[0]) == 0
            and np.array_equal(
                part.key_offs[1:],
                (np.cumsum(part.key_lens) - part.key_lens)[1:],
            )
        )
        if not dense_uniform:
            return None
        if uniform_len and L != uniform_len:
            return None
        uniform_len = L
        total_rows += part.n
    if not total_rows:
        return None

    splitters = None
    for part in parts:
        if part.n:
            n_shards = (
                _device_shards(total_rows)
                if total_rows >= _SHARD_MIN_ROWS else 1
            )
            splitters = _shard_splitters(part, n_shards)
            break
    shards = [([], []) for _ in range(len(splitters) + 1)]
    row_base = 0
    try:
        for part in parts:
            if not part.n:
                continue
            bounds = _part_bounds(part, splitters)
            for s in range(len(bounds) - 1):
                lo, hi = bounds[s], bounds[s + 1]
                if lo == hi:
                    continue
                blo = int(part.key_offs[lo])
                bhi = int(part.key_offs[hi - 1]) + int(part.key_lens[hi - 1])
                shards[s][0].append(ck.prepare_uniform_chunk(
                    part.key_buf[blo:bhi], hi - lo, uniform_len,
                ))
                shards[s][1].append((row_base + lo, row_base + hi))
            row_base += part.n
    except NotSupported:
        return None
    shards = [sh for sh in shards if sh[0]]
    for chunks, _ranges in shards:
        if sum(c[3] for c in chunks) > ck.MAX_SHARD_ROWS:
            return None  # skewed splitters blew the 24-bit row budget
    return shards or None


def _ranges_lmap(ranges) -> np.ndarray:
    """Local shard row -> global concat row map for a shard's chunk
    (global_lo, global_hi) spans."""
    if not ranges:
        return np.empty(0, np.int32)
    return np.concatenate([
        np.arange(lo, hi, dtype=np.int32) for lo, hi in ranges
    ])


def _kv_user_key(kv, r: int) -> bytes:
    o = int(kv.key_offs[r])
    return kv.key_buf[o: o + int(kv.key_lens[r]) - 8].tobytes()


def _patch_kv_values(kv, rows: list[int], vals: list[bytes]) -> None:
    """Append replacement values (folded merge results etc.) to kv's value
    buffer and repoint the rows at them — the columnar writer then emits
    them with zero further special-casing."""
    side = b"".join(vals)
    base = len(kv.val_buf)
    if base + len(side) > 2 ** 31 - 8:
        raise _FallbackToEntries()  # int32 offset budget
    kv.val_buf = np.concatenate([
        kv.val_buf, np.frombuffer(side, dtype=np.uint8)
    ])
    if not kv.val_offs.flags.writeable:
        kv.val_offs = kv.val_offs.copy()
    if not kv.val_lens.flags.writeable:
        kv.val_lens = kv.val_lens.copy()
    off = base
    for r, v in zip(rows, vals):
        kv.val_offs[r] = off
        kv.val_lens[r] = len(v)
        off += len(v)


def _resolve_complex_stream(kv, order, cx_flags, trailer_override, seqs,
                            vtypes, helper):
    """Fold the complex (MERGE / SINGLE_DELETE) user-key groups the device
    flagged in the survivor stream through the reference state machine
    (CompactionIterator._process_group, the MergeHelper::MergeUntil role,
    /root/reference/db/merge_helper.h:104) WITHOUT abandoning the columnar
    path: each group's emitted entries overwrite the group's leading rows
    (trailer/seq/vtype overrides + value replacements appended to kv's
    side buffer); surplus rows drop out of the order. Returns the filtered
    order; mutates trailer_override/seqs/vtypes and patches kv in place."""
    n_stream = len(order)
    keep_mask = np.ones(n_stream, dtype=bool)
    repl_rows: list[int] = []
    repl_vals: list[bytes] = []
    pos_list = np.flatnonzero(cx_flags)
    i = 0
    P = len(pos_list)
    while i < P:
        p0 = int(pos_list[i])
        uk = _kv_user_key(kv, int(order[p0]))
        j = i + 1
        while (j < P and int(pos_list[j]) == int(pos_list[j - 1]) + 1
               and _kv_user_key(kv, int(order[int(pos_list[j])])) == uk):
            j += 1
        rows = [int(order[int(pos_list[t])]) for t in range(i, j)]
        group = [(int(seqs[r]), int(vtypes[r]), kv.value(r)) for r in rows]
        emitted = list(helper._process_group(uk, group))
        if len(emitted) > len(rows):
            raise _FallbackToEntries()  # cannot happen; belt and braces
        for t, (ik, v) in enumerate(emitted):
            r = rows[t]
            if ik[:-8] != uk:
                raise _FallbackToEntries()
            packed = int.from_bytes(ik[-8:], "little")
            if packed >= 2 ** 63:
                raise _FallbackToEntries()  # int64 trailer budget
            trailer_override[r] = packed
            seqs[r] = packed >> 8
            vtypes[r] = packed & 0xFF
            if v != kv.value(r):
                repl_rows.append(r)
                repl_vals.append(v)
        for t in range(len(emitted), len(rows)):
            keep_mask[int(pos_list[i + t])] = False
        i = j
    if repl_rows:
        _patch_kv_values(kv, repl_rows, repl_vals)
    return order[keep_mask]


def _verify_columnar_output(env, icmp, table_options, path, kv, vtypes,
                            sel) -> None:
    """Protection check for ONE columnar-plane output file: the entries
    on disk must be exactly the surviving input rows `sel` (post
    merge-resolution value patching, seq zeroing exempt) — the
    per-entry-checksum form of paranoid_file_checks, shared by the serial
    columnar, sharded-device, and pipelined paths."""
    from toplingdb_tpu.compaction.compaction_job import verify_output_table
    from toplingdb_tpu.utils import protection as _p

    pb = table_options.protection_bytes_per_key
    expected: dict[int, int] = {}
    for r in sel.tolist():
        ik = kv.ikey(r)
        cs = _p.truncate(
            _p.protect_entry(int(vtypes[r]), ik[:-8], kv.value(r)), pb)
        expected[cs] = expected.get(cs, 0) + 1
    verify_output_table(env, path, icmp, table_options, expected, len(sel))


def _outputs_from_files(env, files, kv, vtypes, stats, icmp=None,
                        table_options=None):
    """Output FileMetaData list from write_tables_columnar tuples: empty
    outputs deleted, blob refs decoded from surviving BLOB_INDEX rows —
    shared by the serial columnar and pipelined paths. With icmp +
    table_options given and protection active, every output is re-read
    and verified against its surviving input rows before it is returned
    (_verify_columnar_output)."""
    from toplingdb_tpu.db.blob import decode_blob_index
    from toplingdb_tpu.db.version_edit import FileMetaData

    pb = (getattr(table_options, "protection_bytes_per_key", 0)
          if table_options is not None else 0)
    outputs = []
    for fnum, path, props, smallest, largest, sel in files:
        if props.num_entries == 0 and props.num_range_deletions == 0:
            env.delete_file(path)
            continue
        if pb:
            _verify_columnar_output(env, icmp, table_options, path, kv,
                                    vtypes, sel)
        blob_refs = set()
        bi_mask = vtypes[sel] == dbformat.ValueType.BLOB_INDEX
        if bi_mask.any():
            for oi in sel[bi_mask]:
                blob_refs.add(decode_blob_index(kv.value(oi))[0])
        meta = FileMetaData(
            number=fnum, file_size=env.get_file_size(path),
            smallest=smallest, largest=largest,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
            num_entries=props.num_entries,
            num_deletions=props.num_deletions,
            num_range_deletions=props.num_range_deletions,
            blob_refs=sorted(blob_refs),
        )
        outputs.append(meta)
        stats.output_bytes += meta.file_size
        stats.output_files += 1
        stats.output_records += props.num_entries
    return outputs


def _run_device_compaction_columnar(env, dbname, icmp, compaction, table_cache,
                                    table_options, snapshots, merge_operator,
                                    new_file_number, creation_time,
                                    device_name, column_family=(0, "default"),
                                    blob_resolver=None):
    from toplingdb_tpu.compaction.compaction_job import (
        surviving_tombstone_fragments,
    )
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.ops.columnar_io import write_tables_columnar

    from toplingdb_tpu.utils.status import NotSupported

    t0 = time.time()
    stats = CompactionStats(device=device_name)
    stats.input_bytes = compaction.total_input_bytes()
    stats.input_files = len(compaction.all_inputs())

    # Pipelined data plane first: scan, sort+GC and encode overlap at
    # key-range-shard granularity (ops/pipeline.py), byte-identical
    # outputs. Shapes it does not cover fall through to the serial path
    # below with clean stats.
    from toplingdb_tpu.ops import pipeline as pl

    if pl.pipeline_enabled(table_options):
        pstats = CompactionStats(device=device_name)
        pstats.input_bytes = stats.input_bytes
        pstats.input_files = stats.input_files
        try:
            pfiles, pkv, pvt, _ptombs = pl.run_pipelined(
                env, dbname, icmp, compaction, table_cache, table_options,
                snapshots, new_file_number, creation_time, pstats,
                MAX_DEVICE_KEY_BYTES, column_family,
            )
        except (pl.PipelineIneligible, NotSupported):
            pass  # serial path decides (and re-raises what it must)
        else:
            outputs = _outputs_from_files(env, pfiles, pkv, pvt, pstats,
                                          icmp=icmp,
                                          table_options=table_options)
            pstats.work_time_usec = int((time.time() - t0) * 1e6)
            return outputs, pstats
    try:
        kv, rd, shards, parts = _collect_raw_columnar(
            compaction, table_cache, icmp, want_uploads=not _host_sort(),
        )
    except NotSupported:
        raise _FallbackToEntries()  # >2GiB columnar buffers etc.
    stats.input_scan_usec = int((time.time() - t0) * 1e6)
    stats.input_records = kv.n
    if kv.n == 0 and rd.empty():
        stats.work_time_usec = int((time.time() - t0) * 1e6)
        return [], stats
    if kv.n and int(kv.key_lens.max()) - 8 > MAX_DEVICE_KEY_BYTES:
        # Exceeds the sort-operand budget (and the 4096B native block-builder
        # key buffer); the entries path re-checks and routes to the CPU.
        raise _FallbackToEntries()
    t_fin = time.time()
    mkb = max(4, int(kv.key_lens.max()) - 8) if kv.n else 4
    col = any_complex = None
    if not _host_sort():
        # Host-sort mode gets seq/vtype from the fused native merge+GC —
        # gathering trailers here would be pure waste at bench scale.
        col = _kv_seq_vtype(kv)
        _VT = dbformat.ValueType
        any_complex = bool(kv.n) and bool(np.any(
            (col.vtype == int(_VT.MERGE))
            | (col.vtype == int(_VT.SINGLE_DELETION))
        ))
    stats.finish_usec += int((time.time() - t_fin) * 1e6)
    streamed = False
    order = zero_flags = cx_flags = None
    has_complex = False
    try:
        # Range tombstones ride the fused kernels as a per-row max-covering
        # seqno side input (stripe-clamped on host; fragments are few).
        t_cov = time.time()
        cover = (None if rd.empty() else _cover_for_parts(
            parts, rd, icmp.user_comparator, snapshots))
        stats.host_compute_usec += int((time.time() - t_cov) * 1e6)
        if not _host_sort():
            from toplingdb_tpu.ops import block_assembly as ba

            if ba.assembly_supported(table_options, kv, shards, any_complex,
                                     compaction.max_output_file_size,
                                     col.vtype):
                # Full block build ON DEVICE: finished payloads come back,
                # the host only frames + indexes (TPULSM_DEVICE_BLOCKS=1).
                tombs = surviving_tombstone_fragments(
                    rd, snapshots, compaction.bottommost,
                    icmp.user_comparator,
                )
                files = ba.run_block_assembly(
                    env, dbname, icmp, kv, shards[0], cover, snapshots,
                    compaction.bottommost, table_options, new_file_number,
                    creation_time, tombs, column_family,
                )
                outputs = []
                pb_ = getattr(table_options, "protection_bytes_per_key", 0)
                for fnum, path, props, smallest, largest, _sel in files:
                    if (props.num_entries == 0
                            and props.num_range_deletions == 0):
                        env.delete_file(path)
                        continue
                    if pb_:
                        _verify_columnar_output(env, icmp, table_options,
                                                path, kv, col.vtype, _sel)
                    meta = FileMetaData(
                        number=fnum, file_size=env.get_file_size(path),
                        smallest=smallest, largest=largest,
                        smallest_seqno=props.smallest_seqno,
                        largest_seqno=props.largest_seqno,
                        num_entries=props.num_entries,
                        num_deletions=props.num_deletions,
                        num_range_deletions=props.num_range_deletions,
                    )
                    outputs.append(meta)
                    stats.output_bytes += meta.file_size
                    stats.output_files += 1
                    stats.output_records += props.num_entries
                stats.work_time_usec = int((time.time() - t0) * 1e6)
                return outputs, stats
        if _host_sort():
            import types as _types

            t_hc = time.time()
            rs = np.cumsum([0] + [p_.n for p_ in parts], dtype=np.int64)
            order, zero_flags, cx_flags, has_complex, seq_a, vt_a = \
                ck.host_fused_full(
                    kv.key_buf, kv.key_offs, kv.key_lens, mkb,
                    snapshots, compaction.bottommost, cover,
                    run_starts=rs,
                )
            stats.host_compute_usec += int((time.time() - t_hc) * 1e6)
            col = _types.SimpleNamespace(seq=seq_a, vtype=vt_a, n=kv.n)
        elif shards is not None:
            # Upload + dispatch through the mesh seam: serial mode uploads
            # every shard up front to the default device (device_put and
            # jit dispatch are async; shard s+1's transfer streams while
            # shard s computes, and fused_uniform_shard_start enqueues
            # each D2H copy so results stream back); TPULSM_MESH_COMPACT
            # places shards round-robin over every chip instead, double-
            # buffered per chip (ops/mesh_compaction.py).
            from toplingdb_tpu.ops import mesh_compaction as mc
            from toplingdb_tpu.utils import telemetry as _tele

            t_up = time.time()
            finish_shard, _mesh_on = mc.dispatch_shards(
                shards, cover, snapshots, compaction.bottommost,
                stats=stats, any_complex=bool(any_complex),
                trace=_tele.current_handle(),
            )
            # Upload-enqueue span (device_put is async, so this is a lower
            # bound; the blocking download waits below add the rest).
            stats.transfer_time_usec += int((time.time() - t_up) * 1e6)
            if not any_complex and \
                    getattr(table_options, "format", "block") in ("block",
                                                                  "zip"):
                # STREAM each shard's survivors straight into the SST
                # writer — block building overlaps the remaining shards'
                # compute + download. (The zip writer drains the feed,
                # overlapping shard compute with its own encode setup.)
                streamed = True
            else:
                # Complex groups must fold BEFORE the writer hoists its
                # value-buffer pointers, so collect every shard first;
                # the shard programs still overlap each other.
                orders, zfs, cxs = [], [], []
                for s_i, (_chunks, ranges) in enumerate(shards):
                    t_dn = time.time()
                    o, z, cx, hc = finish_shard(s_i)
                    stats.device_wait_usec += int(
                        (time.time() - t_dn) * 1e6)
                    lmap = _ranges_lmap(ranges)
                    orders.append(lmap[o])
                    zfs.append(z)
                    cxs.append(cx)
                    has_complex = has_complex or hc
                order = (np.concatenate(orders) if orders
                         else np.empty(0, np.int32))
                zero_flags = (np.concatenate(zfs) if zfs
                              else np.empty(0, bool))
                cx_flags = (np.concatenate(cxs) if cxs
                            else np.empty(0, bool))
        else:
            order, zero_flags, cx_flags, has_complex = \
                ck.fused_encode_sort_gc(
                    kv.key_buf, kv.key_offs, kv.key_lens, mkb, snapshots,
                    compaction.bottommost, cover,
                )
    except NotSupported:
        raise _FallbackToEntries()  # non-dense buffers, >cap snapshots etc.

    t_fin = time.time()
    trailer_override = np.full(kv.n, -1, dtype=np.int64)
    seqs = col.seq.copy()
    vtypes = col.vtype
    if not streamed:
        # packed trailer for seq 0 is just the type byte. Complex rows'
        # zero flags are provisional — _process_group re-decides them.
        zmask = zero_flags if not has_complex else (zero_flags & ~cx_flags)
        zero_orig = order[zmask]
        trailer_override[zero_orig] = col.vtype[zero_orig].astype(np.int64)
        seqs[zero_orig] = 0
        if has_complex:
            vtypes = vtypes.copy()
            helper = CompactionIterator(
                _EmptyIter(), icmp, snapshots,
                bottommost_level=compaction.bottommost,
                merge_operator=merge_operator,
                range_del_agg=None if rd.empty() else rd,
                blob_resolver=blob_resolver,
            )
            t_rs = time.time()
            order = _resolve_complex_stream(
                kv, order, cx_flags, trailer_override, seqs, vtypes, helper
            )
            stats.resolve_usec = int((time.time() - t_rs) * 1e6)
        order_feed = order
    else:
        # Shard streaming: each chunk's trailers/seqs land just before the
        # writer consumes it (the writer reads both arrays per native call).
        def _shard_order_chunks():
            for s_i, (_chunks, ranges) in enumerate(shards):
                t_dn = time.time()
                o, z, _cx, hc = finish_shard(s_i)
                stats.device_wait_usec += int((time.time() - t_dn) * 1e6)
                if hc:
                    raise _FallbackToEntries()
                lmap = _ranges_lmap(ranges)
                order_s = lmap[o]
                zero_s = order_s[z]
                trailer_override[zero_s] = \
                    col.vtype[zero_s].astype(np.int64)
                seqs[zero_s] = 0
                yield order_s

        order_feed = _shard_order_chunks()

    tombs = surviving_tombstone_fragments(
        rd, snapshots, compaction.bottommost, icmp.user_comparator
    )
    # finish = zero-seq patch + tombstone finalize, MINUS the separately
    # reported complex-group resolve that ran inside this window.
    stats.finish_usec += max(
        0, int((time.time() - t_fin) * 1e6) - stats.resolve_usec)
    outputs = []
    t_wr = time.time()
    if order is None or len(order) or tombs:
        try:
            if getattr(table_options, "format", "block") == "zip":
                from toplingdb_tpu.table.zip_table import (
                    write_tables_zip_columnar,
                )

                files = write_tables_zip_columnar(
                    env, dbname, new_file_number, icmp, table_options, kv,
                    order_feed, trailer_override, vtypes, seqs, tombs,
                    creation_time if creation_time is not None
                    else int(time.time()),
                    max_output_file_size=compaction.max_output_file_size,
                    column_family=column_family,
                )
            else:
                files = write_tables_columnar(
                    env, dbname, new_file_number, icmp, table_options, kv,
                    order_feed, trailer_override, vtypes, seqs, tombs,
                    creation_time if creation_time is not None
                    else int(time.time()),
                    max_output_file_size=compaction.max_output_file_size,
                    column_family=column_family,
                )
        except NotSupported:
            # Native builder refused (oversized key / restart overflow):
            # the per-entry path handles these (partials already cleaned).
            raise _FallbackToEntries()
        outputs = _outputs_from_files(env, files, kv, vtypes, stats,
                                      icmp=icmp,
                                      table_options=table_options)
    stats.encode_write_usec = int((time.time() - t_wr) * 1e6)
    stats.work_time_usec = int((time.time() - t0) * 1e6)
    return outputs, stats


def run_device_compaction(env, dbname, icmp, compaction, table_cache,
                          table_options, snapshots, merge_operator=None,
                          compaction_filter=None, new_file_number=None,
                          creation_time=None, device_name="tpu",
                          blob_resolver=None, blob_gc=None,
                          column_family=(0, "default")):
    """Device counterpart of run_compaction_to_tables — same signature shape,
    byte-identical outputs (including output cutting). Jobs without a
    compaction filter take the fully-columnar native fast path; the rest
    stream through the per-entry generator. Active blob GC rewrites values,
    so it routes through the per-entry path."""
    from toplingdb_tpu import native

    if (native.lib() is not None
            and compaction_filter is None
            and (blob_gc is None or not blob_gc.active)
            and not getattr(table_options, "properties_collector_factories", None)
            and getattr(table_options, "format", "block") in ("block",
                                                                "zip")
            and getattr(table_options, "index_type", "binary") == "binary"
            and icmp.user_comparator.name() == dbformat.BYTEWISE.name()):
        try:
            return _run_device_compaction_columnar(
                env, dbname, icmp, compaction, table_cache, table_options,
                snapshots, merge_operator, new_file_number, creation_time,
                device_name, column_family, blob_resolver=blob_resolver,
            )
        except _FallbackToEntries:
            pass
        except Exception as e:  # noqa: BLE001
            # A compiled-kernel failure on the real chip (e.g. a Mosaic
            # lowering gap in an optional kernel) must degrade to the
            # CONSERVATIVE device kernels — not lose the device data
            # plane to the scheduler's run-local fallback. One retry
            # with the optional kernels disabled and trace caches
            # cleared (the kernel-choice env vars read at trace time).
            if os.environ.get("TPULSM_PALLAS_GC") == "0" \
                    and os.environ.get("TPULSM_DEVICE_MERGE") == "0":
                raise
            import sys as _sys

            print(f"device columnar path failed ({e!r:.200}); retrying "
                  "with conservative kernels", file=_sys.stderr, flush=True)
            os.environ["TPULSM_PALLAS_GC"] = "0"
            os.environ["TPULSM_DEVICE_MERGE"] = "0"
            import jax as _jax

            _jax.clear_caches()
            try:
                return _run_device_compaction_columnar(
                    env, dbname, icmp, compaction, table_cache,
                    table_options, snapshots, merge_operator,
                    new_file_number, creation_time, device_name,
                    column_family, blob_resolver=blob_resolver,
                )
            except _FallbackToEntries:
                pass
    t0 = time.time()
    stats = CompactionStats(device=device_name)
    stats.input_bytes = compaction.total_input_bytes()
    stats.input_files = len(compaction.all_inputs())
    entries, rd = collect_raw_entries(compaction, table_cache, icmp, stats)
    stats.input_records = len(entries)
    rd_or_none = None if rd.empty() else rd
    stream = device_gc_entries(
        entries, icmp, snapshots, compaction.bottommost,
        merge_operator=merge_operator, compaction_filter=compaction_filter,
        compaction_filter_level=compaction.output_level, rd=rd_or_none,
        blob_resolver=blob_resolver,
    )
    tombs = surviving_tombstone_fragments(
        rd, snapshots, compaction.bottommost, icmp.user_comparator
    )
    if blob_gc is not None and blob_gc.active:
        stream = blob_gc.rewrite(stream)
    try:
        outputs = build_outputs(
            env, dbname, icmp, compaction, stream, tombs, new_file_number,
            table_options, stats,
            creation_time if creation_time is not None else int(time.time()),
            column_family=column_family,
        )
    except BaseException:
        if blob_gc is not None:
            blob_gc.abort()
        raise
    if blob_gc is not None:
        blob_gc.finish()
    stats.work_time_usec = int((time.time() - t0) * 1e6)
    return outputs, stats
